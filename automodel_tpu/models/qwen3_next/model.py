"""Qwen3-Next family — TPU-native (reference models/qwen3_next/model.py).

Hybrid decoder: most layers mix tokens with a *gated DeltaNet* linear-attention
recurrence (reference model.py:38-39 delegates to HF Qwen3NextGatedDeltaNet); every
``full_attention_interval``-th layer is gated full attention (q carries a sigmoid
output gate, reference layers.py:56-153); every layer's MLP is Qwen-style MoE with a
gated shared expert (reference model.py:117-139).

TPU-first structure: layers are stored as two stacked streams ("linear_layers",
"full_layers") in execution order. When the layer pattern is uniform — (P-1) linear +
1 full repeated, the shape of every released Qwen3-Next checkpoint — the forward scans
over *period groups*: params reshape to (G, P-1, ...) / (G, ...) and one
``lax.scan`` body traces P layers, so compile time stays flat in depth. Non-uniform
patterns fall back to an unrolled loop.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import _constrain
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.dispatch import make_moe_block_forward
from automodel_tpu.utils.tracing import scoped
from automodel_tpu.moe.layers import cast_moe_compute_params, init_moe_params, moe_logical_axes
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.gated_delta import (
    causal_conv1d,
    chunk_gated_delta_rule,
    conv_state_from_prefill,
    conv_step,
    gated_rms_norm,
)
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope, rope_attention_scaling, rope_frequencies

__all__ = ["Qwen3NextConfig", "Qwen3NextForCausalLM"]

LINEAR = "linear_attention"
FULL = "full_attention"


@dataclasses.dataclass
class Qwen3NextConfig:
    vocab_size: int = 1024
    hidden_size: int = 256
    intermediate_size: int = 512
    num_hidden_layers: int = 4
    layer_types: tuple[str, ...] = (LINEAR, LINEAR, LINEAR, FULL)
    # full attention
    num_attention_heads: int = 4
    num_key_value_heads: int = 2
    head_dim: int = 64
    partial_rotary_factor: float = 0.25
    rope_theta: float = 10000.0
    rope_scaling: dict[str, Any] | None = None
    # gated deltanet
    linear_num_value_heads: int = 4
    linear_num_key_heads: int = 2
    linear_key_head_dim: int = 32
    linear_value_head_dim: int = 32
    linear_conv_kernel_dim: int = 4
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 4096
    initializer_range: float = 0.02
    moe: MoEConfig | None = None

    def __post_init__(self):
        if self.moe is None:
            raise ValueError("Qwen3NextConfig requires a MoEConfig in .moe")
        if len(self.layer_types) != self.num_hidden_layers:
            raise ValueError("layer_types length must equal num_hidden_layers")

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3NextConfig":
        if hf.get("mlp_only_layers"):
            raise NotImplementedError("qwen3_next dense-MLP layers are not supported")
        rope = hf.get("rope_parameters") or {}
        # rope_parameters doubles as the scaling config when rope_type != default
        rope_scaling = hf.get("rope_scaling")
        if rope_scaling is None and rope.get("rope_type", "default") != "default":
            rope_scaling = rope
        layer_types = hf.get("layer_types")
        if layer_types is None:
            interval = hf.get("full_attention_interval", 4)
            layer_types = [
                FULL if (i + 1) % interval == 0 else LINEAR for i in range(hf["num_hidden_layers"])
            ]
        moe = MoEConfig(
            n_routed_experts=hf["num_experts"],
            n_activated_experts=hf["num_experts_per_tok"],
            dim=hf["hidden_size"],
            moe_inter_dim=hf["moe_intermediate_size"],
            n_shared_experts=1,
            shared_expert_inter_dim=hf.get("shared_expert_intermediate_size", hf["moe_intermediate_size"]),
            shared_expert_gate=True,
            score_func="softmax",
            softmax_before_topk=True,
            norm_topk_prob=hf.get("norm_topk_prob", True),
            aux_loss_coeff=hf.get("router_aux_loss_coef", 0.0),
        )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf.get("intermediate_size", 0),
            num_hidden_layers=hf["num_hidden_layers"],
            layer_types=tuple(layer_types),
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf["num_key_value_heads"],
            head_dim=hf.get("head_dim", hf["hidden_size"] // hf["num_attention_heads"]),
            partial_rotary_factor=rope.get(
                "partial_rotary_factor", hf.get("partial_rotary_factor", 0.25)
            ),
            rope_theta=rope.get("rope_theta", hf.get("rope_theta", 10000.0)),
            rope_scaling=rope_scaling,
            linear_num_value_heads=hf["linear_num_value_heads"],
            linear_num_key_heads=hf["linear_num_key_heads"],
            linear_key_head_dim=hf["linear_key_head_dim"],
            linear_value_head_dim=hf["linear_value_head_dim"],
            linear_conv_kernel_dim=hf["linear_conv_kernel_dim"],
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
        )

    @property
    def linear_layer_indices(self) -> tuple[int, ...]:
        return tuple(i for i, t in enumerate(self.layer_types) if t == LINEAR)

    @property
    def full_layer_indices(self) -> tuple[int, ...]:
        return tuple(i for i, t in enumerate(self.layer_types) if t == FULL)

    @property
    def conv_dim(self) -> int:
        return 2 * self.linear_num_key_heads * self.linear_key_head_dim + (
            self.linear_num_value_heads * self.linear_value_head_dim
        )

    @property
    def period(self) -> int | None:
        """Group size P when layer_types is ((P-1) x linear, full) repeated, else None."""
        full = self.full_layer_indices
        if not full or not self.linear_layer_indices:
            return None
        P = full[0] + 1
        uniform = self.num_hidden_layers % P == 0 and all(
            t == (FULL if (i + 1) % P == 0 else LINEAR) for i, t in enumerate(self.layer_types)
        )
        return P if uniform else None


def _linear_attn_shapes(cfg: Qwen3NextConfig) -> dict:
    """HF's fused projections stay fused as single leaves: one big MXU matmul each
    and a 1:1 state-dict mapping (in_proj_qkvz rows are per-key-head
    [q|k|v·r|z·r] — HF fix_query_key_value_ordering, modeling_qwen3_next.py:631)."""
    D = cfg.hidden_size
    Hk, dk = cfg.linear_num_key_heads, cfg.linear_key_head_dim
    Hv, dv = cfg.linear_num_value_heads, cfg.linear_value_head_dim
    r = Hv // Hk
    return {
        "attn_norm": (D,),
        "mlp_norm": (D,),
        "wqkvz": (D, Hk, 2 * dk + 2 * r * dv),
        "wba": (D, Hk, 2 * r),
        "conv_w": (cfg.conv_dim, cfg.linear_conv_kernel_dim),
        "dt_bias": (Hv,),
        "a_log": (Hv,),
        "norm": (dv,),
        "wo": (Hv, dv, D),
    }


def _full_attn_shapes(cfg: Qwen3NextConfig) -> dict:
    D, H, Hkv, dh = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    return {
        "attn_norm": (D,),
        "mlp_norm": (D,),
        "wq": (D, H, 2 * dh),  # per-head [q | sigmoid output gate] (HF q_proj 2x width)
        "wk": (D, Hkv, dh),
        "wv": (D, Hkv, dh),
        "wo": (H, dh, D),
        "q_norm": (dh,),
        "k_norm": (dh,),
    }


_LINEAR_AXES = {
    "attn_norm": ("norm",),
    "mlp_norm": ("norm",),
    "wqkvz": ("embed", "kv_heads", "head_dim"),
    "wba": ("embed", "kv_heads", "head_dim"),
    "conv_w": (None, None),
    "dt_bias": ("heads",),
    "a_log": ("heads",),
    "norm": ("norm",),
    "wo": ("heads", "head_dim", "embed"),
}

_FULL_AXES = {
    "attn_norm": ("norm",),
    "mlp_norm": ("norm",),
    "wq": ("embed", "heads", "head_dim"),
    "wk": ("embed", "kv_heads", "head_dim"),
    "wv": ("embed", "kv_heads", "head_dim"),
    "wo": ("heads", "head_dim", "embed"),
    "q_norm": ("norm",),
    "k_norm": ("norm",),
}


class Qwen3NextForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = Qwen3NextConfig
    hf_architectures = ("Qwen3NextForCausalLM",)

    def __init__(self, config: Qwen3NextConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # ---- params ----

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        cfg = self.config
        std = cfg.initializer_range
        k_embed, k_lin, k_full, k_moe, k_head = jax.random.split(key, 5)

        def init_stack(shapes: dict, L: int, key) -> dict:
            keys = jax.random.split(key, len(shapes))
            out = {}
            for idx, (name, shape) in enumerate(shapes.items()):
                if name.endswith("norm") or name == "norm":
                    # HF zero-centered RMSNorm for attn/mlp/q/k norms; gated norm is
                    # standard (ones) — both store the HF tensor verbatim: zeros here
                    # means "identity" for the zero-centered ones, so init gated norm
                    # weights to ones and the rest to zeros
                    fill = jnp.ones if name == "norm" else jnp.zeros
                    out[name] = fill((L, *shape), dtype)
                elif name == "dt_bias":
                    out[name] = jnp.ones((L, *shape), dtype)
                elif name == "a_log":
                    u = jax.random.uniform(keys[idx], (L, *shape), jnp.float32, 1e-4, 16.0)
                    out[name] = jnp.log(u).astype(jnp.float32)  # kept fp32 (HF casts too)
                else:
                    out[name] = (jax.random.normal(keys[idx], (L, *shape), jnp.float32) * std).astype(dtype)
            return out

        L_lin, L_full = len(cfg.linear_layer_indices), len(cfg.full_layer_indices)
        params: dict = {
            "embed": (jax.random.normal(k_embed, (cfg.vocab_size, cfg.hidden_size), jnp.float32) * std).astype(dtype),
            "final_norm": jnp.zeros((cfg.hidden_size,), dtype),
        }
        if L_lin:
            lin = init_stack(_linear_attn_shapes(cfg), L_lin, k_lin)
            lin["moe"] = jax.vmap(lambda k: init_moe_params(cfg.moe, k, dtype, std))(
                jax.random.split(jax.random.fold_in(k_moe, 0), L_lin)
            )
            params["linear_layers"] = lin
        if L_full:
            full = init_stack(_full_attn_shapes(cfg), L_full, k_full)
            full["moe"] = jax.vmap(lambda k: init_moe_params(cfg.moe, k, dtype, std))(
                jax.random.split(jax.random.fold_in(k_moe, 1), L_full)
            )
            params["full_layers"] = full
        if not cfg.tie_word_embeddings:
            params["lm_head"] = (
                jax.random.normal(k_head, (cfg.hidden_size, cfg.vocab_size), jnp.float32) * std
            ).astype(dtype)
        return params

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def logical_axes(self) -> dict:
        cfg = self.config
        axes: dict = {
            "embed": ("vocab", "embed"),
            "final_norm": ("norm",),
        }
        if cfg.linear_layer_indices:
            lin = {k: ("layers",) + v for k, v in _LINEAR_AXES.items()}
            lin["moe"] = jax.tree.map(
                lambda t: ("layers",) + t,
                moe_logical_axes(cfg.moe),
                is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
            )
            axes["linear_layers"] = lin
        if cfg.full_layer_indices:
            full = {k: ("layers",) + v for k, v in _FULL_AXES.items()}
            full["moe"] = jax.tree.map(
                lambda t: ("layers",) + t,
                moe_logical_axes(cfg.moe),
                is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
            )
            axes["full_layers"] = full
        if not cfg.tie_word_embeddings:
            axes["lm_head"] = ("embed", "vocab")
        return axes

    # ---- forward ----

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None):
        cfg, backend = self.config, self.backend
        dtype = backend.jnp_dtype
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        emit_aux = cfg.moe.aux_loss_coeff > 0 and training and not backend.fake_balanced_gate

        inv_freq = rope_frequencies(
            cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
            partial_rotary_factor=cfg.partial_rotary_factor,
        )
        attn_scale = rope_attention_scaling(cfg.rope_scaling)

        moe_fwd = make_moe_block_forward(cfg.moe, backend, rules, training=training)

        if cache is not None:
            if segment_ids is None:
                raise ValueError("cache decoding requires segment_ids (1 = real token)")
            h = params["embed"].astype(dtype)[input_ids]
            return self._decode_forward(params, h, positions, segment_ids, cache,
                                        dtype, moe_fwd, inv_freq, attn_scale)

        @scoped("moe")
        def moe_block(lp, h):
            x = rms_norm(h, lp["mlp_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
            moe_params = cast_moe_compute_params(lp["moe"], dtype)
            y, aux, load, dropped = moe_fwd(moe_params, x, token_mask)
            h = _constrain(h + y, rules, ("batch", "act_seq", "act_embed"))
            return h, (aux if emit_aux else jnp.float32(0), load, dropped)

        @scoped("delta_net")
        def linear_block(lp, h):
            x = rms_norm(h, lp["attn_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
            if token_mask is not None:
                # conv + recurrence leak across positions: zero padded tokens
                # (HF apply_mask_to_padding_states)
                x = x * token_mask[..., None].astype(x.dtype)
            h = h + self._gated_delta_attn(lp, x, dtype, segment_ids)
            h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))
            return moe_block(lp, h)

        @scoped("gated_attention")
        def full_block(lp, h):
            x = rms_norm(h, lp["attn_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
            h = h + self._gated_full_attn(lp, x, positions, segment_ids, inv_freq, attn_scale, dtype)
            h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))
            return moe_block(lp, h)

        h = params["embed"].astype(dtype)[input_ids]
        h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))

        P = cfg.period
        lin_params = params.get("linear_layers")
        full_params = params.get("full_layers")
        if P is not None and backend.scan_layers:
            G = cfg.num_hidden_layers // P
            glin = jax.tree.map(lambda a: a.reshape(G, P - 1, *a.shape[1:]), lin_params)
            gfull = jax.tree.map(lambda a: a.reshape(G, *a.shape[1:]), full_params)

            def group_body(h, lp_group):
                gl, gf = lp_group
                ys = []
                for j in range(P - 1):
                    h, y = linear_block(jax.tree.map(lambda a: a[j], gl), h)
                    ys.append(y)
                h, y = full_block(gf, h)
                ys.append(y)
                return h, jax.tree.map(lambda *a: jnp.stack(a), *ys)

            h, (auxs, loads, droppeds) = jax.lax.scan(
                backend.layer_remat(group_body), h, (glin, gfull)
            )
            auxs = auxs.reshape(-1)
            loads = loads.reshape(-1, *loads.shape[2:])
            droppeds = droppeds.reshape(-1)
        else:
            lin_i, full_i = 0, 0
            ys = []
            for t in cfg.layer_types:
                if t == LINEAR:
                    lp = jax.tree.map(lambda a: a[lin_i], lin_params)
                    h, y = backend.layer_remat(linear_block)(lp, h)
                    lin_i += 1
                else:
                    lp = jax.tree.map(lambda a: a[full_i], full_params)
                    h, y = backend.layer_remat(full_block)(lp, h)
                    full_i += 1
                ys.append(y)
            auxs, loads, droppeds = (jnp.stack(a) for a in zip(*ys))

        stats = {"aux_loss": auxs.sum() if emit_aux else None, "expert_load": loads}
        if backend.dispatcher == "a2a":
            stats["dropped_token_frac"] = droppeds.mean()

        h = rms_norm(h, params["final_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
        if return_hidden:
            return h, stats
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, stats

    def _gated_delta_attn(self, lp, x, dtype, segment_ids=None, token_mask=None,
                          conv_state=None, rec_state=None, return_state=False):
        """Gated DeltaNet token mixer (HF Qwen3NextGatedDeltaNet.forward,
        modeling_qwen3_next.py:660-775).

        Packed sequences: the recurrence resets at document boundaries by injecting a
        large negative log-decay at each segment start — within-segment decays are
        differences of cumulative sums, so the injection cancels exactly there and
        zeroes every cross-segment path (state carry, intra-chunk attention, and the
        chunk-state write). The conv masks its cross-segment taps directly.

        Decode: ``conv_state`` ((B, K-1, C) trailing pre-conv inputs) and
        ``rec_state`` ((B, Hv, dk, dv) delta-rule state) continue the recurrence;
        ``return_state=True`` (prefill) extracts both from the prompt.
        ``token_mask`` neutralizes right-padding: pad tokens get decay 1 / write
        strength 0, so the state each row carries out of prefill is exactly its
        last VALID token's. Stateful calls return ``(out, (conv_state, rec_state))``.
        """
        cfg = self.config
        B, S, _ = x.shape
        Hk, dk = cfg.linear_num_key_heads, cfg.linear_key_head_dim
        Hv, dv = cfg.linear_num_value_heads, cfg.linear_value_head_dim
        r = Hv // Hk
        K = cfg.linear_conv_kernel_dim

        qkvz = jnp.einsum("bsd,dhm->bshm", x, lp["wqkvz"].astype(dtype))  # (B,S,Hk,2dk+2rdv)
        ba = jnp.einsum("bsd,dhm->bshm", x, lp["wba"].astype(dtype))  # (B,S,Hk,2r)
        q = qkvz[..., :dk]
        k = qkvz[..., dk : 2 * dk]
        v = qkvz[..., 2 * dk : 2 * dk + r * dv].reshape(B, S, Hv, dv)
        z = qkvz[..., 2 * dk + r * dv :].reshape(B, S, Hv, dv)
        b = ba[..., :r].reshape(B, S, Hv)
        a = ba[..., r:].reshape(B, S, Hv)

        beta = jax.nn.sigmoid(b.astype(jnp.float32))
        g = -jnp.exp(lp["a_log"].astype(jnp.float32)) * jax.nn.softplus(
            a.astype(jnp.float32) + lp["dt_bias"].astype(jnp.float32)
        )
        if token_mask is not None:
            valid = token_mask.astype(jnp.float32)[..., None]
            beta = beta * valid  # pad: no write
            g = g * valid  # pad: decay exp(0) = 1, state passes through
        if segment_ids is not None and token_mask is None:
            # -50 in log space ≈ exp(-50) ~ 2e-22: dead past, still fp32-cancellable
            seg_start = jnp.concatenate(
                [jnp.zeros((B, 1), bool), segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1
            )
            g = g + jnp.where(seg_start, -50.0, 0.0)[..., None]

        mixed = jnp.concatenate(
            [q.reshape(B, S, Hk * dk), k.reshape(B, S, Hk * dk), v.reshape(B, S, Hv * dv)], axis=-1
        )
        new_conv = None
        if conv_state is not None:
            conv_out, new_conv = conv_step(conv_state, mixed, lp["conv_w"].astype(dtype))
        else:
            conv_out = causal_conv1d(
                mixed, lp["conv_w"].astype(dtype),
                segment_ids=segment_ids if token_mask is None else None,
            )
            if return_state:
                lens = (token_mask.sum(-1) if token_mask is not None
                        else jnp.full((B,), S, jnp.int32))
                new_conv = conv_state_from_prefill(mixed, lens, K)
        q, k, v = jnp.split(conv_out, [Hk * dk, 2 * Hk * dk], axis=-1)
        q = jnp.repeat(q.reshape(B, S, Hk, dk), r, axis=2)
        k = jnp.repeat(k.reshape(B, S, Hk, dk), r, axis=2)
        v = v.reshape(B, S, Hv, dv)

        stateful = return_state or rec_state is not None
        core, final = chunk_gated_delta_rule(
            q, k, v, g, beta, chunk_size=min(64, S),
            initial_state=rec_state, output_final_state=stateful,
        )
        core = gated_rms_norm(core, lp["norm"].astype(dtype), z, cfg.rms_norm_eps)
        out = jnp.einsum("bshk,hkd->bsd", core, lp["wo"].astype(dtype))
        if stateful:
            return out, (new_conv, final)
        return out

    def _gated_full_attn(self, lp, x, positions, segment_ids, inv_freq, attn_scale, dtype,
                         kv=None, cache_meta=None):
        """Full attention with per-head sigmoid output gate (reference
        qwen3_next/layers.py:95-153). With ``kv=(k_cache, v_cache)`` (decode) the
        fresh k/v write into the cache and attention runs position-masked against
        it; returns ``(out, (k_cache, v_cache))``."""
        cfg = self.config
        dh = cfg.head_dim
        qg = jnp.einsum("bsd,dhk->bshk", x, lp["wq"].astype(dtype))
        q, gate = qg[..., :dh], qg[..., dh:]
        k = jnp.einsum("bsd,dhk->bshk", x, lp["wk"].astype(dtype))
        v = jnp.einsum("bsd,dhk->bshk", x, lp["wv"].astype(dtype))
        q = rms_norm(q, lp["q_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
        k = rms_norm(k, lp["k_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
        q = apply_rope(q, positions, inv_freq, attn_scale)
        k = apply_rope(k, positions, inv_freq, attn_scale)
        if kv is not None:
            from automodel_tpu.models.common.transformer import _cache_write

            k_cache = _cache_write(kv[0], k.astype(kv[0].dtype), cache_meta["write_idx"])
            v_cache = _cache_write(kv[1], v.astype(kv[1].dtype), cache_meta["write_idx"])
            attn = dot_product_attention(
                q, k_cache.astype(q.dtype), v_cache.astype(q.dtype),
                causal=True,
                segment_ids_q=segment_ids,
                segment_ids_kv=cache_meta["valid"],
                positions_q=positions,
                positions_kv=cache_meta["positions"],
                backend="xla",
            )
            attn = attn * jax.nn.sigmoid(gate)
            return jnp.einsum("bshk,hkd->bsd", attn, lp["wo"].astype(dtype)), (k_cache, v_cache)
        attn = dot_product_attention(
            q, k, v,
            causal=True,
            segment_ids_q=segment_ids,
            segment_ids_kv=segment_ids,
            backend=self.backend.attention,
        )
        attn = attn * jax.nn.sigmoid(gate)
        return jnp.einsum("bshk,hkd->bsd", attn, lp["wo"].astype(dtype))

    # ---- decode ----

    def init_decode_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        """Hybrid decode cache: KV for the full-attention layers, conv taps +
        delta-rule state (fp32 — the recurrence compounds rounding) for the
        DeltaNet layers. positions/valid/write_idx follow the generation loop's
        shared-contract (generation.init_kv_cache)."""
        cfg = self.config
        Lf = len(cfg.full_layer_indices)
        Ll = len(cfg.linear_layer_indices)
        Hv, dk, dv = cfg.linear_num_value_heads, cfg.linear_key_head_dim, cfg.linear_value_head_dim
        return {
            "k": jnp.zeros((Lf, batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim), dtype),
            "v": jnp.zeros((Lf, batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim), dtype),
            "conv": jnp.zeros((Ll, batch_size, cfg.linear_conv_kernel_dim - 1, cfg.conv_dim), dtype),
            "rec": jnp.zeros((Ll, batch_size, Hv, dk, dv), jnp.float32),
            "positions": jnp.zeros((batch_size, max_len), jnp.int32),
            "valid": jnp.zeros((batch_size, max_len), jnp.int32),
            "write_idx": jnp.zeros((batch_size,), jnp.int32),
        }

    def _decode_forward(self, params, h, positions, segment_ids, cache, dtype,
                        moe_fwd, inv_freq, attn_scale):
        """Unrolled cached forward (prefill S>1, decode S=1). Layer scanning is
        skipped: decode shapes are tiny and the per-kind cache threading (kv vs
        conv+rec) is simplest unrolled."""
        cfg = self.config
        S = h.shape[1]
        token_mask = segment_ids != 0
        cache_meta = {"write_idx": cache["write_idx"], "valid": cache["valid"],
                      "positions": cache["positions"]}
        lin_params = params.get("linear_layers")
        full_params = params.get("full_layers")
        k_all, v_all = cache["k"], cache["v"]
        conv_all, rec_all = cache["conv"], cache["rec"]
        lin_i = full_i = 0
        for t in cfg.layer_types:
            if t == LINEAR:
                lp = jax.tree.map(lambda a, i=lin_i: a[i], lin_params)
                x = rms_norm(h, lp["attn_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
                x = x * token_mask[..., None].astype(x.dtype)
                out, (nc, nr) = self._gated_delta_attn(
                    lp, x, dtype, token_mask=token_mask,
                    conv_state=(conv_all[lin_i] if S == 1 else None),
                    rec_state=rec_all[lin_i], return_state=True,
                )
                conv_all = conv_all.at[lin_i].set(nc.astype(conv_all.dtype))
                rec_all = rec_all.at[lin_i].set(nr)
                h = h + out
                lin_i += 1
            else:
                lp = jax.tree.map(lambda a, i=full_i: a[i], full_params)
                x = rms_norm(h, lp["attn_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
                out, (kc, vc) = self._gated_full_attn(
                    lp, x, positions, segment_ids, inv_freq, attn_scale, dtype,
                    kv=(k_all[full_i], v_all[full_i]), cache_meta=cache_meta,
                )
                k_all = k_all.at[full_i].set(kc)
                v_all = v_all.at[full_i].set(vc)
                h = h + out
                full_i += 1
            x = rms_norm(h, lp["mlp_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
            moe_params = cast_moe_compute_params(lp["moe"], dtype)
            y, _, _, _ = moe_fwd(moe_params, x, token_mask)
            h = h + y
        h = rms_norm(h, params["final_norm"].astype(dtype), cfg.rms_norm_eps, offset=1.0)
        # next-token logits only (B, 1, V)
        last = jnp.maximum(segment_ids.sum(-1) - 1, 0).astype(jnp.int32)
        h = jnp.take_along_axis(h, last[:, None, None], axis=1)
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, dict(cache, k=k_all, v=v_all, conv=conv_all, rec=rec_all)

    def generate(self, params, input_ids, **kw):
        """Sample with the hybrid conv+recurrence+KV cache (automodel_tpu.generation)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.qwen3_next.state_dict_adapter import Qwen3NextStateDictAdapter

        return Qwen3NextStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = Qwen3NextConfig.from_hf(config)
        return cls(config, backend)
