"""Shared training.jsonl readers for the functional suite.

The metric stream is self-describing since the perf-observability work: it
carries a one-time ``{"run_header": true, ...}`` row and event rows
(``compile_costs``, resilience events) alongside the per-step metric rows.
Tests that index ``["loss"]`` or count steps must read through
:func:`metric_rows` rather than assuming every line is a step.
"""

import json


def read_rows(path):
    """Every row, verbatim — headers and events included."""
    return [json.loads(line) for line in open(path)]


def metric_rows(path):
    """Only per-step metric rows (the ones carrying a loss)."""
    return [r for r in read_rows(path) if "loss" in r]


def losses(path):
    return [r["loss"] for r in metric_rows(path)]
