"""Signal-guided search policy: what to try first, what never to compile.

The signals bundle (observability/signals.py) already diagnoses each cell:
the roofline/measured ``bound`` names the binding resource, and the memory
plan (observability/memory_plan.py) says whether a config fits its chip
before anything compiles. This module turns those two signals into policy:

- **Pruning** — a trial whose memory plan says ``fits is False`` is recorded
  and discarded *before any compile*. ``fits is None`` (no known HBM limit —
  CPU hosts without an override) never prunes: honesty over guessing.
- **Ordering** — exploration starts with the knob class the bound implicates.
  Compute-bound cells move remat down the ladder (spend memory to stop
  replaying the forward) and layouts; memory-bound cells move remat up and
  the microbatch split; input-bound cells move the prefetch depths;
  comms/moe_a2a-bound cells move the dispatcher and sharding layout.
- **Attribution** — the winner is never a mystery: ``attribute_winner``
  produces a machine-readable line citing the signal keys and deltas that
  decided it, which the ledger (runner.py) persists next to the winner.
"""

from __future__ import annotations

from typing import Any

from automodel_tpu.tuning.space import REMAT_LADDER, Trial

__all__ = ["KNOB_PRIORITY", "prune", "order_trials", "attribute_winner"]

# bound -> knob classes in exploration order (space.Trial field-name groups).
# The first entries are the knobs the bound diagnosis implicates; the rest
# follow so an exhaustive space still enumerates completely.
_REMAT = ("remat_policy",)
_MICRO = ("micro_batch_size", "grad_acc_steps")
_PREFETCH = ("prefetch_host_depth", "prefetch_device_depth")
# the MoE hot-path levers move together: which dispatcher, how many overlap
# chunks its a2a is sliced into, and which grouped-GEMM backend feeds it —
# comms/moe_a2a-bound cells explore all three first
_DISPATCH = ("dispatcher", "a2a_chunks", "experts_backend")
_LAYOUT = ("layout",)
KNOB_PRIORITY: dict[str, tuple[tuple[str, ...], ...]] = {
    "compute": (_REMAT, _LAYOUT, _MICRO, _PREFETCH, _DISPATCH),
    "memory": (_REMAT, _MICRO, _LAYOUT, _PREFETCH, _DISPATCH),
    "input": (_PREFETCH, _MICRO, _REMAT, _LAYOUT, _DISPATCH),
    "comms": (_DISPATCH, _LAYOUT, _MICRO, _REMAT, _PREFETCH),
    "moe_a2a": (_DISPATCH, _LAYOUT, _MICRO, _REMAT, _PREFETCH),
}

# remat exploration direction per bound: compute-bound walks DOWN the ladder
# (toward "full": save more, recompute less), memory-bound walks UP (toward
# "none": save less). +1 = prefer higher ladder index first.
_REMAT_DIRECTION = {"compute": +1, "memory": -1}


def prune(trial: Trial, plan: Any) -> str | None:
    """Reason to discard ``trial`` before compiling, or None to keep it.

    ``plan`` is the trial's analytic MemoryPlan (or None when the caller could
    not build one). Only an explicit ``fits is False`` verdict prunes — the
    plan's job is to stop configs that CANNOT fit from ever compiling, not to
    guess about unknown chips.
    """
    if plan is None:
        return None
    if plan.fits is False:
        headroom = plan.headroom_bytes
        total = plan.total_bytes
        return (f"memory_plan: does not fit — total {total / 2**30:.4f} GiB, "
                f"headroom {headroom / 2**30:.4f} GiB (mem_plan/fits=false)")
    return None


def _knob_rank(moved: list[str], priority: tuple[tuple[str, ...], ...]) -> int:
    """Earliest priority class a trial's moved knobs fall into; trials that
    move nothing (the baseline itself) sort first."""
    if not moved:
        return -1
    ranks = []
    for knob in moved:
        for i, group in enumerate(priority):
            if knob in group:
                ranks.append(i)
                break
        else:
            ranks.append(len(priority))
    return min(ranks)


def _remat_key(trial: Trial, direction: int) -> float:
    try:
        idx = REMAT_LADDER.index(trial.remat_policy)
    except ValueError:
        idx = 0  # repo-specific ladder names sort as the most-remat end
    return -direction * idx


def order_trials(trials: list[Trial], bound: str | None,
                 baseline: Trial | None = None) -> list[Trial]:
    """Deterministic, signal-guided exploration order.

    Primary key: which knob class the trial explores relative to ``baseline``,
    ranked by the bound's KNOB_PRIORITY (unknown/None bound keeps "compute"'s
    order — the least surprising default). Secondary: fewer knobs moved at
    once first (attribution stays readable when early trials are one-knob
    moves). Then the bound's remat direction, then the digest for stability.
    """
    base = baseline or (trials[0] if trials else Trial())
    priority = KNOB_PRIORITY.get(bound or "", KNOB_PRIORITY["compute"])
    direction = _REMAT_DIRECTION.get(bound or "", +1)

    def key(t: Trial):
        moved = t.moved_knobs(base)
        return (_knob_rank(moved, priority), len(moved),
                _remat_key(t, direction), t.digest())

    return sorted(trials, key=key)


def attribute_winner(winner: dict[str, Any],
                     runner_up: dict[str, Any] | None,
                     bound: str | None = None) -> dict[str, Any]:
    """The signal-citing attribution the ledger stores next to the winner.

    ``winner`` / ``runner_up`` are ledger entries (runner.py shape): a dict
    with ``digest``, ``trial`` (override mapping) and ``outcome.metrics``
    holding the ``tuner/*`` rows the trial emitted. Returns ``{"line",
    "signal_keys", "deltas"}`` where every entry of ``signal_keys`` is a real
    key present in the winner's metrics (tests enforce this), and ``deltas``
    maps each cited key to (runner_up value -> winner value).
    """
    metrics = (winner.get("outcome") or {}).get("metrics") or {}
    cited = [k for k in ("tuner/tps", "tuner/hbm_gib_peak") if metrics.get(k) is not None]
    deltas: dict[str, Any] = {}
    clauses: list[str] = []
    other = (runner_up or {}).get("outcome", {}).get("metrics") or {}
    for key in cited:
        ours, theirs = metrics.get(key), other.get(key)
        deltas[key] = {"winner": ours, "runner_up": theirs}
        if theirs:
            rel = (ours - theirs) / abs(theirs) * 100.0
            clauses.append(f"{key} {theirs:.6g} -> {ours:.6g} ({rel:+.1f}%)")
        else:
            clauses.append(f"{key} {ours:.6g} (no runner-up)")
    if bound:
        clauses.append(f"cell bound={bound}")
    moved = sorted(set(winner.get("trial") or {})
                   - {k for k, v in (runner_up or {}).get("trial", {}).items()
                      if (winner.get("trial") or {}).get(k) == v})
    if moved:
        clauses.append("moved " + ", ".join(moved))
    line = f"winner {winner.get('digest')}: " + "; ".join(clauses)
    return {"line": line, "signal_keys": cited, "deltas": deltas}
