"""DeepSeek-V3.2 sparse indexer: Hadamard transform, top-k mask semantics, DSv3
equivalence at full top-k, adapter round-trip. (No HF reference implementation exists
in this transformers version, so checks are self-consistency + structural.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.deepseek_v3.model import DeepseekV3ForCausalLM
from automodel_tpu.models.deepseek_v32.model import (
    DeepseekV32Config,
    DeepseekV32ForCausalLM,
    hadamard_transform,
)
from automodel_tpu.moe.config import MoEConfig


def _cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=3, num_attention_heads=4,
        q_lora_rank=24, kv_lora_rank=32, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16, first_k_dense_replace=1, max_position_embeddings=128,
        index_n_heads=4, index_head_dim=16, index_topk=8,
        moe=MoEConfig(
            n_routed_experts=8, n_activated_experts=2, dim=64, moe_inter_dim=32,
            n_shared_experts=1, n_expert_groups=2, n_limited_groups=1,
            gate_bias_update_factor=0.001, score_func="sigmoid", route_scale=2.5,
            norm_topk_prob=True,
        ),
    )
    base.update(kw)
    return DeepseekV32Config(**base)


def _fp32_backend(**kw):
    return BackendConfig(dtype="float32", remat_policy="full", **kw)


class TestHadamard:
    def test_matches_explicit_matrix(self):
        n = 16
        H = np.array([[1.0]])
        while H.shape[0] < n:
            H = np.block([[H, H], [H, -H]])
        rng = np.random.RandomState(0)
        x = rng.randn(3, 5, n).astype(np.float32)
        ours = np.asarray(hadamard_transform(jnp.array(x), n**-0.5))
        ref = x @ H.T * n**-0.5
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_orthonormal(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 32).astype(np.float32)
        y = hadamard_transform(jnp.array(x), 32**-0.5)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1), np.linalg.norm(x, axis=-1), rtol=1e-5
        )


class TestDeepseekV32:
    def test_full_topk_equals_dsv3(self):
        """index_topk >= seq => the sparse bias is all-zero and V3.2 must reproduce
        the plain DSv3 forward on the shared MLA/MoE weights."""
        cfg = _cfg(index_topk=64)
        model = DeepseekV32ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(0), jnp.float32)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
        out32, _ = model(params, ids, training=False)

        v3 = DeepseekV3ForCausalLM(cfg, _fp32_backend())
        strip = lambda d: {k: v for k, v in d.items() if not k.startswith(("idx_", "b_idx_"))}
        params_v3 = dict(params)
        for leaf in ("dense_layers", "moe_layers"):
            params_v3[leaf] = strip(params[leaf])
        out3, _ = v3(params_v3, ids, training=False)
        np.testing.assert_allclose(np.asarray(out32), np.asarray(out3), atol=1e-5)

    def test_sparse_topk_changes_output_but_stays_causal(self):
        cfg = _cfg(index_topk=4)
        model = DeepseekV32ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(1), jnp.float32)
        rng = np.random.RandomState(1)
        ids = jnp.asarray(rng.randint(0, 128, (1, 16)))
        out, _ = model(params, ids, training=False)
        assert np.all(np.isfinite(np.asarray(out)))
        # causality: perturbing future tokens leaves earlier logits unchanged
        ids2 = ids.at[0, 12:].set((ids[0, 12:] + 1) % 128)
        out2, _ = model(params, ids2, training=False)
        np.testing.assert_allclose(
            np.asarray(out[0, :12]), np.asarray(out2[0, :12]), atol=1e-5
        )

    def test_sparse_differs_from_dense(self):
        cfg = _cfg(index_topk=2)
        model = DeepseekV32ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(2), jnp.float32)
        ids = jnp.asarray(np.random.RandomState(2).randint(0, 128, (1, 16)))
        out_sparse, _ = model(params, ids, training=False)
        model_full = DeepseekV32ForCausalLM(_cfg(index_topk=64), _fp32_backend())
        out_full, _ = model_full(params, ids, training=False)
        assert np.abs(np.asarray(out_sparse) - np.asarray(out_full)).max() > 1e-4

    def test_adapter_roundtrip(self):
        cfg = _cfg()
        model = DeepseekV32ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(3), jnp.float32)
        adapter = model.state_dict_adapter()
        hf = adapter.to_hf(params)
        assert "model.layers.0.self_attn.indexer.wq_b.weight" in hf
        assert "model.layers.2.self_attn.indexer.k_norm.bias" in hf
        back = adapter.from_hf(hf)
        for path in (
            ("moe_layers", "idx_wq_b"),
            ("moe_layers", "idx_k_norm"),
            ("dense_layers", "idx_weights"),
        ):
            a, b = params, back
            for p in path:
                a, b = a[p], b[p]
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6, err_msg=str(path))

    def test_grads_finite(self):
        cfg = _cfg(index_topk=4)
        model = DeepseekV32ForCausalLM(cfg, _fp32_backend())
        params = model.init(jax.random.key(4), jnp.float32)
        ids = jnp.asarray(np.random.RandomState(4).randint(0, 128, (2, 12)))

        def loss_fn(p):
            logits, _ = model(p, ids[:, :-1], training=True)
            ll = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.take_along_axis(ll, ids[:, 1:, None], -1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(np.asarray(g))) for g in jax.tree.leaves(grads))

    def test_from_hf_config(self):
        hf = dict(
            architectures=["DeepseekV32ForCausalLM"], vocab_size=128, hidden_size=64,
            intermediate_size=96, moe_intermediate_size=32, num_hidden_layers=3,
            num_attention_heads=4, q_lora_rank=24, kv_lora_rank=32,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
            n_group=2, topk_group=1, routed_scaling_factor=2.5, norm_topk_prob=True,
            first_k_dense_replace=1, index_n_heads=4, index_head_dim=16, index_topk=8,
        )
        cfg = DeepseekV32Config.from_hf(hf)
        assert cfg.index_topk == 8 and cfg.moe.score_func == "sigmoid"
        model = DeepseekV32ForCausalLM.from_config(hf)
        assert isinstance(model.config, DeepseekV32Config)
