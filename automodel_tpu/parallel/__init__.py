from automodel_tpu.parallel.mesh import (
    MeshAxis,
    MeshContext,
    ShardingRules,
    create_device_mesh,
    default_sharding_rules,
)

__all__ = [
    "MeshAxis",
    "MeshContext",
    "ShardingRules",
    "create_device_mesh",
    "default_sharding_rules",
]
