"""Shared SFT formatting/tokenization (reference datasets/llm/formatting_utils.py).

Two entry shapes, both returning our collate contract
``{"input_ids", "labels" | "prompt_len"}``:

- :func:`format_prompt_completion` — plain prompt+answer with prompt-span masking;
- :func:`format_chat_messages` — OpenAI-style ``messages`` through the tokenizer's
  chat template, with loss restricted to assistant spans via incremental prefix
  tokenization (the reference computes the same spans by re-tokenizing truncated
  message lists, formatting_utils.py).
"""

from __future__ import annotations

import logging
from typing import Any, Mapping, Sequence

logger = logging.getLogger(__name__)

IGNORE_INDEX = -100

__all__ = ["format_prompt_completion", "format_chat_messages", "IGNORE_INDEX"]


def format_prompt_completion(
    tokenizer,
    prompt: str,
    answer: str,
    add_eos: bool = True,
    answer_only_loss: bool = True,
) -> dict[str, Any]:
    """Tokenize prompt+answer; ``prompt_len`` marks the masked span for collate."""
    prompt_ids = tokenizer.encode(prompt)
    full_ids = tokenizer.encode(prompt + answer)
    eos = getattr(tokenizer, "eos_token_id", None)
    if add_eos and eos is not None and (not full_ids or full_ids[-1] != eos):
        full_ids = full_ids + [eos]
    if full_ids[: len(prompt_ids)] != prompt_ids:
        # tokenizer merged across the boundary; recompute the prompt span by the
        # longest common prefix so masking never leaks answer tokens into the loss
        n = 0
        for a, b in zip(prompt_ids, full_ids):
            if a != b:
                break
            n += 1
        prompt_len = n
    else:
        prompt_len = len(prompt_ids)
    ex: dict[str, Any] = {"input_ids": full_ids}
    if answer_only_loss:
        ex["prompt_len"] = prompt_len
    return ex


def _apply_chat_template(tokenizer, messages: Sequence[Mapping[str, Any]], **kw) -> list[int]:
    return list(tokenizer.apply_chat_template(messages, tokenize=True, **kw))


def format_chat_messages(
    tokenizer,
    messages: Sequence[Mapping[str, Any]],
    answer_only_loss: bool = True,
) -> dict[str, Any]:
    """messages -> {"input_ids", "labels"} with loss on assistant spans only.

    Works for any number of turns: for each assistant message i, the tokens between
    template(messages[:i]+generation prompt) and template(messages[:i+1]) carry loss.
    """
    if not hasattr(tokenizer, "apply_chat_template") or tokenizer.chat_template is None:
        # no template: fall back to role-prefixed text with loss on assistant turns
        text_parts, spans, pos = [], [], 0
        for m in messages:
            part = f"{m['role']}: {m['content']}\n"
            ids = tokenizer.encode(part) if pos == 0 else tokenizer.encode(part, add_special_tokens=False)
            if m["role"] == "assistant":
                spans.append((pos, pos + len(ids)))
            text_parts.extend(ids)
            pos += len(ids)
        labels = [IGNORE_INDEX] * len(text_parts)
        for lo, hi in spans:
            labels[lo:hi] = text_parts[lo:hi]
        return {"input_ids": text_parts, "labels": labels}

    full_ids = _apply_chat_template(tokenizer, messages)
    labels = [IGNORE_INDEX] * len(full_ids)
    if not answer_only_loss:
        return {"input_ids": full_ids, "labels": list(full_ids)}
    for i, m in enumerate(messages):
        if m.get("role") != "assistant":
            continue
        # prefix WITH generation prompt marks where the assistant span starts;
        # prefix including message i marks where it ends
        try:
            start_ids = _apply_chat_template(
                tokenizer, list(messages[:i]), add_generation_prompt=True
            )
        except Exception:
            start_ids = _apply_chat_template(tokenizer, list(messages[:i]))
        end_ids = _apply_chat_template(tokenizer, list(messages[: i + 1]))
        lo, hi = len(start_ids), len(end_ids)
        # templates may append a trailing newline/eos after the turn; clamp to range
        lo, hi = min(lo, len(full_ids)), min(hi, len(full_ids))
        labels[lo:hi] = full_ids[lo:hi]
    return {"input_ids": full_ids, "labels": labels}
