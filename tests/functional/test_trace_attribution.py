"""Measured trace attribution, end to end on the CPU backend: a real recipe
run arms the on-demand profiler, the captured jax.profiler trace window is
machine-read by trace_analysis.py, and the run directory must hold a
self-consistent ``trace_report.json``, a ``trace_summary`` metric row in the
training stream, and a schema-valid ``signals.json``."""

import json
import math
import textwrap

import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.observability.signals import validate_signals
from automodel_tpu.recipes.llm.train_ft import (
    TrainFinetuneRecipeForNextTokenPrediction,
)

from .jsonl import metric_rows, read_rows

_MEASURED_KEYS = (
    "measured_step_time_s", "measured_t_compute_s", "measured_t_comm_s",
    "measured_t_moe_a2a_s", "measured_t_host_s", "measured_t_overlap_s",
    "measured_frac_compute", "measured_frac_comm", "measured_frac_moe_a2a",
    "measured_frac_host", "overlap_frac",
)


def _write_cfg(tmp_path):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 128
      seed: 0
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 6
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-3
    checkpoint:
      enabled: false
    observability:
      profiling:
        trace_steps: 2
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory, cpu_devices):
    """One run with a programmatically armed 2-step trace window; the manager
    analyzes the completed window in-line (no test-side parsing plumbing)."""
    tmp = tmp_path_factory.mktemp("traced_run")
    cfg = load_config(_write_cfg(tmp))
    recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
    recipe.observability.profiler.request_trace()  # SIGUSR1 equivalent
    recipe.run_train_validation_loop()
    out = tmp / "out"
    return {
        "out": out,
        "rows": read_rows(out / "training.jsonl"),
        "report": json.load(open(out / "trace_report.json")),
        "signals": json.load(open(out / "signals.json")),
    }


class TestTraceReport:
    def test_report_written_with_finite_categories(self, traced_run):
        doc = traced_run["report"]
        for key in ("compute_s", "comm_s", "moe_a2a_s", "host_s", "overlap_s",
                    "step_time_s", "window_s", "overlap_frac"):
            assert math.isfinite(doc[key]) and doc[key] >= 0.0, key
        assert doc["num_events"] > 0
        assert doc["step_time_s"] > 0

    def test_categories_sum_to_step_time(self, traced_run):
        """The category identity: compute + comm - overlap + host must equal
        the measured wall step time of the window (well within 20%)."""
        doc = traced_run["report"]
        total = (doc["compute_s"] + doc["comm_s"] - doc["overlap_s"]
                 + doc["host_s"])
        assert total == pytest.approx(doc["step_time_s"], rel=0.2)
        # and in fact exactly: the accounting is an interval-union identity
        assert total == pytest.approx(doc["step_time_s"], rel=1e-6)

    def test_overlap_frac_in_unit_interval(self, traced_run):
        assert 0.0 <= traced_run["report"]["overlap_frac"] <= 1.0

    def test_window_covers_traced_steps(self, traced_run):
        # trace_steps=2, and the profiler hands the exact window coverage to
        # the analyzer as steps_hint — no multiplicity estimation involved
        doc = traced_run["report"]
        assert doc["steps"] == 2
        assert doc["steps_hint"] == 2
        assert doc["window_s"] == pytest.approx(
            doc["step_time_s"] * doc["steps"], rel=1e-9)

    def test_reconciliation_verdict_present(self, traced_run):
        """The analytic roofline exists on CPU runs (compile_costs row), so
        the report must carry the measured-vs-analytic verdict."""
        rec = traced_run["report"]["reconciliation"]
        assert rec["verdict"] == "agree" or \
            rec["verdict"].startswith("disagree")
        assert isinstance(rec["bound_agrees"], bool)
        assert traced_run["report"]["measured_bound"] in (
            "compute", "comms", "moe_a2a", "input")


class TestTraceSummaryRow:
    def test_exactly_one_summary_row_with_measured_keys(self, traced_run):
        rows = [r for r in traced_run["rows"]
                if r.get("event") == "trace_summary"]
        assert len(rows) == 1
        (row,) = rows
        for key in _MEASURED_KEYS:
            assert key in row, key
            assert math.isfinite(row[key]), key
        assert 0.0 <= row["overlap_frac"] <= 1.0
        assert row["trace/steps"] >= 1

    def test_summary_row_does_not_disturb_step_metrics(self, traced_run):
        # per-step rows still parse and carry losses — the event row rides
        # the same stream without breaking metric readers
        assert len(metric_rows(traced_run["out"] / "training.jsonl")) >= 6


class TestSignalsArtifact:
    def test_signals_validates_against_schema(self, traced_run):
        assert validate_signals(traced_run["signals"]) == []

    def test_measured_and_reconciliation_sections_populated(self, traced_run):
        (cell,) = traced_run["signals"]["cells"]
        assert cell["measured"] is not None
        assert cell["measured"]["measured_step_time_s"] > 0
        assert cell["reconciliation"] is not None
        assert isinstance(cell["reconciliation"]["agrees"], bool)
        assert cell["analytic"] is not None
        assert cell["compile_cache"] is not None

    def test_cell_identity_matches_run(self, traced_run):
        (cell,) = traced_run["signals"]["cells"]
        assert cell["cell"]["seq_len"] == 32
        mesh = cell["cell"]["mesh"]
        assert mesh["dp_shard"] == 4 and mesh["tp"] == 2
