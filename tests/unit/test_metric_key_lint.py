"""Metric-key lint (tools/check_metric_keys.py): emitted keys <-> docs.

Tier-1: the lint itself must pass on the repo (both directions), and the
extraction/matching machinery must behave — wildcard compatibility, docstring
exclusion, prefix fan-out — so a green lint means something.
"""

from __future__ import annotations

import importlib.util
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def _load_lint():
    path = REPO / "tools" / "check_metric_keys.py"
    spec = importlib.util.spec_from_file_location("check_metric_keys", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPatternMatching:
    def test_literal_equality(self):
        lint = _load_lint()
        assert lint.patterns_match("moe/aux_loss", "moe/aux_loss")
        assert not lint.patterns_match("moe/aux_loss", "moe/aux_loss_ema")

    def test_wildcard_segment(self):
        lint = _load_lint()
        assert lint.patterns_match("dynamics/*/grad_norm", "dynamics/layers.mlp/grad_norm")
        assert lint.patterns_match("dynamics/layers.mlp/grad_norm", "dynamics/*/grad_norm")
        assert not lint.patterns_match("dynamics/*/grad_norm", "dynamics/layers.mlp/param_norm")

    def test_partial_wildcard_within_segment(self):
        lint = _load_lint()
        # f-string `top{rank}_expert{e}_util` vs docs `top{rank}_expert{e}_util`
        assert lint.patterns_match(
            "moe_load/top*_expert*_util", "moe_load/top*_expert*_util")
        assert lint.patterns_match("mem/*_gib", "mem/args_gib")
        assert not lint.patterns_match("mem/*_gib", "mem_plan/fits")

    def test_trailing_glob_absorbs_segments(self):
        lint = _load_lint()
        assert lint.patterns_match("dynamics/*", "dynamics/layers.mlp/grad_norm")
        assert lint.patterns_match("mem_plan/*", "mem_plan/fits")
        # but a mid-pattern wildcard is one segment only
        assert not lint.patterns_match("dynamics/*/grad_norm", "dynamics/grad_norm")

    def test_bare_family_shorthand_is_not_documentation(self):
        lint = _load_lint()
        assert lint._is_bare_shorthand("moe_load/*")
        assert not lint._is_bare_shorthand("moe_load/max_util_mean")
        undoc, _ = lint.check(
            {"moe_load/invented_key": ["x.py:1"]}, {"moe_load/*": ["moe_load/*"]})
        assert "moe_load/invented_key" in undoc


class TestCodeExtraction:
    def test_known_keys_extracted(self):
        lint = _load_lint()
        code = lint.code_patterns()
        # a literal, an f-string with module-const substitution, and the
        # prefix= fan-out from moe/metrics.py must all be present
        assert "mem_plan/params_gib" in code
        assert "dynamics/num/grad_amax" in code
        assert "moe_load/max_util_mean" in code and "moe/max_util_mean" in code
        # emit sites are file:line strings inside the repo
        site = code["mem_plan/params_gib"][0]
        assert site.startswith("automodel_tpu/") and ":" in site

    def test_docstring_keys_excluded(self):
        lint = _load_lint()
        code = lint.code_patterns()
        # dynamics.py's module docstring mentions the family; the collected
        # patterns must all come from executable strings (no pattern should
        # be a prose fragment with spaces)
        assert all(" " not in pat for pat in code)

    def test_doc_side_extraction(self):
        lint = _load_lint()
        docs = lint.doc_patterns()
        assert "goodput/rollback" in docs
        # docs placeholders normalize to the same wildcard spelling
        assert "dynamics/*/grad_norm" in docs or "dynamics/*/*" in docs


class TestRepoIsClean:
    def test_lint_passes_on_repo(self):
        lint = _load_lint()
        undocumented, unemitted = lint.check(lint.code_patterns(), lint.doc_patterns())
        assert not undocumented, (
            "metric keys emitted but missing from docs/observability.md: "
            f"{sorted(undocumented)}")
        assert not unemitted, (
            "metric keys documented but emitted nowhere: "
            f"{sorted(unemitted)}")

    def test_cli_exit_zero(self):
        lint = _load_lint()
        assert lint.main([]) == 0

    def test_invented_key_would_fail(self):
        """The lint is not vacuous: an undocumented key trips it."""
        lint = _load_lint()
        code = lint.code_patterns()
        code["dynamics/zzz_invented/bogus_metric"] = ["fake.py:1"]
        undocumented, _ = lint.check(code, lint.doc_patterns())
        assert "dynamics/zzz_invented/bogus_metric" in undocumented
