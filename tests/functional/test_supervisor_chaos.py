"""Pytest entry for the supervisor chaos scenarios (tools/supervisor_smoke.py,
docs/resilience.md "Supervised runs").

Marked ``chaos`` + ``slow`` so the real-training phases stay out of the tier-1
``-m 'not slow'`` suite; run explicitly with ``pytest -m chaos``. Each phase
launches tools/supervise.py around the real train recipe with chaos injection:

- ``supervise``: SIGKILL at step 6 + silent hang at step 10 -> two restarts,
  resume from the newest verifiable checkpoint, continuous step coverage,
  taxonomies crash/unknown then watchdog, timeline spans per episode.
- ``torn``: SIGKILL inside an async save -> the torn step is walked back past
  on restart (``.saving`` marker + no manifest), re-saved, and CRC-verifies.

The process-level supervisor mechanics (poll/kill/reap, budget, heartbeat)
have fast coverage in tests/unit/test_supervisor.py.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tools"))


@pytest.mark.chaos
@pytest.mark.slow
def test_supervisor_recovers_kill_and_hang(tmp_path, cpu_devices):
    import supervisor_smoke

    assert supervisor_smoke.main(str(tmp_path), phase="supervise") == 0


@pytest.mark.chaos
@pytest.mark.slow
def test_torn_save_walked_back_and_recommitted(tmp_path, cpu_devices):
    import supervisor_smoke

    assert supervisor_smoke.main(str(tmp_path), phase="torn") == 0
