"""The autotuner's auditable-loop contract (docs/observability.md "Autotuning
& the perf lab"): pruning never discards a config that fits, the trial ledger
resumes byte-identically with completed trials skipped, the winner's
attribution always cites real signal keys, and the tuned yaml round-trips
through the recipe config loader. The golden fixture pins the exact report
bytes a deterministic search produces — no timestamps, no dict-order drift."""

import dataclasses
import json
import os

import pytest

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.tuning import (
    REMAT_LADDER,
    SearchSpace,
    Trial,
    TrialLedger,
    apply_tuned_config,
    attribute_winner,
    order_trials,
    prune,
    run_search,
    write_tuned_config,
)
from automodel_tpu.tuning.runner import TUNER_REPORT_VERSION, validate_report

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "tuner_report_golden.json")


@dataclasses.dataclass
class FakePlan:
    """MemoryPlan shape the policy + signals snapshot consume."""

    fits: bool | None
    total_bytes: int = 100 * 2**20
    headroom_bytes: int | None = 20 * 2**20
    hbm_limit_bytes: int | None = 120 * 2**20


def _golden_space() -> SearchSpace:
    return SearchSpace(
        remat_policies=("none", "dots"),
        microbatch_splits=((2, 1), (64, 1)),
        prefetch_depths=((2, 2),),
        layouts=("scan",),
    )


def _golden_plan(trial: Trial) -> FakePlan:
    if (trial.micro_batch_size or 0) >= 64:
        return FakePlan(fits=False, total_bytes=400 * 2**20,
                        headroom_bytes=-280 * 2**20)
    return FakePlan(fits=True)


def _golden_measure(trial: Trial) -> dict:
    # deterministic in the trial alone: same space -> same report bytes
    tps = 100.0 + 10.0 * REMAT_LADDER.index(trial.remat_policy)
    tps += float(trial.prefetch_host_depth or 0)
    return {"tps": tps, "hbm_gib_peak": 0.05,
            "signals": {"cell": {"model": "dense", "seq_len": 2048}}}


def _run_golden(report_path: str, trials=None, measure=_golden_measure) -> dict:
    ledger = TrialLedger(report_path,
                         cell={"model": "dense", "seq_len": 2048},
                         bound="memory")
    return run_search(trials if trials is not None else _golden_space().enumerate(),
                      measure=measure, ledger=ledger, plan_fn=_golden_plan,
                      bound="memory")


class TestSpace:
    def test_enumeration_deterministic_with_unique_digests(self):
        a = SearchSpace.smoke().enumerate()
        b = SearchSpace.smoke().enumerate()
        assert a == b
        digests = [t.digest() for t in a]
        assert len(set(digests)) == len(digests) == 12

    def test_untouched_knobs_stay_out_of_overrides_and_digest(self):
        bare = Trial(remat_policy="dots")
        assert bare.overrides() == {"backend.remat_policy": "dots"}
        with_depth = Trial(remat_policy="dots", prefetch_host_depth=2)
        assert bare.digest() != with_depth.digest()
        assert "dataloader.prefetch.enabled" in with_depth.overrides()

    def test_dispatcher_axis_gated_on_ep(self):
        space = SearchSpace(remat_policies=("none",), dispatchers=("dense", "a2a"))
        assert all(t.dispatcher is None for t in space.enumerate())
        space.ep = 2
        assert {t.dispatcher for t in space.enumerate()} == {"dense", "a2a"}


class TestPolicy:
    @pytest.mark.parametrize("fits", [True, None])
    def test_pruning_never_discards_a_fitting_config(self, fits):
        # the property the perf lab stakes its honesty on: only an explicit
        # does-not-fit verdict prunes; unknown limits (fits=None) never do
        for trial in SearchSpace.smoke().enumerate():
            assert prune(trial, FakePlan(fits=fits)) is None
            assert prune(trial, None) is None

    def test_pruning_reason_cites_the_plan_verdict(self):
        reason = prune(Trial(), FakePlan(fits=False, headroom_bytes=-2**20))
        assert "mem_plan/fits=false" in reason
        assert "headroom" in reason

    def test_input_bound_explores_prefetch_first(self):
        base = Trial(remat_policy="none")
        trials = [Trial(remat_policy="dots"),
                  Trial(remat_policy="none", prefetch_host_depth=4,
                        prefetch_device_depth=2),
                  base]
        ordered = order_trials(trials, "input", baseline=base)
        assert ordered[0] == base  # moves nothing
        assert ordered[1].prefetch_host_depth == 4

    def test_memory_bound_walks_remat_toward_none(self):
        base = Trial(remat_policy="dots")
        trials = [Trial(remat_policy="full"), Trial(remat_policy="none")]
        ordered = order_trials(trials, "memory", baseline=base)
        assert ordered[0].remat_policy == "none"
        ordered = order_trials(trials, "compute", baseline=base)
        assert ordered[0].remat_policy == "full"

    def test_attribution_cites_only_real_signal_keys(self):
        result = _run_golden_tmp()
        attribution = result["attribution"]
        metrics = result["winner"]["outcome"]["metrics"]
        assert attribution["signal_keys"]
        for key in attribution["signal_keys"]:
            assert key in metrics
            assert key in attribution["line"]
        assert result["winner"]["digest"] in attribution["line"]


def _run_golden_tmp():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        return _run_golden(os.path.join(d, "tuner_report.json"))


class TestLedger:
    def test_golden_fixture_bytes(self, tmp_path):
        path = tmp_path / "tuner_report.json"
        _run_golden(str(path))
        assert path.read_bytes() == open(FIXTURE, "rb").read(), (
            "deterministic search no longer reproduces the golden report — "
            "if the schema changed on purpose, regenerate the fixture "
            "(see _regen_golden_fixture in this file)")

    def test_golden_fixture_is_schema_valid(self):
        doc = json.load(open(FIXTURE))
        assert validate_report(doc) == []
        statuses = [e["outcome"]["status"] for e in doc["trials"]]
        assert "pruned" in statuses and "ran" in statuses

    def test_resume_skips_completed_trials_byte_identically(self, tmp_path):
        path = tmp_path / "tuner_report.json"
        _run_golden(str(path))
        before = path.read_bytes()

        def exploding_measure(trial):
            raise AssertionError("resume must not re-measure completed trials")

        result = _run_golden(str(path), measure=exploding_measure)
        assert path.read_bytes() == before
        assert result["counts"]["skipped_resume"] == result["counts"]["total"]

    def test_resume_mid_search_completes_only_the_remainder(self, tmp_path):
        path = tmp_path / "tuner_report.json"
        all_trials = _golden_space().enumerate()
        head = order_trials(all_trials, "memory")[:2]
        _run_golden(str(path), trials=head)
        head_entries = json.load(open(path))["trials"]

        measured = []

        def counting_measure(trial):
            measured.append(trial.digest())
            return _golden_measure(trial)

        result = _run_golden(str(path), measure=counting_measure)
        doc = json.load(open(path))
        assert validate_report(doc) == []
        assert doc["trials"][:2] == head_entries  # untouched, not re-run
        assert set(measured).isdisjoint(e["digest"] for e in head_entries)
        assert result["counts"]["skipped_resume"] == 2
        assert len(doc["trials"]) == len(all_trials)

    def test_every_trial_has_an_outcome_and_failures_stay_in_the_ledger(
            self, tmp_path):
        path = tmp_path / "tuner_report.json"

        def flaky_measure(trial):
            if trial.remat_policy == "dots":
                raise RuntimeError("boom")
            return _golden_measure(trial)

        result = _run_golden(str(path), measure=flaky_measure)
        doc = json.load(open(path))
        assert validate_report(doc) == []
        statuses = {e["outcome"]["status"] for e in doc["trials"]}
        assert statuses == {"ran", "pruned", "failed"}
        failed = [e for e in doc["trials"] if e["outcome"]["status"] == "failed"]
        assert all("boom" in e["outcome"]["error"] for e in failed)
        assert result["winner"]["outcome"]["metrics"]["tuner/tps"] > 0

    def test_ledger_rejects_corrupt_and_mismatched_files(self, tmp_path):
        bad = tmp_path / "tuner_report.json"
        bad.write_text("{not json")
        with pytest.raises(ValueError, match="unreadable"):
            TrialLedger(str(bad))
        bad.write_text(json.dumps({"version": 99, "trials": []}))
        with pytest.raises(ValueError, match="version"):
            TrialLedger(str(bad))

    def test_validate_report_flags_broken_docs(self):
        assert validate_report([]) != []
        assert validate_report({"version": TUNER_REPORT_VERSION}) != []
        doc = {"version": TUNER_REPORT_VERSION,
               "trials": [{"digest": "d", "trial": {},
                           "outcome": {"status": "ran"}}],
               "winner": {"digest": "other",
                          "attribution": {"line": "x", "signal_keys": ["k"]}}}
        problems = validate_report(doc)
        assert any("lacks 'metrics'" in p for p in problems)
        assert any("winner.digest" in p for p in problems)

    def test_attribute_winner_without_runner_up(self):
        winner = {"digest": "abc", "trial": {"backend.remat_policy": "dots"},
                  "outcome": {"metrics": {"tuner/tps": 10.0}}}
        out = attribute_winner(winner, None, bound="compute")
        assert out["signal_keys"] == ["tuner/tps"]
        assert "no runner-up" in out["line"]
        assert "bound=compute" in out["line"]


class TestTunedConfig:
    def test_yaml_roundtrip_through_config_loader(self, tmp_path):
        result = _run_golden(str(tmp_path / "tuner_report.json"))
        path = tmp_path / "dense_s2048_test.yaml"
        write_tuned_config(str(path), cell_name="dense_s2048_test",
                           entry=result["winner"],
                           attribution=result["attribution"])
        cfg = ConfigNode({"backend": {"remat_policy": "full"},
                          "micro_batch_size": 1})
        provenance = apply_tuned_config(cfg, str(path))
        overrides = result["winner"]["trial"]
        assert cfg.get("backend.remat_policy") == overrides["backend.remat_policy"]
        assert cfg.get("micro_batch_size") == overrides["micro_batch_size"]
        assert cfg.get("dataloader.prefetch.enabled") is True
        assert provenance == {"tuned_config": str(path),
                              "tuned_cell": "dense_s2048_test",
                              "tuned_digest": result["winner"]["digest"]}

    def test_missing_tuned_config_raises_with_pointer(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="bench.py --tune"):
            apply_tuned_config(ConfigNode({}), str(tmp_path / "nope.yaml"))


def _regen_golden_fixture():  # pragma: no cover — maintenance helper
    """python -c "import tests.unit.test_tuning as t; t._regen_golden_fixture()" """
    _run_golden(FIXTURE)


if __name__ == "__main__":  # allow direct regen without pytest
    _regen_golden_fixture()
