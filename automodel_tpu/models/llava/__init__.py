from automodel_tpu.models.llava.model import LlavaConfig, LlavaForConditionalGeneration

__all__ = ["LlavaConfig", "LlavaForConditionalGeneration"]
