"""Dion optimizer: orthonormal low-rank updates, mixed grouping, descent."""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from automodel_tpu.optim.dion import build_dion_optimizer, dion


class TestDion:
    def test_update_is_orthonormal_low_rank(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(32, 16).astype(np.float32))
        g = jnp.asarray(rng.randn(32, 16).astype(np.float32))
        tx = dion(0.1, rank_fraction=0.5)
        state = tx.init({"w": w})
        upd, state = tx.update({"w": g}, state)
        u = np.asarray(upd["w"]) / -0.1 / np.sqrt(32 / 16)
        # u = P Q^T with P orthonormal (rows x r), Q col-normalized -> rank <= r
        r = 8
        s = np.linalg.svd(u, compute_uv=False)
        assert (s[r:] < 1e-4).all()

    def test_stacked_leaves_vmapped(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(4, 16, 8).astype(np.float32))  # (layers, m, n)
        tx = dion(0.1)
        state = tx.init({"w": w})
        upd, _ = tx.update({"w": w}, state)
        assert upd["w"].shape == (4, 16, 8)

    def test_mixed_groups_descend(self):
        """Tiny regression: dion on the matrix, adamw on bias/embedding — loss drops."""
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(64, 8).astype(np.float32))
        w_true = rng.randn(8, 4).astype(np.float32)
        y = x @ jnp.asarray(w_true)  # realizable: optimum loss ~0
        params = {
            "w_proj": jnp.asarray(rng.randn(8, 4).astype(np.float32) * 0.1),
            "bias": jnp.zeros((4,), jnp.float32),
            "embed": jnp.asarray(rng.randn(10, 8).astype(np.float32) * 0.1),
        }
        sched = optax.constant_schedule(0.02)
        tx = build_dion_optimizer(sched, rank_fraction=1.0, max_grad_norm=1.0)
        state = tx.init(params)

        def loss_fn(p):
            pred = x @ p["w_proj"] + p["bias"] + p["embed"][:4].sum() * 0
            return ((pred - y) ** 2).mean()

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(loss_fn)(p)
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s, l

        losses = []
        for _ in range(80):
            params, state, l = step(params, state)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.5

    def test_grouping_labels(self):
        from automodel_tpu.optim.dion import _is_matrix_path

        import jax.tree_util as jtu

        params = {
            "embed": jnp.zeros((10, 4)),
            "layers": {"wq": jnp.zeros((2, 4, 4)), "attn_norm": jnp.zeros((2, 4))},
            "lm_head": jnp.zeros((4, 10)),
        }
        labels = jtu.tree_map_with_path(
            lambda p, l: "dion" if _is_matrix_path(p, l) else "adamw", params
        )
        assert labels["embed"] == "adamw"
        assert labels["lm_head"] == "adamw"
        assert labels["layers"]["wq"] == "dion"
        assert labels["layers"]["attn_norm"] == "adamw"
