from automodel_tpu.models.qwen3_next.model import Qwen3NextConfig, Qwen3NextForCausalLM

__all__ = ["Qwen3NextConfig", "Qwen3NextForCausalLM"]
