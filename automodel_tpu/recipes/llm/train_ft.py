"""LLM finetune/pretrain recipe
(reference TrainFinetuneRecipeForNextTokenPrediction, recipes/llm/train_ft.py:803).

The YAML contract mirrors the reference's:

.. code-block:: yaml

    seed: 42
    model:
      pretrained_model_name_or_path: /path/to/hf_dir    # or config: {...} for scratch
    distributed:
      dp_shard: -1    # mesh axes; -1 infers
      tp: 1
      cp: 1
    backend:
      attention: xla
      remat_policy: none
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      ...
    step_scheduler: {grad_acc_steps: 1, ckpt_every_steps: 0, max_steps: 50, num_epochs: 1}
    optimizer: {lr: 1.0e-5, weight_decay: 0.0, betas: [0.9, 0.95], max_grad_norm: 1.0}
    lr_scheduler: {lr_warmup_steps: 10, lr_decay_style: cosine}
    packed_sequence: {packed_sequence_size: 0}
    micro_batch_size: 2
    seq_len: 512
    checkpoint: {enabled: false, checkpoint_dir: ckpts, save_consolidated: false}
    validation_dataset: {...}   # optional

Differences from the reference are all TPU-native: one jitted train step owns
grad-accum + collectives (SURVEY.md §7 table), params are sharded by logical-axis
rules rather than module wrappers, and resume restores directly into shardings.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.checkpoint.checkpointing import Checkpointer, CheckpointingConfig
from automodel_tpu.checkpoint.reshard import build_topology
from automodel_tpu.data.collate import sft_collate, stack_batches
from automodel_tpu.data.loader import DataLoader
from automodel_tpu.loggers.log_utils import setup_logging
from automodel_tpu.loggers.metric_logger import MetricLogger
from automodel_tpu.models.auto import AutoModelForCausalLM, load_hf_config
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.observability import Observability
from automodel_tpu.optim import build_lr_schedule, build_optimizer
from automodel_tpu.ops.losses import linear_cross_entropy, masked_cross_entropy
from automodel_tpu.parallel.init import initialize_distributed
from automodel_tpu.parallel.mesh import MeshContext, default_sharding_rules
from automodel_tpu.training.rng import StatefulRNG
from automodel_tpu.training.step_scheduler import StepScheduler
from automodel_tpu.training.train_step import count_label_tokens, make_train_step

logger = logging.getLogger(__name__)

__all__ = ["TrainFinetuneRecipeForNextTokenPrediction", "main"]


class TrainFinetuneRecipeForNextTokenPrediction:
    # class-level defaults: subclasses (KD, VLM, ...) override _build_train_step
    # without necessarily setting these
    _pre_qat_step = None
    _qat_start_step = 0
    _step_needs_rng = False
    _dynamics = False  # set by _build_train_step when the dynamics pillar is on
    # static per-run fields a subclass wants appended to every training.jsonl row
    # (the KD recipe logs kd_ratio/temperature per row, reference kd.py:456)
    _static_log_fields: dict = {}

    def __init__(self, cfg: ConfigNode):
        self.cfg = cfg

    # ------------------------------------------------------------------ setup
    def setup(self):
        self._check_nan_grads = bool(self.cfg.get("distributed.check_for_nan_in_grad", False))
        cfg = self.cfg
        setup_logging(cfg.get("log_level", "INFO"))
        # tuned_config: a bench.py --tune winner (tuned/<cell>.yaml). Applied
        # FIRST so every consumer below — backend, microbatch, prefetch,
        # step_scheduler — sees the tuned values; the returned provenance
        # (tuned_config/tuned_cell/tuned_digest) rides the run header so a
        # training.jsonl always says which autotuner verdict shaped it.
        self._tuned_provenance: dict | None = None
        tuned_path = cfg.get("tuned_config")
        if tuned_path:
            from automodel_tpu.tuning import apply_tuned_config

            self._tuned_provenance = apply_tuned_config(cfg, str(tuned_path))
        # persistent XLA compile cache (warm restart, docs/resilience.md): must
        # be configured before the FIRST compile of the process — the jit model
        # init a few lines down already writes/reads cache entries
        from automodel_tpu.observability import compile_cache

        compile_cache.configure(cfg.get("compile_cache"))
        # events fired before the metric loggers exist (restore-time elastic/
        # unverified events during _maybe_resume) buffer here; flushed once the
        # loggers come up
        self._deferred_events: list[tuple[int, dict]] = []
        # wall seconds _maybe_resume spent restoring (observability does not
        # exist yet at that point; back-billed to the `restore` goodput bucket
        # once it does, so resume cost stops vanishing into idle)
        self._restore_s = 0.0
        self.dist = initialize_distributed(auto=bool(cfg.get("distributed.auto_init", False)))
        self.rng = StatefulRNG(seed=int(cfg.get("seed", 42)))

        # mesh + sharding rules
        dist_cfg = {k: v for k, v in (cfg.get("distributed") or ConfigNode()).items()
                    if k in ("pp", "dp_replicate", "dp_shard", "ep", "cp", "tp")}
        self.mesh_ctx = MeshContext(**dist_cfg)
        self.mesh = self.mesh_ctx.build_mesh()
        self.rules = default_sharding_rules(
            sequence_parallel=bool(cfg.get("distributed.sequence_parallel", True)),
        ).with_mesh(self.mesh)
        logger.info("mesh: %s", dict(self.mesh.shape))

        # batch-stack shardings, built once and reused by every device_put
        # (and by the device prefetcher) instead of per key per batch
        self._stack_shardings = self._build_stack_shardings()
        # live only while a train pass runs; _save consults it so checkpoints
        # under prefetch carry the consumed-position scheduler/dataloader state
        self._pipeline = None

        # backend + model + params
        backend_cfg = cfg.get("backend")
        self.backend = BackendConfig(**backend_cfg.to_dict()) if backend_cfg else BackendConfig()
        self._build_model_and_params()
        self._build_peft()

        # tokenizer (optional for mock data)
        self.tokenizer = self._build_tokenizer()

        # data
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self.seq_len = int(cfg.get("seq_len", 1024))
        global_batch = self.micro_batch_size * jax.process_count()
        if global_batch % self.mesh_ctx.dp_size != 0:
            raise ValueError(
                f"micro_batch_size*processes = {global_batch} must divide by the data-"
                f"parallel degree dp_replicate*dp_shard*ep = {self.mesh_ctx.dp_size}"
            )
        self.dataloader = self._build_dataloader(cfg.get("dataset"), is_train=True)
        val_cfg = cfg.get("validation_dataset")
        self.val_dataloader = self._build_dataloader(val_cfg, is_train=False) if val_cfg else None
        # unsized validation streams would hang the val loop without a bound
        self.max_val_batches = cfg.get("validation_max_batches")
        if self.max_val_batches is not None:
            self.max_val_batches = int(self.max_val_batches)
        elif self.val_dataloader is not None and self.val_dataloader.num_batches is None:
            raise ValueError(
                "streaming (unsized) validation datasets need validation_max_batches: "
                "the validation loop would otherwise never terminate"
            )

        # step scheduler
        ss = (cfg.get("step_scheduler") or ConfigNode()).to_dict()
        ss.setdefault("grad_acc_steps", 1)
        if not getattr(self.dataloader, "_sized", True) and not ss.get("max_steps"):
            raise ValueError(
                "streaming (unsized) datasets need step_scheduler.max_steps: "
                "epoch length is unknown, so num_epochs cannot bound training"
            )
        self.step_scheduler = StepScheduler(dataloader=self.dataloader, **ss)

        # optimizer + schedule
        opt_cfg = (cfg.get("optimizer") or ConfigNode()).to_dict()
        lr_cfg = (cfg.get("lr_scheduler") or ConfigNode()).to_dict()
        max_lr = float(opt_cfg.pop("lr", 1e-5))
        # decay horizon is in OPTIMIZER steps: microbatches / grad_acc_steps
        n_batches = self.dataloader.num_batches
        if n_batches is None:  # unsized stream: max_steps guarded above
            total_steps = ss["max_steps"]
        else:
            steps_per_epoch = max(n_batches // int(ss["grad_acc_steps"]), 1)
            total_steps = ss.get("max_steps") or (steps_per_epoch * int(ss.get("num_epochs", 1)))
        lr_cfg.setdefault("lr_decay_steps", total_steps)
        self.lr_schedule = build_lr_schedule(max_lr=max_lr, **lr_cfg)
        betas = opt_cfg.pop("betas", (0.9, 0.95))
        if opt_cfg.get("optimizer") == "dion" and self.peft is None:
            # layout-driven matrix canonicalization (head-split dims merge into the
            # true matmul matrix); under PEFT the adapter tree has its own paths and
            # dion falls back to the name heuristic
            opt_cfg.setdefault("logical_axes", self.model.logical_axes())
        self.optimizer = build_optimizer(
            lr=self.lr_schedule, betas=tuple(betas), **opt_cfg
        )
        from automodel_tpu.parallel.sharding_utils import make_sharded_init

        with self.mesh:
            # moments born sharded like their params; scalars replicated. Under PEFT
            # the optimizer tracks only the rank-r adapter tree (reference freezes the
            # base via requires_grad, _peft/lora.py:335; here it is simply not an
            # optimizer argument).
            self.opt_state = make_sharded_init(self.optimizer, self.train_params, self.mesh)(
                self.train_params
            )

        # loss selection (reference build_loss_fn, train_ft.py:345). Big-vocab
        # models default to the fused linear CE (reference defaults to
        # cut-cross-entropy for the same reason, loss/linear_ce.py:119): the
        # (tokens, vocab) logits tensor would otherwise dominate HBM.
        default_loss = "masked_ce"
        if (
            getattr(self.model.config, "vocab_size", 0) >= 65536
            and self.mesh_ctx.pp == 1
            and self._moe_config is None
        ):
            default_loss = "linear_ce"
        self.loss_name = cfg.get("loss.name", default_loss)
        # pallas fused CE runs the kernel on the device-local view; under a
        # multi-device mesh the GSPMD partitioner can't split a pallas_call, so
        # fall back to the XLA blockwise path there (it partitions cleanly)
        impl = cfg.get("loss.impl", "auto")
        if impl == "auto":
            impl = "pallas" if jax.default_backend() == "tpu" and self.mesh.size == 1 else "xla"
        self.loss_impl = impl
        self.loss_filter_eps = cfg.get("loss.filter_eps", 1e-7)
        # MoE load-balance metric logging (reference MoEMetricsConfig, moe/config.py:72)
        self.moe_metrics_mode = cfg.get(
            "moe_metrics.mode", "brief" if self._moe_config is not None else None
        )
        if not cfg.get("moe_metrics.enabled", True):
            self.moe_metrics_mode = None

        # checkpointing
        ck = (cfg.get("checkpoint") or ConfigNode()).to_dict()
        self.checkpointer = Checkpointer(
            CheckpointingConfig(**ck),
            state_dict_adapter=self.model.state_dict_adapter(),
            hf_config=getattr(self, "hf_config", None),
        )
        # resilience (docs/resilience.md): anomaly rollback, verified fallback
        # restore, coordinated preemption, chaos injection. Built before resume
        # (resume goes through the verified-restore path) with a late-bound
        # metric sink — the loggers come up a few lines below, before any event
        # can fire.
        from automodel_tpu.resilience import ResilienceManager

        self.resilience = ResilienceManager.from_config(
            cfg.get("resilience"), checkpointer=self.checkpointer,
            metric_sink=lambda step, **f: self._log_event(step, **f),
        )
        self.chaos = self.resilience.chaos
        # elastic-topology protocol (checkpoint/reshard.py): every save records
        # the saving mesh/pod shape, and restore-time events (elastic_restore,
        # unverified_restore) ride the resilience metric stream
        self.checkpointer.topology = build_topology(self.mesh_ctx)
        self.checkpointer.event_sink = self.resilience.emit
        self._maybe_resume()

        # metrics: JSONL always on; wandb/mlflow when configured (reference
        # train_ft.py:694,1024-1034)
        out_dir = cfg.get("output_dir", None)
        if out_dir is None:
            from automodel_tpu.utils.run_dir import default_output_dir

            out_dir = default_output_dir("train")
        os.makedirs(out_dir, exist_ok=True)
        self.output_dir = out_dir  # one resolved dir for every artifact writer
        # kill/hang chaos sentinels must survive the restart they cause, so
        # their fired-marks live with the run's other artifacts
        if self.chaos is not None:
            self.chaos.state_dir = out_dir
        self.metric_logger = MetricLogger(os.path.join(out_dir, "training.jsonl"))
        self.val_metric_logger = MetricLogger(os.path.join(out_dir, "validation.jsonl"))
        from automodel_tpu.loggers.experiment_loggers import build_experiment_loggers

        self.experiment_loggers = build_experiment_loggers(cfg)
        # restore-time events buffered before the loggers existed land now, in
        # order, ahead of any step row
        for ev_step, ev_fields in self._deferred_events:
            self._log_event(ev_step, **ev_fields)
        self._deferred_events.clear()

        # observability (docs/observability.md): goodput accounting, HBM +
        # compile telemetry, stall watchdog, on-demand profiling. Stall events
        # fan out through the same JSONL/wandb/mlflow sinks as step metrics.
        self.observability = Observability.from_config(
            cfg.get("observability"), out_dir, metric_sink=self._log_event
        )
        # back-bill the checkpoint restore _maybe_resume already paid for
        # (satellite of the run ledger: resume cost must not read as idle)
        if self._restore_s:
            self.observability.record_restore(self._restore_s)
        # axis sizes let the compile-cost row attribute collective bytes to
        # ep/dp/tp/pp (and the roofline grow its moe_a2a bound category)
        self.observability.mesh_axes = {
            str(name): int(size) for name, size in self.mesh.shape.items()
        }
        # identifies this run's cell in signals.json (tuners match on it);
        # same model-id fallback chain as the run header below
        _arch = None
        if isinstance(getattr(self, "hf_config", None), dict):
            _arch = (self.hf_config.get("architectures") or [None])[0]
        self.observability.cell_info = {
            "model": str(cfg.get("model.pretrained_model_name_or_path")
                         or _arch or "scratch"),
            "seq_len": int(self.seq_len),
        }
        # analytic HBM plan: the sharded params/opt_state give exact per-shard
        # bytes and the config gives batch/activation estimates, so the
        # headroom/fits verdict exists BEFORE the first compile; compile_step
        # later reconciles it against the compiled step's memory_analysis()
        from automodel_tpu.observability.memory_plan import build_memory_plan

        try:
            self.observability.memory_plan = build_memory_plan(
                self.train_params, self.opt_state,
                micro_batch_size=self.micro_batch_size, seq_len=self.seq_len,
                grad_acc_steps=int(ss["grad_acc_steps"]),
                dp_degree=self.mesh_ctx.dp_size,
                model_config=getattr(self, "hf_config", None) or self.model.config,
                hbm_limit_override_gib=self.observability.config.hbm_limit_gib,
            )
        except Exception:
            logger.warning("analytic memory plan failed (run continues)",
                           exc_info=True)
        # moe/* telemetry rows (routing entropy, utilization spread, dropped
        # tokens, aux-loss trend); None on dense runs
        from automodel_tpu.observability.moe_stats import MoEStats, local_expert_coords

        self._moe_stats = MoEStats() if self.moe_metrics_mode is not None else None
        # this host's ep-shard coordinates: each host samples the utilization
        # of its OWN experts so the aggregator can name a hot_expert_host
        self._local_ep_coords = (
            local_expert_coords(self.mesh) if self._moe_stats is not None else None
        )
        # per-log-row MFU needs the analytic FLOPs formula; families outside
        # the formula table (VLM towers, audio) skip gracefully
        try:
            from automodel_tpu.utils.flops import flops_per_token

            self._flops_per_token = float(flops_per_token(self.hf_config, self.seq_len))
        except Exception:
            self._flops_per_token = None
        self._device_kind = jax.devices()[0].device_kind

        # self-describing stream: one header row up front (git sha, versions,
        # mesh axis sizes, model id, config digest) so any training.jsonl can
        # be joined to a bench baseline without its YAML
        from automodel_tpu.loggers.metric_logger import build_run_header

        arch = None
        if isinstance(getattr(self, "hf_config", None), dict):
            arch = (self.hf_config.get("architectures") or [None])[0]
        model_id = cfg.get("model.pretrained_model_name_or_path") or arch or "scratch"
        from automodel_tpu.observability import compile_cache

        plan = self.observability.memory_plan
        self.metric_logger.log_header(**build_run_header(
            cfg=cfg, mesh=self.mesh, model_id=model_id, seq_len=self.seq_len,
            # persistent-XLA-cache config + hit/miss traffic from the
            # model-init compiles (run totals land in compile_summary)
            compile_cache=compile_cache.snapshot(),
            # the fit-before-run verdict: a header reader (or a human tailing
            # the stream) sees whether this config fits its chip before step 0
            **(plan.header_row() if plan is not None else {}),
            # autotuner provenance: which tuned/<cell>.yaml (and which ledger
            # winner digest) shaped this run's config, if any
            **(self._tuned_provenance or {}),
        ))

        # the jitted step
        self._train_step = self._build_train_step()
        self._eval_step = None  # VLM/seq-cls overrides use the single-slot form
        self._eval_steps = {}  # base: keyed by qat-active (delayed-start switch)
        return self

    def _build_model_and_params(self):
        cfg = self.cfg
        pretrained = cfg.get("model.pretrained_model_name_or_path")
        # fp32 master params by default (the reference's mixed-precision contract);
        # "bfloat16" = pure-bf16 training — halves params+grads HBM, the trade
        # benchmark / memory-bound configs take
        params_dtype = jnp.dtype(cfg.get("model.params_dtype", "float32"))
        with self.mesh:
            if pretrained:
                self.hf_config = load_hf_config(pretrained)
                self.model, self.params = AutoModelForCausalLM.from_pretrained(
                    pretrained, backend=self.backend, dtype=params_dtype, rules=self.rules
                )
            else:
                model_cfg = cfg.get("model.config")
                if model_cfg is None:
                    raise ValueError("config needs model.pretrained_model_name_or_path or model.config")
                self.hf_config = model_cfg.to_dict() if isinstance(model_cfg, ConfigNode) else dict(model_cfg)
                self.model = AutoModelForCausalLM.from_config(self.hf_config, backend=self.backend)
                axes = self.model.logical_axes()
                shardings = self.rules.tree_sharding(axes)
                init_fn = jax.jit(
                    lambda k: self.model.init(k, params_dtype), out_shardings=shardings
                )
                self.params = init_fn(self.rng.key("model_init"))
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))
        logger.info("model: %s (%.1fM params)", type(self.model).__name__, n_params / 1e6)

    def _build_peft(self):
        """LoRA/DoRA adapter tree (reference apply_lora_to_linear_modules,
        _peft/lora.py:335): self.train_params is what the optimizer and checkpointer
        see — the adapter under PEFT, the full params otherwise."""
        peft_cfg = self.cfg.get("peft")
        self.peft = None
        self.train_params = self.params
        if peft_cfg is None:
            return
        from automodel_tpu.peft.lora import (
            PeftConfig, count_lora_params, init_lora_params, lora_logical_axes,
            merge_lora_params,
        )

        self.peft = PeftConfig.from_dict(peft_cfg.to_dict())
        axes = self.model.logical_axes()
        host_lora = init_lora_params(self.params, axes, self.peft, self.rng.key("lora_init"))
        shardings = self.rules.tree_sharding(lora_logical_axes(axes, self.peft))
        self.train_params = jax.tree.map(jax.device_put, host_lora, shardings)
        # QLoRA (reference quantization/qlora.py): store the adapted base weights
        # int8/nf4 at rest; merge dequantizes transiently inside the step. Must run
        # AFTER lora init (DoRA magnitudes need the dense weights).
        qlora_scheme = peft_cfg.get("qlora")
        if qlora_scheme:
            from automodel_tpu.peft.lora import match_lora_paths
            from automodel_tpu.quantization.qlora import quantize_params, tree_nbytes

            matched = match_lora_paths(axes, self.peft)  # path -> (n_stack, split)
            before = tree_nbytes(self.params)
            self.params = quantize_params(self.params, matched, qlora_scheme)
            logger.info(
                "qlora(%s): base %.1fMB -> %.1fMB (%d tensors quantized)",
                qlora_scheme, before / 2**20, tree_nbytes(self.params) / 2**20, len(matched),
            )
        # one compiled merge reused by every consolidated save
        self._merge_lora = jax.jit(lambda base, lora: merge_lora_params(base, lora, self.peft))
        logger.info(
            "peft: lora dim=%d alpha=%d dora=%s — %.2fM trainable params",
            self.peft.dim, self.peft.alpha, self.peft.use_dora,
            count_lora_params(self.train_params) / 1e6,
        )

    def _build_tokenizer(self):
        tok_cfg = self.cfg.get("tokenizer")
        pretrained = self.cfg.get("model.pretrained_model_name_or_path")
        if tok_cfg and "_target_" in tok_cfg:
            return tok_cfg.instantiate()
        path = (tok_cfg or ConfigNode()).get("pretrained_model_name_or_path") or pretrained
        if path and os.path.exists(os.path.join(path, "tokenizer_config.json")):
            from automodel_tpu.models.auto_tokenizer import AutoTokenizer

            return AutoTokenizer.from_pretrained(path)
        return None

    def _build_dataloader(self, ds_cfg, is_train: bool):
        if ds_cfg is None:
            raise ValueError("config needs a dataset section")
        kwargs = {}
        if self.tokenizer is not None:
            kwargs["tokenizer"] = self.tokenizer
        try:
            dataset = ds_cfg.instantiate(**kwargs)
        except TypeError:
            dataset = ds_cfg.instantiate()  # dataset doesn't take a tokenizer (mock)
        pad_id = 0
        if self.tokenizer is not None and getattr(self.tokenizer, "pad_token_id", None) is not None:
            pad_id = self.tokenizer.pad_token_id
        dataset, collate = self._wrap_dataset_and_collate(dataset, pad_id)
        return DataLoader(
            dataset,
            batch_size=self.micro_batch_size * jax.process_count(),
            collate_fn=collate,
            seed=int(self.cfg.get("seed", 42)),
            shuffle=is_train,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )

    def _wrap_dataset_and_collate(self, dataset, pad_id: int):
        """Hook: per-recipe dataset wrapping + collate choice (seq-cls overrides
        this to swap in class-label collation; the base handles packing)."""
        # sequence packing (reference packed_sequence section, train_ft.py:402): each
        # example becomes a fixed-size pack, segment ids carry the boundaries
        pack_size = int(self.cfg.get("packed_sequence.packed_sequence_size", 0))
        if pack_size > 0:
            from automodel_tpu.data.llm.packed import pack_dataset, packed_collate

            if not self.backend.attention_segments:
                raise ValueError(
                    "packed sequences need segment masking in attention; drop "
                    "backend.attention_segments: false (it is a fast path for "
                    "right-padded UNPACKED batches only)"
                )
            if pack_size % self.mesh_ctx.cp != 0:
                raise ValueError(
                    f"packed_sequence_size {pack_size} must divide by cp={self.mesh_ctx.cp}"
                )
            dataset = pack_dataset(
                dataset,
                pack_size,
                pad_token_id=pad_id,
                max_packs=self.cfg.get("packed_sequence.max_packs"),
                drop_long_samples=bool(self.cfg.get("packed_sequence.drop_long_samples", False)),
            )
            self.seq_len = pack_size
            return dataset, packed_collate
        return dataset, (lambda exs: sft_collate(exs, seq_len=self.seq_len, pad_token_id=pad_id))

    @property
    def _moe_config(self):
        cfg = self.model.config
        return getattr(cfg, "moe", None) or getattr(getattr(cfg, "text", None), "moe", None)

    def _model_forward(self, params, batch, training):
        """The model call; subclasses (VLM) override to thread extra modalities
        while the loss/aux handling below stays shared."""
        kwargs = {}
        if self._moe_config is not None:
            # segment id 0 marks padding (sft_collate contract): pad tokens must not
            # count for routing load, aux loss, or the gate-bias update
            kwargs = {"token_mask": batch["segment_ids"] != 0, "training": training}
        # sharding constraints are pure fusion barriers on a single device
        rules = self.rules if self.mesh.size > 1 else None
        return self.model(
            params, batch["input_ids"], positions=batch["positions"],
            segment_ids=batch["segment_ids"], rules=rules,
            return_hidden=self.loss_name == "linear_ce", **kwargs,
        )

    def _forward_loss(self, params, batch, num_label_tokens, training=True):
        out = self._model_forward(params, batch, training)
        out, stats = out if isinstance(out, tuple) else (out, None)
        if self.loss_name == "linear_ce":
            from automodel_tpu.models.common.transformer import resolve_unembed

            # cast to the activation dtype: matches the masked path's logits
            # precision and halves the kernel's VMEM tile footprint; the helper
            # folds tied-embedding fallback + granite logits_scaling in
            mcfg = getattr(self.model.config, "text", self.model.config)
            unembed = resolve_unembed(mcfg, params, out.dtype)
            if unembed is None:
                raise ValueError("linear_ce: model has neither lm_head nor a tied embedding table")
            loss = linear_cross_entropy(
                out, unembed, batch["labels"],
                num_label_tokens, impl=self.loss_impl, filter_eps=self.loss_filter_eps,
            )
        else:
            loss = masked_cross_entropy(out, batch["labels"], num_label_tokens)
        if stats is None:
            return loss
        aux = {"expert_load": stats["expert_load"]}
        if "dropped_token_frac" in stats:
            # a2a dispatch: capacity-overflow rate, summed across microbatches in
            # the step carry -> divide by grad-accum steps at log time
            aux["dropped_token_frac"] = stats["dropped_token_frac"]
        if stats["aux_loss"] is not None:
            # reference scales aux by token count to undo 1/num_label_tokens grad
            # normalization (layers.py:367-372 MoEAuxLossAutoScaler); additive across
            # microbatches this weights each microbatch's aux by its token fraction
            mb_tokens = count_label_tokens(batch["labels"]).astype(jnp.float32)
            loss = loss + self._moe_config.aux_loss_coeff * stats["aux_loss"] * (
                mb_tokens / num_label_tokens
            )
            # unscaled balance loss, token-weighted the same way: summed across
            # microbatches it is the step-level weighted mean for moe/aux_loss
            aux["moe_aux_loss"] = stats["aux_loss"] * (mb_tokens / num_label_tokens)
        return loss, aux

    def _post_update(self):
        """Gate-bias loss-free-balancing hook (reference update_moe_gate_bias,
        train_ft.py:1341): pure param update from the accumulated expert load."""
        moe = self._moe_config
        if moe is None or moe.gate_bias_update_factor <= 0:
            return None
        from automodel_tpu.moe.gate import make_gate_bias_post_update

        return make_gate_bias_post_update(moe.gate_bias_update_factor)

    def _build_train_step(self):
        self._pre_qat_step = None
        self._qat_start_step = 0
        self._step_needs_rng = False
        # resilience keeps params restorable THROUGH an anomaly: the jitted step
        # must zero non-finite updates so the tree the host later rolls back
        # from (or keeps, on skip_update) is never poisoned
        self._guard_nonfinite = self._check_nan_grads or self.resilience.guards_updates
        # the dynamics pillar asks the jitted step for the per-subtree telemetry
        # pytree; the reductions fuse into the step, the host syncs on cadence
        self._dynamics = self.observability.dynamics_enabled
        qfn = self._qat_param_fn()
        qat_cfg = self.cfg.get("qat")
        qat_start = int(qat_cfg.get("fake_quant_after_n_steps") or 0) if qat_cfg else 0

        def build(with_qat: bool):
            """One step builder covering every composition; QAT is a param-level
            transform so it threads through pp / peft / plain identically."""
            q = qfn if (with_qat and qfn is not None) else (lambda p: p)
            if self.mesh_ctx.pp > 1:
                from automodel_tpu.parallel.pipeline import (
                    make_dense_decoder_pp_loss,
                    make_moe_pp_loss,
                )
                from automodel_tpu.training.train_step import make_pp_train_step

                virtual = int(self.cfg.get("distributed.pp_virtual_stages", 1))
                if self._moe_config is not None:
                    pp_loss = make_moe_pp_loss(
                        self.model, self.mesh, self.rules, loss_name=self.loss_name,
                        seq_len_hint=self.seq_len, circular_repeats=virtual,
                    )
                    pp_post_update = self._post_update() if self.peft is None else None
                    if self.peft is not None and self._post_update() is not None:
                        logger.warning("moe gate-bias update disabled under peft (base is frozen)")
                else:
                    pp_loss = make_dense_decoder_pp_loss(
                        self.model, self.mesh, self.rules, loss_name=self.loss_name,
                        circular_repeats=virtual,
                    )
                    pp_post_update = None
                if self.peft is not None:
                    # peft + pp (reference composes them, infrastructure.py:303):
                    # the LoRA merge happens OUTSIDE the pp-manual region in plain
                    # GSPMD — merged layer stacks stay (L, ...) and shard over pp
                    # as usual; grads flow only to the rank-r adapter. qat x peft
                    # x pp: the BASE quantizes before the merge (the adapter
                    # trains in full precision on a quantized base, reference
                    # QLoRA-style qat semantics).
                    from automodel_tpu.peft.lora import lora_merged_loss

                    # dropout rides the merged-delta mask (peft/lora.py:296);
                    # the merge — and thus the mask — happens once per step
                    # outside the pp-manual region (make_pp_train_step docs)
                    use_dropout = self.peft.dropout > 0.0
                    pp_peft_loss = lora_merged_loss(
                        lambda merged, base, bs, n: pp_loss(merged, bs, n),
                        q, self.peft, use_dropout,
                    )
                    self._step_needs_rng = use_dropout
                    return make_pp_train_step(pp_peft_loss, self.optimizer,
                                              guard_nonfinite=self._guard_nonfinite,
                                              with_frozen=True,
                                              pass_rng=use_dropout,
                                              dynamics=self._dynamics)
                # qat x pp: quantize the stacked layer params (and head/embed)
                # BEFORE the manual region — fake-quant is elementwise, GSPMD
                # partitions it over the pp-sharded layer dim like any other op
                return make_pp_train_step(lambda p, bs, n: pp_loss(q(p), bs, n),
                                          self.optimizer,
                                          post_update=pp_post_update,
                                          guard_nonfinite=self._guard_nonfinite,
                                          dynamics=self._dynamics)
            if self.peft is not None:
                from automodel_tpu.peft.lora import lora_merged_loss

                if self._post_update() is not None:
                    logger.warning("moe gate-bias update disabled under peft (base is frozen)")

                use_dropout = self.peft.dropout > 0.0
                peft_loss = lora_merged_loss(
                    lambda merged, base, b, n: self._forward_loss(merged, b, n),
                    q, self.peft, use_dropout,
                )
                self._step_needs_rng = use_dropout
                return make_train_step(peft_loss, self.optimizer, with_frozen=True,
                                       guard_nonfinite=self._guard_nonfinite,
                                       pass_rng=use_dropout,
                                       dynamics=self._dynamics)
            return make_train_step(
                lambda p, b, n: self._forward_loss(q(p), b, n),
                self.optimizer, post_update=self._post_update(),
                guard_nonfinite=self._guard_nonfinite,
                dynamics=self._dynamics,
            )

        step = build(with_qat=True)
        # QAT delayed start (reference qat.py:46 fake_quant_after_n_steps): two
        # compiled steps, python-level switch on the scheduler step — zero
        # per-step overhead vs a lax.cond inside jit. Applies to every
        # composition since build() is uniform.
        if qfn is not None and qat_start > 0:
            self._pre_qat_step = jax.jit(build(with_qat=False), donate_argnums=(0, 1))
            self._qat_start_step = qat_start
        return jax.jit(step, donate_argnums=(0, 1))

    def _qat_param_fn(self):
        """params -> fake-quantized params, or None when QAT is off.

        The param-level transform is what makes QAT compose: the pp loss, the
        LoRA base, and the plain forward all consume a param tree, so one
        transform serves qat, qat x pp, and qat x peft (reference threads the
        same module-swap through its one sequencing path, infrastructure.py:303).
        Memoized: the path match never changes after setup and validation calls
        this every pass.
        """
        if not hasattr(self, "_qat_fn_memo"):
            self._qat_fn_memo = self._build_qat_param_fn()
        return self._qat_fn_memo

    def _build_qat_param_fn(self):
        qat_cfg = self.cfg.get("qat")
        if qat_cfg is None or not qat_cfg.get("enabled", True):
            return None
        import dataclasses

        from automodel_tpu.peft.lora import PeftConfig as _MatchCfg, match_lora_paths
        from automodel_tpu.quantization.qat import QATConfig, fake_quant_params

        known = {f.name for f in dataclasses.fields(QATConfig)}
        qat = QATConfig(**{k: v for k, v in qat_cfg.to_dict().items() if k in known})
        # fake_quant_after_n_steps is handled by _build_train_step's two-step switch
        matcher = _MatchCfg(target_modules=qat.target_modules,
                            match_all_linear=qat.target_modules == ["*"])
        paths = sorted(match_lora_paths(self.model.logical_axes(), matcher))
        logger.info("qat: int%d fake-quant on %d weight tensors", qat.weight_bits, len(paths))
        return lambda params: fake_quant_params(params, paths, qat)

    def _qat_wrap(self, forward):
        """QAT (reference quantization/qat.py + train_ft.py:1092): fake-quantize
        matched weights in the forward so training sees post-quantization rounding;
        gradients pass straight through."""
        qfn = self._qat_param_fn()
        if qfn is None:
            return forward

        def qat_forward(params, batch, num_label_tokens):
            return forward(qfn(params), batch, num_label_tokens)

        return qat_forward

    def _maybe_resume(self):
        if not self.checkpointer.config.enabled:
            return
        t0 = time.perf_counter()
        # verified restore with walk-back: a truncated/corrupt latest step falls
        # back to the newest step that passes its integrity manifest, agreed
        # across hosts (docs/resilience.md). load_latest_verified returns None
        # only when NO restorable checkpoint exists — a fresh run.
        el = self.resilience.config.elastic
        restored = self.checkpointer.load_latest_verified(
            self.train_params, self.opt_state,
            # join/leave: a freshly-joined host has no local checkpoint view and
            # abstains from the pod-agreed restore step instead of forcing a
            # fresh run (checkpoints live on storage every host can reach)
            allow_joiners=bool(el.enabled and el.allow_joiners),
        )
        if restored is None:
            return
        self.train_params, self.opt_state, client, step = restored
        logger.info("resuming from step %d", step)
        elastic = client.pop("__elastic__", None)
        host_rows = (client.pop("__hosts__", None) or {}).get("dataloader")
        if elastic is not None and el.enabled:
            self._repartition_client_state(client, host_rows, step)
        self._apply_client_state(client)
        self._restore_s = time.perf_counter() - t0

    def _repartition_client_state(self, client: dict, host_rows, step: int):
        """Elastic resume (docs/resilience.md): Orbax already resharded the
        arrays into the new mesh's templates; what is left is the host state.
        The saved dataloader cursor counts the OLD pod's global batches —
        convert it into this pod's units so no example is double-trained or
        silently dropped across the reshape."""
        from automodel_tpu.resilience.elastic import repartition_dataloader_state

        state = client.get("dataloader")
        if state is None:
            return
        new_state, info = repartition_dataloader_state(
            state, self.dataloader.batch_size, host_rows=host_rows
        )
        client["dataloader"] = new_state
        self._log_event(step, event="elastic_data_repartition", **info)

    def _apply_client_state(self, client: dict):
        """Restore the host-side training services a checkpoint carries; shared
        by process-restart resume and in-process anomaly rollback."""
        if self.peft is None:
            self.params = self.train_params
        if "rng" in client:
            self.rng.load_state_dict(client["rng"])
        if "step_scheduler" in client:
            self.step_scheduler.load_state_dict(client["step_scheduler"])
        if "dataloader" in client:
            self.dataloader.load_state_dict(client["dataloader"])
        if "resilience" in client:
            self.resilience.load_state_dict(client["resilience"])

    def _build_stack_shardings(self) -> dict:
        """Per-stack-key NamedShardings, built once in setup() and reused every
        batch (rebuilding them per key per step was pure host overhead on the
        input path); subclasses with extra modalities add their own entries."""
        return {"tokens": self.rules.sharding((None, "batch", None))}

    def _device_put_stack(self, stack):
        """Shard the stacked (n_micro, B, S) token streams over the batch axes;
        subclasses with extra modalities (VLM media tensors) override per key.
        jax.device_put only *issues* the H2D transfer — under the prefetch
        pipeline the copy overlaps the previous step's compute."""
        sharding = self._stack_shardings["tokens"]
        return {k: jax.device_put(v, sharding) for k, v in stack.items()}

    def _build_input_pipeline(self):
        """Input pipeline for one train pass (docs/performance.md): synchronous
        fetch, or host prefetch thread + device double-buffering behind
        ``dataloader.prefetch``. Rebuilt per pass — a rollback restores
        scheduler/dataloader state, and the worker must restart from there."""
        from automodel_tpu.data.prefetch import InputPipeline, PrefetchConfig

        return InputPipeline(
            scheduler=self.step_scheduler,
            dataloader=self.dataloader,
            stack_fn=stack_batches,
            put_fn=self._device_put_stack,
            config=PrefetchConfig.from_config(self.cfg.get("dataloader.prefetch")),
        )

    def _warmup_step_variants(self, obs, step_fn, exec_fn, stack, extra, step):
        """AOT warmup (docs/resilience.md "warm restart"): pre-compile every
        step shape the scheduler can emit beyond the steady one — today the
        trailing partial-accumulation stack at the epoch tail — into the
        executor's variant table, so no shape demotes to a mid-run jit compile.
        With the persistent compile cache configured, a restarted run's warmup
        deserializes instead of compiling. Warmup stacks are built host-side
        and pushed through the SAME device_put path as real batches so their
        shardings match exactly (device-side slicing could silently differ and
        fake an AOT rejection). Gated by ``compile_cache.warmup`` (default off:
        it fronts the epoch-tail compile cost at step 0)."""
        if not bool(self.cfg.get("compile_cache.warmup", False)):
            return
        from automodel_tpu.resilience.elastic import plan_warmup_micro_counts

        for n_micro in plan_warmup_micro_counts(
            self.dataloader.num_batches, self.step_scheduler.grad_acc_steps
        ):
            host_stack = {
                k: np.zeros((n_micro,) + tuple(v.shape[1:]), dtype=v.dtype)
                for k, v in stack.items()
            }
            t0 = time.perf_counter()
            ok = obs.precompile_variant(
                exec_fn, step_fn,
                (self.train_params, self.opt_state,
                 self._device_put_stack(host_stack), *extra),
                step=step,
            )
            if ok:
                obs.record_compile(time.perf_counter() - t0)
                logger.info(
                    "warmup: pre-compiled trailing %d-microbatch step shape "
                    "in %.1fs", n_micro, time.perf_counter() - t0,
                )

    # ------------------------------------------------------------------ train
    def _log_event(self, step: int, **fields):
        """Async structured events (watchdog stalls, resilience rollbacks)
        into the metric fan-out and onto the trace timeline."""
        if getattr(self, "metric_logger", None) is None:
            # restore-time events (elastic_restore, unverified_restore) fire
            # during _maybe_resume, before the loggers exist
            self._deferred_events.append((step, dict(fields)))
            return
        self.metric_logger.log(step, **fields)
        for lg in self.experiment_loggers:
            lg.log(step, **fields)
        obs = getattr(self, "observability", None)
        if obs is not None:
            obs.note_event(step, fields)

    def run_train_validation_loop(self):
        obs = self.observability
        obs.start()
        # compile billing survives rollback re-entries: a restored pass reuses
        # the already-jitted step, so it must not re-charge the compile bucket
        self._compiled_fns: set[int] = set()
        # id(step_fn) -> executor from obs.compile_step (the AOT-compiled
        # object whose costs were extracted; shares no cache with jit)
        self._step_executors: dict[int, Any] = {}
        self._checked_vocab = False
        outcome = "done"
        try:
            with self.mesh:
                # each pass runs until done/preempted or an anomaly rolls state
                # back to the last verifiable checkpoint, in-process; the pass
                # then restarts with a fresh scheduler iterator (same mechanics
                # as a process-restart resume, without losing the jit cache)
                while True:
                    outcome = self._train_pass(obs)
                    if outcome != "rollback":
                        break
            # final checkpoint; wait() commits any async save's latest symlink.
            # A preempted pass already saved under its grace deadline — a second
            # save here would re-run the consolidated export it chose to skip.
            if self.checkpointer.config.enabled:
                with obs.track("checkpoint"):
                    if outcome != "preempted":
                        self._save(self.step_scheduler.step)
                    self.checkpointer.wait()
        except BaseException as exc:
            # OOM flight recorder: when the failure is an allocator
            # exhaustion, harvest the live-buffer census + memory plan +
            # per-device counters into oom_report.json while the buffers
            # still exist, then re-raise — orchestration must still see the
            # original failure
            obs.maybe_dump_oom(exc, step=self.step_scheduler.step)
            raise
        finally:
            # run-total AOT/jit-fallback/demotion + compile-cache traffic (the
            # run_header only sees the setup-time counts)
            self._log_event(self.step_scheduler.step, event="compile_summary",
                            **obs.compile_summary())
            obs.close()
            self.metric_logger.close()
            self.val_metric_logger.close()
            for lg in self.experiment_loggers:
                lg.close()

    def _train_pass(self, obs) -> str:
        """One pass over the step loop inside the mesh context. Returns
        ``"done"`` (data exhausted / max_steps), ``"preempted"`` (SIGTERM saved
        and exited), or ``"rollback"`` (state restored to the last good
        checkpoint — the caller re-enters). Owns the input pipeline's
        lifecycle: built per pass from the (possibly restored) scheduler
        position, closed on every exit path so no worker thread outlives the
        pass or keeps mutating scheduler/dataloader state."""
        pipeline = self._pipeline = self._build_input_pipeline()
        try:
            return self._run_step_loop(obs, pipeline)
        finally:
            # a SIGTERM truncation inside the loop may have swapped in a
            # rebuilt pipeline (the original is already closed); close the
            # live one — close() is idempotent
            (self._pipeline or pipeline).close()
            self._pipeline = None

    def _run_step_loop(self, obs, pipeline) -> str:
        t_last = time.perf_counter()
        steps_since_log = 0
        window_overhead = 0.0  # eval/ckpt seconds to exclude from step_time_s
        compiled_fns = self._compiled_fns
        last_dyn_row: dict = {}  # latest cadence sample; merged into log rows
        while True:
            with obs.track("data_wait"):
                # synchronous: fetch + collate + stack + device_put inline.
                # prefetched: pops an already-transferred stack — this blocks
                # only when the host worker is behind, so data_wait now
                # measures true input stalls
                fetched = pipeline.get()
            if fetched is None:
                if pipeline.truncated_by_local_sigterm():
                    # The worker stops on the LOCAL flag only (no collectives
                    # off the main thread), so on the signaled host the stream
                    # can end with data remaining while the pod has NOT agreed
                    # to preempt. Returning "done" here would desync the pod:
                    # the other hosts keep stepping and their per-step agreed
                    # allgather waits forever while this host runs teardown/
                    # final-save collectives — and the grace-window checkpoint
                    # is lost. Rebuild from the live scheduler position
                    # (exactly the last consumed step) and keep the step
                    # rhythm: the next consumed step's agreed check sees this
                    # host's flag, so every host takes the preemption save
                    # together at the same step. At most one rebuild per
                    # signal — the worker always yields >= 1 item before its
                    # post-yield flag check, and that step's agreed check
                    # returns True pod-wide.
                    pipeline.close()
                    pipeline = self._pipeline = self._build_input_pipeline()
                    continue
                return "done"
            stack = fetched.stack
            if not self._checked_vocab:
                # tokenizer/model vocab mismatch shows up as NaN loss deep in
                # training; fail loudly on the first batch instead
                vocab = getattr(getattr(self.model.config, "text", self.model.config),
                                "vocab_size", None)
                if vocab is not None:
                    for key in ("input_ids", "q_ids", "p_ids"):
                        if key in stack and int(stack[key].max()) >= vocab:
                            raise ValueError(
                                f"batch {key} contains token id {int(stack[key].max())} "
                                f">= model vocab_size {vocab}: tokenizer/model mismatch"
                            )
                self._checked_vocab = True
            # the consumed step rides on the fetched batch: under prefetch the
            # scheduler's own counter runs ahead (worker thread)
            step = fetched.step
            obs.on_step_start(step)
            extra = (self.params,) if self.peft is not None else ()
            if self._step_needs_rng:
                extra = (*extra, self.rng.key("lora_dropout"))
            step_fn = self._train_step
            if self._pre_qat_step is not None and step < self._qat_start_step:
                step_fn = self._pre_qat_step
            if id(step_fn) not in compiled_fns:
                # first call of a jitted step pays tracing + XLA compile
                # (step 0, and again at a delayed-QAT switch): bill it to
                # the compile bucket and keep it OUT of the throughput
                # window — the first step_time_s/tps row would otherwise
                # absorb minutes of compile. float() pulls a scalar to
                # host: a real sync even through remote-execution tunnels
                # where block_until_ready is a no-op.
                #
                # compile_step AOT-compiles BEFORE the first execution (the
                # step donates its params — afterwards the example buffers are
                # gone), extracts HLO costs + the roofline once, and hands
                # back the executor the rest of the run steps through.
                t0 = time.perf_counter()
                exec_fn = obs.compile_step(
                    step_fn, (self.train_params, self.opt_state, stack, *extra),
                    step=step,
                )
                self.train_params, self.opt_state, metrics = exec_fn(
                    self.train_params, self.opt_state, stack, *extra
                )
                float(metrics["loss"])
                obs.record_compile(time.perf_counter() - t0)
                compiled_fns.add(id(step_fn))
                self._step_executors[id(step_fn)] = exec_fn
                # warm restart (docs/resilience.md): pre-compile the other step
                # shapes the scheduler can emit so none demotes to mid-run jit
                self._warmup_step_variants(obs, step_fn, exec_fn, stack, extra, step)
                t_last = time.perf_counter()
                steps_since_log = 0  # compile step excluded from the window
                window_overhead = 0.0
            else:
                exec_fn = self._step_executors.get(id(step_fn), step_fn)
                with obs.track("device_step"):
                    self.train_params, self.opt_state, metrics = exec_fn(
                        self.train_params, self.opt_state, stack, *extra
                    )
                steps_since_log += 1
            if self.chaos is not None and self.chaos.should_poison(step):
                # fault injection (resilience/chaos.py): simulate corruption
                # the jit guard missed — params AND metrics go non-finite,
                # so recovery genuinely requires a checkpoint rollback
                self.train_params, metrics = self.chaos.poison(
                    step, self.train_params, metrics
                )
            if self.chaos is not None and self.chaos.should_spike(step):
                # finite-spike injection: one layer's params blow up, metrics
                # stay clean — the NEXT step's loss z-score and per-layer
                # dynamics must detect it organically and name the layer
                self.train_params = self.chaos.spike(step, self.train_params)
            if self.peft is None:
                self.params = self.train_params
            obs.heartbeat(step)
            # dynamics pillar (observability/dynamics.py): fold the step's
            # per-subtree telemetry on cadence, run the loss-spike flight
            # recorder, and derive the per-layer attribution (layer_hint) the
            # resilience verdicts and skip/raise events cite
            dyn_row, layer_hint = self._dynamics_host_step(obs, step, metrics, stack)
            if dyn_row:
                last_dyn_row = dyn_row
            if self.resilience.active:
                # same-step anomaly handling (docs/resilience.md): one
                # scalar device->host sync per step buys detection before
                # the bad trajectory reaches the next checkpoint
                action = self.resilience.on_step(
                    step,
                    float(metrics["loss"]),
                    float(metrics["grad_norm"]),
                    bool(metrics.get("nonfinite", False)),
                    layer=layer_hint,
                )
                if action == "rollback":
                    # stop the worker BEFORE restoring: it mutates the very
                    # scheduler/dataloader state the rollback rewrites, and the
                    # restore must not race in-flight prefetches
                    pipeline.close()
                    if self._perform_rollback(step, obs):
                        return "rollback"
                    action = "abort"  # nothing verifiable to roll back to
                if action == "abort":
                    raise RuntimeError(
                        f"resilience: unrecoverable training anomaly at step {step} "
                        f"(loss={float(metrics['loss'])}, "
                        f"grad_norm={float(metrics['grad_norm'])}"
                        + (f", layer={layer_hint}" if layer_hint else "") + "); "
                        "rollback budget exhausted or no verifiable checkpoint"
                    )
                # skip_update: the jitted guard already zeroed the bad
                # update — params/optimizer state are the pre-step values
            elif self._check_nan_grads and bool(metrics["nonfinite"]):
                # reference check_for_nan_in_grad (distributed/config.py:129):
                # without resilience a non-finite gradient is a training
                # bug. The jitted step already SKIPPED the corrupt update
                # (guard_nonfinite), so params and optimizer state stay
                # clean; raise loudly here every step.
                raise RuntimeError(
                    f"non-finite training signal at step {step}: "
                    f"loss={float(metrics['loss'])} "
                    f"grad_norm={float(metrics['grad_norm'])}"
                    + (f" first nonfinite subtree={layer_hint}" if layer_hint else "")
                    + " (the offending update was skipped; params remain clean)"
                )
            if self.step_scheduler.is_log_step_at(step):
                with obs.track("device_step"):
                    # the scalar pulls block on the step's device work, so
                    # this wait is device time, not idle
                    loss = float(metrics["loss"])
                    gnorm = float(metrics["grad_norm"])
                    ntok = int(metrics["num_label_tokens"])
                now = time.perf_counter()
                # per-step time, with eval/ckpt pauses subtracted;
                # steps_since_log == 0 <=> the window held only a compile
                # step, whose device time already lives in compile_time_s
                # — no throughput to report yet
                dt = (max(now - t_last - window_overhead, 0.0) / steps_since_log
                      if steps_since_log else None)
                t_last = now
                steps_since_log = 0
                window_overhead = 0.0
                # global tokens per optimizer step (local slice x process count);
                # biencoder batches carry q_ids/p_ids instead of input_ids
                step_tokens = sum(
                    int(np.prod(stack[k].shape))
                    for k in ("input_ids", "q_ids", "p_ids") if k in stack
                ) * jax.process_count()
                extra = {}
                moe_max_util = None
                if "expert_load" in metrics and self.moe_metrics_mode:
                    from automodel_tpu.moe.metrics import compute_load_balance_metrics

                    extra = compute_load_balance_metrics(
                        np.asarray(metrics["expert_load"]), mode=self.moe_metrics_mode
                    )
                if "dropped_token_frac" in metrics:
                    # summed over the step's microbatches in the train-step carry
                    extra["moe_load/dropped_token_frac"] = float(
                        np.asarray(metrics["dropped_token_frac"])
                    ) / max(1, self.step_scheduler.grad_acc_steps)
                if self._moe_stats is not None:
                    # the moe/* family: routing entropy, utilization spread,
                    # dropped tokens, aux-loss trend, routed tokens/s/chip
                    extra.update(self._moe_stats.rows(
                        metrics,
                        grad_acc_steps=self.step_scheduler.grad_acc_steps,
                        step_time_s=dt,
                        device_count=jax.device_count(),
                        mode=self.moe_metrics_mode,
                    ))
                    if "expert_load" in metrics:
                        from automodel_tpu.observability.moe_stats import (
                            local_expert_max_util,
                        )

                        moe_max_util = local_expert_max_util(
                            np.asarray(metrics["expert_load"]),
                            self._local_ep_coords,
                            self.observability.mesh_axes.get("ep", 1),
                        )
                row = dict(
                    loss=loss,
                    grad_norm=gnorm,
                    lr=float(self.lr_schedule(step)),
                    num_label_tokens=ntok,
                    step_time_s=round(dt, 4) if dt else None,
                    tps=round(step_tokens / dt, 1) if dt else None,
                    tps_per_chip=(round(step_tokens / dt / jax.device_count(), 1)
                                  if dt else None),
                    **extra,
                    **self._static_log_fields,
                )
                if pipeline.prefetching:
                    # stacks buffered ahead of the consumer at log time; a
                    # persistent 0 with high goodput/data_wait = input-bound
                    row["prefetch_depth"] = pipeline.ready_depth()
                if self._flops_per_token is not None:
                    from automodel_tpu.utils.flops import mfu

                    fpt = self._flops_per_token
                    if dt:
                        tps_now = step_tokens / dt
                        row["tflops_per_chip"] = round(
                            tps_now * fpt / 1e12 / jax.device_count(), 2
                        )
                        # 0.0 on device kinds without a peak-TFLOPs entry (CPU)
                        row["mfu"] = round(
                            mfu(tps_now, fpt, self._device_kind, jax.device_count()), 4
                        )
                    else:  # compile-only window: keys present, no rate yet
                        row["tflops_per_chip"] = None
                        row["mfu"] = None
                if last_dyn_row:
                    # the most recent cadence sample of the per-layer dynamics
                    # telemetry rides the log row (dynamics/<layer>/<metric>)
                    row.update(last_dyn_row)
                row.update(obs.step_metrics())
                row.update(obs.roofline_row(dt))
                # collective on multi-host: every process reaches the log step
                # (the schedule is deterministic), proc 0 writes the result;
                # MoE runs gather max expert utilization too (hot_expert_host);
                # dynamics runs gather the replicated grad_norm so cross-host
                # disagreement raises divergent_host (replica desync)
                row.update(obs.host_metrics(
                    dt, moe_max_util=moe_max_util,
                    grad_norm=gnorm if self._dynamics else None))
                self.metric_logger.log(step, **row)
                for lg in self.experiment_loggers:
                    lg.log(step, **row)
                # the same row feeds the OOM flight recorder's ring (context
                # for a future crash report) and the excursion detector (a
                # step-time spike beyond the rolling median arms an auto-trace)
                obs.record_row(step, row)
                obs.note_step_time(step, dt)
                logger.info(
                    "step %d | loss %.4f | gnorm %.3f | %s", step, loss, gnorm,
                    f"{step_tokens / dt:.0f} tok/s" if dt else "compile step",
                )
            if self.val_dataloader is not None and self.step_scheduler.is_val_step_at(step):
                t_pause = time.perf_counter()
                with obs.track("eval"):
                    self._run_validation(step)
                obs.heartbeat(step)
                window_overhead += time.perf_counter() - t_pause
            if (
                self.checkpointer.config.enabled
                and self.step_scheduler.is_ckpt_step_at(step)
                and getattr(self, "_last_saved_step", None) != step
            ):
                # the best-tracking path may have just saved this very step
                t_pause = time.perf_counter()
                with obs.track("checkpoint"):
                    self._save(step)
                obs.heartbeat(step)
                window_overhead += time.perf_counter() - t_pause
            if self.chaos is not None and self.chaos.should_elastic(step):
                # topology-change injection (resilience/chaos.py): checkpoint,
                # then die carrying the resized mesh — the harness restarts the
                # recipe on it and resume takes the elastic restore path
                new_mesh = self.chaos.elastic_change(step)
                if (self.checkpointer.config.enabled
                        and getattr(self, "_last_saved_step", None) != step):
                    with obs.track("checkpoint"):
                        self._save(step)
                self.checkpointer.wait()
                from automodel_tpu.resilience.elastic import ElasticTopologyChange

                raise ElasticTopologyChange(step, new_mesh)
            if self.chaos is not None and self.chaos.should_kill(step):
                # hard process death (resilience/chaos.py): SIGKILL to self,
                # no cleanup — only the supervisor can turn this into a
                # restart-from-newest-verifiable-checkpoint
                self.checkpointer.wait()
                self.chaos.kill(step)
            if self.chaos is not None and self.chaos.should_hang(step):
                # silent hang: stop heartbeating; the supervisor's staleness
                # detector must SIGABRT (capturing the watchdog stack dump)
                self.chaos.hang(step)
            obs.on_step_end(step, sync=metrics.get("loss"))
            # agreed at the CONSUMED step (deterministic across hosts even
            # while the prefetch worker advances the scheduler's own counter)
            if self.step_scheduler.sigterm_agreed_at(step):
                # coordinated preemption (docs/resilience.md): the flag is
                # pod-agreed, so every host reaches this save together.
                # When the remaining grace window is short, the pod agrees
                # to drop the consolidated HF export — the sharded arrays
                # + client state (all that resume needs) still land.
                logger.warning("SIGTERM received; checkpointing and exiting")
                obs.note_event(step, {"event": "preemption"})
                consolidated = None
                if (self.resilience.config.enabled
                        and self.checkpointer.config.save_consolidated
                        and self.resilience.skip_consolidated_export(
                            self.step_scheduler.sigterm_elapsed_s)):
                    consolidated = False
                with obs.track("checkpoint"):
                    self._save(step, consolidated=consolidated)
                return "preempted"

    def _dynamics_host_step(self, obs, step: int, metrics: dict,
                            stack) -> tuple[dict, str | None]:
        """Host half of the dynamics pillar for one step.

        Returns ``(dyn_row, layer_hint)``: the flat ``dynamics/*`` row when
        this step is a cadence (or excursion) sample, else ``{}``; and the
        per-layer attribution — nonfinite provenance when the guard tripped,
        otherwise the flight recorder's EMA-excursion suspect on a loss
        spike — that the resilience verdicts and skip/raise messages cite.

        The per-bucket reductions already ran in-graph; what is gated on the
        cadence here is only the device->host sync of the ~two dozen scalars
        (the overhead contract, docs/observability.md). A loss z-score
        excursion forces an off-cadence sample so the spike report and the
        attribution see the offending step itself, and dumps
        ``spike_report.json`` (never raises) outside its cooldown.
        """
        tracker = obs.dynamics
        if tracker is None or "dynamics" not in metrics:
            return {}, None
        layer_hint = None
        import math as _math

        from automodel_tpu.observability.dynamics import (
            batch_fingerprint,
            first_nonfinite_bucket,
        )

        # the recorder needs the loss each step it observes; piggyback on the
        # per-step sync resilience already pays, else observe on cadence only
        observe = self.resilience.active or tracker.due(step)
        zscore = None
        loss_h = None
        if observe:
            loss_h = float(metrics["loss"])
            zscore = tracker.recorder.observe(step, loss_h)
        dyn_row: dict = {}
        if tracker.due(step) or zscore is not None:
            dyn_row = obs.dynamics_row(step, metrics["dynamics"])
        if "nonfinite_map" in metrics and bool(
                np.asarray(metrics.get("nonfinite", False))):
            layer_hint = first_nonfinite_bucket(metrics["nonfinite_map"])
        if zscore is not None:
            suspect = tracker.stats.suspect()
            if layer_hint is None and suspect is not None:
                layer_hint = suspect[0]
            if not tracker.recorder.in_cooldown(step):
                path = tracker.recorder.dump(
                    step, "loss_zscore", loss=loss_h,
                    zscore=None if _math.isinf(zscore) else round(zscore, 3),
                    suspect=suspect, batch=batch_fingerprint(stack),
                )
                if path is not None:
                    self.resilience.emit(step, "spike_report",
                                         path=path, layer=layer_hint)
        return dyn_row, layer_hint

    def _perform_rollback(self, bad_step: int, obs) -> bool:
        """In-process restore from the newest pod-agreed verifiable checkpoint
        (PaLM-style spike recovery: restore, then skip the offending data
        window). Returns False when no restorable checkpoint exists."""
        self.checkpointer.wait()  # commit any in-flight save before choosing
        with obs.track("rollback"):
            restored = self.checkpointer.load_latest_verified(
                self.train_params, self.opt_state
            )
            if restored is None:
                return False
            self.train_params, self.opt_state, client, to_step = restored
            # the live anomaly counters (rollback budget, skip streak) must
            # survive the restore — reloading them from the checkpoint would
            # reset the budget and let a persistent fault loop forever
            client.pop("resilience", None)
            self._apply_client_state(client)
            # the step counter jumps back to bad_step (monotone logs, LR
            # schedule continues) while the data cursor fast-forwards past the
            # offending window [to_step+1, bad_step] plus skip_steps fresh
            # batches — the PaLM recipe: do not re-feed the data that spiked
            skip = int(self.resilience.config.rollback.skip_steps)
            n_bad = bad_step - self.step_scheduler.step
            self.dataloader.fast_forward(
                max(n_bad + skip, 0) * self.step_scheduler.grad_acc_steps
            )
            self.step_scheduler.step = bad_step
            # fast-forward may have crossed an epoch boundary; the scheduler
            # counts epochs by completed dataloader passes, so re-sync
            self.step_scheduler.epoch = self.dataloader.epoch
            self.resilience.note_rollback(bad_step, to_step, n_bad + skip)
        return True

    def _run_validation(self, step: int):
        # validate on the SAME weights training currently sees: before a delayed
        # QAT start the train step runs un-quantized, so validation must too —
        # a quantized eval there would measure a different model than is being
        # trained and fake a train/val gap until fake_quant_after_n_steps
        qat_active = self._qat_param_fn() is not None and step >= self._qat_start_step
        eval_step = self._eval_steps.get(qat_active)
        if eval_step is None:
            from automodel_tpu.training.train_step import make_eval_step

            # training=False: no aux balance term in validation loss, pure CE
            if self.peft is not None:
                from automodel_tpu.peft.lora import merge_lora_params

                qfn = (self._qat_param_fn() or (lambda p: p)) if qat_active else (lambda p: p)
                eval_loss = lambda lora, base, b, n: self._forward_loss(
                    merge_lora_params(qfn(base), lora, self.peft), b, n, training=False
                )
                eval_step = jax.jit(make_eval_step(eval_loss, with_frozen=True))
            else:
                plain = lambda p, b, n: self._forward_loss(p, b, n, training=False)
                eval_loss = self._qat_wrap(plain) if qat_active else plain
                eval_step = jax.jit(make_eval_step(eval_loss))
            self._eval_steps[qat_active] = eval_step
        total, count = 0.0, 0
        extra = (self.params,) if self.peft is not None else ()
        for batch in self._iter_val_batches():
            n = int((batch["labels"] != -100).sum())
            total += float(eval_step(self.train_params, batch, n, *extra)) * n
            count += n
        self._log_val_loss(step, total, count)

    def _iter_val_batches(self):
        """Bounded, state-neutral pass over the validation loader.

        Restores the loader's resume cursor afterwards so every validation pass
        evaluates the SAME window: breaking out of a streaming loader at
        validation_max_batches would otherwise leave the cursor advanced, and
        each later pass would skip-drain all previously consumed examples and
        score a different (ever further) slice of the stream."""
        import itertools

        dl = self.val_dataloader
        state = dl.state_dict() if hasattr(dl, "state_dict") else None
        try:
            # islice stops BEFORE pulling batch max_val_batches+1: no wasted
            # fetch+collate (expensive for VLM patchify/mel collators)
            yield from itertools.islice(dl, self.max_val_batches)
        finally:
            if state is not None and hasattr(dl, "load_state_dict"):
                dl.load_state_dict(state)

    def _log_val_loss(self, step: int, total: float, count: float,
                      extra_sums: dict[str, float] | None = None):
        """Token-weighted mean aggregated across the pod: each process sees a
        different dataloader shard, so a host-local mean would log a different
        val_loss per host (reference allreduces val loss the same way,
        train_ft.py:1456). ``extra_sums``: additional per-example metric SUMS
        sharing ``count`` as denominator (biencoder acc@1/recall@k/MRR) —
        summed across hosts like the loss."""
        extra_sums = extra_sums or {}
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils

            # ship each host sum as an f32 hi/lo (Dekker) pair and rebuild in
            # np.float64 on the host: jnp.float64 silently downcasts to f32
            # without jax_enable_x64, which loses the low-order bits of large
            # token-weighted loss sums exactly when the pod is big enough for
            # them to matter
            vals = np.asarray([total, float(count), *extra_sums.values()],
                              np.float64)
            hi = vals.astype(np.float32)
            lo = (vals - hi.astype(np.float64)).astype(np.float32)
            agg = np.asarray(multihost_utils.process_allgather(
                jnp.asarray(np.stack([hi, lo]), jnp.float32)))
            # agg: [hosts, 2, K] -> exact per-host f64 values, summed in f64
            sums = (agg[:, 0, :].astype(np.float64)
                    + agg[:, 1, :].astype(np.float64)).sum(axis=0)
            total, count = float(sums[0]), float(sums[1])
            extra_sums = {k: float(sums[2 + i])
                          for i, k in enumerate(extra_sums)}
        if count:
            val_loss = total / count
            extras = {k: v / count for k, v in extra_sums.items()}
            self.val_metric_logger.log(step, val_loss=val_loss, **extras)
            for lg in self.experiment_loggers:
                lg.log(step, val_loss=val_loss, **extras)
            logger.info("validation @ step %d: loss %.4f%s", step, val_loss,
                        "".join(f" | {k} {v:.4f}" for k, v in extras.items()))
            # best-checkpoint tracking (reference base_recipe.py:383-425): save
            # the improving step and point the `best` symlink at it. is_best()
            # decides on process 0 and broadcasts internally — per-host
            # filesystem reads can skew, and orbax save is a collective, so a
            # split decision would deadlock the pod.
            if self.checkpointer.config.enabled and bool(self.cfg.get("checkpoint.save_best", True)):
                if self.checkpointer.is_best(val_loss):
                    self._save(step)
                    self.checkpointer.mark_best(step, val_loss)

    def _save(self, step: int, consolidated: bool | None = None):
        """PEFT saves are adapter-only (reference PEFT checkpoint addon,
        checkpoint/addons.py); consolidated HF export merges the adapter so the
        output is a plain HF model either way. ``consolidated=False`` drops the
        HF export for this save (preemption under a short grace window)."""
        self._last_saved_step = step
        client = {
            "rng": self.rng,
            "step_scheduler": self.step_scheduler,
            "dataloader": self.dataloader,
            "resilience": self.resilience,
        }
        if self._pipeline is not None:
            # prefetch: the live scheduler/dataloader have been advanced past
            # the consumed step by the worker — checkpoint the consumed-position
            # snapshots instead, so resume replays every in-flight batch
            client.update(self._pipeline.client_states())
        do_consolidated = (self.checkpointer.config.save_consolidated
                           if consolidated is None else consolidated)
        hf_params = None
        if self.peft is not None:
            client["peft_config"] = self.peft.to_dict()
            if do_consolidated:
                hf_params = self._merge_lora(self.params, self.train_params)
        d = self.checkpointer.save(
            step, self.train_params, self.opt_state, client_states=client,
            hf_params=hf_params, consolidated=consolidated,
        )
        self.resilience.record_checkpoint(step)
        if d and self.chaos is not None and self.chaos.should_kill(step, point="save"):
            # torn-write injection: with async save the arrays are still
            # in flight and the manifest/latest commit has NOT happened — the
            # restart must reject this step and walk back (checkpointing.py)
            self.chaos.kill(step)
        if d and self.chaos is not None and self.chaos.should_corrupt(step):
            # fault injection: finalize first (manifest written, latest committed)
            # so the truncation exercises verify-and-walk-back, not a half save
            self.checkpointer.wait()
            self.chaos.corrupt_checkpoint(step, d)
        if d and self.peft is not None and do_consolidated:
            # adapter-only HF PEFT export alongside the merged model: deployable
            # via peft.PeftModel without shipping base weights
            from automodel_tpu.checkpoint.checkpointing import _full_host_array
            from automodel_tpu.checkpoint.peft_export import save_peft_adapter

            save_peft_adapter(
                os.path.join(d, "hf_adapter"), self.train_params, self.peft,
                self.model.state_dict_adapter().entries,
                host_fn=_full_host_array,
                base_model_name=self.cfg.get("model.pretrained_model_name_or_path"),
                write=jax.process_index() == 0,
            )


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    return recipe


if __name__ == "__main__":
    main()
