"""AutoModel factory — the day-0 HF entry point
(reference NeMoAutoModelForCausalLM, _transformers/auto_model.py:583,340,480).

``from_pretrained(path)`` reads an HF model directory (config.json + safetensors),
resolves the family via the architecture registry, and loads weights through the
family's state-dict adapter — directly into (optionally sharded) jax arrays; there is
no intermediate torch model and no meta-device dance (jax.eval_shape covers abstract
init natively).
"""

from __future__ import annotations

import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.checkpoint.safetensors_io import load_safetensors
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.registry import resolve_model_class

logger = logging.getLogger(__name__)

__all__ = ["AutoModelForCausalLM", "AutoModelForImageTextToText", "load_hf_config"]


def load_hf_config(path: str) -> dict:
    with open(os.path.join(path, "config.json")) as f:
        return json.load(f)


class AutoModelForCausalLM:
    """Build a model (+ params) from an HF checkpoint directory or config dict."""

    _default_architecture = "LlamaForCausalLM"

    @classmethod
    def from_config(cls, config: dict, backend: BackendConfig | None = None):
        arch = (config.get("architectures") or [cls._default_architecture])[0]
        try:
            model_cls = resolve_model_class(arch)
        except KeyError as registry_err:
            # day-0 coverage for unregistered llama-delta architectures
            # (reference model_init.py:89 wraps any HF class; structural.py is
            # the torch-free equivalent — alias or fail naming the field)
            from automodel_tpu.models.structural import (
                StructuralDivergence, resolve_llama_delta,
            )

            try:
                return resolve_llama_delta(arch, config, backend)
            except StructuralDivergence as diverged:
                raise KeyError(f"{registry_err.args[0]} Auto-alias also failed: "
                               f"{diverged}") from diverged
        return model_cls.from_config(config, backend)

    @classmethod
    def from_pretrained(
        cls,
        path: str,
        backend: BackendConfig | None = None,
        dtype=jnp.bfloat16,
        rules=None,
        return_params: bool = True,
    ):
        """Load model + params from an HF dir.

        When ``rules`` (a mesh-bound ShardingRules) is given, each param lands directly
        on devices with its PartitionSpec — per-tensor host->device streaming, never a
        full replicated copy (reference load-before-shard rules,
        _transformers/infrastructure.py:397-403).

        ``path`` may be a local HF directory or a hub repo id
        (``meta-llama/Llama-3.2-1B``): ids resolve through a process-0-first
        snapshot download (models/hub.py; reference model_init.py:194).
        """
        from automodel_tpu.models.hub import resolve_pretrained_path

        path = resolve_pretrained_path(path)
        config = load_hf_config(path)
        model = cls.from_config(config, backend)
        if not return_params:
            return model
        adapter = model.state_dict_adapter()
        tensors = load_safetensors(path)
        host_params = adapter.from_hf(tensors, dtype=_np_dtype(dtype))
        params = _place(host_params, model, rules)
        return model, params


class AutoModelForImageTextToText(AutoModelForCausalLM):
    """VLM factory (reference NeMoAutoModelForImageTextToText, auto_model.py:614).

    Same registry/load machinery — VLM architectures (LLaVA, ...) register next to
    the causal families; only the default architecture fallback differs.
    """

    _default_architecture = "LlavaForConditionalGeneration"


def _np_dtype(dtype):
    import ml_dtypes  # ships with jax

    return np.dtype(dtype) if dtype is not None else None


def _place(host_params, model, rules):
    """Host numpy tree -> device arrays, sharded per the model's logical axes."""
    if rules is None or rules.mesh is None:
        return jax.tree.map(jnp.asarray, host_params)
    axes = model.logical_axes()

    def put(x, logical):
        return jax.device_put(x, rules.sharding(logical))

    return jax.tree.map(
        put, host_params, axes,
        is_leaf=lambda x: isinstance(x, (np.ndarray, jnp.ndarray)),
    )
