"""Qwen3-MoE family — TPU-native (reference models/qwen3_moe/model.py).

Qwen3 dense attention (qk_norm, head_dim override) + softmax-before-topk routing with
optional top-k renorm, every layer MoE (decoder_sparse_step=1; sparse-step/mlp_only
patterns other than a dense prefix are rejected — none of the released checkpoints use
them). Also serves Qwen2-MoE-style configs without shared experts.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.moe_transformer import (
    MoEDecoderConfig,
    init_moe_decoder_params,
    moe_decoder_forward,
    moe_decoder_logical_axes,
)
from automodel_tpu.moe.config import MoEConfig

__all__ = ["Qwen3MoeConfig", "Qwen3MoeForCausalLM"]


@dataclasses.dataclass
class Qwen3MoeConfig(MoEDecoderConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3MoeConfig":
        n_layers = hf["num_hidden_layers"]
        mlp_only = hf.get("mlp_only_layers") or []
        sparse_step = hf.get("decoder_sparse_step", 1)
        # Support dense-prefix patterns only (all released Qwen3-MoE ckpts are all-MoE).
        moe_flags = [
            (i not in mlp_only) and sparse_step > 0 and ((i + 1) % sparse_step == 0)
            for i in range(n_layers)
        ]
        first_dense = moe_flags.index(True) if any(moe_flags) else n_layers
        if not all(moe_flags[first_dense:]):
            raise NotImplementedError("non-prefix dense/MoE interleavings are not supported")
        moe = MoEConfig(
            n_routed_experts=hf["num_experts"],
            n_activated_experts=hf["num_experts_per_tok"],
            dim=hf["hidden_size"],
            moe_inter_dim=hf["moe_intermediate_size"],
            score_func="softmax",
            softmax_before_topk=True,
            norm_topk_prob=hf.get("norm_topk_prob", False),
            aux_loss_coeff=hf.get("router_aux_loss_coef", 0.0),
        )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=n_layers,
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling=hf.get("rope_scaling"),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            attention_bias=hf.get("attention_bias", False),
            qk_norm=True,
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
            first_k_dense_replace=first_dense,
        )


class Qwen3MoeForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = Qwen3MoeConfig
    hf_architectures = ("Qwen3MoeForCausalLM",)

    def __init__(self, config: Qwen3MoeConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        return init_moe_decoder_params(self.config, key, dtype)

    def logical_axes(self) -> dict:
        return moe_decoder_logical_axes(self.config)

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None,
                 inputs_embeds=None):
        return moe_decoder_forward(
            self.config, self.backend, params, input_ids,
            positions=positions, segment_ids=segment_ids, token_mask=token_mask,
            rules=rules, return_hidden=return_hidden, training=training, cache=cache,
            inputs_embeds=inputs_embeds,
        )

    def generate(self, params, input_ids, **kw):
        """Sample with a KV cache (see :func:`automodel_tpu.generation.generate`)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    def state_dict_adapter(self):
        from automodel_tpu.models.qwen3_moe.state_dict_adapter import Qwen3MoeStateDictAdapter

        return Qwen3MoeStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = Qwen3MoeConfig.from_hf(config)
        return cls(config, backend)
