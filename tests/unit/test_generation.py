"""KV-cache generation: cache-vs-full-recompute parity, HF greedy parity, uneven
right-padded prompts, sampling controls, MoE decode, and the MLA fence.

Reference analogue: the reference reaches generation through HF modules'
``.generate()`` (examples/vlm_generate/vlm_generate.py:1); here the decode loop
is native (generation/__init__.py) so parity is checked both internally (cache
decode == full-forward argmax at every step) and externally (HF greedy match).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.generation import generate, init_kv_cache, sample_token
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM


def _tiny_llama(seed=0, **kw):
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128, **kw,
    )
    model = LlamaForCausalLM(cfg, BackendConfig(dtype="float32", remat_policy="none"))
    params = model.init(jax.random.key(seed), jnp.float32)
    return model, params


def _full_greedy(model, params, prompt_rows, n_new):
    """Reference decode: re-run the FULL forward over the growing sequence."""
    outs = []
    for row in prompt_rows:
        ids = list(row)
        for _ in range(n_new):
            x = jnp.asarray([ids], jnp.int32)
            logits = model(params, x, segment_ids=jnp.ones_like(x))
            ids.append(int(np.asarray(logits)[0, -1].argmax()))
        outs.append(ids[len(row):])
    return np.asarray(outs, np.int32)


def _greedy_full_stats(model, params, row, n_new):
    """Reference decode for (logits, stats)-returning models: full forward over
    the growing sequence, eval-mode gating."""
    ids = list(row)
    for _ in range(n_new):
        x = jnp.asarray([ids], jnp.int32)
        logits, _ = model(params, x, segment_ids=jnp.ones_like(x), training=False)
        ids.append(int(np.asarray(logits)[0, -1].argmax()))
    return ids[len(row):]



class TestCacheParity:
    def test_greedy_matches_full_recompute(self):
        model, params = _tiny_llama()
        rng = np.random.RandomState(0)
        prompts = rng.randint(0, 128, (2, 7)).astype(np.int32)
        want = _full_greedy(model, params, prompts, n_new=8)
        got = generate(model, params, prompts, max_new_tokens=8, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)
        assert got["sequences"].shape == (2, 15)

    def test_uneven_right_padded_prompts(self):
        model, params = _tiny_llama(seed=3)
        rng = np.random.RandomState(1)
        rows = [rng.randint(1, 128, (5,)), rng.randint(1, 128, (9,))]
        want = _full_greedy(model, params, rows, n_new=6)
        s = max(len(r) for r in rows)
        ids = np.zeros((2, s), np.int32)
        mask = np.zeros((2, s), np.int32)
        for i, r in enumerate(rows):
            ids[i, :len(r)] = r
            mask[i, :len(r)] = 1
        got = generate(model, params, ids, attention_mask=mask,
                       max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)

    def test_sliding_window_cache_decode(self):
        model, params = _tiny_llama(seed=5, sliding_window=4,
                                    layer_types=["sliding_attention", "full_attention"])
        rng = np.random.RandomState(2)
        prompts = rng.randint(0, 128, (1, 10)).astype(np.int32)
        want = _full_greedy(model, params, prompts, n_new=5)
        got = generate(model, params, prompts, max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)


class TestSampling:
    def test_eos_stops_and_pads(self):
        model, params = _tiny_llama()
        prompts = np.random.RandomState(0).randint(0, 128, (2, 4)).astype(np.int32)
        ref = generate(model, params, prompts, max_new_tokens=8, temperature=0.0)
        eos = int(np.asarray(ref["tokens"])[0, 2])  # force an early stop on row 0
        got = generate(model, params, prompts, max_new_tokens=8, temperature=0.0,
                       eos_token_id=eos, pad_token_id=0)
        toks = np.asarray(got["tokens"])
        row = toks[0]
        stop = int(np.asarray(got["lengths"])[0])
        assert row[stop - 1] == eos
        assert (row[stop:] == 0).all()

    def test_temperature_topk_topp_in_vocab(self):
        model, params = _tiny_llama()
        prompts = np.random.RandomState(0).randint(0, 128, (2, 4)).astype(np.int32)
        got = generate(model, params, prompts, max_new_tokens=6, temperature=0.8,
                       top_k=20, top_p=0.9, seed=7)
        toks = np.asarray(got["tokens"])
        assert ((toks >= 0) & (toks < 128)).all()

    def test_top_p_cuts_tail(self):
        # peaked logits: top_p keeps only the dominant token
        logits = jnp.asarray([[10.0, 0.0, -1.0, -2.0]])
        tok = sample_token(logits, jax.random.key(0), temperature=1.0, top_p=0.5)
        assert int(tok[0]) == 0


class TestMoEDecode:
    def test_qwen3_moe_cache_matches_full(self):
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["Qwen3MoeForCausalLM"],
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "moe_intermediate_size": 32, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
            "num_experts": 4, "num_experts_per_tok": 2, "norm_topk_prob": True,
            "max_position_embeddings": 64,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none")
        )
        params = model.init(jax.random.key(2), jnp.float32)
        rng = np.random.RandomState(4)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32)
        got = generate(model, params, prompts, max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)

    def test_olmo2_post_norm_cache_matches_full(self):
        """The post-norm (olmo2) block's decode branch: attention reads raw h,
        norms apply to sublayer outputs — cache decode == full recompute."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["Olmo2ForCausalLM"],
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "rms_norm_eps": 1e-5,
            "max_position_embeddings": 64,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none"))
        params = model.init(jax.random.key(13), jnp.float32)
        prompts = np.random.RandomState(14).randint(0, 128, (2, 6)).astype(np.int32)
        want = _full_greedy(model, params, prompts, 5)
        got = generate(model, params, prompts, max_new_tokens=5,
                       cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)

    def test_glm4_sandwich_cache_matches_full(self):
        """GLM4's sandwich norms + interleaved partial rope through the decode
        cache == full recompute."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["Glm4ForCausalLM"],
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "rms_norm_eps": 1e-5,
            "partial_rotary_factor": 0.5, "max_position_embeddings": 64,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none"))
        params = model.init(jax.random.key(21), jnp.float32)
        prompts = np.random.RandomState(22).randint(0, 128, (2, 6)).astype(np.int32)
        want = _full_greedy(model, params, prompts, 5)
        got = generate(model, params, prompts, max_new_tokens=5,
                       cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)

    def test_cohere_parallel_block_cache_matches_full(self):
        """Cohere's parallel attn||mlp block + centered LN + interleaved rope
        through the decode cache path == full recompute."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["CohereForCausalLM"],
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "layer_norm_eps": 1e-5,
            "logit_scale": 0.0625, "max_position_embeddings": 64,
            "tie_word_embeddings": True,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none"))
        params = model.init(jax.random.key(17), jnp.float32)
        prompts = np.random.RandomState(18).randint(0, 128, (2, 6)).astype(np.int32)
        want = _full_greedy(model, params, prompts, 5)
        got = generate(model, params, prompts, max_new_tokens=5,
                       cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), want)

    def test_moe_inputs_embeds_eos_in_one_path(self):
        """inputs_embeds + eos + MoE composed (a VERDICT r3 breadth gap): the
        embeds-prefill must reproduce the ids-prefill exactly, and eos padding
        applies on top."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["Qwen3MoeForCausalLM"],
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "moe_intermediate_size": 32, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
            "num_experts": 4, "num_experts_per_tok": 2, "norm_topk_prob": True,
            "max_position_embeddings": 64,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none")
        )
        params = model.init(jax.random.key(2), jnp.float32)
        rng = np.random.RandomState(11)
        prompts = rng.randint(2, 128, (2, 6)).astype(np.int32)

        ref = generate(model, params, prompts, max_new_tokens=8, temperature=0.0,
                       cache_dtype=jnp.float32)
        # eos = the first greedily generated token of row 0 -> that row must
        # stop immediately and pad the rest
        eos = int(ref["tokens"][0, 0])
        embeds = jnp.asarray(params["embed"])[prompts]
        got = generate(model, params, prompts, inputs_embeds=embeds,
                       max_new_tokens=8, temperature=0.0, eos_token_id=eos,
                       pad_token_id=0, cache_dtype=jnp.float32)
        assert int(got["tokens"][0, 0]) == eos
        assert int(got["lengths"][0]) == 1
        np.testing.assert_array_equal(np.asarray(got["tokens"][0, 1:]), 0)
        # the other row follows the ids-path trajectory until (if ever) eos
        their = np.asarray(ref["tokens"][1])
        mine = np.asarray(got["tokens"][1])
        upto = np.argmax(their == eos) if (their == eos).any() else len(their)
        np.testing.assert_array_equal(mine[:upto], their[:upto])

    def test_cacheless_model_raises(self):
        """Forwards without a cache parameter point at HF export instead of
        TypeError-ing inside jit (every shipped causal family now decodes, so
        this guards the contract for future/external models)."""

        class _Cfg:
            num_hidden_layers = 2

        class _Model:
            config = _Cfg()

            def __call__(self, params, input_ids, positions=None, segment_ids=None):
                raise AssertionError("must not be called")

        with pytest.raises(NotImplementedError, match="no cache path"):
            generate(_Model(), {}, np.zeros((1, 4), np.int32), max_new_tokens=2)

    def test_gpt2_cache_matches_full(self):
        """Learned-positional-embedding decode (GPT-2 MHA) == full recompute."""
        from automodel_tpu.models.gpt2.model import GPT2Config, GPT2LMHeadModel

        cfg = GPT2Config(vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64)
        model = GPT2LMHeadModel(cfg, BackendConfig(dtype="float32", remat_policy="full"))
        params = model.init(jax.random.key(22), jnp.float32)
        prompts = np.random.RandomState(23).randint(0, 128, (2, 6)).astype(np.int32)

        def full(row, n_new):
            ids = list(row)
            for _ in range(n_new):
                x = jnp.asarray([ids], jnp.int32)
                logits = model(params, x, segment_ids=jnp.ones_like(x))
                ids.append(int(np.asarray(logits)[0, -1].argmax()))
            return ids[len(row):]

        want = np.asarray([full(r, 5) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)


class TestHFParity:
    def test_greedy_matches_hf_generate(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")
        from automodel_tpu.models.auto import AutoModelForCausalLM

        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
        )
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(cfg).eval()
        d = str(tmp_path / "hf")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32,
            backend=BackendConfig(dtype="float32", remat_policy="none"),
        )
        ids = np.random.RandomState(0).randint(0, 128, (2, 8))
        with torch.no_grad():
            theirs = hf.generate(
                torch.tensor(ids), max_new_tokens=8, do_sample=False,
                pad_token_id=0,
            )[:, 8:].numpy()
        got = generate(model, params, ids, max_new_tokens=8, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(got["tokens"]), theirs)


class TestVLMGenerate:
    def test_llava_image_conditioned_greedy(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")
        from automodel_tpu.models.auto import AutoModelForImageTextToText

        IMAGE_TOKEN = 120
        cfg = transformers.LlavaConfig(
            vision_config=transformers.CLIPVisionConfig(
                hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                num_attention_heads=4, image_size=28, patch_size=14,
            ),
            text_config=transformers.LlamaConfig(
                vocab_size=128, hidden_size=48, intermediate_size=96,
                num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                max_position_embeddings=64,
            ),
            image_token_index=IMAGE_TOKEN,
            vision_feature_layer=-2,
            vision_feature_select_strategy="default",
        )
        torch.manual_seed(0)
        hf = transformers.LlavaForConditionalGeneration(cfg).eval()
        d = str(tmp_path / "hf")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForImageTextToText.from_pretrained(
            d, dtype=jnp.float32, backend=BackendConfig(dtype="float32")
        )
        rng = np.random.RandomState(0)
        # prompt: 4 image placeholders + 4 text tokens
        ids = np.concatenate(
            [np.full((1, 4), IMAGE_TOKEN), rng.randint(0, 100, (1, 4))], axis=1
        ).astype(np.int32)
        pixels = jnp.asarray(rng.randn(1, 3, 28, 28).astype(np.float32))

        got = model.generate(params, ids, pixel_values=pixels,
                             max_new_tokens=6, temperature=0.0)
        # reference: HF generate greedy with the same inputs
        with torch.no_grad():
            theirs = hf.generate(
                input_ids=torch.tensor(ids), pixel_values=torch.tensor(np.asarray(pixels)),
                max_new_tokens=6, do_sample=False, pad_token_id=0,
            )[:, ids.shape[1]:].numpy()
        np.testing.assert_array_equal(np.asarray(got["tokens"]), theirs)


class TestMLADecode:
    def test_deepseek_v3_cache_matches_full(self):
        """MLA expanded-head cache decode == full recompute, greedy."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["DeepseekV3ForCausalLM"],
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "moe_intermediate_size": 32, "num_hidden_layers": 3,
            "num_attention_heads": 4, "q_lora_rank": 24, "kv_lora_rank": 32,
            "qk_nope_head_dim": 16, "qk_rope_head_dim": 8, "v_head_dim": 16,
            "n_routed_experts": 8, "num_experts_per_tok": 2, "n_shared_experts": 1,
            "norm_topk_prob": True, "first_k_dense_replace": 1,
            "max_position_embeddings": 64, "rope_scaling": None,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none")
        )
        params = model.init(jax.random.key(3), jnp.float32)
        rng = np.random.RandomState(5)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)

    def test_deepseek_v32_indexer_cache_matches_full(self):
        """DSv32 sparse-indexer decode (the last r3 generation fence): the
        per-layer idx_k cache + incremental top-k bias must reproduce the
        training-mode dense (S,S) selection — greedy tokens equal full
        recompute. index_topk=4 < sequence length so sparsity actually bites."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["DeepseekV32ForCausalLM"],
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "moe_intermediate_size": 32, "num_hidden_layers": 3,
            "num_attention_heads": 4, "q_lora_rank": 24, "kv_lora_rank": 32,
            "qk_nope_head_dim": 16, "qk_rope_head_dim": 8, "v_head_dim": 16,
            "n_routed_experts": 8, "num_experts_per_tok": 2, "n_shared_experts": 1,
            "norm_topk_prob": True, "first_k_dense_replace": 1,
            "index_n_heads": 4, "index_head_dim": 32, "index_topk": 4,
            "max_position_embeddings": 64, "rope_scaling": None,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none")
        )
        params = model.init(jax.random.key(7), jnp.float32)
        rng = np.random.RandomState(9)
        prompts = rng.randint(0, 128, (2, 8)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)

    def test_uneven_padded_prompts(self):
        from automodel_tpu.models.auto import AutoModelForCausalLM

        hf_cfg = {
            "architectures": ["DeepseekV3ForCausalLM"],
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "moe_intermediate_size": 32, "num_hidden_layers": 2,
            "num_attention_heads": 4, "q_lora_rank": None, "kv_lora_rank": 32,
            "qk_nope_head_dim": 16, "qk_rope_head_dim": 8, "v_head_dim": 16,
            "n_routed_experts": 4, "num_experts_per_tok": 2, "n_shared_experts": 0,
            "norm_topk_prob": True, "first_k_dense_replace": 0,
            "max_position_embeddings": 64, "rope_scaling": None,
        }
        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none")
        )
        params = model.init(jax.random.key(4), jnp.float32)
        rng = np.random.RandomState(6)
        # row 1 is shorter, right-padded
        ids = rng.randint(1, 128, (2, 6)).astype(np.int32)
        mask = np.ones((2, 6), np.int32)
        ids[1, 4:] = 0
        mask[1, 4:] = 0

        out = model.generate(params, ids, attention_mask=mask, max_new_tokens=1,
                             cache_dtype=jnp.float32)
        assert int(out["tokens"][0, 0]) == _greedy_full_stats(model, params, list(ids[0]), 1)[0]
        assert int(out["tokens"][1, 0]) == _greedy_full_stats(model, params, list(ids[1, :4]), 1)[0]


class TestHybridDecode:
    def _tiny_next(self):
        from automodel_tpu.models.auto import AutoModelForCausalLM

        return AutoModelForCausalLM.from_config(
            {"architectures": ["Qwen3NextForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 96, "moe_intermediate_size": 32,
             "shared_expert_intermediate_size": 32, "num_hidden_layers": 4,
             "full_attention_interval": 4, "num_attention_heads": 4,
             "num_key_value_heads": 2, "head_dim": 16,
             "linear_num_value_heads": 4, "linear_num_key_heads": 2,
             "linear_key_head_dim": 16, "linear_value_head_dim": 16,
             "linear_conv_kernel_dim": 4, "num_experts": 4,
             "num_experts_per_tok": 2, "norm_topk_prob": True,
             "max_position_embeddings": 64},
            BackendConfig(dtype="float32", remat_policy="none"),
        )

    def test_qwen3_next_cache_matches_full(self):
        """Hybrid decode (conv taps + delta-rule state + KV for the periodic
        full-attention layer) == full recompute, greedy."""
        model = self._tiny_next()
        params = model.init(jax.random.key(9), jnp.float32)
        rng = np.random.RandomState(10)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)

    def test_uneven_padded_prompts(self):
        """Right-padding must not pollute the conv taps or the recurrent state."""
        model = self._tiny_next()
        params = model.init(jax.random.key(11), jnp.float32)
        rng = np.random.RandomState(12)
        ids = rng.randint(1, 128, (2, 7)).astype(np.int32)
        mask = np.ones((2, 7), np.int32)
        ids[1, 4:] = 0
        mask[1, 4:] = 0

        out = model.generate(params, ids, attention_mask=mask, max_new_tokens=1,
                             cache_dtype=jnp.float32)
        assert int(out["tokens"][0, 0]) == _greedy_full_stats(model, params, list(ids[0]), 1)[0]
        assert int(out["tokens"][1, 0]) == _greedy_full_stats(model, params, list(ids[1, :4]), 1)[0]


class TestNemotronDecode:
    def _tiny(self):
        from automodel_tpu.models.nemotron_v3.model import NemotronHForCausalLM, NemotronV3Config
        from automodel_tpu.moe.config import MoEConfig

        cfg = NemotronV3Config(
            vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=4,
            layers_block_type=("mamba", "attention", "mlp", "moe"),
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            mamba_num_heads=4, mamba_head_dim=8, ssm_state_size=16, n_groups=2,
            chunk_size=16, conv_kernel=4,
            moe=MoEConfig(
                n_routed_experts=4, n_activated_experts=2, dim=64, moe_inter_dim=32,
                score_func="sigmoid", expert_activation="relu2",
            ),
        )
        model = NemotronHForCausalLM(cfg, BackendConfig(dtype="float32", remat_policy="full"))
        return model, model.init(jax.random.key(13), jnp.float32)

    def test_cache_matches_full(self):
        """Mamba2 SSD state + conv taps + KV decode == full recompute, greedy."""
        model, params = self._tiny()
        rng = np.random.RandomState(14)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)

    def test_uneven_padded_prompts(self):
        model, params = self._tiny()
        rng = np.random.RandomState(15)
        ids = rng.randint(1, 128, (2, 7)).astype(np.int32)
        mask = np.ones((2, 7), np.int32)
        ids[1, 3:] = 0
        mask[1, 3:] = 0

        out = model.generate(params, ids, attention_mask=mask, max_new_tokens=1,
                             cache_dtype=jnp.float32)
        assert int(out["tokens"][0, 0]) == _greedy_full_stats(model, params, list(ids[0]), 1)[0]
        assert int(out["tokens"][1, 0]) == _greedy_full_stats(model, params, list(ids[1, :3]), 1)[0]


class TestMixedGeometryDecode:
    def test_step3p5_cache_matches_full(self):
        """Per-layer KV tuples (sliding layers use different head counts) decode
        == full recompute across the mixed geometries + head-wise gate + MoE."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_config(
            {"architectures": ["Step3p5ForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 96, "num_hidden_layers": 4,
             "num_attention_heads": 4, "num_attention_groups": 2, "head_dim": 16,
             "layer_types": ["full_attention", "sliding_attention",
                             "full_attention", "sliding_attention"],
             "attention_other_setting": {"num_attention_heads": 2, "num_attention_groups": 1},
             "sliding_window": 4, "use_head_wise_attn_gate": True,
             "moe_layers_enum": "2,3", "moe_num_experts": 4, "moe_top_k": 2,
             "moe_intermediate_size": 32, "share_expert_dims": 48,
             "max_position_embeddings": 64},
            BackendConfig(dtype="float32", remat_policy="none"),
        )
        params = model.init(jax.random.key(16), jnp.float32)
        rng = np.random.RandomState(17)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)

    def test_gpt_oss_sinks_sliding_decode(self):
        """gpt-oss decode: sinks + alternating sliding windows through the
        common MoE stack's cache path."""
        from automodel_tpu.models.auto import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_config(
            {"architectures": ["GptOssForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 48, "num_hidden_layers": 2,
             "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
             "num_local_experts": 4, "num_experts_per_tok": 2, "sliding_window": 4,
             "layer_types": ["sliding_attention", "full_attention"],
             "max_position_embeddings": 64, "swiglu_limit": 7.0},
            BackendConfig(dtype="float32", remat_policy="none"),
        )
        params = model.init(jax.random.key(18), jnp.float32)
        rng = np.random.RandomState(19)
        prompts = rng.randint(0, 128, (2, 6)).astype(np.int32)

        want = np.asarray([_greedy_full_stats(model, params, r, 6) for r in prompts], np.int32)
        out = model.generate(params, prompts, max_new_tokens=6, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)


class TestCommonMoEStackDecodeVariants:
    """Decode parity for the remaining common-MoE-stack geometries: GLM4-MoE
    (qk-norm + attention bias + partial rotary + dense prefix) and MiniMax-M2."""

    def _parity(self, hf_cfg, seed):
        from automodel_tpu.models.auto import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_config(
            hf_cfg, BackendConfig(dtype="float32", remat_policy="none")
        )
        params = model.init(jax.random.key(seed), jnp.float32)
        prompts = np.random.RandomState(seed).randint(0, 128, (2, 6)).astype(np.int32)
        want = np.asarray(
            [_greedy_full_stats(model, params, r, 5) for r in prompts], np.int32
        )
        out = model.generate(params, prompts, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)

    def test_glm4_moe(self):
        self._parity(
            {"architectures": ["Glm4MoeForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 96, "moe_intermediate_size": 32,
             "num_hidden_layers": 2, "first_k_dense_replace": 1,
             "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
             "partial_rotary_factor": 0.5, "use_qk_norm": True, "attention_bias": True,
             "n_routed_experts": 4, "num_experts_per_tok": 2, "n_shared_experts": 1,
             "norm_topk_prob": True, "max_position_embeddings": 64},
            seed=20,
        )

    def test_minimax_m2(self):
        self._parity(
            {"architectures": ["MiniMaxM2ForCausalLM"], "vocab_size": 128,
             "hidden_size": 64, "intermediate_size": 96, "moe_intermediate_size": 32,
             "num_hidden_layers": 2, "num_attention_heads": 4,
             "num_key_value_heads": 2, "head_dim": 16, "rotary_dim": 8,
             "num_local_experts": 4, "num_experts_per_tok": 2,
             "scoring_func": "sigmoid", "use_qk_norm": True,
             "max_position_embeddings": 64},
            seed=21,
        )
