"""CLI argument parsing with dotted config overrides.

Parity with reference ``components/config/_arg_parser.py:20,77``: a recipe accepts
``-c/--config path.yaml`` plus any number of ``--section.key value`` overrides
(``--flag`` with no value sets True; ``--key=value`` also accepted).
"""

from __future__ import annotations

import sys
from typing import Sequence

from automodel_tpu.config.loader import ConfigNode, load_config, translate_value

__all__ = ["parse_args_and_load_config", "parse_cli_argv"]


def _normalize_key(key: str) -> str:
    """``--micro-batch-size`` and ``--micro_batch_size`` address the same key."""
    return ".".join(seg.replace("-", "_") for seg in key.split("."))


def parse_cli_argv(argv: Sequence[str]) -> tuple[str | None, list[tuple[str, object]]]:
    """Split argv into (config_path, [(dotted_key, value), ...])."""
    config_path: str | None = None
    overrides: list[tuple[str, object]] = []
    i = 0
    argv = list(argv)
    while i < len(argv):
        arg = argv[i]
        if arg in ("-c", "--config"):
            if i + 1 >= len(argv):
                raise ValueError(f"{arg} requires a value")
            config_path = argv[i + 1]
            i += 2
        elif arg.startswith("--"):
            key = arg[2:]
            if "=" in key:
                key, raw = key.split("=", 1)
                overrides.append((_normalize_key(key), translate_value(raw)))
                i += 1
            elif i + 1 < len(argv) and not argv[i + 1].startswith("--"):
                overrides.append((_normalize_key(key), translate_value(argv[i + 1])))
                i += 2
            else:
                overrides.append((_normalize_key(key), True))
                i += 1
        else:
            raise ValueError(f"unexpected positional argument {arg!r}")
    return config_path, overrides


def parse_args_and_load_config(argv: Sequence[str] | None = None, default_config: str | None = None) -> ConfigNode:
    """Parse ``-c cfg.yaml --a.b.c v ...`` and return the merged ConfigNode."""
    if argv is None:
        argv = sys.argv[1:]
    config_path, overrides = parse_cli_argv(argv)
    if config_path is None:
        config_path = default_config
    if config_path is None:
        raise ValueError("no config file given (use -c/--config)")
    cfg = load_config(config_path)
    for key, value in overrides:
        cfg.set_by_path(key, value)
    return cfg
