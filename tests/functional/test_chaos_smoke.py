"""Pytest entry for the chaos smoke (tools/chaos_smoke.py, docs/resilience.md).

Marked ``chaos`` + ``slow`` so it stays out of the tier-1 ``-m 'not slow'``
suite; run explicitly with ``pytest -m chaos``. The fast-path coverage of the
same machinery lives in tests/functional/test_train_recipe.py::TestResilience.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tools"))


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_smoke(tmp_path, cpu_devices):
    import chaos_smoke

    assert chaos_smoke.main(str(tmp_path)) == 0
