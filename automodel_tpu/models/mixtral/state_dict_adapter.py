"""Mixtral HF key/layout mapping.

Same stacked-expert layout as the Qwen3-MoE adapter, but HF Mixtral names the MoE
block ``block_sparse_moe`` and its expert projections w1 (gate) / w3 (up) / w2 (down)
(transformers MixtralSparseMoeBlock).
"""

from __future__ import annotations

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.qwen3_moe.state_dict_adapter import (
    _gate_up_in,
    _gate_up_out,
    _t,
    attention_entries,
)
from automodel_tpu.models.common.moe_transformer import MoEDecoderConfig

__all__ = ["MixtralStateDictAdapter"]


class MixtralStateDictAdapter(MappingAdapter):
    def __init__(self, cfg: MoEDecoderConfig, scan_layers: bool = True):
        L = cfg.num_hidden_layers
        pre = "model.layers.{i}.block_sparse_moe"
        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            Entry("model.norm.weight", "final_norm"),
            *attention_entries(cfg, "moe_layers", layer_range=(0, L)),
            Entry(f"{pre}.gate.weight", "moe_layers.moe.gate.weight", layer_range=(0, L)),
            Entry(
                (f"{pre}.experts.{{e}}.w1.weight", f"{pre}.experts.{{e}}.w3.weight"),
                "moe_layers.moe.experts.gate_up_proj",
                _gate_up_in,
                _gate_up_out,
                layer_range=(0, L),
            ),
            Entry(
                f"{pre}.experts.{{e}}.w2.weight",
                "moe_layers.moe.experts.down_proj",
                _t,
                _t,
                layer_range=(0, L),
            ),
        ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))
        super().__init__(entries, L, scan_layers, num_experts=cfg.moe.n_routed_experts)
