"""Run-lifetime goodput ledger: badput taxonomy across restarts and rollbacks.

``GoodputTracker`` dies with its process, so a supervised run that crashes
hourly reports the same per-episode ``goodput`` as one that never does — the
restart backoff, re-init, restore, recompile, and the optimizer steps
*re-trained* since the last verifiable checkpoint are invisible. This module
stitches the artifacts every subsystem already writes — the multi-episode
``training.jsonl`` (run_header + goodput rows stamped with ``episode``),
``supervisor_report.json`` episodes with their failure taxonomy, and the
resilience rollback events — into one atomic ``run_ledger.json`` that accounts
every wall second of the run.

Accounting is interval-union style like ``trace_analysis.py``: each class of
seconds is carved out of the run's wall window and ``idle`` is defined as the
remainder, so ``goodput_e2e + sum(badput_frac) == 1`` by construction rather
than by hope. The badput taxonomy:

- ``restart_backoff`` — supervisor sleep between a death and the next episode
- ``reinit``          — process boot to goodput-tracker start (imports, mesh,
  model build) of every episode after the first, plus episodes that died
  before logging anything
- ``restore``         — checkpoint restore on resume (the ``restore`` goodput
  bucket) plus in-process rollback restores (the ``rollback`` bucket)
- ``recompile``       — the per-episode ``compile`` bucket (a warm restart
  with a persistent cache shrinks this; the ledger is how you see it)
- ``wasted_steps``    — device-step time spent re-executing optimizer steps a
  previous episode already ran past, or steps a rollback discarded
- ``data_stall`` / ``eval`` / ``checkpoint`` — the matching tracker buckets
- ``idle``            — everything unaccounted, including the death window
  between an episode's last metric row and the supervisor reaping it

**Wasted steps** come from step-number overlap between consecutive episode
segments (a crash-restart resumes from the newest verifiable checkpoint and
re-trains up to where the dead episode had logged) plus the walk-back recorded
by ``rollback_done`` events. **Time-to-recovery** is crash -> first productive
step (the first logged step exceeding everything trained before the failure),
keyed by the supervisor's ``classify_failure`` taxonomy.

The supervisor updates the ledger after every episode (and on abort); a flat
``ledger/*`` + ``badput/*`` metric row rides ``supervisor.jsonl``, badput
spans land on the supervisor timeline, ``tools/goodput_report.py`` renders the
ledger, and ``regression.py`` gates ``goodput_e2e`` / ``badput/*`` /
``wasted_steps`` / ``recovery_s`` like any other perf metric
(docs/observability.md "Run-level goodput & SLOs").
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Any

__all__ = [
    "BADPUT_CLASSES",
    "RUN_LEDGER_VERSION",
    "LEDGER_FILENAME",
    "EpisodeSegment",
    "segments_from_rows",
    "wasted_step_counts",
    "build_ledger",
    "update_run_ledger",
    "load_ledger",
    "validate_ledger",
    "gate_metrics",
    "ledger_metric_rows",
    "emit_timeline_spans",
]

RUN_LEDGER_VERSION = 1
LEDGER_FILENAME = "run_ledger.json"

# every wall second of the run lands in exactly one of these, or in goodput
BADPUT_CLASSES = ("restart_backoff", "reinit", "restore", "recompile",
                  "wasted_steps", "data_stall", "eval", "checkpoint", "idle")

# goodput-tracker bucket -> ledger badput class for the non-device buckets.
# ``rollback`` is an in-process restore (params/opt/rng re-loaded from the
# newest clean checkpoint) — same badput class as the cross-process restore.
_BUCKET_TO_CLASS = {
    "compile": "recompile",
    "data_wait": "data_stall",
    "restore": "restore",
    "rollback": "restore",
    "eval": "eval",
    "checkpoint": "checkpoint",
    "idle": "idle",
}


# ------------------------------------------------------------------ segments


@dataclasses.dataclass
class EpisodeSegment:
    """One episode's slice of the metric stream, reduced for accounting."""

    index: int
    steps: list[int] = dataclasses.field(default_factory=list)
    # (ts, step) per trained (loss-carrying) row, stream order
    step_rows: list[tuple[float, int]] = dataclasses.field(default_factory=list)
    first_ts: float | None = None
    last_ts: float | None = None
    # cumulative goodput state at the segment's last snapshot
    tracker_wall_s: float = 0.0
    tracker_end_ts: float | None = None
    bucket_s: dict[str, float] = dataclasses.field(default_factory=dict)
    # optimizer steps a rollback_done event discarded (from_step - to_step)
    rollback_wasted: int = 0

    @property
    def tracker_start_ts(self) -> float | None:
        if self.tracker_end_ts is None:
            return None
        return self.tracker_end_ts - self.tracker_wall_s


def _row_episode(row: dict[str, Any]) -> int | None:
    ep = row.get("episode")
    return int(ep) if isinstance(ep, (int, float)) and not isinstance(ep, bool) \
        else None


def segments_from_rows(rows: list[dict[str, Any]]) -> dict[int, EpisodeSegment]:
    """Group a (possibly multi-episode) metric stream into episode segments.

    Primary key is the ``episode`` stamp the supervisor exports via
    ``AUTOMODEL_EPISODE``; streams that predate the stamp fall back to
    splitting on ``run_header`` rows (each episode writes exactly one).
    """
    stamped = any(_row_episode(r) is not None for r in rows)
    out: dict[int, EpisodeSegment] = {}
    fallback_index = 0
    seen_header = False
    for row in rows:
        if stamped:
            index = _row_episode(row)
            if index is None:
                index = fallback_index
            else:
                fallback_index = index
        else:
            if row.get("run_header") and seen_header:
                fallback_index += 1
            index = fallback_index
        seen_header = seen_header or bool(row.get("run_header"))
        seg = out.setdefault(index, EpisodeSegment(index=index))
        ts = row.get("ts")
        ts = float(ts) if isinstance(ts, (int, float)) else None
        if ts is not None:
            seg.first_ts = ts if seg.first_ts is None else min(seg.first_ts, ts)
            seg.last_ts = ts if seg.last_ts is None else max(seg.last_ts, ts)
        if "loss" in row and isinstance(row.get("step"), int):
            seg.steps.append(row["step"])
            if ts is not None:
                seg.step_rows.append((ts, row["step"]))
        if row.get("resilience/event") == "rollback_done":
            frm, to = row.get("resilience/from_step"), row.get("resilience/to_step")
            if isinstance(frm, int) and isinstance(to, int):
                seg.rollback_wasted += max(frm - to, 0)
        wall = row.get("goodput_wall_s")
        if isinstance(wall, (int, float)) and wall >= seg.tracker_wall_s:
            seg.tracker_wall_s = float(wall)
            seg.tracker_end_ts = ts if ts is not None else seg.tracker_end_ts
            seg.bucket_s = {
                k.split("/", 1)[1]: max(float(v), 0.0) * float(wall)
                for k, v in row.items()
                if k.startswith("goodput/") and isinstance(v, (int, float))
            }
    for seg in out.values():
        seg.steps.sort()
    return out


def wasted_step_counts(
    segments: dict[int, EpisodeSegment],
) -> tuple[int, dict[int, int]]:
    """(total, per-episode) optimizer steps whose work was thrown away.

    Two sources: step-number overlap between consecutive episode segments
    (a restart resumes from the newest verifiable checkpoint and re-executes
    everything the dead episode had already logged past it — elastic resumes
    included, the optimizer-step numbering is topology-invariant), and the
    walk-back recorded by in-process ``rollback_done`` events (steps trained
    and then discarded when params rewound).
    """
    per: dict[int, int] = {}
    prev_max: int | None = None
    total = 0
    for index in sorted(segments):
        seg = segments[index]
        overlap = 0
        if prev_max is not None:
            overlap = sum(1 for s in seg.steps if s <= prev_max)
        per[index] = overlap + seg.rollback_wasted
        total += per[index]
        if seg.steps:
            prev_max = max(prev_max, seg.steps[-1]) if prev_max is not None \
                else seg.steps[-1]
    return total, per


# ------------------------------------------------------------------ ledger


def _report_episodes(report: dict[str, Any] | None) -> dict[int, dict[str, Any]]:
    out: dict[int, dict[str, Any]] = {}
    for ep in (report or {}).get("episodes", []) or []:
        if isinstance(ep, dict) and isinstance(ep.get("index"), int):
            out[ep["index"]] = ep
    return out


def build_ledger(rows: list[dict[str, Any]],
                 report: dict[str, Any] | None = None) -> dict[str, Any] | None:
    """Reduce a run's artifacts to the run-lifetime goodput ledger document.

    ``rows`` is the parsed multi-episode training.jsonl; ``report`` the
    supervisor report (None for unsupervised runs — the ledger then covers
    the logged window only, with no backoff/reinit attribution). Returns None
    when there is nothing to account (no rows and no episodes).
    """
    segments = segments_from_rows(rows)
    rep_eps = _report_episodes(report)
    indices = sorted(set(segments) | set(rep_eps))
    if not indices:
        return None
    wasted_total, wasted_per = wasted_step_counts(segments)

    # -- per-episode wall windows -------------------------------------------
    windows: dict[int, tuple[float, float]] = {}
    clock = 0.0  # synthetic clock for segments with no timestamps at all
    for index in indices:
        seg = segments.get(index)
        rep = rep_eps.get(index, {})
        start = rep.get("started")
        start = float(start) if isinstance(start, (int, float)) else None
        if start is None and seg is not None:
            cands = [t for t in (seg.tracker_start_ts, seg.first_ts)
                     if t is not None]
            start = min(cands) if cands else None
        dur = rep.get("duration_s")
        end = start + float(dur) if start is not None \
            and isinstance(dur, (int, float)) else None
        if end is None and seg is not None and seg.last_ts is not None:
            end = seg.last_ts if start is None else max(seg.last_ts, start)
        if start is None:
            start = end if end is not None else clock
        if end is None:
            end = start
        windows[index] = (start, max(end, start))
        clock = max(clock, end)

    # -- seconds accounting --------------------------------------------------
    goodput_s = 0.0
    totals = {c: 0.0 for c in BADPUT_CLASSES}
    episodes_out: list[dict[str, Any]] = []
    for pos, index in enumerate(indices):
        seg = segments.get(index)
        rep = rep_eps.get(index, {})
        start, end = windows[index]
        ep_sec = {c: 0.0 for c in BADPUT_CLASSES}
        ep_good = 0.0
        if seg is not None and seg.tracker_end_ts is not None:
            t_start = seg.tracker_start_ts
            ep_sec["reinit"] += max(t_start - start, 0.0)
            # tracker-window buckets; the snapshot fractions were rounded, so
            # any slack between their sum and the tracker wall goes to idle
            dev = seg.bucket_s.get("device_step", 0.0)
            accounted = 0.0
            for bucket, sec in seg.bucket_s.items():
                cls = _BUCKET_TO_CLASS.get(bucket)
                if cls is not None:
                    ep_sec[cls] += sec
                    accounted += sec
            n_steps = len(seg.steps)
            wasted_frac = min(wasted_per.get(index, 0) / n_steps, 1.0) \
                if n_steps else (1.0 if wasted_per.get(index) else 0.0)
            ep_sec["wasted_steps"] += dev * wasted_frac
            ep_good += dev * (1.0 - wasted_frac)
            accounted += dev
            ep_sec["idle"] += max(seg.tracker_wall_s - accounted, 0.0)
            # death/teardown window after the last snapshot
            ep_sec["idle"] += max(end - seg.tracker_end_ts, 0.0)
        else:
            # died (or was reaped) before the tracker ever snapshot: the
            # whole episode is initialization that never paid off
            ep_sec["reinit"] += end - start
        if pos + 1 < len(indices):
            nxt_start = windows[indices[pos + 1]][0]
            ep_sec["restart_backoff"] += max(nxt_start - end, 0.0)
        goodput_s += ep_good
        for c, v in ep_sec.items():
            totals[c] += v
        steps = seg.steps if seg is not None else []
        episodes_out.append({
            "index": index,
            "taxonomy": rep.get("taxonomy"),
            "hang": bool(rep.get("hang", False)),
            "start_ts": round(start, 3),
            "end_ts": round(end, 3),
            "steps": [steps[0], steps[-1]] if steps else None,
            "trained_steps": len(steps),
            "wasted_steps": wasted_per.get(index, 0),
            "seconds": {"goodput": round(ep_good, 3),
                        **{c: round(v, 3) for c, v in ep_sec.items()}},
        })

    # -- close the books: idle is the remainder, fractions sum to 1 ----------
    run_start = windows[indices[0]][0]
    run_end = max(w[1] for w in windows.values())
    accounted = goodput_s + sum(totals.values())
    measured = run_end - run_start
    if measured > accounted:
        totals["idle"] += measured - accounted
        wall = measured
    else:
        # clock skew between row timestamps and the supervisor's wall clock:
        # the components are the ground truth, the window stretches to fit
        wall = accounted
    wall = max(wall, 1e-9)
    badput_frac = {c: round(totals[c] / wall, 6) for c in BADPUT_CLASSES
                   if c != "idle"}
    goodput_e2e = round(goodput_s / wall, 6)
    # idle absorbs the rounding so the fractions sum to exactly 1
    badput_frac["idle"] = round(1.0 - goodput_e2e - sum(badput_frac.values()), 6)

    # -- recovery: failure -> first productive step --------------------------
    all_step_rows = sorted(
        (ts, step, seg.index)
        for seg in segments.values() for ts, step in seg.step_rows)
    recovery: dict[str, list[float]] = {}
    for ep in episodes_out:
        if ep["taxonomy"] is None:
            continue
        fail_end = ep["end_ts"]
        prev_max = max(
            (segments[i].steps[-1] for i in segments
             if i <= ep["index"] and segments[i].steps), default=None)
        rec = None
        for ts, step, seg_index in all_step_rows:
            if seg_index <= ep["index"]:
                continue
            if prev_max is None or step > prev_max:
                rec = max(ts - fail_end, 0.0)
                break
        ep["recovery_s"] = round(rec, 3) if rec is not None else None
        if rec is not None:
            recovery.setdefault(ep["taxonomy"], []).append(rec)

    run_id = (report or {}).get("run_id")
    if run_id is None:
        run_id = next((r.get("run_id") for r in rows
                       if r.get("run_header") and r.get("run_id")), None)
    all_steps = [s for seg in segments.values() for s in seg.steps]
    return {
        "version": RUN_LEDGER_VERSION,
        "run_id": run_id,
        "status": (report or {}).get("status", "unsupervised"),
        "restarts": int((report or {}).get("restarts", max(len(indices) - 1, 0))),
        "wall_s": round(wall, 3),
        "goodput_s": round(goodput_s, 3),
        "goodput_e2e": goodput_e2e,
        "badput": {c: round(totals[c], 3) for c in BADPUT_CLASSES},
        "badput_frac": badput_frac,
        "wasted_steps": wasted_total,
        "productive_steps": len(set(all_steps)),
        "final_step": max(all_steps) if all_steps else None,
        "recovery": {
            cls: {"count": len(vals),
                  "mean_s": round(sum(vals) / len(vals), 3),
                  "max_s": round(max(vals), 3)}
            for cls, vals in sorted(recovery.items())
        },
        "episodes": episodes_out,
    }


# ------------------------------------------------------------------ file IO


def _atomic_write_json(path: str, doc: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".run_ledger.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _read_jsonl(path: str) -> list[dict[str, Any]]:
    rows: list[dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue  # a torn tail line must not sink the ledger
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def update_run_ledger(out_dir: str,
                      report: dict[str, Any] | None = None) -> dict[str, Any] | None:
    """Rebuild ``<out_dir>/run_ledger.json`` from the run's artifacts.

    Idempotent and crash-safe (tmp + rename); called by the supervisor after
    every episode and by ``tools/goodput_report.py`` on demand. ``report``
    defaults to the on-disk ``supervisor_report.json`` when present.
    """
    rows = _read_jsonl(os.path.join(out_dir, "training.jsonl"))
    if report is None:
        try:
            with open(os.path.join(out_dir, "supervisor_report.json")) as f:
                report = json.load(f)
        except (OSError, json.JSONDecodeError):
            report = None
    ledger = build_ledger(rows, report=report)
    if ledger is None:
        return None
    _atomic_write_json(os.path.join(out_dir, LEDGER_FILENAME), ledger)
    return ledger


def load_ledger(path: str) -> dict[str, Any]:
    """Read a ledger document; ``path`` may be the file or the run directory."""
    if os.path.isdir(path):
        path = os.path.join(path, LEDGER_FILENAME)
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------------ schema


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_ledger(doc: Any) -> list[str]:
    """Schema problems with a ledger document; empty list = valid."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["ledger is not a JSON object"]
    if doc.get("version") != RUN_LEDGER_VERSION:
        problems.append(f"version {doc.get('version')!r} != {RUN_LEDGER_VERSION}")
    if not _is_num(doc.get("wall_s")) or doc.get("wall_s", 0) <= 0:
        problems.append("wall_s missing or non-positive")
    g = doc.get("goodput_e2e")
    if not _is_num(g) or not 0.0 <= g <= 1.0:
        problems.append("goodput_e2e missing or outside [0, 1]")
    for field in ("badput", "badput_frac"):
        sec = doc.get(field)
        if not isinstance(sec, dict) or set(sec) != set(BADPUT_CLASSES):
            problems.append(f"{field} keys != badput taxonomy")
            continue
        bad = [c for c, v in sec.items() if not _is_num(v) or v < 0]
        if bad:
            problems.append(f"{field} has negative/non-numeric classes: {bad}")
    if isinstance(doc.get("badput_frac"), dict) and _is_num(g):
        fracs = [v for v in doc["badput_frac"].values() if _is_num(v)]
        if abs(g + sum(fracs) - 1.0) > 1e-3:
            problems.append(
                f"goodput_e2e + sum(badput_frac) = {g + sum(fracs):.6f} != 1")
    if not isinstance(doc.get("wasted_steps"), int) or doc["wasted_steps"] < 0:
        problems.append("wasted_steps missing or negative")
    eps = doc.get("episodes")
    if not isinstance(eps, list) or not eps:
        problems.append("episodes missing or empty")
    else:
        for ep in eps:
            if not isinstance(ep, dict) or not isinstance(ep.get("index"), int):
                problems.append(f"malformed episode entry: {ep!r}")
                continue
            sec = ep.get("seconds")
            if not isinstance(sec, dict) or "goodput" not in sec:
                problems.append(f"episode {ep['index']}: seconds malformed")
    rec = doc.get("recovery")
    if not isinstance(rec, dict):
        problems.append("recovery missing")
    else:
        for cls, st in rec.items():
            if not isinstance(st, dict) or not _is_num(st.get("mean_s")) \
                    or st.get("mean_s", 0) < 0 or not st.get("count"):
                problems.append(f"recovery[{cls!r}] malformed")
    return problems


# ------------------------------------------------------------------ emission


def gate_metrics(ledger: dict[str, Any]) -> dict[str, float]:
    """Flatten a ledger into regression-gateable metrics: ``goodput_e2e``,
    ``wasted_steps``, ``badput/<class>`` fractions, and per-failure-class
    ``recovery_s/<class>`` mean seconds."""
    out: dict[str, float] = {}
    if _is_num(ledger.get("goodput_e2e")):
        out["goodput_e2e"] = float(ledger["goodput_e2e"])
    if _is_num(ledger.get("wasted_steps")):
        out["wasted_steps"] = float(ledger["wasted_steps"])
    for cls, frac in (ledger.get("badput_frac") or {}).items():
        if _is_num(frac):
            out[f"badput/{cls}"] = float(frac)
    for cls, st in (ledger.get("recovery") or {}).items():
        if isinstance(st, dict) and _is_num(st.get("mean_s")):
            out[f"recovery_s/{cls}"] = float(st["mean_s"])
    return out


def ledger_metric_rows(ledger: dict[str, Any]) -> dict[str, Any]:
    """One flat ``ledger/*`` + ``badput/*`` row for the supervisor's metric
    stream — the run-level counterpart of the per-step goodput snapshot."""
    row: dict[str, Any] = {
        "ledger/goodput_e2e": ledger.get("goodput_e2e"),
        "ledger/wall_s": ledger.get("wall_s"),
        "ledger/wasted_steps": ledger.get("wasted_steps"),
        "ledger/episodes": len(ledger.get("episodes") or []),
    }
    for cls, frac in (ledger.get("badput_frac") or {}).items():
        row[f"badput/{cls}"] = frac
    for cls, st in (ledger.get("recovery") or {}).items():
        if isinstance(st, dict):
            row[f"ledger/recovery_s/{cls}"] = st.get("mean_s")
    return row


def emit_timeline_spans(ledger: dict[str, Any], timeline: Any,
                        episode_t0s: list[float] | None = None) -> None:
    """Chrome-trace badput spans on the supervisor timeline (tid 4): one span
    per episode per non-zero class, laid out sequentially inside the episode's
    window so Perfetto shows where each episode's wall clock went next to the
    ``supervisor/episode_*`` spans."""
    if timeline is None:
        return
    t0s = episode_t0s or []
    cursor = 0.0
    for pos, ep in enumerate(ledger.get("episodes") or []):
        sec = ep.get("seconds") or {}
        t = t0s[pos] if pos < len(t0s) else cursor
        for cls in ("goodput",) + BADPUT_CLASSES:
            dur = sec.get(cls)
            if not _is_num(dur) or dur <= 0:
                continue
            name = "goodput_e2e" if cls == "goodput" else f"badput/{cls}"
            cat = "goodput" if cls == "goodput" else "badput"
            timeline.complete(name, cat, t, dur, tid=4,
                              episode=ep.get("index"),
                              taxonomy=ep.get("taxonomy"))
            t += dur
        cursor = t
