"""docs/model-coverage.md freshness (tools/gen_model_coverage.py).

The coverage doc is generated from MODEL_REGISTRY / structural aliasing
tables; a new family landing without a regeneration must fail CI here, not
drift silently.
"""

from __future__ import annotations

import importlib.util
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def _load_gen():
    path = REPO / "tools" / "gen_model_coverage.py"
    spec = importlib.util.spec_from_file_location("gen_model_coverage", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_doc_is_fresh():
    gen = _load_gen()
    committed = (REPO / "docs" / "model-coverage.md").read_text()
    assert committed == gen.render(), (
        "docs/model-coverage.md is stale — regenerate with "
        "`python tools/gen_model_coverage.py`")


def test_doc_covers_registry():
    """Every registered architecture appears in the rendered doc."""
    gen = _load_gen()
    text = gen.render()
    registry = gen._load(
        REPO / "automodel_tpu" / "models" / "registry.py", "_cov_reg_test")
    for arch in registry.MODEL_REGISTRY:
        assert f"`{arch}`" in text
    structural = gen._load(
        REPO / "automodel_tpu" / "models" / "structural.py", "_cov_struct_test")
    for arch in (*structural._ARCH_DELTAS, *structural._DENYLIST):
        assert f"`{arch}`" in text


def test_check_mode_detects_staleness(tmp_path, monkeypatch):
    gen = _load_gen()
    assert gen.main(["--check"]) == 0
    stale = tmp_path / "model-coverage.md"
    stale.write_text("# stale\n")
    monkeypatch.setattr(gen, "DOC", stale)
    assert gen.main(["--check"]) == 1
