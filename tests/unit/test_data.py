import json

import numpy as np
import pytest

from automodel_tpu.data.collate import IGNORE_INDEX, sft_collate, stack_batches
from automodel_tpu.data.loader import DataLoader
from automodel_tpu.data.llm.column_mapped import ColumnMappedTextInstructionDataset
from automodel_tpu.data.llm.mock import MockSFTDataset


class _Tok:
    eos_token_id = 2

    def encode(self, text):
        return [ord(c) % 50 + 3 for c in text][:32]


class TestCollate:
    def test_shift_and_mask(self):
        ex = {"input_ids": [5, 6, 7, 8, 9], "prompt_len": 2}
        out = sft_collate([ex], seq_len=8)
        np.testing.assert_array_equal(out["input_ids"][0, :4], [5, 6, 7, 8])
        # target t predicts token t+1; prompt_len-1 first targets masked
        assert out["labels"][0, 0] == IGNORE_INDEX
        np.testing.assert_array_equal(out["labels"][0, 1:4], [7, 8, 9])
        assert (out["labels"][0, 4:] == IGNORE_INDEX).all()
        np.testing.assert_array_equal(out["segment_ids"][0, :4], [1, 1, 1, 1])
        assert (out["segment_ids"][0, 4:] == 0).all()

    def test_truncation(self):
        ex = {"input_ids": list(range(3, 20)), "prompt_len": 0}
        out = sft_collate([ex], seq_len=8)
        assert out["input_ids"].shape == (1, 8)
        assert (out["labels"][0] != IGNORE_INDEX).sum() == 8

    def test_stack(self):
        b1 = sft_collate([{"input_ids": [1, 2, 3], "prompt_len": 0}], seq_len=4)
        b2 = sft_collate([{"input_ids": [4, 5, 6], "prompt_len": 0}], seq_len=4)
        s = stack_batches([b1, b2])
        assert s["input_ids"].shape == (2, 1, 4)


class TestDataLoader:
    def test_determinism_and_len(self):
        ds = list(range(100))
        dl1 = DataLoader(ds, batch_size=8, seed=1)
        dl2 = DataLoader(ds, batch_size=8, seed=1)
        assert len(dl1) == 12
        assert list(dl1)[0] == list(dl2)[0]

    def test_epochs_reshuffle(self):
        ds = list(range(32))
        dl = DataLoader(ds, batch_size=8, seed=1)
        e0 = [tuple(b) for b in dl]
        e1 = [tuple(b) for b in dl]
        assert e0 != e1

    def test_resume_mid_epoch(self):
        ds = list(range(64))
        dl = DataLoader(ds, batch_size=8, seed=3)
        it = iter(dl)
        first_two = [next(it), next(it)]
        state = dl.state_dict()
        rest = list(it)

        dl2 = DataLoader(ds, batch_size=8, seed=3)
        dl2.load_state_dict(state)
        rest2 = list(dl2)
        assert [tuple(b) for b in rest] == [tuple(b) for b in rest2]

    def test_process_sharding(self):
        ds = list(range(16))
        a = DataLoader(ds, batch_size=8, seed=0, process_index=0, process_count=2)
        b = DataLoader(ds, batch_size=8, seed=0, process_index=1, process_count=2)
        ba, bb = next(iter(a)), next(iter(b))
        assert len(ba) == 4 and len(bb) == 4
        assert set(ba).isdisjoint(bb)


class TestDatasets:
    def test_column_mapped_jsonl(self, tmp_path):
        p = tmp_path / "d.jsonl"
        rows = [{"q": "what is 2+2?", "a": "4"}, {"q": "capital of france?", "a": "paris"}]
        p.write_text("\n".join(json.dumps(r) for r in rows))
        ds = ColumnMappedTextInstructionDataset(
            str(p), {"question": "q", "answer": "a"}, tokenizer=_Tok()
        )
        assert len(ds) == 2
        ex = ds[0]
        assert ex["prompt_len"] > 0
        assert ex["input_ids"][-1] == _Tok.eos_token_id

    def test_column_mapped_requires_answer(self, tmp_path):
        with pytest.raises(ValueError):
            ColumnMappedTextInstructionDataset("x.jsonl", {"question": "q"})

    def test_mock_dataset_deterministic(self):
        ds = MockSFTDataset(vocab_size=100, seq_len=16, num_samples=4)
        assert ds[2]["input_ids"] == ds[2]["input_ids"]
        assert len(ds[0]["input_ids"]) == 17
