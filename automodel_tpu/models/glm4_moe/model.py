"""GLM4-MoE family (GLM-4.5/4.6) — TPU-native (reference models/glm4_moe/model.py).

Dense GQA attention with optional per-head qk RMSNorm, partial rotary (GLM ropes only
the first half of head_dim), attention bias; DeepSeek-style sigmoid gating with
group-limited routing, e_score_correction_bias, routed scaling, one shared expert,
and a dense layer prefix (reference model.py:38,98-118).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.moe_transformer import (
    MoEDecoderConfig,
    init_moe_decoder_params,
    moe_decoder_forward,
    moe_decoder_logical_axes,
)
from automodel_tpu.moe.config import MoEConfig

__all__ = ["Glm4MoeConfig", "Glm4MoeForCausalLM"]


@dataclasses.dataclass
class Glm4MoeConfig(MoEDecoderConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Glm4MoeConfig":
        rope_params = hf.get("rope_parameters") or {}
        # new-style rope_parameters can carry the scaling spec (rope_type/factor)
        rope_scaling = hf.get("rope_scaling") or (
            rope_params if rope_params.get("rope_type") not in (None, "default") else None
        )
        moe = MoEConfig(
            n_routed_experts=hf["n_routed_experts"],
            n_activated_experts=hf["num_experts_per_tok"],
            dim=hf["hidden_size"],
            moe_inter_dim=hf["moe_intermediate_size"],
            n_shared_experts=hf.get("n_shared_experts", 1),
            n_expert_groups=max(hf.get("n_group") or 1, 1),
            n_limited_groups=max(hf.get("topk_group") or 1, 1),
            gate_bias_update_factor=0.001,  # noaux-tc loss-free balancing
            score_func="sigmoid",
            route_scale=hf.get("routed_scaling_factor", 1.0),
            norm_topk_prob=hf.get("norm_topk_prob", True),
        )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            rope_theta=rope_params.get("rope_theta", hf.get("rope_theta", 10000.0)),
            rope_scaling=rope_scaling,
            partial_rotary_factor=rope_params.get(
                "partial_rotary_factor", hf.get("partial_rotary_factor", 0.5)
            ),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            # HF Glm4MoeConfig defaults both to False; GLM-4.5/4.6 checkpoints set
            # them explicitly in config.json
            attention_bias=hf.get("attention_bias", False),
            qk_norm=hf.get("use_qk_norm", False),
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
            first_k_dense_replace=hf.get("first_k_dense_replace", 1),
        )


class Glm4MoeForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = Glm4MoeConfig
    hf_architectures = ("Glm4MoeForCausalLM",)

    def __init__(self, config: Glm4MoeConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        return init_moe_decoder_params(self.config, key, dtype)

    def logical_axes(self) -> dict:
        return moe_decoder_logical_axes(self.config)

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None):
        return moe_decoder_forward(
            self.config, self.backend, params, input_ids,
            positions=positions, segment_ids=segment_ids, token_mask=token_mask,
            rules=rules, return_hidden=return_hidden, training=training, cache=cache,
        )

    def generate(self, params, input_ids, **kw):
        """Sample with a KV cache (see :func:`automodel_tpu.generation.generate`)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    def state_dict_adapter(self):
        from automodel_tpu.models.glm4_moe.state_dict_adapter import Glm4MoeStateDictAdapter

        return Glm4MoeStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = Glm4MoeConfig.from_hf(config)
        return cls(config, backend)
