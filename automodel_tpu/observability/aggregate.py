"""Cross-host metric aggregation: make a lagging host visible from proc 0.

Per-step metrics are proc-0-only, so a pod where one host's input pipeline (or
one chip) runs 2x slower looks healthy in ``training.jsonl`` — every step just
takes longer, because collectives wait for the slowest participant. The
aggregator all-gathers each host's sample (step wall time, cumulative data
wait, HBM high-water) at every log step; proc 0 then logs min/median/max per
key and flags a ``straggler_host`` when one host's step time exceeds the
median by a configurable factor. MoE runs gather one extra key (the host's
max expert utilization, :data:`MOE_HOST_KEYS`) and analogously flag a
``hot_expert_host`` — under expert parallelism a single host holding the
hot experts stalls every a2a combine the same way a slow input pipeline
stalls every all-reduce.

Collective discipline: ``aggregate()`` must be called by EVERY process at the
same point (the train loop's log step, which is deterministic across hosts).
Single-host runs return ``{}`` — nothing to compare.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable, Sequence

logger = logging.getLogger(__name__)

__all__ = ["CrossHostAggregator", "HOST_KEYS", "MOE_HOST_KEYS",
           "DYNAMICS_HOST_KEYS", "host_keys"]

# the per-host sample, in wire order; headroom (limit - in_use, from the
# allocator or the analytic memory plan) travels so proc 0 can flag the host
# closest to an OOM before the allocator does
HOST_KEYS = ("step_time_s", "data_wait_s", "hbm_gib_peak", "hbm_headroom_gib")
# MoE runs append the host's max expert utilization (>1 = hot expert); a
# separate tuple so dense runs keep the exact legacy wire format
MOE_HOST_KEYS = HOST_KEYS + ("moe_max_util",)
# dynamics runs append the host's view of the (replicated) global grad norm:
# every host must see the same scalar, so cross-host disagreement is replica
# desync — bitrot in a collective, a bad chip, or divergent param state
DYNAMICS_HOST_KEYS = ("grad_norm",)


def host_keys(moe: bool = False, dynamics: bool = False) -> tuple[str, ...]:
    """The wire key tuple for a run's pillar mix; extensions append in a
    fixed order so every host derives an identical format from the shared
    config (the aggregate contract — no negotiation on the wire)."""
    keys = MOE_HOST_KEYS if moe else HOST_KEYS
    if dynamics:
        keys = keys + DYNAMICS_HOST_KEYS
    return keys


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


class CrossHostAggregator:
    """All-gather per-host samples and reduce them to min/median/max + straggler.

    ``allgather_fn(values) -> list[list[float]]`` is injectable so the 8-host
    straggler logic is unit-testable on a single process; the default is
    :func:`automodel_tpu.parallel.init.allgather_host_rows`.
    """

    def __init__(self, straggler_factor: float = 2.0,
                 keys: Sequence[str] = HOST_KEYS,
                 allgather_fn: Callable[[Sequence[float]], list] | None = None,
                 process_count: int | None = None,
                 oom_risk_gib: float = 1.0,
                 divergence_rtol: float = 1e-4):
        if straggler_factor <= 1.0:
            raise ValueError(f"straggler_factor must be > 1, got {straggler_factor}")
        self.straggler_factor = float(straggler_factor)
        self.oom_risk_gib = float(oom_risk_gib)
        self.divergence_rtol = float(divergence_rtol)
        self.keys = tuple(keys)
        if allgather_fn is None:
            import jax

            from automodel_tpu.parallel.init import allgather_host_rows

            allgather_fn = allgather_host_rows
            if process_count is None:
                process_count = jax.process_count()
        self._allgather = allgather_fn
        self.process_count = process_count  # None = trust the gathered table

    @property
    def active(self) -> bool:
        """False on single-host runs: no gather, no overhead, no output."""
        return self.process_count is None or self.process_count > 1

    def aggregate(self, sample: dict[str, Any]) -> dict[str, Any]:
        """One log-step reduction; collective on multi-host (see module doc).

        Missing/None values travel as NaN and are excluded per-key, so a host
        without HBM counters (CPU) doesn't poison the pod-wide stats.
        """
        if not self.active:
            return {}
        vec = [float(sample[k]) if sample.get(k) is not None else math.nan
               for k in self.keys]
        try:
            rows = self._allgather(vec)
        except Exception:
            logger.exception("cross-host metric allgather failed (run continues)")
            return {}
        out: dict[str, Any] = {"host/n": len(rows)}
        for i, key in enumerate(self.keys):
            vals = [r[i] for r in rows if not math.isnan(r[i])]
            if not vals:
                continue
            out[f"host/{key}_min"] = round(min(vals), 4)
            out[f"host/{key}_median"] = round(_median(vals), 4)
            out[f"host/{key}_max"] = round(max(vals), 4)
        self._flag_straggler(rows, out)
        self._flag_hot_expert(rows, out)
        self._flag_oom_risk(rows, out)
        self._flag_divergent(rows, out)
        return out

    def _worst_vs_median(self, rows: list, key: str) -> tuple[float, int] | None:
        """(worst/median ratio, worst host) for ``key``, or None if degenerate."""
        if key not in self.keys:
            return None
        idx = self.keys.index(key)
        vals = [(r[idx], host) for host, r in enumerate(rows)
                if not math.isnan(r[idx])]
        if len(vals) < 2:
            return None
        med = _median([v for v, _ in vals])
        worst, host = max(vals)
        if med <= 0:
            return None
        return worst / med, host

    def _flag_straggler(self, rows: list, out: dict[str, Any]) -> None:
        hit = self._worst_vs_median(rows, "step_time_s")
        if hit and hit[0] >= self.straggler_factor:
            out["straggler_host"] = hit[1]
            out["straggler_ratio"] = round(hit[0], 3)

    def _flag_hot_expert(self, rows: list, out: dict[str, Any]) -> None:
        """Flag the host whose local experts run hottest vs the pod median.

        Same worst/median≥factor shape as the straggler flag, applied to
        ``moe_max_util`` when the MoE key set is in use: the flagged host is
        where a capacity bump or rebalance would land.
        """
        hit = self._worst_vs_median(rows, "moe_max_util")
        if hit and hit[0] >= self.straggler_factor:
            out["hot_expert_host"] = hit[1]
            out["hot_expert_ratio"] = round(hit[0], 3)

    def _flag_oom_risk(self, rows: list, out: dict[str, Any]) -> None:
        """Flag the host with the LEAST headroom when it drops below the
        absolute ``oom_risk_gib`` threshold.

        Absolute, not worst/median: memory is a cliff, not a gradient — a
        pod where every host sits at 0.5 GiB headroom has a median as bad as
        its worst, and a ratio test would stay silent right up to the OOM.
        """
        if "hbm_headroom_gib" not in self.keys:
            return
        idx = self.keys.index("hbm_headroom_gib")
        vals = [(r[idx], host) for host, r in enumerate(rows)
                if not math.isnan(r[idx])]
        if not vals:
            return
        worst, host = min(vals)
        if worst < self.oom_risk_gib:
            out["oom_risk_host"] = host
            out["oom_risk_headroom_gib"] = round(worst, 3)

    def _flag_divergent(self, rows: list, out: dict[str, Any]) -> None:
        """Flag the host whose view of the replicated grad norm disagrees.

        ``grad_norm`` is a pod-replicated scalar: XLA reduces it across every
        data axis, so each host must read back the same value up to float
        noise. Relative deviation beyond ``divergence_rtol`` is not a hot
        input or a slow chip — it is replica desync (a corrupted collective,
        a flipped bit in param state, a host that silently restarted with
        stale weights) and the flagged host is where the state dump belongs.
        A NaN on exactly one host flags that host for the same reason.
        """
        if "grad_norm" not in self.keys:
            return
        idx = self.keys.index("grad_norm")
        vals = [(r[idx], host) for host, r in enumerate(rows)]
        finite = [(v, h) for v, h in vals if not math.isnan(v)]
        if len(vals) < 2:
            return
        nan_hosts = [h for v, h in vals if math.isnan(v)]
        if nan_hosts and finite:
            out["divergent_host"] = nan_hosts[0]
            out["divergence_rel"] = math.inf
            return
        if len(finite) < 2:
            return
        med = _median([v for v, _ in finite])
        scale = max(abs(med), 1e-12)
        rel, host = max((abs(v - med) / scale, h) for v, h in finite)
        if rel > self.divergence_rtol:
            out["divergent_host"] = host
            out["divergence_rel"] = round(rel, 6)
