"""Pallas grouped expert GEMM parity vs ``jax.lax.ragged_dot`` (interpret mode).

The kernel (ops/pallas/grouped_gemm.py) runs its exact schedule on CPU via
``interpret=True``; these tests diff forward AND the fused custom-VJP backward
against ragged_dot across group shapes — balanced, ragged boundaries inside
row blocks, empty experts at head/mid/tail, one expert owning everything,
padded tails — plus bf16 accumulate-in-f32 tolerance, the XLA fallback for
shapes the tile picker rejects, the "mlp_act_dot" remat rung sized for the
kernel, and the pallas backend wired through ``moe_forward``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.ops.pallas.grouped_gemm import grouped_matmul, pick_grouped_blocks

# group-size layouts over E=4 experts (except singletons): every structural
# edge the tile schedule handles — interior full blocks, boundaries mid-block,
# empty experts (whose dW block must still be written, with zeros), a single
# expert owning every row, and a total row count that is not a block multiple
# (exercises the pad-and-slice wrapper)
GROUPINGS = {
    "balanced": (8, 8, 8, 8),
    "ragged": (3, 13, 1, 15),
    "empty_head": (0, 0, 17, 15),
    "empty_mid_tail": (11, 0, 21, 0),
    "one_big": (0, 32, 0, 0),
    "ragged_tail": (5, 9, 7, 9),  # N=30: pads to the next block multiple
    "singletons": (1,) * 8,
}


def _case(sizes, d=16, f=24, dtype=jnp.float32, seed=0):
    sizes = np.asarray(sizes, np.int32)
    n = int(sizes.sum())
    kx, kw = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(kx, (n, d), jnp.float32).astype(dtype)
    w = (jax.random.normal(kw, (len(sizes), d, f), jnp.float32) / np.sqrt(d)
         ).astype(dtype)
    return x, w, jnp.asarray(sizes, jnp.int32)


@pytest.mark.parametrize("name", sorted(GROUPINGS))
def test_forward_matches_ragged_dot_f32(name):
    x, w, gs = _case(GROUPINGS[name])
    got = grouped_matmul(x, w, gs, interpret=True)
    want = jax.lax.ragged_dot(x, w, gs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("name", sorted(GROUPINGS))
def test_forward_bf16_within_tolerance(name):
    """bf16 operands, f32 accumulate: <= 1e-2 relative against the f32 GEMM
    over the SAME bf16-rounded inputs (isolates kernel error from input
    rounding)."""
    x, w, gs = _case(GROUPINGS[name], dtype=jnp.bfloat16)
    got = np.asarray(grouped_matmul(x, w, gs, interpret=True), np.float32)
    want = np.asarray(jax.lax.ragged_dot(
        x.astype(jnp.float32), w.astype(jnp.float32), gs))
    denom = np.maximum(np.abs(want), 1e-2)
    assert np.max(np.abs(got - want) / denom) <= 1e-2


@pytest.mark.parametrize(
    "name", ["balanced", "ragged", "empty_head", "empty_mid_tail", "one_big",
             "ragged_tail"])
def test_custom_vjp_grads_match_ragged_dot(name):
    x, w, gs = _case(GROUPINGS[name])

    def loss_pallas(x, w):
        return jnp.sum(jnp.sin(grouped_matmul(x, w, gs, interpret=True)))

    def loss_ref(x, w):
        return jnp.sum(jnp.sin(jax.lax.ragged_dot(x, w, gs)))

    gx, gw = jax.jit(jax.grad(loss_pallas, argnums=(0, 1)))(x, w)
    rx, rw = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               atol=1e-5, rtol=1e-4)


def test_multi_output_block_schedule():
    """Explicit small tiles force a multi-(row,expert,out)-block grid on a
    test-sized input; result must not depend on the blocking."""
    x, w, gs = _case(GROUPINGS["ragged"])
    want = jax.lax.ragged_dot(x, w, gs)
    for bn, bo in ((4, 8), (8, 24), (16, 12)):
        got = grouped_matmul(x, w, gs, interpret=True, block_n=bn, block_o=bo)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_compiled_path_falls_back_on_misaligned_shapes():
    """interpret=False with lane-misaligned dims must silently use ragged_dot
    (callers opt into the kernel, never into a crash) — and stay
    differentiable through the fallback."""
    assert pick_grouped_blocks(16, 24) is None
    x, w, gs = _case(GROUPINGS["balanced"])
    got = grouped_matmul(x, w, gs)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(jax.lax.ragged_dot(x, w, gs)))
    g = jax.grad(lambda x: jnp.sum(grouped_matmul(x, w, gs)))(x)
    assert np.isfinite(np.asarray(g)).all()


def test_pick_grouped_blocks_contract():
    assert pick_grouped_blocks(100, 128) is None  # misaligned contraction
    assert pick_grouped_blocks(128, 130) is None  # misaligned output
    picked = pick_grouped_blocks(512, 256)
    assert picked is not None
    bn, bo = picked
    assert 256 % bo == 0
    # the row-divisibility constraint is honored when n is known
    picked_n = pick_grouped_blocks(512, 256, n=48)
    assert picked_n is not None and 48 % picked_n[0] == 0


def test_mlp_act_dot_remat_rung_lowers_and_matches():
    """The MoE-tuned remat rung saves only the "mlp_act" tensor; grads through
    the rematerialized expert GEMMs must equal the un-remat grads (remat never
    changes the math, only what is recomputed)."""
    from automodel_tpu.models.common.backend import BackendConfig
    from automodel_tpu.moe import MoEConfig, init_moe_params, moe_forward

    cfg = MoEConfig(n_routed_experts=4, n_activated_experts=2, dim=16,
                    moe_inter_dim=8)
    params = init_moe_params(cfg, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 6, cfg.dim))
    backend = BackendConfig(remat_policy="mlp_act_dot")

    def loss(p, x):
        y, _, _ = moe_forward(cfg, p, x)
        return (y ** 2).sum()

    g_plain = jax.jit(jax.grad(loss))(params, x)
    g_remat = jax.jit(jax.grad(backend.layer_remat(loss)))(params, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6),
        g_plain, g_remat)


def test_moe_forward_pallas_backend_matches_ragged_dot():
    """backend.experts_backend='pallas' end-to-end through moe_forward (the
    dense-dispatcher model path): same outputs, loads, and grads."""
    from automodel_tpu.moe import MoEConfig, init_moe_params, moe_forward

    cfg = MoEConfig(n_routed_experts=4, n_activated_experts=2, dim=16,
                    moe_inter_dim=8)
    params = init_moe_params(cfg, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (4, 6, cfg.dim))

    y_r, _, load_r = moe_forward(cfg, params, x)
    y_p, _, load_p = moe_forward(cfg, params, x, experts_backend="pallas")
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_r),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(load_p), np.asarray(load_r))

    def loss(p, backend):
        y, _, _ = moe_forward(cfg, p, x, experts_backend=backend)
        return (y ** 2).sum()

    g_r = jax.jit(jax.grad(loss), static_argnums=1)(params, "ragged_dot")
    g_p = jax.jit(jax.grad(loss), static_argnums=1)(params, "pallas")
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
        g_r, g_p)
