"""Shared prompt/answer tokenization for SFT datasets."""

from __future__ import annotations

from typing import Any

__all__ = ["tokenize_sft_example"]


def tokenize_sft_example(tokenizer, prompt: str, answer: str, sep: str = " ") -> dict[str, Any]:
    """Tokenize prompt+answer; return input_ids (EOS-terminated) and prompt_len.

    prompt_len counts the prompt's tokens inside the full encoding so collation can
    mask the prompt span from the loss (answer-only loss).
    """
    prompt_ids = tokenizer.encode(prompt)
    full_ids = list(tokenizer.encode(prompt + sep + answer))
    eos = getattr(tokenizer, "eos_token_id", None)
    if eos is not None and (not full_ids or full_ids[-1] != eos):
        full_ids = full_ids + [eos]
    return {"input_ids": full_ids, "prompt_len": len(prompt_ids)}
