"""The dryrun's collective-byte accounting (__graft_entry__._collective_bytes):
the parser the MULTICHIP_r* comm tables and analytic floor/ceiling assertions
stand on. Pin its conventions on synthetic HLO text."""

import importlib.util
import sys


def _graft():
    if "__graft_entry__" in sys.modules:
        return sys.modules["__graft_entry__"]
    spec = importlib.util.spec_from_file_location("__graft_entry__", "__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["__graft_entry__"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_sums_output_bytes_per_collective_kind():
    g = _graft()
    hlo = """
  %ag = f32[16,64]{1,0} all-gather(f32[4,64]{1,0} %p0), dimensions={0}
  %ar = bf16[8,128]{1,0} all-reduce(bf16[8,128]{1,0} %x), to_apply=%sum
  %rs = f32[2,64]{1,0} reduce-scatter(f32[8,64]{1,0} %y), dimensions={0}
  %cp = s32[5]{0} collective-permute(s32[5]{0} %z), source_target_pairs={{0,1}}
  %a2a = f32[4,8,32]{2,1,0} all-to-all(f32[4,8,32]{2,1,0} %w), dimensions={0}
"""
    got = g._collective_bytes(hlo)
    assert got["all-gather"] == 16 * 64 * 4
    assert got["all-reduce"] == 8 * 128 * 2
    assert got["reduce-scatter"] == 2 * 64 * 4
    assert got["collective-permute"] == 5 * 4
    assert got["all-to-all"] == 4 * 8 * 32 * 4


def test_async_start_counts_result_not_operand_alias():
    """-start ops carry (operand alias, ..., result) tuples; counting every
    element would inflate all-gather ~1.5x (the review-caught double count)."""
    g = _graft()
    hlo = """
  %ags = (f32[4,64]{1,0}, f32[16,64]{1,0}) all-gather-start(f32[4,64]{1,0} %p0), dimensions={0}
  %agd = f32[16,64]{1,0} all-gather-done((f32[4,64]{1,0}, f32[16,64]{1,0}) %ags)
"""
    got = g._collective_bytes(hlo)
    # only the -start result (the LAST tuple element); -done doesn't re-count
    assert got["all-gather"] == 16 * 64 * 4


def test_sync_tuple_output_sums_all_elements():
    """A plain (non-start) variadic all-to-all's tuple output is all real data."""
    g = _graft()
    hlo = "  %t = (f32[2,8]{1,0}, f32[2,8]{1,0}) all-to-all(f32[2,8] %a, f32[2,8] %b), dimensions={0}"
    got = g._collective_bytes(hlo)
    assert got["all-to-all"] == 2 * (2 * 8 * 4)


def test_non_collective_lines_ignored():
    g = _graft()
    hlo = """
  %d = f32[128,128]{1,0} dot(f32[128,64] %a, f32[64,128] %b)
  %f = f32[8]{0} fusion(f32[8] %x), kind=kLoop
"""
    assert g._collective_bytes(hlo) == {}
