"""Ministral-3 — TPU-native (reference models/mistral3/model.py:507).

A Llama-lineage GQA decoder whose distinctives all live in config translation:
``rope_parameters`` carries YaRN scaling (mscale/mscale_all_dim/truncate,
reference model.py:58-81) plus the llama-4-style long-context query scaling
``llama_4_scaling_beta`` (q *= 1 + beta*log(1 + pos//original_max), model.py:282-284).
The compute path is the shared dense decoder; weights use standard Llama keys
(the reference registers its class over HF's AutoModelForCausalLM, model.py:610).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM

__all__ = ["Ministral3Config", "Ministral3ForCausalLM"]


@dataclasses.dataclass
class Ministral3Config(LlamaConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Ministral3Config":
        rope = hf.get("rope_parameters") or {}
        base = LlamaConfig.from_hf(hf)
        kwargs = dataclasses.asdict(base)
        kwargs["rope_theta"] = rope.get("rope_theta", kwargs["rope_theta"])
        rope_type = rope.get("rope_type") or rope.get("type", "default")
        if rope_type != "default":
            # rope_parameters doubles as the scaling dict (yarn for Ministral-3)
            kwargs["rope_scaling"] = {"rope_type": rope_type, **rope}
        beta = rope.get("llama_4_scaling_beta")
        if beta is not None:
            kwargs["llama4_attn_scale_beta"] = float(beta)
            kwargs["original_max_position_embeddings"] = rope.get(
                "original_max_position_embeddings", kwargs["max_position_embeddings"]
            )
        return cls(**kwargs)


class Ministral3ForCausalLM(LlamaForCausalLM):
    config_class = Ministral3Config
    hf_architectures = ("Ministral3ForCausalLM",)

    @classmethod
    def from_config(cls, config, backend=None):
        if isinstance(config, dict):
            config = Ministral3Config.from_hf(config)
        return cls(config, backend)
