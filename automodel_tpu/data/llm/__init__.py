from automodel_tpu.data.llm.column_mapped import ColumnMappedTextInstructionDataset
from automodel_tpu.data.llm.hellaswag import HellaSwagDataset
from automodel_tpu.data.llm.mock import MockSFTDataset

__all__ = ["ColumnMappedTextInstructionDataset", "HellaSwagDataset", "MockSFTDataset"]
