"""YAML -> ConfigNode attribute tree with Hydra-style ``_target_`` instantiation.

Behavioral parity with the reference config system
(nemo_automodel/components/config/loader.py:265,325,437):

- ``load_config(path)`` parses YAML into a :class:`ConfigNode` supporting attribute
  access, dotted ``get("a.b.c")``, ``to_dict()``, and containment checks.
- ``_target_:`` keys name any dotted callable; ``node.instantiate(**overrides)``
  imports and calls it with the node's remaining keys as kwargs (nested nodes with
  their own ``_target_`` are instantiated recursively).
- Keys ending in ``_fn`` whose value is a dotted path resolve to the *function object*
  instead of being called.
- ``${oc.env:VAR}`` / ``${oc.env:VAR,default}`` interpolation is deferred until value
  access so secrets never appear in printed configs.
- The raw config dict is preserved (``raw_dict``) for checkpoint signature comparison.
"""

from __future__ import annotations

import importlib
import os
import re
from typing import Any, Iterator

import yaml

__all__ = ["ConfigNode", "instantiate", "load_config", "translate_value"]

_ENV_RE = re.compile(r"\$\{oc\.env:([A-Za-z_][A-Za-z0-9_]*)(?:[,|]([^}]*))?\}")

# Python literals that YAML may hand us as strings from CLI overrides.
_BOOL = {"true": True, "false": False, "True": True, "False": False}


def translate_value(s: str) -> Any:
    """Best-effort convert a CLI-override string to a Python value."""
    if not isinstance(s, str):
        return s
    if s in _BOOL:
        return _BOOL[s]
    if s.lower() in ("none", "null"):
        return None
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    if (s.startswith("[") and s.endswith("]")) or (s.startswith("{") and s.endswith("}")):
        try:
            return yaml.safe_load(s)
        except yaml.YAMLError:
            pass
    return s


def _resolve_env(value: str) -> str:
    """Expand ``${oc.env:VAR}`` / ``${oc.env:VAR,default}`` in a string."""

    def repl(m: re.Match) -> str:
        var, default = m.group(1), m.group(2)
        if var in os.environ:
            return os.environ[var]
        if default is not None:
            return default
        raise KeyError(f"environment variable {var!r} is not set and has no default")

    return _ENV_RE.sub(repl, value)


def resolve_target(path: str) -> Any:
    """Import a dotted path ``pkg.mod.attr`` (also ``pkg.mod:attr``) to an object."""
    path = path.replace(":", ".")
    parts = path.split(".")
    # Find the longest importable module prefix, then getattr the rest.
    last_err: Exception | None = None
    for i in range(len(parts), 0, -1):
        mod_name = ".".join(parts[:i])
        try:
            obj = importlib.import_module(mod_name)
        except ImportError as e:
            last_err = e
            continue
        try:
            for attr in parts[i:]:
                obj = getattr(obj, attr)
        except AttributeError as e:
            raise ImportError(f"cannot resolve {path!r}: {e}") from e
        return obj
    raise ImportError(f"cannot resolve {path!r}: no importable module prefix ({last_err})")


def _is_dotted_path(value: Any) -> bool:
    return isinstance(value, str) and bool(re.fullmatch(r"[A-Za-z_][\w\.]*[\w]", value)) and "." in value


class ConfigNode:
    """Attribute-access view over a nested dict parsed from YAML."""

    def __init__(self, data: dict[str, Any] | None = None):
        object.__setattr__(self, "_data", {})
        for k, v in (data or {}).items():
            self._data[k] = self._wrap(v)

    @classmethod
    def _wrap(cls, v: Any) -> Any:
        if isinstance(v, dict):
            return cls(v)
        if isinstance(v, (list, tuple)):
            return [cls._wrap(x) for x in v]
        return v

    @staticmethod
    def _unwrap(v: Any, resolve_env: bool = True) -> Any:
        if isinstance(v, ConfigNode):
            return v.to_dict(resolve_env=resolve_env)
        if isinstance(v, list):
            return [ConfigNode._unwrap(x, resolve_env) for x in v]
        if resolve_env and isinstance(v, str):
            return _resolve_env(v)
        return v

    # -- mapping protocol ---------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        try:
            v = self._data[name]
        except KeyError:
            raise AttributeError(f"config has no key {name!r} (available: {list(self._data)})")
        if isinstance(v, str):
            return _resolve_env(v)
        return v

    def __setattr__(self, name: str, value: Any) -> None:
        self._data[name] = self._wrap(value)

    def __getitem__(self, name: str) -> Any:
        return self.__getattr__(name)

    def __setitem__(self, name: str, value: Any) -> None:
        self._data[name] = self._wrap(value)

    def __contains__(self, name: str) -> bool:
        if "." in name:
            return self.get(name, _MISSING) is not _MISSING
        return name in self._data

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, ConfigNode):
            return self.to_dict(resolve_env=False) == other.to_dict(resolve_env=False)
        if isinstance(other, dict):
            return self.to_dict(resolve_env=False) == other
        return NotImplemented

    def keys(self):
        return self._data.keys()

    def items(self):
        return [(k, self.__getattr__(k)) for k in self._data]

    def values(self):
        return [self.__getattr__(k) for k in self._data]

    def get(self, key: str, default: Any = None) -> Any:
        """Dotted-path get: ``cfg.get("model.pretrained_model_name_or_path")``."""
        node: Any = self
        for part in key.split("."):
            if isinstance(node, ConfigNode) and part in node._data:
                node = node.__getattr__(part)
            else:
                return default
        return node

    def set_by_path(self, path: str, value: Any) -> None:
        """Dotted-path set, creating intermediate nodes (CLI override support)."""
        parts = path.split(".")
        node = self
        for part in parts[:-1]:
            if part not in node._data or not isinstance(node._data[part], ConfigNode):
                node._data[part] = ConfigNode()
            node = node._data[part]
        node._data[parts[-1]] = self._wrap(value)

    def to_dict(self, resolve_env: bool = True) -> dict[str, Any]:
        return {k: self._unwrap(v, resolve_env) for k, v in self._data.items()}

    @property
    def raw_dict(self) -> dict[str, Any]:
        """Config as plain dict with env interpolations left unresolved (secret-safe)."""
        return self.to_dict(resolve_env=False)

    def __repr__(self) -> str:
        return f"ConfigNode({self.to_dict(resolve_env=False)!r})"

    def __deepcopy__(self, memo):
        import copy

        return ConfigNode(copy.deepcopy(self.to_dict(resolve_env=False), memo))

    # -- instantiation ------------------------------------------------------
    def instantiate(self, *args: Any, **overrides: Any) -> Any:
        return instantiate(self, *args, **overrides)


_MISSING = object()


def _materialize(value: Any) -> Any:
    """Recursively instantiate nested ``_target_`` nodes and resolve ``*_fn`` paths."""
    if isinstance(value, ConfigNode):
        if "_target_" in value:
            return instantiate(value)
        return value
    if isinstance(value, list):
        return [_materialize(v) for v in value]
    return value


def instantiate(node: ConfigNode | dict, *args: Any, **overrides: Any) -> Any:
    """Instantiate ``node._target_`` with the node's keys (plus overrides) as kwargs.

    Nested nodes carrying their own ``_target_`` are instantiated depth-first.
    Keys ending in ``_fn`` whose value is a dotted path resolve to the callable itself.
    """
    if isinstance(node, dict):
        node = ConfigNode(node)
    if "_target_" not in node:
        raise ValueError(f"cannot instantiate a config without _target_: {node!r}")
    target = node.__getattr__("_target_")
    fn = resolve_target(target) if isinstance(target, str) else target

    kwargs: dict[str, Any] = {}
    for key in node:
        if key == "_target_":
            continue
        val = node.__getattr__(key)
        if isinstance(val, ConfigNode) and "_target_" in val:
            val = instantiate(val)
        elif isinstance(val, list):
            val = [_materialize(v) for v in val]
        elif key.endswith("_fn") and _is_dotted_path(val):
            val = resolve_target(val)
        kwargs[key] = val
    kwargs.update(overrides)
    return fn(*args, **kwargs)


def load_config(path: str | os.PathLike) -> ConfigNode:
    """Load a YAML file into a :class:`ConfigNode`."""
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise TypeError(f"top-level YAML in {path} must be a mapping, got {type(data)}")
    return ConfigNode(data)
