"""HF safetensors read/write (reference checkpoint/_backports/hf_storage.py +
consolidate_hf_safetensors.py, rebuilt on the safetensors library).

Reading: accepts a directory (single file, or sharded ``model-XXXXX-of-YYYYY`` files
with ``model.safetensors.index.json``) or one ``.safetensors`` file, and returns a
lazy mapping so tensors are materialized one at a time (host RAM bounded by the
largest tensor, not the checkpoint).

Writing: emits HF-layout sharded files + index.json so any checkpoint we save is
loadable by ``transformers.AutoModel.from_pretrained`` — the reference's dual-format
guarantee (SURVEY.md §3.4).
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from typing import Iterator

import numpy as np

__all__ = ["load_safetensors", "save_safetensors", "LazySafetensors"]

_INDEX_NAME = "model.safetensors.index.json"


def _open_file(path: str):
    from safetensors import safe_open

    # numpy framework keeps tensors on host (bf16 via ml_dtypes) — no device round-trip
    return safe_open(path, framework="numpy")


class LazySafetensors(Mapping):
    """Dict-like view over one or more safetensors files; loads tensors on access."""

    def __init__(self, files: dict[str, str]):
        # files: tensor key -> file path
        self._files = files

    def __getitem__(self, key: str) -> np.ndarray:
        from automodel_tpu.utils.retry import with_retry

        path = self._files[key]

        def read():
            with _open_file(path) as f:
                return f.get_tensor(key)

        # network/remote filesystems (GCS FUSE, NFS) surface transient EIOs;
        # a truncated file raises a safetensors format error (not transient)
        # and fails immediately (utils/retry.py allowlist)
        return np.asarray(with_retry(read, description=f"safetensors read {key!r}"))

    def __iter__(self) -> Iterator[str]:
        return iter(self._files)

    def __len__(self) -> int:
        return len(self._files)


def load_safetensors(path: str) -> LazySafetensors:
    """Load a safetensors file / HF model dir into a lazy key->tensor mapping."""
    if os.path.isfile(path):
        files = [path]
    else:
        index = os.path.join(path, _INDEX_NAME)
        if os.path.exists(index):
            try:
                with open(index) as f:
                    weight_map = json.load(f)["weight_map"]
            except (ValueError, KeyError) as e:
                raise ValueError(
                    f"corrupt safetensors index at {index!r} "
                    f"({type(e).__name__}: {e}); re-export or delete the file "
                    "to fall back to directory scanning"
                ) from e
            key_to_file = {k: os.path.join(path, v) for k, v in weight_map.items()}
            missing = sorted({v for v in key_to_file.values() if not os.path.exists(v)})
            if missing:
                raise FileNotFoundError(
                    f"safetensors index {index!r} references missing shard "
                    f"file(s): {[os.path.basename(m) for m in missing[:3]]}"
                    f"{' ...' if len(missing) > 3 else ''} — incomplete download/export?"
                )
            return LazySafetensors(key_to_file)
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path) if f.endswith(".safetensors")
        )
        if not files:
            raise FileNotFoundError(f"no .safetensors files under {path!r}")
    key_to_file: dict[str, str] = {}
    for fp in files:
        with _open_file(fp) as f:
            for k in f.keys():
                key_to_file[k] = fp
    return LazySafetensors(key_to_file)


def save_safetensors(
    tensors: Mapping[str, np.ndarray],
    out_dir: str,
    max_shard_bytes: int = 5 * 1024**3,
    metadata: dict[str, str] | None = None,
    write: bool = True,
) -> list[str]:
    """Write tensors as HF-sharded safetensors (+ index.json when sharded).

    Values may be dense arrays OR lazy leaves (jax arrays, LazyHFTensor): each
    lands on host only while its shard is being written, so peak host memory is
    one shard (<= ``max_shard_bytes``), not the checkpoint. ``write=False`` runs
    the identical materialization sequence WITHOUT writing — non-zero ranks of a
    multi-host pod call it this way so the per-tensor host gathers (collectives)
    stay in lockstep with the writing rank, one tensor in flight at a time.
    """
    from safetensors.numpy import save_file

    if write:
        os.makedirs(out_dir, exist_ok=True)
    items = list(tensors.items())
    # greedy sharding by byte size WITHOUT materializing: jax arrays, numpy, and
    # lazy host leaves all expose nbytes; tensors only land on host one shard at
    # a time inside _to_numpy_dict below (then the shard buffer is dropped)
    shards: list[list[tuple[str, np.ndarray]]] = [[]]
    size = 0
    for k, v in items:
        nbytes = int(getattr(v, "nbytes", 0)) or np.asarray(v).nbytes
        if size + nbytes > max_shard_bytes and shards[-1]:
            shards.append([])
            size = 0
        shards[-1].append((k, v))
        size += nbytes

    meta = {"format": "pt", **(metadata or {})}
    written: list[str] = []
    if len(shards) == 1:
        fp = os.path.join(out_dir, "model.safetensors")
        buf = _to_numpy_dict(dict(shards[0]), keep=write)
        if write:
            save_file(buf, fp, metadata=meta)
        return [fp] if write else []

    weight_map: dict[str, str] = {}
    total = 0
    n = len(shards)
    for idx, shard in enumerate(shards, start=1):
        name = f"model-{idx:05d}-of-{n:05d}.safetensors"
        fp = os.path.join(out_dir, name)
        buf = _to_numpy_dict(dict(shard), keep=write)
        if write:
            save_file(buf, fp, metadata=meta)
            for k, v in buf.items():
                weight_map[k] = name
                total += v.nbytes
            written.append(fp)
        del buf  # free the shard before materializing the next
    if write:
        with open(os.path.join(out_dir, _INDEX_NAME), "w") as f:
            json.dump({"metadata": {"total_size": total}, "weight_map": weight_map}, f, indent=2)
    return written


def _to_numpy_dict(d: dict[str, np.ndarray], keep: bool = True) -> dict[str, np.ndarray]:
    # np.asarray on a jax array device-gets to host (LazyHFTensor gathers +
    # transforms); ml_dtypes covers bf16. keep=False (non-writing ranks) still
    # materializes every tensor IN ORDER — the gathers are collectives — but
    # drops each immediately, bounding peak host use to one tensor.
    if not keep:
        for v in d.values():
            np.asarray(v)
        return {}
    return {k: np.ascontiguousarray(np.asarray(v)) for k, v in d.items()}
