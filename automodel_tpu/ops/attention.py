"""Backend-agnostic attention (reference components/attention/utils.py:25).

The reference switches between TE fused attention / SDPA / FlexAttention; here the
switchboard is ``backend="xla" | "flash"``:

- ``xla``: plain einsum-softmax attention. XLA fuses it well and it runs anywhere
  (CPU tests, interpreter); also the reference implementation for kernel parity tests.
- ``flash``: Pallas blockwise flash attention (automodel_tpu.ops.pallas.flash_attention)
  on TPU; falls back to ``xla`` off-TPU.

Sequence packing uses segment ids (the TPU-native replacement for the reference's whole
BSHD/THD machinery, distributed/thd_utils.py): tokens attend only within their segment.
GQA/MQA is handled by broadcasting kv heads.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp

__all__ = ["dot_product_attention"]

Backend = Literal["xla", "flash"]


def _attention_bias(
    seq_q: int,
    seq_kv: int,
    *,
    causal: bool,
    segment_ids_q: jnp.ndarray | None,
    segment_ids_kv: jnp.ndarray | None,
    positions_q: jnp.ndarray | None = None,
    positions_kv: jnp.ndarray | None = None,
    sliding_window: int | None = None,
    dtype=jnp.float32,
) -> jnp.ndarray | None:
    """Additive mask bias (0 allowed / -inf disallowed), shape (b or 1, 1, sq, skv)."""
    masks = []
    if causal:
        if positions_q is None:
            q_pos = jnp.arange(seq_q)[:, None]
            kv_pos = jnp.arange(seq_kv)[None, :]
            masks.append((q_pos >= kv_pos)[None, None])
        else:
            q_pos = positions_q[:, :, None]
            kv_pos = (positions_kv if positions_kv is not None else positions_q)[:, None, :]
            masks.append((q_pos >= kv_pos)[:, None])
    if sliding_window is not None:
        if positions_q is None:
            q_pos = jnp.arange(seq_q)[:, None]
            kv_pos = jnp.arange(seq_kv)[None, :]
            masks.append((q_pos - kv_pos < sliding_window)[None, None])
        else:
            q_pos = positions_q[:, :, None]
            kv_pos = (positions_kv if positions_kv is not None else positions_q)[:, None, :]
            masks.append((q_pos - kv_pos < sliding_window)[:, None])
    if segment_ids_q is not None:
        kv_seg = segment_ids_kv if segment_ids_kv is not None else segment_ids_q
        masks.append((segment_ids_q[:, :, None] == kv_seg[:, None, :])[:, None])
    if not masks:
        return None
    allowed = masks[0]
    for m in masks[1:]:
        allowed = jnp.logical_and(allowed, m)
    return jnp.where(allowed, 0.0, jnp.finfo(dtype).min).astype(dtype)


def dot_product_attention(
    q: jnp.ndarray,  # (b, sq, n_heads, head_dim)
    k: jnp.ndarray,  # (b, skv, n_kv_heads, head_dim)
    v: jnp.ndarray,  # (b, skv, n_kv_heads, head_dim_v)
    *,
    causal: bool = True,
    segment_ids_q: jnp.ndarray | None = None,
    segment_ids_kv: jnp.ndarray | None = None,
    positions_q: jnp.ndarray | None = None,
    positions_kv: jnp.ndarray | None = None,
    sliding_window: int | None = None,
    softmax_scale: float | None = None,
    logit_soft_cap: float | None = None,
    sinks: jnp.ndarray | None = None,  # (n_heads,) attention sink logits (gpt-oss)
    extra_bias: jnp.ndarray | None = None,  # (b, sq, skv) additive logit bias (DSv3.2 sparse mask)
    backend: Backend = "xla",
) -> jnp.ndarray:
    """Multi-head attention with GQA, packing segments, sliding window, soft-cap, sinks."""
    interpret = backend == "flash_interpret"  # CPU kernel-semantics testing
    if (
        backend in ("flash", "flash_interpret")
        and extra_bias is None
        and (jax.default_backend() == "tpu" or interpret)
        and positions_q is None  # flash path masks by absolute index, not positions
        and positions_kv is None
        # kernel constraints: uniform head_dim, seqs divisible by some block >= 8
        # (the kernel's block picker halves until it divides); sliding windows may
        # be ints OR traced scalars (they ride into the kernel through SMEM)
        and q.shape[-1] == v.shape[-1]
        and q.shape[1] % 8 == 0
        and k.shape[1] % 8 == 0
    ):
        from automodel_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(
            q, k, v,
            causal=causal,
            segment_ids_q=segment_ids_q,
            segment_ids_kv=segment_ids_kv,
            sliding_window=sliding_window,
            softmax_scale=softmax_scale,
            logit_soft_cap=logit_soft_cap,
            sinks=sinks,
            interpret=interpret,
        )

    b, sq, nh, hd = q.shape
    _, skv, nkv, _ = k.shape
    if softmax_scale is None:
        softmax_scale = hd**-0.5
    groups = nh // nkv

    qf = q.astype(jnp.float32) * softmax_scale
    # (b, sq, kv, g, d) x (b, skv, kv, d) -> (b, kv, g, sq, skv)
    qf = qf.reshape(b, sq, nkv, groups, hd)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qf, k.astype(jnp.float32))
    if logit_soft_cap is not None:
        logits = jnp.tanh(logits / logit_soft_cap) * logit_soft_cap
    bias = _attention_bias(
        sq, skv,
        causal=causal,
        segment_ids_q=segment_ids_q,
        segment_ids_kv=segment_ids_kv,
        positions_q=positions_q,
        positions_kv=positions_kv,
        sliding_window=sliding_window,
    )
    if bias is not None:
        logits = logits + bias[:, :, None]  # broadcast over the GQA group dim
    if extra_bias is not None:
        logits = logits + extra_bias[:, None, None].astype(jnp.float32)
    if sinks is not None:
        # gpt-oss attention sinks: an extra per-head logit column that absorbs mass.
        sink = jnp.broadcast_to(sinks.reshape(1, nkv, groups, 1, 1), (b, nkv, groups, sq, 1)).astype(jnp.float32)
        logits_max = jnp.max(jnp.concatenate([logits, sink], axis=-1), axis=-1, keepdims=True)
        unnorm = jnp.exp(logits - logits_max)
        denom = unnorm.sum(-1, keepdims=True) + jnp.exp(sink - logits_max)
        probs = unnorm / denom
    else:
        probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, sq, nh, v.shape[-1]).astype(q.dtype)
