from automodel_tpu.models.kimi_k25_vl.model import (
    KimiK25VLConfig,
    KimiK25VLForConditionalGeneration,
)

__all__ = ["KimiK25VLConfig", "KimiK25VLForConditionalGeneration"]
