"""LLaVA HF key mapping: language entries are the Llama adapter's with prefixes
rewritten (``model.`` -> ``model.language_model.``, params under ``language_model.``),
plus CLIP vision tower and projector entries."""

from __future__ import annotations

import dataclasses

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import LlamaStateDictAdapter, _t

__all__ = ["LlavaStateDictAdapter"]

_V = "vision_tower.vision_model"


def _conv_in(w: np.ndarray) -> np.ndarray:
    """HF OIHW conv -> HWIO."""
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def _conv_out(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.transpose(w, (3, 2, 0, 1)))


def _vision_entries(num_layers: int) -> list[Entry]:
    pre = f"{_V}.encoder.layers.{{i}}"
    ours = "vision_tower.layers"
    pairs = [
        ("layer_norm1.weight", "ln1_w", None), ("layer_norm1.bias", "ln1_b", None),
        ("self_attn.q_proj.weight", "wq", _t), ("self_attn.q_proj.bias", "bq", None),
        ("self_attn.k_proj.weight", "wk", _t), ("self_attn.k_proj.bias", "bk", None),
        ("self_attn.v_proj.weight", "wv", _t), ("self_attn.v_proj.bias", "bv", None),
        ("self_attn.out_proj.weight", "wo", _t), ("self_attn.out_proj.bias", "bo", None),
        ("layer_norm2.weight", "ln2_w", None), ("layer_norm2.bias", "ln2_b", None),
        ("mlp.fc1.weight", "fc1", _t), ("mlp.fc1.bias", "fc1_b", None),
        ("mlp.fc2.weight", "fc2", _t), ("mlp.fc2.bias", "fc2_b", None),
    ]
    entries = []
    rng = (0, num_layers)  # vision depth differs from the text stack
    for hf_key, our_key, tf in pairs:
        if tf is None:
            entries.append(Entry(f"{pre}.{hf_key}", f"{ours}.{our_key}", layer_range=rng))
        else:
            entries.append(Entry(f"{pre}.{hf_key}", f"{ours}.{our_key}", tf, tf, layer_range=rng))
    entries += [
        Entry(f"{_V}.embeddings.class_embedding", "vision_tower.class_embed"),
        Entry(f"{_V}.embeddings.patch_embedding.weight", "vision_tower.patch_embed", _conv_in, _conv_out),
        Entry(f"{_V}.embeddings.position_embedding.weight", "vision_tower.pos_embed"),
        Entry(f"{_V}.pre_layrnorm.weight", "vision_tower.pre_ln_w"),  # (sic, HF typo)
        Entry(f"{_V}.pre_layrnorm.bias", "vision_tower.pre_ln_b"),
        Entry(f"{_V}.post_layernorm.weight", "vision_tower.post_ln_w"),
        Entry(f"{_V}.post_layernorm.bias", "vision_tower.post_ln_b"),
    ]
    return entries


class LlavaStateDictAdapter(MappingAdapter):
    def __init__(self, cfg, scan_layers: bool = True):
        # safetensors layout nests the text model as language_model.model.* with
        # lm_head at language_model.lm_head (HF save_pretrained serialization)
        text_adapter = LlamaStateDictAdapter(cfg.text, scan_layers)
        text_entries = []
        for e in text_adapter.entries:
            hf_keys = tuple(f"language_model.{k}" for k in e.hf_keys)
            text_entries.append(
                dataclasses.replace(
                    e,
                    hf=hf_keys if len(hf_keys) > 1 else hf_keys[0],
                    ours=f"language_model.{e.ours}",
                )
            )
        entries = text_entries + _vision_entries(cfg.vision.num_hidden_layers) + [
            Entry("multi_modal_projector.linear_1.weight", "projector.linear_1", _t, _t),
            Entry("multi_modal_projector.linear_1.bias", "projector.linear_1_b"),
            Entry("multi_modal_projector.linear_2.weight", "projector.linear_2", _t, _t),
            Entry("multi_modal_projector.linear_2.bias", "projector.linear_2_b"),
        ]
        super().__init__(entries, cfg.text.num_hidden_layers, scan_layers)
