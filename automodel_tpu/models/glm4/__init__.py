from automodel_tpu.models.glm4.model import Glm4ForCausalLM

__all__ = ["Glm4ForCausalLM"]
