"""Kimi-VL — TPU-native (reference models/kimivl/model.py:625 KimiVLForConditionalGeneration).

MoonViT native-resolution vision tower (models/vision/moonvit.py) + multimodal
projector (pre-norm LayerNorm -> merge-flatten -> 2-layer GELU MLP,
reference :378-399) + DeepSeek-V2/V3 MLA text decoder (reused from the
deepseek_v3 family). Vision features replace the embedding rows at
``media_placeholder_token_id`` positions (reference _merge_with_image_features).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.moe_transformer import moe_decoder_forward
from automodel_tpu.models.deepseek_v3.model import (
    DeepseekV3Config,
    DeepseekV3ForCausalLM,
)
from automodel_tpu.models.vision.moonvit import (
    MoonViTConfig,
    init_moonvit_params,
    moonvit_forward,
    moonvit_logical_axes,
    prepare_moonvit_inputs,
)
from automodel_tpu.ops.norms import layer_norm

__all__ = ["KimiVLConfig", "KimiVLForConditionalGeneration"]


@dataclasses.dataclass
class KimiVLConfig:
    text: DeepseekV3Config = None
    vision: MoonViTConfig = None
    media_placeholder_token_id: int = 163605

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "KimiVLConfig":
        return cls(
            text=DeepseekV3Config.from_hf(hf["text_config"]),
            vision=MoonViTConfig.from_hf(hf.get("vision_config", {})),
            media_placeholder_token_id=hf.get("media_placeholder_token_id", 163605),
        )


class KimiVLForConditionalGeneration:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = KimiVLConfig
    hf_architectures = ("KimiVLForConditionalGeneration",)

    def __init__(self, config: KimiVLConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()
        self._text = DeepseekV3ForCausalLM(config.text, self.backend)

    # ---- params ----

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        cfg = self.config
        k_text, k_vis, k_proj = jax.random.split(key, 3)
        params = self._text.init(k_text, dtype)
        params["visual"] = init_moonvit_params(cfg.vision, k_vis, dtype)
        d_vis = cfg.vision.hidden_size
        mu = cfg.vision.merge_kernel_size[0] * cfg.vision.merge_kernel_size[1]
        dm = d_vis * mu
        std = cfg.text.initializer_range
        k1, k2 = jax.random.split(k_proj)
        params["projector"] = {
            "pre_ln_w": jnp.ones((d_vis,), dtype), "b_pre_ln": jnp.zeros((d_vis,), dtype),
            "w1": (jax.random.normal(k1, (dm, dm), jnp.float32) * std).astype(dtype),
            "b1": jnp.zeros((dm,), dtype),
            "w2": (jax.random.normal(k2, (dm, cfg.text.hidden_size), jnp.float32) * std).astype(dtype),
            "b2": jnp.zeros((cfg.text.hidden_size,), dtype),
        }
        return params

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def logical_axes(self) -> dict:
        axes = self._text.logical_axes()
        axes["visual"] = moonvit_logical_axes(self.config.vision)
        axes["projector"] = {
            "pre_ln_w": ("norm",), "b_pre_ln": ("norm",),
            "w1": ("embed", "mlp"), "b1": ("mlp",),
            "w2": ("mlp", "embed"), "b2": ("norm",),
        }
        return axes

    # ---- host-side helpers ----

    def prepare_vision_inputs(self, grid_hws: np.ndarray) -> dict[str, np.ndarray]:
        return prepare_moonvit_inputs(grid_hws, self.config.vision)

    def media_token_coords(self, input_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        b, s = np.where(input_ids == self.config.media_placeholder_token_id)
        return b.astype(np.int32), s.astype(np.int32)

    # ---- forward ----

    def __call__(
        self,
        params,
        input_ids,
        pixel_values=None,  # (T, C*P*P) flattened patches
        vision_inputs=None,  # dict from prepare_vision_inputs
        media_coords=None,  # (b_idx, s_idx) of placeholder tokens
        positions=None,
        segment_ids=None,
        token_mask=None,
        rules=None,
        return_hidden=False,
        training=True,
    ):
        cfg = self.config
        dtype = self.backend.jnp_dtype
        embeds = params["embed"].astype(dtype)[input_ids]

        if pixel_values is not None:
            vi = vision_inputs
            mu = cfg.vision.merge_kernel_size[0] * cfg.vision.merge_kernel_size[1]
            # merged-slot count is a static shape: one projector row per media token.
            # OOB scatter indices are silently dropped by .at[].add, so mismatched
            # placeholder/pixel counts must fail loudly here (shapes are host-known).
            n_merged_units = media_coords[0].shape[0] * mu
            if vi["out_idx"].shape[0] != pixel_values.shape[0]:
                raise ValueError("vision_inputs do not match pixel_values token count")
            feats = moonvit_forward(
                cfg.vision, self.backend, params["visual"], pixel_values,
                vi["rope_angles"], vi["segment_ids"], vi["pos_idx"], vi["pos_w"],
                vi["out_idx"], vi["out_w"], n_merged_units,
                time_emb=vi.get("time_emb"),
            )  # (Tm, mu, d_vis)
            pp = params["projector"]
            ln_eps = getattr(cfg, "projector_ln_eps", 1e-5)
            x = layer_norm(feats, pp["pre_ln_w"].astype(dtype), pp["b_pre_ln"].astype(dtype), ln_eps)
            x = x.reshape(feats.shape[0], -1)
            x = jax.nn.gelu(x @ pp["w1"].astype(dtype) + pp["b1"].astype(dtype), approximate=False)
            x = x @ pp["w2"].astype(dtype) + pp["b2"].astype(dtype)
            b_idx, s_idx = media_coords
            embeds = embeds.at[b_idx, s_idx].set(x.astype(dtype))

        return moe_decoder_forward(
            cfg.text, self.backend, params, input_ids,
            positions=positions, segment_ids=segment_ids, token_mask=token_mask,
            rules=rules, return_hidden=return_hidden, training=training,
            attention_fn=self._text.make_attention_fn(),
            inputs_embeds=embeds,
        )

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.kimivl.state_dict_adapter import KimiVLStateDictAdapter

        return KimiVLStateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = KimiVLConfig.from_hf(config)
        return cls(config, backend)
