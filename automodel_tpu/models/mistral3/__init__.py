from automodel_tpu.models.mistral3.model import Ministral3Config, Ministral3ForCausalLM

__all__ = ["Ministral3Config", "Ministral3ForCausalLM"]
