#!/usr/bin/env python
"""Run any entrypoint under the run supervisor (docs/resilience.md).

Wraps the command after ``--`` in a monitored subprocess: heartbeat-based hang
detection (SIGABRT + restart), failure taxonomy from exit status + stderr +
forensics artifacts, and bounded restart with jittered backoff. The child is
re-invoked with the SAME argv on every restart — a recipe with checkpointing
enabled resumes from its newest verifiable checkpoint (elastic restore
included), so a restart on a degraded topology proceeds instead of aborting.

Usage::

    python tools/supervise.py --out-dir out/run1 [--max-restarts 3] \\
        [--hang-timeout 900] [--poll-interval 0.5] -- \\
        python -m automodel_tpu.recipes.llm.train_ft --config run.yaml

The episode history lands atomically in ``<out-dir>/supervisor_report.json``
(plus a Chrome-trace ``supervisor_timeline.json`` and flat ``supervisor/*``
rows in ``supervisor.jsonl``). Exit status: the child's final status — 0 on
success, the last failing status (or 1) when the restart budget is spent.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--" in argv:
        split = argv.index("--")
        own, child = argv[:split], argv[split + 1:]
    else:
        own, child = argv, []
    parser = argparse.ArgumentParser(
        prog="supervise", description=__doc__.splitlines()[0])
    parser.add_argument("--out-dir", required=True,
                        help="child artifact dir: heartbeat file, stall dumps, "
                             "supervisor_report.json all live here")
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--hang-timeout", type=float, default=900.0,
                        metavar="SECONDS",
                        help="no-heartbeat window before SIGABRT (keep it above "
                             "the child's watchdog.threshold_s so the stack "
                             "dump lands first)")
    parser.add_argument("--poll-interval", type=float, default=0.5)
    parser.add_argument("--grace", type=float, default=10.0,
                        help="seconds between SIGABRT and SIGKILL")
    args = parser.parse_args(own)
    if not child:
        parser.error("no child command given; usage: supervise.py [opts] -- cmd ...")

    from automodel_tpu.resilience.supervisor import Supervisor, SupervisorConfig
    from automodel_tpu.utils.retry import RetryConfig

    config = SupervisorConfig(
        max_restarts=args.max_restarts,
        hang_timeout_s=args.hang_timeout,
        poll_interval_s=args.poll_interval,
        grace_s=args.grace,
        backoff=RetryConfig(base_delay_s=2.0, max_delay_s=60.0),
    )
    os.makedirs(args.out_dir, exist_ok=True)
    sup = Supervisor(child, args.out_dir, config=config)
    rc = sup.run()
    print(f"[supervise] {sup.report['status']} after "
          f"{len(sup.report['episodes'])} episode(s), "
          f"{sup.report['restarts']} restart(s) -> {sup.report_path}",
          file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
