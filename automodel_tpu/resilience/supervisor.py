"""Process-level run supervision: heartbeat, hang detection, failure taxonomy,
and bounded restart-from-checkpoint (docs/resilience.md "Supervised runs").

Everything below the process boundary — rollback, verified restore, elastic
resume — already survives inside a live interpreter. What nothing survived
until now is the interpreter itself dying: SIGKILL from the OOM killer, a
wedged runtime that stops making progress without exiting, a crash loop that
burns the restart budget in seconds. :class:`Supervisor` wraps any entrypoint
(train recipe or bench) in a monitored subprocess and closes that gap:

- **Heartbeat contract**: the child writes ``{"step", "time", "pid"}`` to the
  file named by the ``AUTOMODEL_HEARTBEAT_FILE`` env var (atomic tmp+rename;
  :class:`HeartbeatWriter` is wired into ``Observability.heartbeat`` so every
  recipe emits it for free). Hang detection arms only after the FIRST beat —
  an uninstrumented child is never killed for silence it never promised to
  break.
- **Hang detector**: no beat for ``hang_timeout_s`` -> SIGABRT (the in-process
  stall watchdog has already dumped all-thread stacks to ``stall_*.txt`` by
  then — the report links the newest one), grace, SIGKILL, restart.
- **Failure taxonomy** (:func:`classify_failure`): exit status + stderr tail +
  forensics artifacts (``oom_report.json``, ``spike_report.json``) reduce to
  one label — ``backend-init`` / ``oom`` / ``numerics`` / ``preemption`` /
  ``data`` / ``watchdog`` / ``crash`` / ``unknown`` — with a transient flag
  that decides whether a *bench cell* retry is worth anything (the supervisor
  itself restarts every failure class within budget; restart is cheap, a lost
  run is not).
- **Crash-loop protection**: restarts are bounded (``max_restarts``) with the
  ``utils/retry.py`` backoff curve between attempts — per-host deterministic
  jitter, so a pod's workers do not thundering-herd the TPU runtime when they
  all die together. Budget exhausted -> structured abort in the report.
- **Restart-from-checkpoint**: a restart re-invokes the same argv; the
  recipe's resume path restores the newest *verifiable* checkpoint and the
  elastic restore (PR 14) lets a restart on a degraded topology proceed
  instead of aborting. The supervisor adds nothing to that path — which is
  the point: one recovery implementation, exercised from both sides of the
  process boundary.

Every episode is a span on an ``events.py`` timeline plus a ``supervisor/*``
metric row, and the whole run is summarized in an atomic
``supervisor_report.json`` (tools/supervise.py is the CLI).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable

from automodel_tpu.utils.retry import RetryConfig

logger = logging.getLogger(__name__)

__all__ = [
    "HEARTBEAT_ENV",
    "EPISODE_ENV",
    "SUPERVISOR_REPORT_VERSION",
    "HeartbeatWriter",
    "read_heartbeat",
    "classify_error_text",
    "classify_failure",
    "SupervisorConfig",
    "Supervisor",
]

HEARTBEAT_ENV = "AUTOMODEL_HEARTBEAT_FILE"
# JSON {"index": episode, "run_id": ...} exported to every child so the
# MetricLogger can stamp episode identity into the shared training.jsonl
# (loggers/metric_logger.py duplicates the literal to stay import-light)
EPISODE_ENV = "AUTOMODEL_EPISODE"
# v2: run-level run_id/started, per-episode started timestamps (the run
# ledger stitches episode wall windows from them)
SUPERVISOR_REPORT_VERSION = 2

# -------------------------------------------------------------- heartbeat file


class HeartbeatWriter:
    """Atomic step-stamped heartbeat file, written from the train loop's step
    callback (``Observability.heartbeat``). Time-throttled so a fast step loop
    does not turn the beat into fsync noise; a step change always writes."""

    def __init__(self, path: str, min_interval_s: float = 1.0):
        self.path = str(path)
        self.min_interval_s = float(min_interval_s)
        self._last_wall = 0.0
        self._last_step: int | None = None

    @classmethod
    def from_env(cls, env: Any = None) -> "HeartbeatWriter | None":
        path = (env or os.environ).get(HEARTBEAT_ENV)
        return cls(path) if path else None

    def beat(self, step: int | None = None) -> None:
        now = time.time()
        if (step == self._last_step
                and now - self._last_wall < self.min_interval_s):
            return
        self._last_wall = now
        self._last_step = step
        doc = {"step": step, "time": now, "pid": os.getpid()}
        try:
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            fd, tmp = tempfile.mkstemp(prefix=".heartbeat.", dir=d)
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            # a beat must never take the run down; the supervisor treats a
            # silent child as hung, which is the honest signal anyway
            logger.debug("heartbeat write to %s failed", self.path, exc_info=True)


def read_heartbeat(path: str) -> dict[str, Any] | None:
    """The last beat, or None when the file is absent/unreadable."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


# ---------------------------------------------------------------- taxonomy

# Markers of a backend attach that can genuinely succeed on retry: another
# holder releasing the chips, a runtime restarting, a transient RPC timeout.
# The runtime-layer names (libtpu/PJRT/"TPU platform") and bare "UNAVAILABLE"
# are here too — they identify infrastructure faults, BUT only after the
# non-transient overrides below have had their look (BENCH_r05: a lowering
# error whose message contains "UNAVAILABLE" is still a compile failure).
TRANSIENT_INIT_MARKERS = (
    "Unable to initialize backend",
    "No visible",
    "failed to connect",
    "DEADLINE_EXCEEDED",
    "Device or resource busy",
    "already in use",
    "halted",
    "hardware failure",
    "libtpu",
    "PJRT",
    "TPU platform",
    "UNAVAILABLE",
)
# Markers that override ANY init-looking text: the error came out of lowering/
# compilation or mid-dispatch, where "UNAVAILABLE" wraps a deterministic
# failure (BENCH_r05: a convert_element_type lowering error whose message
# *contains* "Unable to initialize backend ... UNAVAILABLE" retried on CPU as
# if the chip were absent). Retrying these wastes the budget and mislabels a
# code/compiler bug as infrastructure.
NON_TRANSIENT_MARKERS = (
    "setup/compile error",
    "convert_element_type",
    "INVALID_ARGUMENT",
    "Mosaic failed",
    "lowering",
    "INTERNAL: during context",
)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating", "MemoryError")
_NUMERICS_MARKERS = ("non-finite", "nonfinite", "NaN", "overflow encountered",
                     "loss=nan")
_PREEMPT_MARKERS = ("SIGTERM received", "preemption", "preempted")
_DATA_MARKERS = ("DataLoader", "dataset", "tokenizer", "vocab size mismatch",
                 "collate")


def classify_error_text(text: str) -> tuple[str, bool]:
    """Reduce an error message / traceback tail to ``(taxonomy, transient)``.

    Order matters: OOM and numerics first (their tracebacks routinely thread
    through backend frames), then the non-transient lowering/compile markers
    (which override init-looking text — the r05 misclassification), then the
    genuinely transient init markers, then preemption/data.
    """
    t = text or ""
    if any(m in t for m in _OOM_MARKERS):
        return "oom", False
    if any(m in t for m in _NUMERICS_MARKERS):
        return "numerics", False
    if any(m in t for m in NON_TRANSIENT_MARKERS):
        return "compile", False
    if any(m in t for m in TRANSIENT_INIT_MARKERS):
        return "backend-init", True
    if any(m in t for m in _PREEMPT_MARKERS):
        return "preemption", True
    if any(m in t for m in _DATA_MARKERS):
        return "data", False
    return "unknown", False


def _fresh(path: str, since: float | None) -> bool:
    try:
        return os.path.exists(path) and (
            since is None or os.path.getmtime(path) >= since)
    except OSError:
        return False


def classify_failure(
    returncode: int | None = None,
    stderr_tail: str = "",
    out_dir: str | None = None,
    hang: bool = False,
    since: float | None = None,
) -> dict[str, Any]:
    """One failed episode -> ``{"taxonomy", "transient", "evidence"}``.

    Evidence precedence: a supervisor-detected hang beats everything (the
    child may have been SIGKILLed into an arbitrary exit status); then the
    forensics artifacts the observability layer wrote *this episode*
    (``oom_report.json`` / ``spike_report.json`` under ``out_dir``, mtime
    gated by ``since``); then the stderr tail text; then the bare exit
    status — SIGTERM reads as preemption, any other signal death as
    ``crash``, a nonzero exit with no markers as ``unknown``.
    """
    if hang:
        return {"taxonomy": "watchdog", "transient": True,
                "evidence": "heartbeat went stale; supervisor killed the run"}
    if out_dir:
        oom = os.path.join(out_dir, "oom_report.json")
        if _fresh(oom, since):
            return {"taxonomy": "oom", "transient": False, "evidence": oom}
        spike = os.path.join(out_dir, "spike_report.json")
        if _fresh(spike, since):
            return {"taxonomy": "numerics", "transient": False, "evidence": spike}
    taxonomy, transient = classify_error_text(stderr_tail)
    if taxonomy != "unknown":
        return {"taxonomy": taxonomy, "transient": transient,
                "evidence": "stderr tail marker"}
    if returncode is not None and returncode < 0:
        sig = -returncode
        if sig == signal.SIGTERM:
            return {"taxonomy": "preemption", "transient": True,
                    "evidence": "killed by SIGTERM"}
        name = signal.Signals(sig).name if sig in signal.Signals._value2member_map_ \
            else str(sig)
        return {"taxonomy": "crash", "transient": True,
                "evidence": f"killed by {name}"}
    return {"taxonomy": "unknown", "transient": False,
            "evidence": f"exit status {returncode}"}


# ---------------------------------------------------------------- supervisor


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Restart budget, hang threshold, and the backoff curve between attempts.

    ``hang_timeout_s`` should sit ABOVE the child's stall-watchdog threshold
    (observability config ``watchdog.threshold_s``) so the in-process stack
    dump lands before the SIGABRT — the report then links it as forensics.
    """

    max_restarts: int = 3
    hang_timeout_s: float = 900.0
    poll_interval_s: float = 0.5
    grace_s: float = 10.0
    stderr_tail_lines: int = 40
    backoff: RetryConfig = dataclasses.field(default_factory=lambda: RetryConfig(
        max_attempts=1, base_delay_s=2.0, max_delay_s=60.0))

    @classmethod
    def from_dict(cls, raw: Any) -> "SupervisorConfig":
        if raw is None:
            return cls()
        if hasattr(raw, "to_dict"):
            raw = raw.to_dict()
        d = dict(raw)
        backoff = RetryConfig.from_dict(d.pop("backoff", None))
        known = {f.name for f in dataclasses.fields(cls)} - {"backoff"}
        return cls(backoff=backoff,
                   **{k: v for k, v in d.items() if k in known})


def _atomic_write_json(path: str, doc: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".supervisor_report.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class _StderrTee(threading.Thread):
    """Drain the child's stderr to ours while keeping a bounded tail for the
    classifier — the pipe must drain regardless or the child blocks on it."""

    def __init__(self, stream, tail_lines: int, echo=None):
        super().__init__(daemon=True)
        self.stream = stream
        self.tail: deque[str] = deque(maxlen=tail_lines)
        self.echo = echo if echo is not None else sys.stderr

    def run(self) -> None:
        try:
            for line in self.stream:
                self.tail.append(line)
                try:
                    self.echo.write(line)
                    self.echo.flush()
                except (OSError, ValueError):
                    pass
        except (OSError, ValueError):
            pass

    def text(self) -> str:
        return "".join(self.tail)


class Supervisor:
    """Run ``argv`` under supervision; see the module docstring for the model.

    ``out_dir`` is where the child writes its artifacts (heartbeat file,
    stall dumps, forensics reports) and where ``supervisor_report.json`` +
    ``supervisor_timeline.json`` land. ``metric_sink(row)`` receives one flat
    ``supervisor/*`` row per episode; by default rows append to
    ``out_dir/supervisor.jsonl``.
    """

    def __init__(
        self,
        argv: list[str],
        out_dir: str,
        config: SupervisorConfig | None = None,
        env: dict[str, str] | None = None,
        metric_sink: Callable[[dict[str, Any]], None] | None = None,
        popen: Callable[..., Any] = subprocess.Popen,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.argv = list(argv)
        self.out_dir = str(out_dir)
        self.config = config or SupervisorConfig()
        self.env = dict(os.environ if env is None else env)
        self.report_path = os.path.join(self.out_dir, "supervisor_report.json")
        self.heartbeat_path = os.path.join(self.out_dir, "heartbeat.json")
        # the child's first heartbeat write must not race directory creation
        os.makedirs(self.out_dir, exist_ok=True)
        self._popen = popen
        self._sleep = sleep
        self._metric_sink = metric_sink
        self.run_id = f"{int(time.time()):x}-{os.getpid():x}"
        self._episode_t0s: list[float] = []
        self.report: dict[str, Any] = {
            "version": SUPERVISOR_REPORT_VERSION,
            "argv": self.argv,
            "run_id": self.run_id,
            "started": round(time.time(), 3),
            "status": "running",
            "restarts": 0,
            "max_restarts": int(self.config.max_restarts),
            "episodes": [],
        }
        from automodel_tpu.observability.events import TraceTimeline

        self.timeline = TraceTimeline(
            os.path.join(self.out_dir, "supervisor_timeline.json"))

    # -- episode ------------------------------------------------------------
    def _run_episode(self, index: int) -> dict[str, Any]:
        cfg = self.config
        try:
            os.unlink(self.heartbeat_path)
        except OSError:
            pass
        env = dict(self.env)
        env[HEARTBEAT_ENV] = self.heartbeat_path
        env[EPISODE_ENV] = json.dumps({"index": index, "run_id": self.run_id})
        started = time.time()
        t0 = self.timeline.now()
        self._episode_t0s.append(t0)
        child = self._popen(self.argv, env=env, stderr=subprocess.PIPE,
                            text=True)
        tee = _StderrTee(child.stderr, cfg.stderr_tail_lines)
        tee.start()
        hang = False
        last_beat: dict[str, Any] | None = None
        last_progress = time.time()
        while True:
            rc = child.poll()
            if rc is not None:
                break
            beat = read_heartbeat(self.heartbeat_path)
            if beat is not None and beat != last_beat:
                last_beat = beat
                last_progress = time.time()
            # hang detection arms only once the child has beaten at least once:
            # silence from a process that never promised heartbeats is not a hang
            if last_beat is not None and \
                    time.time() - last_progress > cfg.hang_timeout_s:
                hang = True
                logger.warning(
                    "supervisor: no heartbeat for %.0fs (last step %s); "
                    "SIGABRT -> SIGKILL", time.time() - last_progress,
                    last_beat.get("step"))
                self._kill(child)
                rc = child.returncode
                break
            self._sleep(cfg.poll_interval_s)
        tee.join(timeout=5.0)
        duration = time.time() - started
        episode: dict[str, Any] = {
            "index": index,
            "returncode": rc,
            "started": round(started, 3),
            "duration_s": round(duration, 3),
            "hang": hang,
            "heartbeat_step": (last_beat or {}).get("step"),
            "stderr_tail": tee.text()[-8000:],
        }
        if rc != 0 or hang:
            verdict = classify_failure(
                returncode=rc, stderr_tail=episode["stderr_tail"],
                out_dir=self.out_dir, hang=hang, since=started)
            episode.update(verdict)
            dump = self._newest_stall_dump(started)
            if dump:
                episode["stall_dump"] = dump
        self.timeline.complete(
            f"supervisor/episode_{index}", "supervisor", t0,
            self.timeline.now() - t0, returncode=rc,
            taxonomy=episode.get("taxonomy"), hang=hang,
            heartbeat_step=episode["heartbeat_step"])
        return episode

    def _kill(self, child: Any) -> None:
        """SIGABRT (forensics), grace, SIGKILL — then reap."""
        for sig, wait_s in ((signal.SIGABRT, self.config.grace_s),
                            (signal.SIGKILL, 30.0)):
            try:
                child.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass
            try:
                child.wait(timeout=wait_s)
                return
            except subprocess.TimeoutExpired:
                continue

    def _newest_stall_dump(self, since: float) -> str | None:
        """The stall watchdog's stack dump from THIS episode, if it fired."""
        dumps = [p for p in glob.glob(os.path.join(self.out_dir, "stall_*.txt"))
                 if _fresh(p, since)]
        return max(dumps, key=os.path.getmtime) if dumps else None

    def _emit(self, row: dict[str, Any]) -> None:
        if self._metric_sink is not None:
            self._metric_sink(row)
            return
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(os.path.join(self.out_dir, "supervisor.jsonl"), "a") as f:
                f.write(json.dumps(row) + "\n")
        except OSError:
            logger.debug("supervisor metric row write failed", exc_info=True)

    def _write_report(self) -> None:
        _atomic_write_json(self.report_path, self.report)

    def _update_ledger(self, final: bool = False) -> None:
        """Rebuild ``run_ledger.json`` from the artifacts on disk (after every
        episode and at terminal states) and emit its flat ``ledger/*`` row.
        Badput timeline spans land only once, at the terminal update, so the
        trace carries one consolidated lane. Ledger failure never takes the
        supervisor down — accounting is forensics, not control flow."""
        try:
            from automodel_tpu.observability import runledger

            ledger = runledger.update_run_ledger(self.out_dir,
                                                 report=self.report)
            if ledger is None:
                return
            self._emit(runledger.ledger_metric_rows(ledger))
            if final:
                runledger.emit_timeline_spans(ledger, self.timeline,
                                              self._episode_t0s)
        except Exception:
            logger.debug("run ledger update failed", exc_info=True)

    # -- run loop -----------------------------------------------------------
    def run(self) -> int:
        """Supervise until the child exits 0, or the restart budget is spent.

        Returns the final child exit status (0 on success; the last failing
        status — or 1 — on structured abort)."""
        cfg = self.config
        restarts = 0
        while True:
            episode = self._run_episode(len(self.report["episodes"]))
            self.report["episodes"].append(episode)
            self.report["restarts"] = restarts
            row = {
                "supervisor/episode": episode["index"],
                "supervisor/returncode": episode["returncode"],
                "supervisor/restarts": restarts,
            }
            if episode.get("taxonomy"):
                row["supervisor/taxonomy"] = episode["taxonomy"]
            if episode["returncode"] == 0 and not episode["hang"]:
                self.report["status"] = "completed"
                self._write_report()
                # ledger row first: the episode row stays the stream's last
                # line, which is what log tails (and tests) key off
                self._update_ledger(final=True)
                self._emit(row)
                self.timeline.close()
                return 0
            if restarts >= cfg.max_restarts:
                # structured abort: budget spent, the report says why each
                # attempt died — the caller gets a status, not a stacktrace
                self.report["status"] = "aborted"
                self.report["abort_reason"] = (
                    f"restart budget exhausted after {restarts} restarts; "
                    f"last failure: {episode.get('taxonomy', 'unknown')}")
                self._write_report()
                self._update_ledger(final=True)
                self._emit(row)
                self.timeline.close()
                logger.error("supervisor: %s", self.report["abort_reason"])
                return episode["returncode"] or 1
            restarts += 1
            delay = cfg.backoff.delay(restarts - 1)
            row["supervisor/restart_delay_s"] = round(delay, 3)
            self._emit(row)
            self.report["status"] = "restarting"
            self._write_report()
            self._update_ledger()
            self.timeline.instant(
                f"supervisor/restart_{restarts}", "supervisor",
                taxonomy=episode.get("taxonomy"), delay_s=round(delay, 3))
            logger.warning(
                "supervisor: episode %d failed (%s); restart %d/%d in %.1fs",
                episode["index"], episode.get("taxonomy", "unknown"),
                restarts, cfg.max_restarts, delay)
            self._sleep(delay)
