import jax
import pytest
from jax.sharding import PartitionSpec as P

from automodel_tpu.parallel.mesh import MeshAxis, MeshContext, ShardingRules, default_sharding_rules


class TestMeshContext:
    def test_infer_dp_shard(self):
        ctx = MeshContext(tp=2, world_size=8)
        assert ctx.dp_shard == 4
        assert ctx.dp_size == 4

    def test_explicit_sizes_validate(self):
        ctx = MeshContext(pp=2, dp_shard=2, tp=2, world_size=8)
        assert ctx.shape == {"pp": 2, "dp_replicate": 1, "dp_shard": 2, "ep": 1, "cp": 1, "tp": 2}

    def test_bad_world_size_raises(self):
        with pytest.raises(ValueError):
            MeshContext(pp=3, world_size=8)
        with pytest.raises(ValueError):
            MeshContext(dp_shard=3, tp=2, world_size=8)

    def test_negative_axis_raises(self):
        with pytest.raises(ValueError):
            MeshContext(tp=0, world_size=8)

    def test_build_mesh(self, cpu_devices):
        ctx = MeshContext(dp_shard=2, cp=2, tp=2, world_size=8)
        mesh = ctx.build_mesh(cpu_devices)
        assert mesh.axis_names == ("pp", "dp_replicate", "dp_shard", "ep", "cp", "tp")
        assert mesh.shape["dp_shard"] == 2 and mesh.shape["tp"] == 2

    def test_ep_carved_from_data(self):
        ctx = MeshContext(ep=4, tp=2, world_size=8)
        assert ctx.dp_shard == 1
        assert ctx.dp_size == 4  # ep counts toward data parallel degree


class TestShardingRules:
    def test_spec_translation(self, mesh8):
        rules = default_sharding_rules().with_mesh(mesh8)
        spec = rules.spec(("embed", "mlp"))
        assert spec == P(("dp_shard", "ep", "cp"), "tp")

    def test_none_dims(self, mesh8):
        rules = default_sharding_rules().with_mesh(mesh8)
        assert rules.spec((None, "heads", None)) == P(None, "tp")
        assert rules.spec(None) == P()

    def test_batch_spec(self, mesh8):
        rules = default_sharding_rules().with_mesh(mesh8)
        assert rules.spec(("batch", "act_seq")) == P(("dp_replicate", "dp_shard", "ep"), ("cp", "tp"))

    def test_conflict_within_spec_dropped(self, mesh8):
        # Same mesh axis mapped twice in one spec: second use is dropped.
        rules = ShardingRules({"a": "tp", "b": "tp"}, mesh8)
        assert rules.spec(("a", "b")) == P("tp")

    def test_unknown_logical_axis_is_replicated(self, mesh8):
        rules = default_sharding_rules().with_mesh(mesh8)
        assert rules.spec(("nonexistent",)) == P()

    def test_sharding_shards_array(self, mesh8):
        import numpy as np

        rules = default_sharding_rules().with_mesh(mesh8)
        x = jax.device_put(np.zeros((8, 16)), rules.sharding(("embed", "mlp")))
        # embed dim split over dp_shard(2)*cp(2)=4 -> local shards 2 rows; mlp over tp=2
        assert x.sharding.shard_shape(x.shape) == (2, 8)

    def test_updated_rules(self, mesh8):
        rules = default_sharding_rules().with_mesh(mesh8).updated(mlp=None)
        assert rules.spec(("embed", "mlp")) == P(("dp_shard", "ep", "cp"))

    def test_bad_mesh_axis_raises(self, mesh8):
        with pytest.raises(ValueError):
            ShardingRules({"a": "bogus_axis"}, mesh8)


class TestMeshAxisGroups:
    def test_groups(self):
        assert MeshAxis.DATA == ("dp_replicate", "dp_shard", "ep")
        assert MeshAxis.FSDP == ("dp_shard", "ep", "cp")


class TestMainProcessFirst:
    def test_single_process_yields_true(self):
        from automodel_tpu.parallel.init import main_process_first

        ran = []
        with main_process_first("t") as should_work:
            if should_work:
                ran.append(1)
        assert ran == [1]


class TestLayerFlags:
    def test_bitfield_semantics(self):
        """layer_flags packs sliding (bit 0) and NoPE (bit 1) into one int
        stream so scan/pipeline tuple shapes never change as flags accrue."""
        from automodel_tpu.models.common.transformer import DenseDecoderConfig

        cfg = DenseDecoderConfig(
            num_hidden_layers=4, sliding_window=8,
            layer_types=["sliding_attention", "full_attention",
                         "sliding_attention", "full_attention"],
            no_rope_layers=[1, 1, 0, 0],  # HF semantics: 1 = rope ON
        )
        # bit0 = sliding, bit1 = NoPE
        assert cfg.layer_flags == [1, 0, 1 | 2, 2]
        # all-rope, no sliding degenerates to zeros (the llama fast path)
        plain = DenseDecoderConfig(num_hidden_layers=2)
        assert plain.layer_flags == [0, 0]
