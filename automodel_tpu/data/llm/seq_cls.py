"""Sequence-classification datasets (reference datasets/llm/seq_cls.py GLUE_MRPC).

Examples carry ``{"input_ids", "label"}``; ``seq_cls_collate`` pads to fixed length
with segment ids so the model pools the last real token.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import numpy as np

from automodel_tpu.data.llm.column_mapped import _load_rows

__all__ = ["SeqClsDataset", "GLUE_MRPC", "seq_cls_collate"]


class SeqClsDataset:
    """Generic text(-pair) classification from local json/jsonl or the HF hub."""

    def __init__(
        self,
        tokenizer,
        path_or_dataset_id: str,
        text_column: str = "text",
        text_pair_column: str | None = None,
        label_column: str = "label",
        split: str = "train",
        limit_dataset_samples: int | None = None,
        config_name: str | None = None,
    ):
        self.rows = _load_rows(path_or_dataset_id, split, config_name)
        if limit_dataset_samples:
            self.rows = self.rows[:limit_dataset_samples]
        self.tokenizer = tokenizer
        self.text_column = text_column
        self.text_pair_column = text_pair_column
        self.label_column = label_column

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict[str, Any]:
        row = self.rows[i]
        text = str(row[self.text_column])
        if self.text_pair_column:
            sep = getattr(self.tokenizer, "sep_token", None) or "\n"
            text = text + sep + str(row[self.text_pair_column])
        return {
            "input_ids": self.tokenizer.encode(text),
            "label": int(row[self.label_column]),
        }


class GLUE_MRPC(SeqClsDataset):
    """Sentence-pair paraphrase classification (reference seq_cls.py GLUE_MRPC)."""

    def __init__(self, tokenizer, split: str = "train", limit_dataset_samples: int | None = None,
                 path_or_dataset_id: str = "nyu-mll/glue"):
        super().__init__(
            tokenizer, path_or_dataset_id,
            text_column="sentence1", text_pair_column="sentence2", label_column="label",
            split=split, limit_dataset_samples=limit_dataset_samples,
            config_name="mrpc",
        )


def seq_cls_collate(
    examples: Sequence[Mapping[str, Any]], seq_len: int, pad_token_id: int = 0
) -> dict[str, np.ndarray]:
    b = len(examples)
    input_ids = np.full((b, seq_len), pad_token_id, np.int32)
    segment_ids = np.zeros((b, seq_len), np.int32)
    positions = np.zeros((b, seq_len), np.int32)
    labels = np.zeros((b,), np.int32)
    for row, ex in enumerate(examples):
        ids = np.asarray(ex["input_ids"], np.int32)[:seq_len]
        n = len(ids)
        input_ids[row, :n] = ids
        segment_ids[row, :n] = 1
        positions[row, :n] = np.arange(n)
        labels[row] = int(ex["label"])
    return {
        "input_ids": input_ids,
        "segment_ids": segment_ids,
        "positions": positions,
        "labels": labels,
    }
