"""Config-driven auto-aliasing for unregistered HF architectures.

The reference wraps ANY HF class day-0 by instantiating the HF module itself
(reference _transformers/model_init.py:89). Torch-free equivalent: most
``*ForCausalLM`` architectures are *llama deltas* — same pre-norm RMSNorm +
rope GQA attention + gated-SiLU MLP body, varying only in config-level
geometry (head counts, rope variant, biases, norm eps). For an architecture
the registry doesn't know, this module checks every field of its config.json
against the dense-decoder lineage's semantics and

- maps it onto :class:`automodel_tpu.models.llama.model.LlamaForCausalLM`
  when every field is consumed, cosmetic, or pinned at the llama-equivalent
  value, and
- raises :class:`StructuralDivergence` naming the exact divergent field(s)
  otherwise (never a silent wrong-math load).

A curated denylist covers architectures whose config fields LOOK llama-shaped
but whose *code* differs (norm placement, parallel blocks) — field inspection
cannot see code, so these are pinned by hand with the reason; the logits-parity
suite in tests/unit/test_structural_alias.py verifies both directions against
the real transformers implementations.
"""

from __future__ import annotations

import dataclasses
import logging

logger = logging.getLogger(__name__)

__all__ = ["StructuralDivergence", "resolve_llama_delta"]


class StructuralDivergence(Exception):
    """The config genuinely diverges from the llama lineage; message names the field."""


# Architectures whose configs pass the field check but whose transformer BLOCK
# differs in code — verified against the transformers implementations (logits
# mismatch at identical weights). Field inspection cannot detect these.
_DENYLIST = {
    # Olmo2/Olmo3 graduated to registered families (llama/model.py: post-norm
    # placement + whole-projection qk-RMSNorm via norm_placement/qk_norm_whole)
    # Glm4ForCausalLM (dense) graduated to a registered family (models/glm4);
    # old GlmForCausalLM aliases via _ARCH_DELTAS (llama + interleaved rope)
    # CohereForCausalLM graduated to a registered family; Cohere2 changes the
    # block again (sliding/rope pattern) and stays pinned
    "Cohere2ForCausalLM": "parallel attention+MLP block with per-layer rope/sliding "
                          "pattern (Cohere2) not yet mapped",
}

# Code-level deltas that ARE expressible as dense-decoder config knobs but are
# invisible in the arch's config.json — verified by the logits-parity suite.
# (Helium/Ernie rotate consecutive element pairs where llama rotates the
# half-split; both implementations exist in ops/rope.py.) Values are either a
# static dict or a callable(hf_config) -> dict for deltas that read config
# values the llama from_hf doesn't consume.
_ARCH_DELTAS = {
    "HeliumForCausalLM": {"rope_interleaved": True},
    "Ernie4_5ForCausalLM": {"rope_interleaved": True},
    # OLMo v1: NON-PARAMETRIC LayerNorm (no weight, no bias, eps pinned 1e-5
    # in code — transformers OlmoLayerNorm) + optional qkv clamping
    "OlmoForCausalLM": lambda hf: {
        "norm_type": "layernorm", "norm_param": False, "rms_norm_eps": 1e-5,
        "clip_qkv": hf.get("clip_qkv"),
    },
    # Starcoder2: affine LayerNorm (weight+bias), ungated c_fc/c_proj MLP with
    # tanh-gelu, biases on every linear (use_bias)
    "Starcoder2ForCausalLM": lambda hf: {
        "norm_type": "layernorm", "norm_bias": True,
        "rms_norm_eps": hf.get("norm_epsilon", 1e-5),
        "mlp_gated": False,
        # HF "gelu_pytorch_tanh" == our tanh-approx "gelu"; bare HF "gelu" is
        # the EXACT erf form — mapping it to the tanh approximation would
        # diverge ~1e-3, far past the parity bar
        "mlp_act": ("gelu_exact" if hf.get("hidden_act") == "gelu" else "gelu"),
        "hf_mlp_names": ("c_fc", "c_proj"),
        "mlp_bias": bool(hf.get("use_bias", True)),
        "attention_bias": bool(hf.get("use_bias", True)),
        "attention_out_bias": bool(hf.get("use_bias", True)),
    },
    # StableLM: affine LayerNorm + partial rope (partial_rotary_factor is
    # consumed by from_hf) + optional parallel residual / qkv bias
    "StableLmForCausalLM": lambda hf: {
        "norm_type": "layernorm", "norm_bias": True,
        "rms_norm_eps": hf.get("layer_norm_eps", 1e-5),
        "attention_bias": bool(hf.get("use_qkv_bias", False)),
        "parallel_block": bool(hf.get("use_parallel_residual", False)),
    },
}

# Per-arch extra config fields the delta itself consumes (bypassing the
# generic gates); each maps to a predicate over the value so a checkpoint
# with an UNEXPECTED value still fails loudly instead of silently mis-mapping.
_ARCH_FIELDS = {
    "OlmoForCausalLM": {"clip_qkv": lambda v: True},
    "Starcoder2ForCausalLM": {
        "use_bias": lambda v: True,
        "norm_epsilon": lambda v: True,
        "hidden_act": lambda v: v in ("gelu_pytorch_tanh", "gelu"),
        "residual_dropout": lambda v: not v,
        "embedding_dropout": lambda v: not v,
    },
    "StableLmForCausalLM": {
        "use_qkv_bias": lambda v: True,
        "use_parallel_residual": lambda v: True,
        "layer_norm_eps": lambda v: True,
        # per-head qk LayerNorm (stablelm-2-12b) is NOT mapped; default False
        # checkpoints pass, qk_layernorm=True fails via the generic gate
    },
}

# rope_scaling variants ops/rope.py:26 implements bit-for-bit
_ROPE_TYPES = {None, "default", "linear", "llama3", "longrope", "yarn"}

# Fields LlamaConfig.from_hf / DenseDecoderConfig consume (llama/model.py:29-51).
_CONSUMED = {
    "vocab_size", "hidden_size", "intermediate_size", "num_hidden_layers",
    "num_attention_heads", "num_key_value_heads", "head_dim",
    "max_position_embeddings", "rope_theta", "rms_norm_eps",
    "tie_word_embeddings", "attention_bias", "qkv_bias", "sliding_window",
    "use_sliding_window", "layer_types", "initializer_range",
    "partial_rotary_factor",
    "embedding_multiplier", "residual_multiplier", "attention_multiplier",
    "logits_scaling", "no_rope_layers", "no_rope_layer_interval",
}

# Fields that never change the computation (identity, tokenizer ids, runtime
# knobs the jax stack doesn't have).
_COSMETIC = {
    "architectures", "model_type", "torch_dtype", "dtype",
    "transformers_version", "_name_or_path", "name_or_path", "auto_map",
    "bos_token_id", "eos_token_id", "pad_token_id", "sep_token_id",
    "unk_token_id", "use_cache", "tokenizer_class", "chat_template",
    "attn_implementation", "_attn_implementation",
    "_attn_implementation_autoset", "output_attentions",
    "output_hidden_states", "return_dict", "use_bfloat16",
    "use_return_dict", "is_decoder", "add_cross_attention", "task_specific_params",
    "gradient_checkpointing", "use_flash_attention_2",
    # PretrainedConfig boilerplate (generation defaults, label maps, export
    # knobs) that transformers serializes but that never touches the math
    "torchscript", "pruned_heads", "chunk_size_feed_forward",
    "is_encoder_decoder", "cross_attention_hidden_size", "tie_encoder_decoder",
    "finetuning_task", "id2label", "label2id", "problem_type", "prefix",
    "decoder_start_token_id", "max_length", "min_length", "do_sample",
    "early_stopping", "num_beams", "num_beam_groups", "diversity_penalty",
    "temperature", "top_k", "top_p", "typical_p", "repetition_penalty",
    "length_penalty", "no_repeat_ngram_size", "encoder_no_repeat_ngram_size",
    "bad_words_ids", "num_return_sequences", "output_scores",
    "return_dict_in_generate", "forced_bos_token_id", "forced_eos_token_id",
    "remove_invalid_values", "exponential_decay_length_penalty",
    "suppress_tokens", "begin_suppress_tokens", "tf_legacy_loss",
    "tokenizer_file", "full_vocab_size",
}

_FALSY = lambda v: not v
_NONE = lambda v: v is None
_ONE = lambda v: v in (None, 1, 1.0)

# Fields accepted only at the value where they mean "same math as llama".
# Each entry: (predicate, human reason used when the predicate fails).
_GATED = {
    "rope_scaling": (
        lambda v: v is None or v.get("rope_type", v.get("type", "default")) in _ROPE_TYPES,
        "rope_scaling variant is not implemented by ops/rope.py",
    ),
    "use_bias": (_FALSY, "linear-layer bias terms are not part of the lineage"),
    "hidden_act": (lambda v: v in ("silu", "swish"), "MLP activation is not gated SiLU"),
    "hidden_activation": (lambda v: v in (None, "silu", "swish"), "MLP activation is not gated SiLU"),
    "activation_function": (lambda v: v in ("silu", "swish"), "MLP activation is not gated SiLU"),
    "mlp_bias": (_FALSY, "llama-lineage MLP has no bias terms"),
    "attention_dropout": (_FALSY, "attention dropout is not part of the lineage"),
    "attn_pdrop": (_FALSY, "attention dropout is not part of the lineage"),
    "resid_pdrop": (_FALSY, "residual dropout is not part of the lineage"),
    "embd_pdrop": (_FALSY, "embedding dropout is not part of the lineage"),
    "hidden_dropout": (_FALSY, "hidden dropout is not part of the lineage"),
    "hidden_dropout_prob": (_FALSY, "hidden dropout is not part of the lineage"),
    "dropout": (_FALSY, "dropout is not part of the lineage"),
    "clip_qkv": (_NONE, "QKV clipping changes the attention math"),
    "pretraining_tp": (_ONE, "pretraining_tp slicing changes the matmul order"),
    "rope_interleaved": (_FALSY, "interleaved rope pairs differ from half-rotation rope"),
    # granite's four mup-style scalars are CONSUMED by DenseDecoderConfig
    # (LlamaConfig.from_hf reads them; transformer.py applies them)
    "logit_scale": (_ONE, "output-logit scaling (cohere convention) is not the granite field"),
    "final_logit_softcapping": (_NONE, "logit soft-capping is the gemma lineage"),
    "attn_logit_softcapping": (_NONE, "attention soft-capping is the gemma lineage"),
    # SmolLM3 NoPE layers are CONSUMED (llama/model.py _no_rope_layers ->
    # DenseDecoderConfig.no_rope_layers, applied per layer via layer_flags)
    "num_experts": (_NONE, "mixture-of-experts MLP (use a registered MoE family)"),
    "num_local_experts": (_NONE, "mixture-of-experts MLP (use a registered MoE family)"),
    "n_routed_experts": (_NONE, "mixture-of-experts MLP (use a registered MoE family)"),
    "moe_intermediate_size": (_NONE, "mixture-of-experts MLP (use a registered MoE family)"),
    "kv_lora_rank": (_NONE, "MLA latent attention (use the DeepseekV3 family)"),
    "q_lora_rank": (_NONE, "MLA latent attention (use the DeepseekV3 family)"),
    "ssm_cfg": (_NONE, "state-space layers (use the NemotronH family)"),
    "layer_norm_eps": (_NONE, "LayerNorm (not RMSNorm) normalization"),
    "layer_norm_epsilon": (_NONE, "LayerNorm (not RMSNorm) normalization"),
    "norm_eps": (_NONE, "ambiguous norm type (rms_norm_eps is the lineage field)"),
    "parallel_attn": (_FALSY, "parallel attention+MLP block"),
    "qk_layernorm": (_FALSY, "whole-projection QK LayerNorm differs from per-head QK-RMSNorm"),
    # per-head qwen3-style QK-RMSNorm IS supported — consumed below
    "use_qk_norm": (lambda v: True, ""),
    "qk_norm": (lambda v: True, ""),
    "max_window_layers": (lambda v: True, ""),  # inert unless use_sliding_window, which _CONSUMED covers
}


def classify_config(hf: dict, architecture: str | None = None) -> list[str]:
    """Return a list of human-readable divergences (empty == llama delta)."""
    arch_fields = _ARCH_FIELDS.get(architecture, {})
    problems = []
    for key, value in hf.items():
        if key in _CONSUMED or key in _COSMETIC or key.startswith("_"):
            continue
        arch_gate = arch_fields.get(key)
        if arch_gate is not None:
            if not arch_gate(value):
                problems.append(
                    f"{key}={value!r} (outside the {architecture} delta's "
                    "supported range)")
            continue
        gate = _GATED.get(key)
        if gate is None:
            problems.append(f"{key}={value!r} (field unknown to the llama lineage)")
        elif not gate[0](value):
            problems.append(f"{key}={value!r} ({gate[1]})")
    return problems


def resolve_llama_delta(architecture: str, hf: dict, backend=None):
    """Map an unregistered ``*ForCausalLM`` config onto the Llama family.

    Returns a model instance, or raises :class:`StructuralDivergence` naming
    the divergent field(s). Mirrors reference model_init.py:89's any-HF-class
    wrapping for the (dominant) llama-delta subset of the CausalLM universe.
    """
    if architecture in _DENYLIST:
        raise StructuralDivergence(
            f"{architecture} cannot auto-alias onto the llama lineage: "
            f"{_DENYLIST[architecture]}. Implement it as a family or register "
            "an explicit mapping with register_model()."
        )
    if not architecture.endswith("ForCausalLM"):
        raise StructuralDivergence(
            f"{architecture} is not a causal-LM architecture; structural "
            "auto-aliasing covers *ForCausalLM configs only."
        )
    raw_delta = _ARCH_DELTAS.get(architecture, {})
    overrides = dict(raw_delta(hf) if callable(raw_delta) else raw_delta)
    problems = classify_config(hf, architecture)
    if "rms_norm_eps" not in hf and "norm_type" not in overrides:
        # configs that omit it are usually NOT RMSNorm; an absent field is as
        # structural as a wrong one — unless the arch delta pins the norm type
        problems.insert(0, "rms_norm_eps missing (norm type unknown — the "
                           "llama lineage is parametric RMSNorm)")
    if problems:
        raise StructuralDivergence(
            f"{architecture} diverges from the llama lineage on: "
            + "; ".join(problems)
            + ". If the divergence is cosmetic for your checkpoint, register "
            "an explicit mapping with automodel_tpu.models.registry.register_model()."
        )
    from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.from_hf(hf)  # consumes partial_rotary_factor directly
    if hf.get("qk_norm") or hf.get("use_qk_norm"):
        overrides["qk_norm"] = True
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    logger.info(
        "architecture %s auto-aliased onto the llama lineage (structural field "
        "check passed%s) — verify held-out logits before trusting a large run",
        architecture, f"; deltas: {overrides}" if overrides else "",
    )
    return LlamaForCausalLM(cfg, backend)
