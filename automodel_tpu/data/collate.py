"""Batch collation for next-token prediction (reference components/datasets/utils.py).

Examples are dicts with ``input_ids`` and optional ``labels`` (pre-masked) or
``prompt_len`` (mask the prompt span). Collation pads/truncates to a *fixed* seq_len —
static shapes are non-negotiable under jit — and emits:

  input_ids (B, S) int32 | labels (B, S) int32 (-100 = ignored) | positions (B, S)
  segment_ids (B, S): 1 for real tokens, 0 for padding (packing reuses this field
  with per-sequence ids — the TPU-native THD replacement, SURVEY.md §5 long-context).

Labels are pre-shifted here (labels[t] = token[t+1]) so the model's logits align
1:1 and the loss never re-slices — one less place for off-by-ones.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import numpy as np

__all__ = ["sft_collate", "shift_example", "stack_batches", "IGNORE_INDEX"]

IGNORE_INDEX = -100


def shift_example(ex: Mapping[str, Any], answer_only_loss: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """Per-example next-token shift -> (input_ids, labels), prompt span masked.

    The single source of truth for the shift/masking arithmetic — sft_collate and
    pack_dataset both build on it.
    """
    ids = np.asarray(ex["input_ids"], dtype=np.int32)
    if "labels" in ex and ex["labels"] is not None:
        tgt_full = np.asarray(ex["labels"], dtype=np.int32)
        return ids[:-1], tgt_full[1:]
    inp, tgt = ids[:-1], ids[1:].copy()
    if answer_only_loss and "prompt_len" in ex:
        # target index t predicts token t+1, so prompt_len-1 targets are masked
        cut = max(int(ex["prompt_len"]) - 1, 0)
        tgt[:cut] = IGNORE_INDEX
    return inp, tgt


def sft_collate(
    examples: Sequence[Mapping[str, Any]],
    seq_len: int,
    pad_token_id: int = 0,
    answer_only_loss: bool = True,
) -> dict[str, np.ndarray]:
    b = len(examples)
    input_ids = np.full((b, seq_len), pad_token_id, dtype=np.int32)
    labels = np.full((b, seq_len), IGNORE_INDEX, dtype=np.int32)
    segment_ids = np.zeros((b, seq_len), dtype=np.int32)
    positions = np.zeros((b, seq_len), dtype=np.int32)

    for row, ex in enumerate(examples):
        inp, tgt = shift_example(ex, answer_only_loss)
        inp, tgt = inp[:seq_len], tgt[:seq_len]  # truncation commutes with the shift
        n = len(inp)
        input_ids[row, :n] = inp
        labels[row, :n] = tgt
        segment_ids[row, :n] = 1
        positions[row, :n] = np.arange(n)
    # padded label positions stay IGNORE_INDEX; mask pad targets too
    labels[segment_ids == 0] = IGNORE_INDEX
    return {
        "input_ids": input_ids,
        "labels": labels,
        "positions": positions,
        "segment_ids": segment_ids,
    }


def stack_batches(batches: Sequence[Mapping[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Stack microbatches into (n_micro, B, S) arrays for the scan inside train_step.

    Tree-mapped so nested batch structures (VLM ``vision_inputs`` dicts) stack
    leaf-wise."""
    import jax

    return jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs], axis=0), *batches
    )
