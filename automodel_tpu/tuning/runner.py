"""Trial runner + the crash-safe trial ledger (``tuner_report.json``).

Every trial the tuner considers leaves an auditable record: its config digest,
the signals snapshot it was judged on, and an outcome — ``pruned(reason)``
(the memory plan rejected it before any compile), ``ran(metrics)``, or
``failed(error)``. The ledger file is written atomically after every trial
(tmp + rename, the write_signals/TraceTimeline discipline) and is *resumable*:
re-running the same search skips trials whose digest already carries an
outcome, byte-identically preserving their entries — a crash mid-search costs
one trial, not the search. Entries carry no wallclock timestamps, so the same
trials + the same measurements produce the same bytes (golden-testable).

Trials also emit flat ``tuner/*`` metric rows (the families contract in
tools/check_metric_keys.py) through the caller's metric sink, and one
``tuner/<digest>`` span per trial on the Chrome-trace timeline (events.py), so
a tuning session reads like any other run in Perfetto.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any, Callable

from automodel_tpu.tuning import policy as _policy
from automodel_tpu.tuning.space import Trial

logger = logging.getLogger(__name__)

__all__ = ["TUNER_REPORT_VERSION", "TrialLedger", "validate_report",
           "run_search", "write_tuned_config", "apply_tuned_config"]

TUNER_REPORT_VERSION = 1


def _atomic_write_json(path: str, doc: dict[str, Any]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".tuner_report.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class TrialLedger:
    """The resumable ``tuner_report.json``: one entry per trial, atomic after
    every append, deterministic bytes for identical searches."""

    def __init__(self, path: str, cell: dict[str, Any] | None = None,
                 bound: str | None = None):
        self.path = str(path)
        doc: dict[str, Any] | None = None
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError) as exc:
                # a torn write cannot happen (atomic rename); a hand-corrupted
                # file must not silently erase the audit trail
                raise ValueError(f"{self.path}: unreadable tuner report ({exc})")
            if doc.get("version") != TUNER_REPORT_VERSION:
                raise ValueError(
                    f"{self.path}: tuner report version {doc.get('version')!r}, "
                    f"expected {TUNER_REPORT_VERSION}")
        if doc is None:
            doc = {"version": TUNER_REPORT_VERSION, "cell": dict(cell or {}),
                   "bound": bound, "trials": [], "winner": None}
        self.doc = doc

    @property
    def completed(self) -> dict[str, dict[str, Any]]:
        """digest -> entry for every trial that already has an outcome."""
        return {e["digest"]: e for e in self.doc.get("trials", [])
                if e.get("outcome")}

    def record(self, entry: dict[str, Any]) -> None:
        self.doc["trials"].append(entry)
        self.write()

    def finalize(self, winner_digest: str | None,
                 attribution: dict[str, Any] | None) -> None:
        self.doc["winner"] = (
            {"digest": winner_digest, "attribution": attribution}
            if winner_digest is not None else None)
        self.write()

    def write(self) -> None:
        _atomic_write_json(self.path, self.doc)


def validate_report(doc: Any) -> list[str]:
    """Schema-check a tuner report; returns problems ([] = valid)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"report is {type(doc).__name__}, expected object"]
    if doc.get("version") != TUNER_REPORT_VERSION:
        problems.append(f"version is {doc.get('version')!r}, "
                        f"expected {TUNER_REPORT_VERSION}")
    trials = doc.get("trials")
    if not isinstance(trials, list):
        return problems + ["trials is not a list"]
    ran: set[str] = set()
    for i, e in enumerate(trials):
        where = f"trials[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where} is not an object")
            continue
        if not isinstance(e.get("digest"), str):
            problems.append(f"{where}.digest missing")
        if not isinstance(e.get("trial"), dict):
            problems.append(f"{where}.trial (override mapping) missing")
        outcome = e.get("outcome")
        if not isinstance(outcome, dict):
            problems.append(f"{where}.outcome missing")
            continue
        status = outcome.get("status")
        payload = {"pruned": "reason", "ran": "metrics", "failed": "error"}
        if status not in payload:
            problems.append(f"{where}.outcome.status is {status!r}")
            continue
        if payload[status] not in outcome:
            problems.append(f"{where}.outcome lacks {payload[status]!r} "
                            f"(status {status})")
        if status == "ran":
            ran.add(e.get("digest"))
    winner = doc.get("winner")
    if winner is not None:
        if not isinstance(winner, dict) or winner.get("digest") not in ran:
            problems.append("winner.digest does not name a ran trial")
        attribution = (winner or {}).get("attribution") or {}
        if not attribution.get("line") or not attribution.get("signal_keys"):
            problems.append("winner.attribution lacks line/signal_keys")
    return problems


def _metric_row(index: int, digest: str, status: str,
                metrics: dict[str, Any] | None) -> dict[str, Any]:
    """The flat ``tuner/*`` row one trial contributes to the metric stream."""
    row: dict[str, Any] = {
        "tuner/trial": index,
        "tuner/digest": digest,
        "tuner/outcome": status,
    }
    for key in ("tuner/tps", "tuner/hbm_gib_peak", "tuner/headroom_gib"):
        if metrics and metrics.get(key) is not None:
            row[key] = metrics[key]
    return row


def run_search(
    trials: list[Trial],
    *,
    measure: Callable[[Trial], dict[str, Any]],
    ledger: TrialLedger,
    plan_fn: Callable[[Trial], Any] | None = None,
    bound: str | None = None,
    baseline: Trial | None = None,
    timeline: Any = None,
    metric_sink: Callable[[dict[str, Any]], None] | None = None,
) -> dict[str, Any]:
    """Walk ``trials`` in signal-guided order; return the winner + attribution.

    ``measure(trial)`` runs one short measured window and returns at least
    ``{"tps": float}``; optional keys: ``hbm_gib_peak``, ``headroom_gib``, and
    ``signals`` (a signals.py cell snapshot stored verbatim on the ledger
    entry). ``plan_fn(trial)`` builds the trial's analytic MemoryPlan for
    pre-compile pruning (None = nothing to prune on). Every trial emits one
    ``tuner/*`` metric row through ``metric_sink`` and one span on
    ``timeline``; the ledger is written after each trial.
    """
    ordered = _policy.order_trials(trials, bound, baseline=baseline)
    done = ledger.completed
    skipped = 0
    for index, trial in enumerate(ordered):
        digest = trial.digest()
        if digest in done:
            skipped += 1
            continue
        t0 = timeline.now() if timeline is not None else 0.0
        plan = plan_fn(trial) if plan_fn is not None else None
        reason = _policy.prune(trial, plan)
        snapshot = None
        if reason is not None:
            status, outcome = "pruned", {"status": "pruned", "reason": reason}
            metrics = _plan_metrics(plan)
            snapshot = _plan_snapshot(plan)
        else:
            try:
                raw = dict(measure(trial))
                snapshot = raw.pop("signals", None)
                metrics = {f"tuner/{k}": v for k, v in raw.items()
                           if isinstance(v, (int, float))}
                metrics.update(_plan_metrics(plan))
                status, outcome = "ran", {"status": "ran", "metrics": metrics}
            except Exception as exc:  # noqa: BLE001 — a dead trial is a ledger
                # entry, not a dead search
                logger.warning("tuner trial %s failed: %r", digest, exc)
                status, outcome = "failed", {"status": "failed", "error": repr(exc)}
                metrics = None
        entry = {"index": index, "digest": digest, "trial": trial.overrides(),
                 "outcome": outcome, "signals": snapshot}
        ledger.record(entry)
        done[digest] = entry
        row = _metric_row(index, digest, status, metrics)
        if metric_sink is not None:
            metric_sink(row)
        if timeline is not None:
            timeline.complete(f"tuner/{digest}", "tuner", t0,
                              timeline.now() - t0, outcome=status,
                              tps=(metrics or {}).get("tuner/tps"))
    ran = [e for e in ledger.doc["trials"]
           if e["outcome"]["status"] == "ran"
           and e["outcome"]["metrics"].get("tuner/tps") is not None]
    ran.sort(key=lambda e: (-e["outcome"]["metrics"]["tuner/tps"], e["digest"]))
    winner = ran[0] if ran else None
    attribution = None
    if winner is not None:
        attribution = _policy.attribute_winner(
            winner, ran[1] if len(ran) > 1 else None, bound=bound)
        ledger.finalize(winner["digest"], attribution)
        if metric_sink is not None:
            metric_sink({"tuner/winner": winner["digest"],
                         "tuner/best_tps": winner["outcome"]["metrics"]["tuner/tps"]})
    else:
        ledger.finalize(None, None)
    counts = {"total": len(ordered), "skipped_resume": skipped}
    for e in ledger.doc["trials"]:
        s = e["outcome"]["status"]
        counts[s] = counts.get(s, 0) + 1
    return {"winner": winner, "attribution": attribution,
            "report_path": ledger.path, "counts": counts}


def _plan_metrics(plan: Any) -> dict[str, Any]:
    if plan is None:
        return {}
    out: dict[str, Any] = {}
    head = plan.headroom_bytes
    if head is not None:
        out["tuner/headroom_gib"] = round(head / 2**30, 4)
    return out


def _plan_snapshot(plan: Any) -> dict[str, Any] | None:
    """A signals cell holding just the memory section — what a pruned trial
    was judged on (it never compiled, so nothing else exists)."""
    if plan is None:
        return None
    from automodel_tpu.observability.signals import build_cell

    return build_cell(memory_plan=plan)


# ------------------------------------------------------------- tuned configs
def write_tuned_config(path: str, *, cell_name: str, entry: dict[str, Any],
                       attribution: dict[str, Any] | None,
                       source: str = "bench.py --tune") -> None:
    """Emit the winning trial as a ``tuned/<cell>.yaml`` the recipe loads.

    The file is two sections: ``overrides`` (dotted config paths, applied with
    ``ConfigNode.set_by_path``) and ``tuned`` (provenance: cell, digest,
    source, the attribution line) — so a tuned run's run_header can say
    exactly where its knobs came from.
    """
    import yaml

    doc = {
        "tuned": {
            "cell": cell_name,
            "digest": entry["digest"],
            "source": source,
            "attribution": (attribution or {}).get("line"),
        },
        "overrides": dict(entry["trial"]),
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write("# generated by the autotuner — docs/observability.md "
                "\"Autotuning & the perf lab\"\n")
        yaml.safe_dump(doc, f, sort_keys=True, default_flow_style=False)
    os.replace(tmp, path)


def apply_tuned_config(cfg: Any, path: str) -> dict[str, Any]:
    """Apply a tuned config onto a recipe ConfigNode; return the provenance
    fields the run_header records (``tuned_config``/``tuned_cell``/
    ``tuned_digest``). Raises with a pointer at the generator when the file
    is missing — a tuned config is an artifact, not something to guess."""
    import yaml

    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"tuned_config {path!r} not found — generate it with "
            f"`python bench.py --tune` (docs/observability.md "
            f"\"Autotuning & the perf lab\")")
    overrides = (doc or {}).get("overrides") or {}
    for key, value in sorted(overrides.items()):
        cfg.set_by_path(key, value)
    meta = (doc or {}).get("tuned") or {}
    return {"tuned_config": str(path), "tuned_cell": meta.get("cell"),
            "tuned_digest": meta.get("digest")}
