"""Training-dynamics & numerics telemetry: the per-layer ``dynamics/*`` rows.

The train loop's native signal is two scalars (loss, grad_norm) and one
boolean (nonfinite). That is enough to *detect* a divergence and not nearly
enough to *localize* one — a loss spike at step 40k names no layer, and a
tripped nonfinite guard says "somewhere". This module is the missing axis of
the observability lab (docs/observability.md): what the optimizer is actually
doing to the weights, per top-level module subtree.

Two halves, mirroring the memory pillar's split:

**In-graph** (called from ``training/train_step.py`` inside jit): pure
reductions over the grad/param/update/optimizer-moment pytrees, bucketed by
top-level module path using the same block taxonomy as the profiler scopes
(``utils/tracing.py scope_blocks``: attention / mlp / moe). Each bucket
reduces to four scalars — grad norm, param norm, update-to-weight ratio,
first-moment norm — so nothing but replicated scalars ever leaves the device:
the sums run sharded and XLA inserts the same cross-device reduction
``optax.global_norm`` already pays for. A ``num`` pseudo-bucket carries the
numerics counters (grad amax + fp8 e4m3/e5m2 saturation fractions on the grad
path, ``ops/fp8.py`` constants) and, under ``guard_nonfinite``, a per-subtree
isfinite map gives nonfinite provenance: the skip event can name the first
offending subtree instead of a bare boolean.

**Host-side**: per-layer EMA trends and excursion attribution
(:class:`DynamicsStats`), and a loss-spike flight recorder modeled on
``observability/oom.py`` — continuously cheap (ring buffers of recent
dynamics/metric rows), expensive only at the excursion, when it dumps
``spike_report.json`` with the per-layer history, the suspect layer, the
offending batch fingerprint, and the last N metric rows. ``dump`` never
raises; a failed report must not take down the run it is documenting.

Overhead contract (docs/observability.md "Training dynamics & numerics"): the
per-bucket reductions are computed every step when the pillar is enabled (they
fuse into the step like ``global_norm`` does), while the *host sync* — pulling
the ~two dozen scalars — happens only every ``dynamics.every_n_steps``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import signal as _signal
import threading
import time
import zlib
from typing import Any, Callable

logger = logging.getLogger(__name__)

__all__ = [
    "DynamicsConfig",
    "DynamicsStats",
    "DynamicsTracker",
    "SpikeFlightRecorder",
    "batch_fingerprint",
    "bucket_for_path",
    "dynamics_tree",
    "first_nonfinite_bucket",
    "flatten_dynamics",
    "nonfinite_provenance",
    "subtree_sq_norms",
]

# leaf-name -> block taxonomy, matching the profiler scope names the layer
# bodies install (transformer.py/moe_transformer.py scope_blocks: "attention",
# "mlp", "moe"). Prefix match on ANY path component, so the dense tree
# ("layers", "wq"), the LoRA tree ("layers", "wq", "lora_a") and the MoE tree
# ("layers", "moe", "w_gate") all land where a profiler trace would put them.
_MOE_PREFIXES = ("moe", "router", "expert", "shared_expert")
_ATTN_PREFIXES = ("wq", "wk", "wv", "wo", "bq", "bk", "bv", "bo",
                  "attn", "q_norm", "k_norm", "sink")
_MLP_PREFIXES = ("w_gate", "w_up", "w_down", "mlp", "c_fc", "c_proj")

# the pseudo-bucket carrying tree-wide numerics counters; never produced by
# path classification (it has no leading module-path component)
NUMERICS_BUCKET = "num"


def _matches(name: str, prefixes: tuple[str, ...]) -> bool:
    return any(name.startswith(p) for p in prefixes)


def bucket_for_path(path: tuple) -> str:
    """Top-level-module bucket for one pytree leaf path.

    Non-layer top-level entries ("embed", "final_norm", "lm_head") are their
    own buckets; anything under "layers" is classified into the scope-block
    taxonomy ("layers.attention" / "layers.mlp" / "layers.moe", fallback
    "layers.other"). Unknown structures degrade to their first path component
    so PEFT/custom trees still bucket deterministically.
    """
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(p if key is None else key))
    if not parts:
        return "params"
    head = parts[0]
    if head != "layers":
        return head
    for name in parts[1:]:
        if _matches(name, _MOE_PREFIXES):
            return "layers.moe"
        if _matches(name, _ATTN_PREFIXES):
            return "layers.attention"
        if _matches(name, _MLP_PREFIXES):
            return "layers.mlp"
    return "layers.other"


def _float_leaves_with_buckets(tree: Any):
    """(bucket, f32 leaf) pairs for every floating leaf of ``tree``."""
    import jax
    import jax.numpy as jnp

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(leaf.dtype, jnp.floating):
            continue
        out.append((bucket_for_path(path), leaf))
    return out


def subtree_sq_norms(tree: Any) -> dict[str, Any]:
    """Per-bucket sum of squares (fp32), as replicated device scalars.

    Reductions only — each sharded leaf reduces in place and XLA derives the
    cross-device sum from the sharding; no tensor is gathered to host.
    """
    import jax.numpy as jnp

    out: dict[str, Any] = {}
    for bucket, leaf in _float_leaves_with_buckets(tree):
        sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        out[bucket] = sq if bucket not in out else out[bucket] + sq
    return out


def _subtree_all_finite(tree: Any) -> dict[str, Any]:
    """Per-bucket all-isfinite flags (device bool scalars)."""
    import jax.numpy as jnp

    out: dict[str, Any] = {}
    for bucket, leaf in _float_leaves_with_buckets(tree):
        ok = jnp.all(jnp.isfinite(leaf))
        out[bucket] = ok if bucket not in out else out[bucket] & ok
    return out


def _find_moment_tree(opt_state: Any) -> Any:
    """First first-moment accumulator found in an optax state tree, or None
    (optimizers without one — adafactor, plain sgd — simply omit the
    ``moment_norm`` metric). The walk itself lives with the optimizer
    builders, which own the state shapes it must understand."""
    from automodel_tpu.optim.builder import first_moment_tree

    return first_moment_tree(opt_state)


def dynamics_tree(grads: Any, params: Any, updates: Any,
                  opt_state: Any = None) -> dict[str, dict[str, Any]]:
    """The compact per-subtree dynamics pytree the jitted step returns.

    ``{bucket: {grad_norm, param_norm, upd_ratio[, moment_norm]}}`` plus the
    ``num`` pseudo-bucket with tree-wide numerics counters: grad amax and the
    fraction of grad values past the fp8 e4m3/e5m2 representable maxima
    (``ops/fp8.py``) — the saturation-overflow signal a precision downshift
    must watch. All values are fp32 device scalars; call inside jit.
    """
    import jax.numpy as jnp

    from automodel_tpu.ops.fp8 import E4M3_MAX, E5M2_MAX

    g_sq = subtree_sq_norms(grads)
    p_sq = subtree_sq_norms(params)
    u_sq = subtree_sq_norms(updates)
    m_sq: dict[str, Any] = {}
    moments = _find_moment_tree(opt_state) if opt_state is not None else None
    if moments is not None:
        m_sq = subtree_sq_norms(moments)

    out: dict[str, dict[str, Any]] = {}
    for bucket in g_sq:
        row = {
            "grad_norm": jnp.sqrt(g_sq[bucket]),
            "param_norm": jnp.sqrt(p_sq.get(bucket, jnp.float32(0.0))),
            "upd_ratio": jnp.sqrt(u_sq.get(bucket, jnp.float32(0.0)))
            / jnp.maximum(jnp.sqrt(p_sq.get(bucket, jnp.float32(0.0))), 1e-12),
        }
        if bucket in m_sq:
            row["moment_norm"] = jnp.sqrt(m_sq[bucket])
        out[bucket] = row

    # numerics counters on the grad path: amax + saturation fractions vs the
    # fp8 formats the bwd/fwd quantizers use, and the nonfinite value count
    amax = jnp.float32(0.0)
    e4m3_sat = jnp.float32(0.0)
    e5m2_sat = jnp.float32(0.0)
    nonfinite_ct = jnp.float32(0.0)
    count = jnp.float32(0.0)
    for _, leaf in _float_leaves_with_buckets(grads):
        a = jnp.abs(leaf.astype(jnp.float32))
        amax = jnp.maximum(amax, jnp.max(a))
        e4m3_sat = e4m3_sat + jnp.sum(a >= E4M3_MAX)
        e5m2_sat = e5m2_sat + jnp.sum(a >= E5M2_MAX)
        nonfinite_ct = nonfinite_ct + jnp.sum(~jnp.isfinite(leaf))
        count = count + jnp.float32(leaf.size)
    denom = jnp.maximum(count, 1.0)
    out[NUMERICS_BUCKET] = {
        "grad_amax": amax,
        "e4m3_sat_frac": e4m3_sat / denom,
        "e5m2_sat_frac": e5m2_sat / denom,
        "nonfinite_ct": nonfinite_ct,
    }
    return out


def nonfinite_provenance(grads: Any, loss: Any) -> dict[str, Any]:
    """Per-subtree nonfinite flags (True = bucket carries a nonfinite grad).

    Joined by a ``loss`` entry so a nonfinite loss with finite grads (a fwd
    overflow the bwd zeroed) still names its origin. Device bools; the host
    names the first offending bucket via :func:`first_nonfinite_bucket`.
    """
    import jax.numpy as jnp

    finite = _subtree_all_finite(grads)
    out = {bucket: ~ok for bucket, ok in finite.items()}
    out["loss"] = ~jnp.isfinite(loss)
    return out


def first_nonfinite_bucket(nonfinite_map: dict[str, Any]) -> str | None:
    """First offending subtree in canonical order, from host-side values."""
    import numpy as np

    named = [b for b in sorted(nonfinite_map) if b != "loss"]
    for bucket in named:
        if bool(np.asarray(nonfinite_map[bucket])):
            return bucket
    if "loss" in nonfinite_map and bool(np.asarray(nonfinite_map["loss"])):
        return "loss"
    return None


def flatten_dynamics(tree: dict[str, dict[str, Any]],
                     ndigits: int = 6) -> dict[str, float]:
    """Device dynamics pytree -> flat ``dynamics/<layer>/<metric>`` floats."""
    import numpy as np

    out: dict[str, float] = {}
    for bucket in sorted(tree):
        for metric in sorted(tree[bucket]):
            val = float(np.asarray(tree[bucket][metric]))
            out[f"dynamics/{bucket}/{metric}"] = round(val, ndigits)
    return out


def batch_fingerprint(stack: Any) -> dict[str, Any]:
    """Cheap identity of one batch stack for the spike report: shapes + a
    CRC32 of the host-addressable token-id shards. Host-local by design
    (multi-host arrays only expose addressable shards) and never raises —
    the fingerprint is forensic garnish, not load-bearing."""
    import numpy as np

    out: dict[str, Any] = {}
    try:
        for key in ("input_ids", "q_ids", "p_ids", "labels"):
            arr = stack.get(key) if hasattr(stack, "get") else None
            if arr is None:
                continue
            out[f"{key}_shape"] = list(getattr(arr, "shape", ()))
            shards = getattr(arr, "addressable_shards", None)
            crc = 0
            if shards is not None:
                for shard in shards:
                    crc = zlib.crc32(np.ascontiguousarray(shard.data).tobytes(), crc)
            else:
                crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
            out[f"{key}_crc32"] = int(crc)
    except Exception:
        logger.debug("batch fingerprint failed", exc_info=True)
        out["fingerprint_error"] = True
    return out


# --------------------------------------------------------------------- config
@dataclasses.dataclass
class DynamicsConfig:
    enabled: bool = False
    every_n_steps: int = 10  # host-sync cadence for the dynamics scalars
    ema_decay: float = 0.9  # per-layer trend EMA
    history: int = 50  # dynamics rows kept for the spike report
    spike_zscore: float = 6.0  # loss z-score that trips the flight recorder
    spike_window: int = 32  # rolling losses behind the z-score
    spike_min_history: int = 8  # losses before excursions are judged
    spike_keep_rows: int = 20  # metric rows ringed into the report
    spike_cooldown_steps: int = 50  # min steps between self-triggered dumps
    snapshot_signal: str | None = "SIGUSR2"  # on-demand snapshot; None = off

    @classmethod
    def from_dict(cls, raw: Any) -> "DynamicsConfig":
        """Build from the ``observability.dynamics`` YAML subsection."""
        if raw is None:
            return cls()
        if isinstance(raw, bool):
            return cls(enabled=raw)
        if hasattr(raw, "to_dict"):
            raw = raw.to_dict()
        d = dict(raw)
        kw: dict[str, Any] = {"enabled": bool(d.get("enabled", True))}
        for field, cast in (("every_n_steps", int), ("ema_decay", float),
                            ("history", int), ("spike_zscore", float),
                            ("spike_window", int), ("spike_min_history", int),
                            ("spike_keep_rows", int),
                            ("spike_cooldown_steps", int)):
            if d.get(field) is not None:
                kw[field] = cast(d[field])
        if "snapshot_signal" in d:
            sig = d["snapshot_signal"]
            kw["snapshot_signal"] = None if (not sig or str(sig).lower() == "none") else str(sig)
        return cls(**kw)

    def resolve_signal(self) -> int | None:
        if not self.snapshot_signal:
            return None
        return getattr(_signal, str(self.snapshot_signal).upper())


class DynamicsStats:
    """Per-layer EMA trends + excursion attribution, host-side.

    ``update(flat_row)`` folds one cadence row into per-(layer, metric) EMAs
    and returns the EMA keys to append to the row
    (``dynamics/<layer>/grad_norm_ema``). ``suspect()`` names the layer whose
    current value deviates most from its own trend — the attribution a
    rollback verdict cites. The ratio compares against the EMA *before* the
    current sample so a genuine step change scores its full excursion.

    A param-norm excursion outranks any grad-norm excursion: backprop spreads
    a corrupted layer's gradient blowup to every subtree upstream of it (the
    worst grad ratio typically lands far from the fault), while the weights
    themselves only jump in the subtree that was actually mutated. Among
    param-norm excursions past ``_PARAM_EXCURSION`` the largest wins; with
    none (e.g. a bad batch: loss spikes, weights are fine) the worst grad-norm
    ratio attributes as before.
    """

    # metrics whose excursions are attribution-worthy; upd_ratio tracks lr
    # schedule moves too closely to blame a layer with
    _ATTRIB_METRICS = ("grad_norm", "param_norm")
    # a >10x jump in a subtree's weight norm in one cadence window is never
    # healthy optimization — treat it as the fault site
    _PARAM_EXCURSION = 10.0

    def __init__(self, ema_decay: float = 0.9):
        if not 0.0 < ema_decay < 1.0:
            raise ValueError(f"ema_decay must be in (0, 1), got {ema_decay}")
        self.ema_decay = float(ema_decay)
        self._ema: dict[str, float] = {}  # "layer/metric" -> ema
        self._last_suspect: tuple[str, str, float] | None = None

    def update(self, flat_row: dict[str, float]) -> dict[str, float]:
        best: tuple[float, str, str] | None = None
        best_param: tuple[float, str, str] | None = None
        out: dict[str, float] = {}
        for key, val in flat_row.items():
            if not key.startswith("dynamics/"):
                continue
            _, layer, metric = key.split("/", 2)
            if layer == NUMERICS_BUCKET:
                continue
            ref = f"{layer}/{metric}"
            prev = self._ema.get(ref)
            if (metric in self._ATTRIB_METRICS and prev is not None
                    and val == val):  # NaN never attributes via ratio
                ratio = val / max(prev, 1e-12)
                if best is None or ratio > best[0]:
                    best = (ratio, layer, metric)
                if (metric == "param_norm" and ratio > self._PARAM_EXCURSION
                        and (best_param is None or ratio > best_param[0])):
                    best_param = (ratio, layer, metric)
            if val == val:  # nonfinite samples must not poison the trend
                self._ema[ref] = (val if prev is None
                                  else self.ema_decay * prev
                                  + (1 - self.ema_decay) * val)
            if metric == "grad_norm" and ref in self._ema:
                out[f"dynamics/{layer}/grad_norm_ema"] = round(self._ema[ref], 6)
        # corrupted weights localize via param_norm; grad blowups propagate
        if best_param is not None:
            best = best_param
        if best is not None:
            self._last_suspect = (best[1], best[2], round(best[0], 4))
        return out

    def suspect(self) -> tuple[str, str, float] | None:
        """(layer, metric, ratio-vs-trend) of the worst recent excursion."""
        return self._last_suspect


class SpikeFlightRecorder:
    """Continuously cheap, expensive only at the excursion (oom.py contract).

    ``observe`` keeps a rolling loss window and returns the z-score when the
    current loss is an excursion; ``record_dynamics``/``record_row`` are deque
    appends. ``dump`` writes ``spike_report.json`` atomically and NEVER raises
    — the report documents a failing run, it must not become the failure.
    """

    def __init__(self, out_dir: str, zscore_threshold: float = 6.0,
                 window: int = 32, min_history: int = 8,
                 keep_rows: int = 20, history: int = 50,
                 cooldown_steps: int = 50):
        self.out_dir = str(out_dir)
        self.report_path = os.path.join(self.out_dir, "spike_report.json")
        self.zscore_threshold = float(zscore_threshold)
        self.min_history = max(int(min_history), 2)
        self.cooldown_steps = int(cooldown_steps)
        self._losses: collections.deque = collections.deque(maxlen=max(int(window), 2))
        self._dyn_rows: collections.deque = collections.deque(maxlen=max(int(history), 1))
        self._rows: collections.deque = collections.deque(maxlen=max(int(keep_rows), 1))
        self._last_dump_step: int | None = None
        self.dumps = 0

    def observe(self, step: int, loss: float) -> float | None:
        """z-score when ``loss`` is an excursion vs the rolling window, else
        None. Excursions (and nonfinite losses, scored as inf) never enter the
        window — a spike must not inflate the std it is judged against."""
        import math

        if not math.isfinite(loss):
            return math.inf
        if len(self._losses) >= self.min_history:
            n = len(self._losses)
            mean = sum(self._losses) / n
            var = sum((x - mean) ** 2 for x in self._losses) / n
            std = max(math.sqrt(var), 1e-3, 1e-3 * abs(mean))
            z = (loss - mean) / std
            if z > self.zscore_threshold:
                return z
        self._losses.append(float(loss))
        return None

    def record_dynamics(self, step: int, flat_row: dict[str, float]) -> None:
        self._dyn_rows.append({"step": int(step), **flat_row})

    def record_row(self, step: int, row: dict[str, Any]) -> None:
        self._rows.append({"step": int(step), **row})

    def in_cooldown(self, step: int) -> bool:
        return (self._last_dump_step is not None
                and step - self._last_dump_step < self.cooldown_steps)

    def dump(self, step: int, reason: str, loss: float | None = None,
             zscore: float | None = None,
             suspect: tuple[str, str, float] | None = None,
             batch: dict[str, Any] | None = None) -> str | None:
        """Write ``spike_report.json``; returns its path, or None on failure."""
        try:
            self._last_dump_step = int(step)
            report: dict[str, Any] = {
                "spike_report": True,
                "time_unix": time.time(),
                "step": int(step),
                "reason": str(reason),
                "loss": loss,
                "zscore": zscore,
                "suspect": (None if suspect is None else
                            {"layer": suspect[0], "metric": suspect[1],
                             "ratio_vs_ema": suspect[2]}),
                "batch": batch or {},
                "loss_window": [round(x, 6) for x in self._losses],
                "dynamics_history": list(self._dyn_rows),
                "last_rows": list(self._rows),
            }
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = f"{self.report_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, self.report_path)
            self.dumps += 1
            logger.error("loss-spike flight recorder: report written to %s "
                         "(reason=%s, suspect=%s)", self.report_path, reason,
                         report["suspect"])
            return self.report_path
        except Exception:
            logger.exception("spike flight recorder failed (run continues)")
            return None


class DynamicsTracker:
    """The manager-facing bundle: cadence, EMA stats, flight recorder, and the
    SIGUSR2 on-demand snapshot (mirror of the profiler's SIGUSR1 hook — the
    handler only sets a flag; the dump happens on the train-loop thread)."""

    def __init__(self, config: DynamicsConfig, out_dir: str,
                 metric_sink: Callable[..., None] | None = None):
        self.config = config
        self.out_dir = str(out_dir)
        self.stats = DynamicsStats(config.ema_decay)
        self.recorder = SpikeFlightRecorder(
            out_dir,
            zscore_threshold=config.spike_zscore,
            window=config.spike_window,
            min_history=config.spike_min_history,
            keep_rows=config.spike_keep_rows,
            history=config.history,
            cooldown_steps=config.spike_cooldown_steps,
        )
        self._sink = metric_sink
        self.signum = config.resolve_signal()
        self.snapshot_path = os.path.join(self.out_dir, "dynamics_snapshot.json")
        self._snapshot_requested = False
        self._prev_handler: Any = None
        self._handler_installed = False
        from automodel_tpu.ops.fp8 import AmaxHistory

        self.amax_history = AmaxHistory()

    # ------------------------------------------------------------- cadence/rows
    def due(self, step: int) -> bool:
        return step % max(int(self.config.every_n_steps), 1) == 0

    def row(self, step: int, dyn_tree: dict[str, dict[str, Any]]) -> dict[str, float]:
        """One cadence sample: flatten the device pytree, fold EMAs, join the
        fp8 amax history, feed the flight-recorder ring."""
        flat = flatten_dynamics(dyn_tree)
        flat.update(self.stats.update(flat))
        amax = flat.get(f"dynamics/{NUMERICS_BUCKET}/grad_amax")
        if amax is not None:
            flat.update(self.amax_history.update(amax))
        self.recorder.record_dynamics(step, flat)
        return flat

    def grad_norm_of(self, flat_row: dict[str, float] | None) -> float | None:
        """The whole-tree grad amax proxy the cross-host wire carries is the
        per-step global grad_norm the recipe already has; this helper exists
        for symmetry when only a dynamics row is at hand."""
        if not flat_row:
            return None
        sq = sum(v * v for k, v in flat_row.items()
                 if k.endswith("/grad_norm") and k.count("/") == 2)
        return sq ** 0.5 if sq else None

    # ----------------------------------------------------------------- signal
    def start(self) -> "DynamicsTracker":
        if self.signum is not None and not self._handler_installed:
            if threading.current_thread() is not threading.main_thread():
                logger.warning("dynamics snapshot handler not installed (non-main thread)")
            else:
                self._prev_handler = _signal.signal(self.signum, self._handle_signal)
                self._handler_installed = True
        return self

    def _handle_signal(self, signum, frame) -> None:
        self._snapshot_requested = True  # flag only: json/io is not signal-safe

    def request_snapshot(self) -> None:
        """Programmatic equivalent of SIGUSR2."""
        self._snapshot_requested = True

    def maybe_snapshot(self, step: int) -> str | None:
        """Called at step boundaries: drain a pending SIGUSR2 request into an
        on-demand snapshot of the dynamics state. Never raises."""
        if not self._snapshot_requested:
            return None
        self._snapshot_requested = False
        try:
            doc = {
                "dynamics_snapshot": True,
                "time_unix": time.time(),
                "step": int(step),
                "ema": {k: round(v, 6) for k, v in sorted(self.stats._ema.items())},
                "suspect": self.stats.suspect(),
                "loss_window": [round(x, 6) for x in self.recorder._losses],
                "dynamics_history": list(self.recorder._dyn_rows),
            }
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = f"{self.snapshot_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, self.snapshot_path)
            logger.info("dynamics snapshot written to %s", self.snapshot_path)
            if self._sink is not None:
                self._sink(step, event="dynamics_snapshot", path=self.snapshot_path)
            return self.snapshot_path
        except Exception:
            logger.exception("dynamics snapshot failed (run continues)")
            return None

    def close(self) -> None:
        """Idempotent; restores the previous handler SIG_IGN-faithfully (the
        same `is not None` dance as OnDemandProfiler.close — SIG_DFL is falsy
        and a C-installed handler reads back as None)."""
        if self._handler_installed:
            prev = self._prev_handler if self._prev_handler is not None else _signal.SIG_DFL
            try:
                _signal.signal(self.signum, prev)
            except (ValueError, OSError):
                logger.warning("could not restore previous %s handler", self.signum)
            finally:
                self._handler_installed = False
                self._prev_handler = None
        self._snapshot_requested = False
