"""On-demand profiling for live runs.

The benchmark recipe can capture a trace, but steady-state production runs are
where the interesting regressions live. Two entry points, both zero-cost until
used:

- ``jax.profiler.start_server(port)`` at init: attach TensorBoard's profile
  plugin (or ``xprof``) to a live run at any time.
- a ``SIGUSR1`` handler that arms a one-shot N-step trace window: the next
  ``on_step_start`` opens ``out_dir/profiles/step_NNNNNN``, and the window
  closes after ``trace_steps`` steps with a device sync so the trace carries
  complete steps. ``kill -USR1 <pid>`` is the whole UX.

The signal handler only sets a flag (async-signal-safe); all profiler calls
happen on the train-loop thread at step boundaries.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Any

import jax

logger = logging.getLogger(__name__)

__all__ = ["OnDemandProfiler"]


class OnDemandProfiler:
    def __init__(
        self,
        out_dir: str,
        trace_steps: int = 5,
        server_port: int = 0,
        signum: int | None = signal.SIGUSR1,
    ):
        self.profile_dir = os.path.join(str(out_dir), "profiles")
        self.trace_steps = max(int(trace_steps), 1)
        self.server_port = int(server_port or 0)
        self.signum = signum
        self._requested = False
        self._tracing = False
        self._stop_after = -1
        self._start_step = -1
        self._trace_path: str | None = None
        self._completed_trace: str | None = None
        #: steps the last closed window actually covered (None when the window
        #: was cut short at run end, where coverage is unknown) — the manager
        #: forwards this to trace_analysis as ``steps_hint`` so per-step
        #: numbers don't rely on the multiplicity estimate
        self.last_window_steps: int | None = None
        self._server: Any = None
        self._prev_handler: Any = None
        self._handler_installed = False

    @property
    def armed(self) -> bool:
        """A trace request is pending (set by SIGUSR1 or request_trace)."""
        return self._requested

    @property
    def tracing(self) -> bool:
        return self._tracing

    def start(self) -> "OnDemandProfiler":
        if self.server_port > 0 and self._server is None:
            try:
                self._server = jax.profiler.start_server(self.server_port)
                logger.info("jax profiler server listening on port %d", self.server_port)
            except Exception:
                logger.exception("could not start jax profiler server on port %d",
                                 self.server_port)
        if self.signum is not None and not self._handler_installed:
            if threading.current_thread() is not threading.main_thread():
                logger.warning("profiler signal handler not installed (non-main thread)")
            else:
                self._prev_handler = signal.signal(self.signum, self._handle_signal)
                self._handler_installed = True
        return self

    def _handle_signal(self, signum, frame) -> None:
        self._requested = True  # flag only: profiler calls are not signal-safe

    def request_trace(self) -> None:
        """Programmatic equivalent of SIGUSR1."""
        self._requested = True

    def take_completed_trace(self) -> str | None:
        """Path of the most recently closed trace window, once.

        The manager polls this after ``on_step_end`` — a non-None return is
        the "a trace just completed, analyze it" handoff (trace_analysis.py);
        the path is cleared so each window is analyzed exactly once.
        """
        path, self._completed_trace = self._completed_trace, None
        return path

    def on_step_start(self, step: int) -> None:
        if self._tracing:
            if self._requested:
                # a re-arm (signal or auto-trace) landed while a window is
                # already open: the open trace covers "now", so the request
                # coalesces into it instead of queueing a second window
                self._requested = False
                logger.info("trace request coalesced into the open window")
            return
        if not self._requested:
            return
        self._requested = False
        path = os.path.join(self.profile_dir, f"step_{step:06d}")
        try:
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
        except Exception:
            logger.exception("on-demand trace failed to start at step %d", step)
            return
        self._tracing = True
        self._trace_path = path
        self._start_step = step
        self._stop_after = step + self.trace_steps - 1
        logger.info("on-demand trace: steps %d..%d -> %s", step, self._stop_after, path)

    def on_step_end(self, step: int, sync: Any = None) -> None:
        if not self._tracing or step < self._stop_after:
            return
        if sync is not None:
            jax.block_until_ready(sync)  # the trace must contain COMPLETE steps
        try:
            jax.profiler.stop_trace()
            self._completed_trace = self._trace_path
            self.last_window_steps = step - self._start_step + 1
        except Exception:
            logger.exception("on-demand trace failed to stop cleanly")
        self._tracing = False
        logger.info("on-demand trace written under %s", self.profile_dir)

    def close(self) -> None:
        """Idempotent: safe to call any number of times, from any teardown path."""
        if self._tracing:
            try:
                jax.profiler.stop_trace()
                # a window cut short by run end is still a complete artifact,
                # but its step coverage is unknown
                self._completed_trace = self._trace_path
                self.last_window_steps = None
            except Exception:
                logger.exception("trace still open at close; stop failed")
            self._tracing = False
        if self._handler_installed:
            # `is not None`, not truthiness: SIG_DFL is 0 (falsy) and a
            # C-installed handler comes back as None — both must restore
            # faithfully, and SIG_IGN (a disposition daemonized jobs often
            # inherit) must come back as SIG_IGN, not SIG_DFL
            prev = self._prev_handler if self._prev_handler is not None else signal.SIG_DFL
            try:
                signal.signal(self.signum, prev)
            except (ValueError, OSError):
                # restoring from a non-main thread (interpreter teardown
                # paths) raises ValueError; the process is exiting anyway
                logger.warning("could not restore previous %s handler", self.signum)
            finally:
                self._handler_installed = False
                self._prev_handler = None
        self._requested = False
        # no public stop for the profiler server; it lives for the process
