"""SLURM submission for multi-host TPU jobs
(reference components/launcher/slurm/: config.py:43, template.py:91, utils.py:65).

Renders an sbatch script that starts one process per node running the same
``automodel`` CLI; JAX's distributed runtime wires the hosts together
(``JAX_DIST_AUTO=1`` -> jax.distributed.initialize()), replacing the reference's
torchrun-per-node + MASTER_ADDR ceremony with the coordinator env vars.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import tempfile

__all__ = ["SlurmConfig", "render_script", "submit_slurm_job"]


@dataclasses.dataclass
class SlurmConfig:
    job_name: str = "automodel"
    nodes: int = 1
    account: str | None = None
    partition: str | None = None
    time: str = "04:00:00"
    container_image: str | None = None
    container_mounts: list[str] | None = None
    env_vars: dict[str, str] | None = None
    extra_sbatch: list[str] | None = None
    hf_home: str | None = None


def render_script(slurm: SlurmConfig, command: str, domain: str, config_path: str) -> str:
    lines = [
        "#!/bin/bash",
        f"#SBATCH --job-name={slurm.job_name}",
        f"#SBATCH --nodes={slurm.nodes}",
        "#SBATCH --ntasks-per-node=1",
        f"#SBATCH --time={slurm.time}",
    ]
    if slurm.account:
        lines.append(f"#SBATCH --account={slurm.account}")
    if slurm.partition:
        lines.append(f"#SBATCH --partition={slurm.partition}")
    for extra in slurm.extra_sbatch or []:
        lines.append(f"#SBATCH {extra}")
    lines.append("")
    env = {
        "COORDINATOR_ADDRESS": "$(scontrol show hostnames $SLURM_JOB_NODELIST | head -n1):12345",
        "NUM_PROCESSES": "$SLURM_NNODES",
        **(slurm.env_vars or {}),
    }
    if slurm.hf_home:
        env["HF_HOME"] = slurm.hf_home
    for k, v in env.items():
        lines.append(f"export {k}={v}")
    srun = "srun "
    if slurm.container_image:
        srun += f"--container-image={slurm.container_image} "
        if slurm.container_mounts:
            srun += f"--container-mounts={','.join(slurm.container_mounts)} "
    lines.append("")
    # PROCESS_ID must be the per-task rank: $SLURM_PROCID only exists inside each
    # srun task (the batch shell's $SLURM_NODEID is always 0), so expand it there.
    lines.append(
        f"{srun}bash -c 'PROCESS_ID=$SLURM_PROCID "
        f"python -m automodel_tpu.cli.app {command} {domain} -c {config_path}'"
    )
    return "\n".join(lines) + "\n"


def submit_slurm_job(cfg, command: str, domain: str) -> str:
    """Render + sbatch; returns the rendered script path (reference utils.py:65)."""
    slurm_cfg = SlurmConfig(**cfg.slurm.to_dict())
    # persist the resolved config next to the script so the job is self-contained
    workdir = cfg.get("slurm_workdir", tempfile.mkdtemp(prefix="automodel_slurm_"))
    os.makedirs(workdir, exist_ok=True)
    cfg_path = os.path.join(workdir, "config.yaml")
    import yaml

    clean = {k: v for k, v in cfg.raw_dict.items() if k != "slurm"}
    with open(cfg_path, "w") as f:
        yaml.safe_dump(clean, f)
    script = render_script(slurm_cfg, command, domain, cfg_path)
    script_path = os.path.join(workdir, "job.sbatch")
    with open(script_path, "w") as f:
        f.write(script)
    try:
        out = subprocess.run(["sbatch", script_path], capture_output=True, text=True, check=True)
        print(out.stdout.strip())
    except FileNotFoundError:
        print(f"sbatch not found; rendered script at {script_path}")
    return script_path
