"""Gradient-accumulation batching and run cadence (reference training/step_scheduler.py:48,136,217).

Yields lists of ``grad_acc_steps`` microbatches per optimizer step, tracks epoch/step
counters, and answers "is it time to checkpoint/validate/log?". Checkpointable via
state_dict/load_state_dict like every training service.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Any, Iterable, Iterator

__all__ = ["StepScheduler"]


class StepScheduler:
    def __init__(
        self,
        grad_acc_steps: int = 1,
        ckpt_every_steps: int = 0,
        val_every_steps: int = 0,
        log_every_steps: int = 1,
        num_epochs: int = 1,
        max_steps: int | None = None,
        dataloader: Iterable | None = None,
        handle_sigterm: bool = True,
    ):
        if grad_acc_steps < 1:
            raise ValueError(f"grad_acc_steps must be >= 1, got {grad_acc_steps}")
        self.grad_acc_steps = grad_acc_steps
        self.ckpt_every_steps = ckpt_every_steps
        self.val_every_steps = val_every_steps
        self.log_every_steps = log_every_steps
        self.num_epochs = num_epochs
        self.max_steps = max_steps
        self.dataloader = dataloader

        self.step = 0  # completed optimizer steps
        self.epoch = 0
        self._sigterm = threading.Event()
        self.sigterm_time: float | None = None  # monotonic stamp of first signal
        self._sigterm_agreed = False
        self._sigterm_poll: tuple[int, bool] | None = None  # (step, agreed result)
        if handle_sigterm:
            self._install_sigterm_handler()

    # -- SIGTERM -> checkpoint-on-preemption (reference signal_handler.py) --
    def _install_sigterm_handler(self) -> None:
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def handler(signum, frame):
                if not self._sigterm.is_set():
                    # the grace clock starts at the FIRST signal: the preemption
                    # deadline (resilience/manager.py skip_consolidated_export)
                    # is measured from here
                    self.sigterm_time = time.monotonic()
                self._sigterm.set()
                if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass  # not in main thread (e.g. under pytest-xdist)

    @property
    def sigterm_received(self) -> bool:
        """Cross-host-agreed SIGTERM at the scheduler's own step counter."""
        return self.sigterm_agreed_at(self.step)

    def sigterm_agreed_at(self, step: int) -> bool:
        """Cross-host-agreed SIGTERM: any host's local flag triggers ALL hosts, so
        everyone exits the step loop together and checkpoints (reference
        step_scheduler.py:217 all-gathers the flag) — one preempted host can never
        strand the others inside a collective. The 1-byte allgather runs at most
        once per optimizer step (the result is cached per step, and sticky once
        True) and every host calls it at the same loop point, so it cannot hang.

        ``step`` keys the cache: under the prefetch pipeline the scheduler's own
        counter runs ahead of the training loop (and is mutated by the worker
        thread), so the loop passes its *consumed* step — deterministic across
        hosts, keeping the collective count uniform."""
        if self._sigterm_agreed:
            return True
        if self._sigterm_poll is not None and self._sigterm_poll[0] == step:
            return self._sigterm_poll[1]
        from automodel_tpu.parallel.init import any_process_flag

        agreed = any_process_flag(self._sigterm.is_set())
        self._sigterm_poll = (step, agreed)
        if agreed:
            self._sigterm_agreed = True
            if self.sigterm_time is None:
                # this host wasn't the one signalled; start its grace clock at
                # agreement time (the first moment it can know)
                self.sigterm_time = time.monotonic()
        return agreed

    @property
    def sigterm_local(self) -> bool:
        """This host's flag only — safe off the main thread (no collectives);
        the prefetch worker stops on it while the train loop owns the agreed
        decision."""
        return self._sigterm.is_set()

    @property
    def sigterm_elapsed_s(self) -> float:
        """Seconds since the preemption signal (0 when none arrived)."""
        return 0.0 if self.sigterm_time is None else time.monotonic() - self.sigterm_time

    # -- iteration ----------------------------------------------------------
    def __iter__(self) -> Iterator[list[Any]]:
        """Yield lists of microbatches, one list per optimizer step."""
        return self.batches()

    def batches(self, collective_sigterm: bool = True) -> Iterator[list[Any]]:
        """The step iterator. ``collective_sigterm=False`` checks only the
        local SIGTERM flag — required when iteration runs on the prefetch
        worker thread, where a multi-host collective would race the main
        loop's own agreed check (and could deadlock the pod)."""
        if self.dataloader is None:
            raise ValueError("StepScheduler has no dataloader")
        while self.epoch < self.num_epochs:
            # a re-entered iterator (in-process rollback restarts the pass,
            # train_ft.py _train_pass) must not overshoot a finished run
            if self.max_steps is not None and self.step >= self.max_steps:
                return
            batches: list[Any] = []
            for batch in self.dataloader:
                batches.append(batch)
                if len(batches) == self.grad_acc_steps:
                    # step is 1-indexed while the consumer processes it, so cadence
                    # flags (is_ckpt_step etc.) are correct inside the loop body.
                    self.step += 1
                    yield batches
                    batches = []
                    if self.max_steps is not None and self.step >= self.max_steps:
                        return
                    if (self.sigterm_received if collective_sigterm
                            else self.sigterm_local):
                        return
            # trailing partial accumulation at epoch end still steps the optimizer
            if batches:
                self.step += 1
                yield batches
                if self.max_steps is not None and self.step >= self.max_steps:
                    return
            self.epoch += 1

    # -- cadence ------------------------------------------------------------
    # The *_at(step) forms exist for the prefetch pipeline: the consumer's
    # current step is carried on each fetched batch (the scheduler's own
    # counter runs ahead). The properties keep the synchronous contract.
    def is_ckpt_step_at(self, step: int) -> bool:
        return self.ckpt_every_steps > 0 and step > 0 and step % self.ckpt_every_steps == 0

    def is_val_step_at(self, step: int) -> bool:
        return self.val_every_steps > 0 and step > 0 and step % self.val_every_steps == 0

    def is_log_step_at(self, step: int) -> bool:
        return self.log_every_steps > 0 and step % self.log_every_steps == 0

    @property
    def is_ckpt_step(self) -> bool:
        return self.is_ckpt_step_at(self.step)

    @property
    def is_val_step(self) -> bool:
        return self.is_val_step_at(self.step)

    @property
    def is_log_step(self) -> bool:
        return self.is_log_step_at(self.step)

    @property
    def done(self) -> bool:
        if self.max_steps is not None and self.step >= self.max_steps:
            return True
        return self.epoch >= self.num_epochs

    # -- checkpointable state ----------------------------------------------
    def state_dict(self) -> dict[str, int]:
        return {"step": self.step, "epoch": self.epoch}

    def load_state_dict(self, state: dict[str, int]) -> None:
        self.step = int(state["step"])
        self.epoch = int(state["epoch"])
