"""GPT pretraining dataset over an MMapIndexedDataset
(reference megatron/gpt_dataset.py:257 GPTDataset).

Three deterministic index arrays turn a document corpus into a stream of fixed-length
training samples (the Megatron recipe, rebuilt):

- ``document_index``: document ids repeated per epoch, each epoch shuffled
  independently (last partial epoch shuffled separately, gpt_dataset.py:715);
- ``sample_index``: (num_samples+1, 2) [doc position, token offset] built by the C++
  helper — sample i spans tokens sample_index[i] .. sample_index[i+1] inclusive;
- ``shuffle_index``: a shuffle over samples (first full-epoch span and trailing span
  shuffled separately, gpt_dataset.py:748).

Samples are ``seq_length+1`` raw tokens; the collate layer applies the next-token
shift, and every token carries loss (pretraining: ``labels=input_ids``).
Index arrays are cached on disk keyed by a config hash, so rank-parallel and
re-run builds are instant (reference path_to_cache behavior).
"""

from __future__ import annotations

import hashlib
import logging
import os

import numpy as np

from automodel_tpu.data.llm.megatron.helpers import build_sample_idx
from automodel_tpu.data.llm.megatron.indexed_dataset import MMapIndexedDataset

logger = logging.getLogger(__name__)

__all__ = ["GPTDataset"]


def _build_document_index(num_docs: int, num_epochs: int, rng: np.random.RandomState,
                          separate_final_epoch: bool) -> np.ndarray:
    if not separate_final_epoch or num_epochs == 1:
        doc_idx = np.mgrid[0:num_epochs, 0:num_docs][1].reshape(-1).astype(np.int64)
        rng.shuffle(doc_idx)
        return doc_idx
    first = _build_document_index(num_docs, num_epochs - 1, rng, False)
    last = _build_document_index(num_docs, 1, rng, False)
    return np.concatenate([first, last])


def _build_shuffle_index(num_samples: int, total_size: int, rng: np.random.RandomState) -> np.ndarray:
    dtype = np.int64 if total_size >= np.iinfo(np.int32).max - 1 else np.int32
    first = np.arange(num_samples, dtype=dtype)
    rng.shuffle(first)
    if num_samples == total_size:
        return first
    last = np.arange(num_samples, total_size, dtype=dtype)
    rng.shuffle(last)
    return np.concatenate([first, last])


class GPTDataset:
    """Deterministic, resumable GPT pretraining sample stream."""

    def __init__(
        self,
        indexed_dataset: MMapIndexedDataset | str,
        seq_length: int,
        num_samples: int | None = None,
        seed: int = 1234,
        cache_dir: str | None = None,
        documents: np.ndarray | None = None,  # restrict to a doc-id subset (splits)
    ):
        if isinstance(indexed_dataset, str):
            indexed_dataset = MMapIndexedDataset(indexed_dataset)
        self.indexed = indexed_dataset
        self.seq_length = seq_length
        self.seed = seed
        if documents is None:
            documents = np.arange(len(indexed_dataset), dtype=np.int64)
        self.documents = documents

        tokens_per_epoch = int(self.indexed.sizes[documents].sum())
        samples_per_epoch = max((tokens_per_epoch - 1) // seq_length, 1)
        if num_samples is None:
            num_samples = samples_per_epoch
        self.num_samples = num_samples
        num_epochs = max(-(-(num_samples * seq_length + 1) // tokens_per_epoch), 1)

        # separate-final-epoch rule (gpt_dataset.py:505): when the last epoch is
        # only partially consumed, shuffle it apart so early training never sees
        # a skewed tail distribution
        separate_final = False
        if num_epochs > 1:
            samples_sans_final = ((num_epochs - 1) * tokens_per_epoch - 1) // seq_length
            final_frac = (num_samples - samples_sans_final) / max(samples_per_epoch, 1)
            separate_final = final_frac < 0.80

        self._load_or_build(num_epochs, separate_final, cache_dir)

    # -- index construction --------------------------------------------------
    def _cache_key(self, num_epochs: int, separate_final: bool) -> str:
        h = hashlib.md5()
        h.update(
            f"{self.indexed.path_prefix}|{self.seq_length}|{self.num_samples}|"
            f"{self.seed}|{num_epochs}|{separate_final}|".encode()
        )
        # hash the document-id content, not just its length: different splits of
        # equal size must never collide (silent train/eval contamination otherwise)
        h.update(np.ascontiguousarray(self.documents).tobytes())
        return h.hexdigest()[:16]

    def _load_or_build(self, num_epochs: int, separate_final: bool, cache_dir: str | None):
        key = self._cache_key(num_epochs, separate_final)
        if cache_dir:
            paths = {n: os.path.join(cache_dir, f"gpt_{key}_{n}.npy")
                     for n in ("doc", "sample", "shuffle")}
            if all(os.path.exists(p) for p in paths.values()):
                self.document_index = np.load(paths["doc"], mmap_mode="r")
                self.sample_index = np.load(paths["sample"], mmap_mode="r")
                self.shuffle_index = np.load(paths["shuffle"], mmap_mode="r")
                return
        rng = np.random.RandomState(self.seed)
        doc_index = _build_document_index(len(self.documents), num_epochs, rng, separate_final)
        # map positions in the (possibly restricted) documents array to real doc ids
        real_doc_index = self.documents[doc_index]
        sample_index = build_sample_idx(
            self.indexed.sizes, real_doc_index, self.seq_length, self.num_samples
        )
        n_avail = len(sample_index) - 1
        shuffle_index = _build_shuffle_index(min(self.num_samples, n_avail), n_avail, rng)
        self.document_index = real_doc_index
        self.sample_index = sample_index
        self.shuffle_index = shuffle_index
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            # atomic publish: rank-parallel builders may race on the same key; a
            # reader must never see a torn .npy (write-to-temp + rename)
            for name, arr in (("doc", real_doc_index), ("sample", sample_index),
                              ("shuffle", shuffle_index)):
                tmp = paths[name] + f".tmp{os.getpid()}.npy"  # .npy: np.save appends otherwise
                np.save(tmp, arr)
                os.replace(tmp, paths[name])
            logger.info("cached gpt indices under %s (%s)", cache_dir, key)

    # -- access --------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.shuffle_index)

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]:
        sample = self.shuffle_index[idx % len(self.shuffle_index)]
        doc_pos_start, offset_start = self.sample_index[sample]
        doc_pos_end, offset_end = self.sample_index[sample + 1]
        parts = []
        if doc_pos_start == doc_pos_end:
            parts.append(
                self.indexed.get(
                    int(self.document_index[doc_pos_start]),
                    offset=int(offset_start),
                    length=int(offset_end) - int(offset_start) + 1,
                )
            )
        else:
            parts.append(self.indexed.get(int(self.document_index[doc_pos_start]), offset=int(offset_start)))
            for p in range(int(doc_pos_start) + 1, int(doc_pos_end)):
                parts.append(self.indexed.get(int(self.document_index[p])))
            parts.append(self.indexed.get(int(self.document_index[doc_pos_end]), length=int(offset_end) + 1))
        tokens = np.concatenate(parts).astype(np.int64)
        assert len(tokens) == self.seq_length + 1, (len(tokens), self.seq_length)
        # pretraining: every position carries loss; collate shifts labels=ids
        return {"input_ids": tokens}
