"""Checkpointer hardening: best-symlink tracking, model-signature compat check,
lazy/sharded consolidated export (reference base_recipe.py:383-425,768-846 +
consolidate_hf_safetensors.py)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.checkpoint.checkpointing import (
    Checkpointer, CheckpointingConfig, _model_signature,
)


def _params(seed=0, d=8):
    rng = np.random.RandomState(seed)
    return {
        "embed": jnp.asarray(rng.randn(16, d), jnp.float32),
        "layers": {"wq": jnp.asarray(rng.randn(2, d, d), jnp.float32)},
    }


class TestBestTracking:
    def test_best_symlink_follows_improvement(self, tmp_path):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        p = _params()
        ck.save(1, p)
        assert ck.mark_best(1, 2.0)
        assert ck.best_step() == 1
        ck.save(2, p)
        assert not ck.mark_best(2, 2.5)  # worse: best stays
        assert ck.best_step() == 1
        ck.save(3, p)
        assert ck.is_best(1.5)
        assert ck.mark_best(3, 1.5)
        link = os.readlink(tmp_path / "ck" / "best")
        assert link == "step_3"

    def test_prune_spares_best(self, tmp_path):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"), keep_last_k=2))
        p = _params()
        ck.save(1, p)
        ck.mark_best(1, 1.0)
        for s in (2, 3, 4):
            ck.save(s, p)
        assert os.path.isdir(ck.step_dir(1))  # best survives keep_last_k=2
        assert not os.path.isdir(ck.step_dir(2))


class TestSignature:
    def test_mismatch_raises_with_diff(self, tmp_path):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        ck.save(1, _params(d=8))
        wrong = _params(d=16)
        with pytest.raises(ValueError, match="different model signature"):
            ck.load(wrong, step=1)

    def test_match_loads(self, tmp_path):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        p = _params()
        ck.save(1, p)
        restored, _, _ = ck.load(jax.tree.map(jnp.zeros_like, p), step=1)
        np.testing.assert_array_equal(np.asarray(restored["embed"]), np.asarray(p["embed"]))

    def test_signature_is_sharding_independent(self):
        sig = _model_signature(_params())
        assert all("/" in v for v in sig.values())
        assert len(sig) == 2


class TestShardedExport:
    def test_sharded_write_sizes_without_upfront_copy(self, tmp_path):
        from automodel_tpu.checkpoint.safetensors_io import load_safetensors, save_safetensors

        tensors = {f"w{i}": jnp.full((64, 64), i, jnp.float32) for i in range(4)}
        written = save_safetensors(tensors, str(tmp_path), max_shard_bytes=40_000)
        assert len(written) > 1  # sharded + index.json
        back = load_safetensors(str(tmp_path))
        assert set(back) == set(tensors)
        np.testing.assert_array_equal(back["w2"], np.full((64, 64), 2, np.float32))

    def test_corrupt_best_json_is_tolerated(self, tmp_path):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        os.makedirs(tmp_path / "ck", exist_ok=True)
        (tmp_path / "ck" / "best.json").write_text("{truncated")
        assert ck.best_step() is None
        assert ck.is_best(1.0)


class TestStreamingHFExport:
    """save_hf must never hold more than one tensor per gather + one shard: the
    adapter yields lazy views, the writer materializes shard by shard."""

    def _model_and_params(self, n_layers=3):
        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48,
            num_hidden_layers=n_layers, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=32, tie_word_embeddings=False,
        )
        model = LlamaForCausalLM(cfg, BackendConfig(dtype="float32"))
        params = model.init(jax.random.key(0), jnp.float32)
        return model, params

    def test_lazy_view_defers_and_memoizes(self):
        model, params = self._model_and_params()
        adapter = model.state_dict_adapter()
        calls = []

        def spy_host(x):
            arr = np.asarray(x)
            calls.append(arr.nbytes)
            return arr

        lazy = adapter.to_hf_lazy(params, host_fn=spy_host)
        assert calls == []  # building the view gathers NOTHING
        dense = adapter.to_hf(jax.tree.map(np.asarray, params))
        assert set(lazy) == set(dense)
        for k in lazy:
            np.testing.assert_array_equal(np.asarray(lazy[k]), dense[k])
        # one gather per (entry, layer) slice — tuple-key entries must hit the
        # memo, and nothing may pull the full stacked tree
        assert len(calls) == len(
            [1 for e in adapter.entries for _ in (range(model.config.num_hidden_layers)
                                                  if e.per_layer else [0])]
        )

    def test_roundtrip_multi_shard_loads_in_transformers(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")
        from automodel_tpu.checkpoint.safetensors_io import save_safetensors

        model, params = self._model_and_params()
        adapter = model.state_dict_adapter()
        lazy = adapter.to_hf_lazy(params)
        out = str(tmp_path / "hf")
        # tiny shard cap -> many shards + index.json (the multi-host layout)
        files = save_safetensors(lazy, out, max_shard_bytes=40_000)
        assert len(files) > 1
        assert os.path.exists(os.path.join(out, "model.safetensors.index.json"))
        hf_cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=32,
            tie_word_embeddings=False,
        )
        # transformers' own sharded loader must read the dir
        loaded = transformers.LlamaForCausalLM.from_pretrained(
            out, config=hf_cfg, torch_dtype=torch.float32
        )
        ours = np.asarray(params["layers"]["wq"][1])  # (D, H, hd)
        theirs = loaded.model.layers[1].self_attn.q_proj.weight.detach().numpy()
        np.testing.assert_allclose(
            ours.reshape(32, -1).T, theirs, rtol=1e-6, atol=1e-6
        )

    def test_nonwriter_materializes_without_writing(self, tmp_path):
        from automodel_tpu.checkpoint.safetensors_io import save_safetensors

        model, params = self._model_and_params()
        adapter = model.state_dict_adapter()
        calls = []

        def spy_host(x):
            calls.append(1)
            return np.asarray(x)

        lazy = adapter.to_hf_lazy(params, host_fn=spy_host)
        out = str(tmp_path / "nonwriter")
        files = save_safetensors(lazy, out, max_shard_bytes=40_000, write=False)
        assert files == []
        assert not os.path.exists(out)  # nothing written...
        assert len(calls) > 0  # ...but every collective gather still ran

    def test_checkpointer_save_hf_streaming(self, tmp_path):
        model, params = self._model_and_params()
        ck = Checkpointer(
            CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")),
            state_dict_adapter=model.state_dict_adapter(),
            hf_config={"architectures": ["LlamaForCausalLM"], "vocab_size": 64},
        )
        out = str(tmp_path / "hf")
        ck.save_hf(out, params)
        from automodel_tpu.checkpoint.safetensors_io import load_safetensors

        tensors = load_safetensors(out)
        dense = model.state_dict_adapter().to_hf(jax.tree.map(np.asarray, params))
        assert set(tensors) == set(dense)
        np.testing.assert_array_equal(
            tensors["model.embed_tokens.weight"], dense["model.embed_tokens.weight"]
        )
        assert json.load(open(os.path.join(out, "config.json")))["vocab_size"] == 64


class TestPeftAdapterExport:
    def test_adapter_loads_in_peft_and_matches_merged(self, tmp_path):
        """Gold test: export our LoRA adapter in HF PEFT format, load it with the
        peft library on the HF base model, and require logits to match OUR
        merged-adapter forward."""
        transformers = pytest.importorskip("transformers")
        torch = pytest.importorskip("torch")
        peft_lib = pytest.importorskip("peft")
        from automodel_tpu.checkpoint.peft_export import save_peft_adapter
        from automodel_tpu.models.auto import AutoModelForCausalLM
        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.peft.lora import (
            PeftConfig, init_lora_params, merge_lora_params,
        )

        cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=32,
        )
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(cfg).eval()
        d = str(tmp_path / "base")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=BackendConfig(dtype="float32")
        )
        pc = PeftConfig(target_modules=["*wq", "*wv"], dim=4, alpha=8)
        lora = init_lora_params(params, model.logical_axes(), pc, jax.random.key(0))
        # B starts at zero (delta = 0, trivially equal) — randomize both factors
        lora = jax.tree.map(
            lambda a: jax.random.normal(jax.random.key(1), a.shape, a.dtype) * 0.05, lora
        )
        out = str(tmp_path / "adapter")
        tensors = save_peft_adapter(
            out, lora, pc, model.state_dict_adapter().entries, base_model_name=d
        )
        assert "base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight" in tensors
        assert tensors[
            "base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight"
        ].shape == (4, 32)

        ids = np.random.RandomState(0).randint(0, 64, (2, 8))
        merged = merge_lora_params(params, lora, pc)
        ours = np.asarray(model(params=merged, input_ids=jnp.asarray(ids)))

        peft_model = peft_lib.PeftModel.from_pretrained(hf, out).eval()
        with torch.no_grad():
            theirs = peft_model(torch.tensor(ids)).logits.float().numpy()
        np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=1e-3)


class TestAsyncCrashSafety:
    """VERDICT r3 #8: the latest symlink is the COMMIT MARKER — it moves only
    after wait_until_finished, and crash states (orbax tmp residue, missing
    model tree) must never win the no-symlink fallback."""

    def test_async_save_defers_latest_until_wait(self, tmp_path):
        ck = Checkpointer(CheckpointingConfig(
            checkpoint_dir=str(tmp_path / "ck"), async_save=True))
        p = _params()
        ck.save(3, p)
        # arrays may be in flight: latest must NOT point anywhere yet
        assert not os.path.islink(tmp_path / "ck" / "latest")
        ck.wait()
        assert os.readlink(tmp_path / "ck" / "latest") == "step_3"

    def test_async_save_resume_roundtrip(self, tmp_path):
        cfg = CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"), async_save=True)
        ck = Checkpointer(cfg)
        p = _params(seed=3)
        opt = {"mu": jnp.asarray(np.random.RandomState(1).randn(4), jnp.float32)}
        ck.save(5, p, opt_state=opt, client_states={"step": 5})
        ck.wait()
        fresh = Checkpointer(CheckpointingConfig(
            checkpoint_dir=str(tmp_path / "ck"), async_save=True))
        assert fresh.latest_step() == 5
        rp, ro, client = fresh.load(_params(seed=9), opt_state_template={"mu": jnp.zeros(4)})
        np.testing.assert_array_equal(np.asarray(rp["layers"]["wq"]),
                                      np.asarray(p["layers"]["wq"]))
        np.testing.assert_array_equal(np.asarray(ro["mu"]), np.asarray(opt["mu"]))
        assert client["step"] == 5

    def test_crash_between_save_and_finalize_resumes_previous_step(self, tmp_path):
        cfg = CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"))
        ck = Checkpointer(cfg)
        ck.save(3, _params())
        assert os.readlink(tmp_path / "ck" / "latest") == "step_3"
        # simulate a crash mid-async-write of step 6: orbax tmp dir present,
        # no committed model tree, signature.json already written (save() writes
        # it synchronously), latest never updated (wait() never ran)
        d6 = ck.step_dir(6)
        os.makedirs(os.path.join(d6, "model.orbax-checkpoint-tmp-1234567"))
        with open(os.path.join(d6, "signature.json"), "w") as f:
            json.dump({}, f)
        fresh = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        assert fresh.latest_step() == 3  # symlink is authoritative
        # worst case: the symlink is ALSO gone — the fallback must skip the
        # incomplete step_6 dir instead of resuming into half-written arrays
        os.remove(tmp_path / "ck" / "latest")
        assert fresh.latest_step() == 3

    def test_fallback_skips_dir_without_model_tree(self, tmp_path):
        cfg = CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"))
        ck = Checkpointer(cfg)
        ck.save(2, _params())
        os.remove(tmp_path / "ck" / "latest")
        os.makedirs(ck.step_dir(9))  # empty dir: save() crashed immediately
        assert Checkpointer(cfg).latest_step() == 2


class TestExportNegativePaths:
    """VERDICT r3 #8: corrupt/truncated HF export artifacts fail loudly with
    the offending file named, never with an opaque downstream error."""

    def _export(self, tmp_path, n=6, shard_bytes=200):
        from automodel_tpu.checkpoint.safetensors_io import save_safetensors

        rng = np.random.RandomState(0)
        tensors = {f"t{i}": rng.randn(4, 4).astype(np.float32) for i in range(n)}
        out = str(tmp_path / "hf")
        save_safetensors(tensors, out, max_shard_bytes=shard_bytes)
        return out, tensors

    def test_corrupt_index_json_raises_cleanly(self, tmp_path):
        from automodel_tpu.checkpoint.safetensors_io import load_safetensors

        out, _ = self._export(tmp_path)
        index = os.path.join(out, "model.safetensors.index.json")
        assert os.path.exists(index)
        with open(index, "w") as f:
            f.write('{"weight_map": {"t0": ')  # truncated mid-write
        with pytest.raises(ValueError, match="corrupt safetensors index"):
            load_safetensors(out)

    def test_index_missing_weight_map_raises_cleanly(self, tmp_path):
        from automodel_tpu.checkpoint.safetensors_io import load_safetensors

        out, _ = self._export(tmp_path)
        index = os.path.join(out, "model.safetensors.index.json")
        with open(index, "w") as f:
            json.dump({"metadata": {}}, f)
        with pytest.raises(ValueError, match="corrupt safetensors index"):
            load_safetensors(out)

    def test_index_referencing_missing_shard_names_it(self, tmp_path):
        from automodel_tpu.checkpoint.safetensors_io import load_safetensors

        out, _ = self._export(tmp_path)
        shards = [f for f in os.listdir(out) if f.endswith(".safetensors")]
        os.remove(os.path.join(out, shards[0]))
        with pytest.raises(FileNotFoundError, match=shards[0].replace(".", r"\.")):
            load_safetensors(out)

    def test_truncated_shard_raises(self, tmp_path):
        from automodel_tpu.checkpoint.safetensors_io import load_safetensors

        out, tensors = self._export(tmp_path, n=2, shard_bytes=10**9)  # single file
        fp = os.path.join(out, "model.safetensors")
        data = open(fp, "rb").read()
        with open(fp, "wb") as f:
            f.write(data[: len(data) // 2])
        with pytest.raises(Exception):
            lazy = load_safetensors(out)
            np.asarray(lazy["t0"])


class TestLockstepMaterializationOrder:
    def test_write_false_materializes_in_writer_order(self, tmp_path):
        """VERDICT r3 #8: non-writing ranks must walk tensors in EXACTLY the
        writer's order — the per-tensor host gathers are collectives, so a
        divergent order deadlocks a real pod. Pin it with recording leaves."""
        from automodel_tpu.checkpoint.safetensors_io import save_safetensors

        class Rec:
            def __init__(self, key, arr, log):
                self.key, self.arr, self.log = key, arr, log
                self.nbytes = arr.nbytes
                self.dtype = arr.dtype

            def __array__(self, dtype=None, copy=None):
                self.log.append(self.key)
                return self.arr

        rng = np.random.RandomState(0)
        arrays = {f"t{i}": rng.randn(8, 8).astype(np.float32) for i in range(7)}

        def run(write, out):
            log = []
            tensors = {k: Rec(k, v, log) for k, v in arrays.items()}
            save_safetensors(tensors, out, max_shard_bytes=600, write=write)
            return log

        writer_order = run(True, str(tmp_path / "w"))
        lockstep_order = run(False, str(tmp_path / "nw"))
        assert len(writer_order) >= 7  # every tensor materialized
        # non-writer sequence must be a prefix-complete replay of the writer's
        assert lockstep_order == writer_order
        assert not os.path.exists(tmp_path / "nw")  # write=False writes nothing
