"""Delta Lake table dataset (reference datasets/llm/delta_lake_dataset.py behavior).

Reads instruction rows straight from a Delta table (local path, s3/gcs URI, or a
Unity-Catalog three-part name via databricks-sql) and column-maps them exactly
like ColumnMappedTextInstructionDataset. Readers are optional dependencies,
probed in the reference's order: ``deltalake`` (delta-rs), then pyspark, then
``databricks-sql-connector``; with none installed construction raises with the
install hint instead of failing deep in a worker.
"""

from __future__ import annotations

import importlib
from typing import Any, Mapping

__all__ = ["DeltaLakeDataset", "delta_reader_available"]


def _has(mod: str) -> bool:
    try:
        importlib.import_module(mod)
        return True
    except ImportError:
        return False


def delta_reader_available() -> bool:
    return _has("deltalake") or _has("pyspark") or _has("databricks.sql")


def _is_unity_catalog_name(path: str) -> bool:
    # catalog.schema.table (no slashes, two dots)
    return "/" not in path and path.count(".") == 2


def _read_rows(path: str, version: int | None, limit: int | None) -> list[dict]:
    if _is_unity_catalog_name(path):
        return _read_unity_catalog(path, version, limit)
    if _has("deltalake"):
        from deltalake import DeltaTable

        dt = DeltaTable(path, version=version) if version is not None else DeltaTable(path)
        table = dt.to_pyarrow_table()
        rows = table.to_pylist()
        return rows[:limit] if limit else rows
    if _has("pyspark"):
        from pyspark.sql import SparkSession

        spark = SparkSession.builder.getOrCreate()
        reader = spark.read.format("delta")
        if version is not None:  # honor the pin like the other two readers
            reader = reader.option("versionAsOf", int(version))
        df = reader.load(path)
        if limit:
            df = df.limit(limit)
        return [r.asDict() for r in df.collect()]
    raise ImportError(
        "reading Delta tables needs a reader: pip install deltalake "
        "(or pyspark / databricks-sql-connector)"
    )


def _read_unity_catalog(name: str, version: int | None, limit: int | None,
                        connect=None) -> list[dict]:
    """catalog.schema.table via databricks-sql (reference delta_lake_dataset's
    UC branch). Credentials ride the standard Databricks env vars —
    DATABRICKS_SERVER_HOSTNAME, DATABRICKS_HTTP_PATH, DATABRICKS_TOKEN — the
    same contract databricks-sql-connector documents. ``connect`` is a test
    seam defaulting to databricks.sql.connect."""
    import os

    if connect is None:
        if not _has("databricks.sql"):
            raise ImportError(
                f"{name!r} looks like a Unity-Catalog table; "
                "pip install databricks-sql-connector to read it"
            )
        from databricks import sql as dbsql

        connect = dbsql.connect
    missing = [v for v in ("DATABRICKS_SERVER_HOSTNAME", "DATABRICKS_HTTP_PATH",
                           "DATABRICKS_TOKEN") if not os.environ.get(v)]
    if missing:
        raise EnvironmentError(
            f"Unity-Catalog table {name!r} needs workspace credentials: "
            f"set {', '.join(missing)} (or pass a file/s3/gs table URI instead)"
        )
    # backtick-quote each identifier part: hyphenated names parse, and a
    # config value can't smuggle SQL past the three-part gate into a query
    # that runs with the user's workspace token
    parts = name.split(".")
    if any("`" in p or not p for p in parts):
        raise ValueError(f"invalid Unity-Catalog table name {name!r}")
    quoted = ".".join(f"`{p}`" for p in parts)
    query = f"SELECT * FROM {quoted}"
    if version is not None:
        query += f" VERSION AS OF {int(version)}"
    if limit:
        query += f" LIMIT {int(limit)}"
    with connect(
        server_hostname=os.environ["DATABRICKS_SERVER_HOSTNAME"],
        http_path=os.environ["DATABRICKS_HTTP_PATH"],
        access_token=os.environ["DATABRICKS_TOKEN"],
    ) as conn:
        with conn.cursor() as cur:
            cur.execute(query)
            cols = [d[0] for d in cur.description]
            return [dict(zip(cols, row)) for row in cur.fetchall()]


class DeltaLakeDataset:
    """Column-mapped SFT dataset over a Delta table snapshot."""

    def __init__(
        self,
        table_path: str,
        column_mapping: Mapping[str, str],
        tokenizer=None,
        version: int | None = None,
        answer_only_loss_mask: bool = True,
        limit_dataset_samples: int | None = None,
    ):
        if "answer" not in column_mapping:
            raise ValueError("column_mapping must include an 'answer' role")
        self.rows = _read_rows(table_path, version, limit_dataset_samples)
        self.mapping = dict(column_mapping)
        self.tokenizer = tokenizer
        self.answer_only = answer_only_loss_mask

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int) -> dict[str, Any]:
        from automodel_tpu.data.llm.column_mapped import format_and_tokenize

        return format_and_tokenize(self.rows[i], self.mapping, self.tokenizer, self.answer_only)
