"""Unit tests for observability/trace_analysis.py (the vendored XPlane reader).

Three layers, none touching the profiler:

- the committed golden fixture (tests/fixtures/trace/, regenerate with
  tools/gen_trace_fixture.py) exercises the wire walker against bytes the
  real jax.profiler wrote;
- hand-encoded synthetic XSpace bytes pin the classification/overlap math to
  values computed by hand;
- randomized interval-set properties check union/intersection against a
  brute-force per-unit-cell count.
"""
from __future__ import annotations

import pathlib
import random
import struct

import pytest

from automodel_tpu.observability import trace_analysis as ta

FIXTURES = pathlib.Path(__file__).resolve().parents[1] / "fixtures" / "trace"


# ------------------------------------------------- wire-format encode helpers
def _vint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(field: int, value: int) -> bytes:
    return _vint(field << 3 | 0) + _vint(value)


def _field_bytes(field: int, payload: bytes) -> bytes:
    return _vint(field << 3 | 2) + _vint(len(payload)) + payload


def _field_str(field: int, s: str) -> bytes:
    return _field_bytes(field, s.encode())


def _event_metadata_entry(meta_id: int, name: str) -> bytes:
    meta = _field_varint(1, meta_id) + _field_str(2, name)
    return _field_varint(1, meta_id) + _field_bytes(2, meta)


def _stat(meta_id: int, *, ref: int | None = None, s: str | None = None,
          i64: int | None = None, dbl: float | None = None) -> bytes:
    out = _field_varint(1, meta_id)
    if ref is not None:
        out += _field_varint(7, ref)
    if s is not None:
        out += _field_str(5, s)
    if i64 is not None:
        out += _field_varint(4, i64 & ((1 << 64) - 1))
    if dbl is not None:
        out += _vint(2 << 3 | 1) + struct.pack("<d", dbl)
    return out


def _event(meta_id: int, offset_ps: int, dur_ps: int,
           stats: tuple[bytes, ...] = ()) -> bytes:
    out = (_field_varint(1, meta_id) + _field_varint(2, offset_ps)
           + _field_varint(3, dur_ps))
    for st in stats:
        out += _field_bytes(4, st)
    return out


def _line(name: str, timestamp_ns: int, events: list[bytes]) -> bytes:
    out = _field_str(2, name) + _field_varint(3, timestamp_ns)
    for ev in events:
        out += _field_bytes(4, ev)
    return out


def _plane(name: str, lines: list[bytes], event_names: dict[int, str],
           stat_names: dict[int, str] | None = None) -> bytes:
    out = _field_str(2, name)
    for ln in lines:
        out += _field_bytes(3, ln)
    for mid, mname in event_names.items():
        out += _field_bytes(4, _event_metadata_entry(mid, mname))
    for mid, mname in (stat_names or {}).items():
        out += _field_bytes(5, _event_metadata_entry(mid, mname))
    return out


def _xspace(*planes: bytes) -> bytes:
    return b"".join(_field_bytes(1, p) for p in planes)


# --------------------------------------------------------------- interval math
class TestIntervalMath:
    def test_merge_basic(self):
        assert ta.merge_intervals([(5, 9), (0, 3), (2, 4)]) == [(0, 4), (5, 9)]

    def test_merge_drops_empty_and_inverted(self):
        assert ta.merge_intervals([(3, 3), (7, 2)]) == []

    def test_union_counts_overlap_once(self):
        assert ta.union_total([(0, 10), (5, 15)]) == 15

    def test_intersection_disjoint(self):
        assert ta.intersection_total([(0, 5)], [(5, 10)]) == 0

    def test_intersection_nested(self):
        assert ta.intersection_total([(0, 100)], [(10, 20), (30, 40)]) == 20

    @pytest.mark.parametrize("seed", range(5))
    def test_union_intersection_vs_bruteforce(self, seed):
        """Randomized interval sets vs counting covered unit cells."""
        rng = random.Random(seed)

        def rand_set(n):
            out = []
            for _ in range(n):
                s = rng.randrange(0, 200)
                out.append((s, s + rng.randrange(0, 40)))
            return out

        a, b = rand_set(rng.randrange(1, 12)), rand_set(rng.randrange(1, 12))
        cover_a = {x for s, e in a for x in range(s, e)}
        cover_b = {x for s, e in b for x in range(s, e)}
        assert ta.union_total(a) == len(cover_a)
        assert ta.union_total(b) == len(cover_b)
        assert ta.intersection_total(a, b) == len(cover_a & cover_b)
        # identity the analyzer relies on: |A|+|B|-|A∩B| == |A∪B|
        assert (ta.union_total(a) + ta.union_total(b)
                - ta.intersection_total(a, b)) == ta.union_total(a + b)


# ----------------------------------------------------------- instruction index
_HLO = """\
HloModule jit_step

ENTRY main {
  %fusion.1 = f32[128,128]{1,0} fusion(f32[128,64]{1,0} %p0), kind=kLoop, metadata={op_name="jit(step)/attention/dot_general"}
  %fusion.7 = f32[64,256]{1,0} fusion(f32[64,256]{1,0} %w1), kind=kLoop, metadata={op_name="jit(step)/moe_experts/moe_combine/mul"}
  %all-reduce.2 = f32[128]{0} all-reduce(f32[128]{0} %fusion.1), replica_groups={{0,1,2,3},{4,5,6,7}}, metadata={op_name="jit(step)/mlp/sum"}
  %all-to-all.3 = f32[8]{0} all-to-all(f32[8]{0} %fusion.1), replica_groups={{0,1}}, metadata={op_name="jit(step)/moe_dispatch/a2a"}
  ROOT %all-gather-start.4 = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %fusion.1), replica_groups={{0,1,2,3,4,5,6,7}}, metadata={op_name="jit(step)/mlp/ag"}
}
"""
_MESH = {"dp": 4, "ep": 2, "tp": 8}


class TestInstructionIndex:
    def test_scopes_and_collectives(self):
        idx = ta.build_instruction_index(_HLO, _MESH)
        assert idx["fusion.1"].scope == "attention"
        assert idx["fusion.1"].collective is None
        # innermost scope wins: moe_combine beats moe_experts
        assert idx["fusion.7"].scope == "moe_combine"
        ar = idx["all-reduce.2"]
        assert (ar.collective, ar.axis, ar.moe) == ("all-reduce", "dp", False)
        a2a = idx["all-to-all.3"]
        assert (a2a.collective, a2a.axis, a2a.moe) == ("all-to-all", "ep", True)
        ag = idx["all-gather-start.4"]
        assert (ag.collective, ag.axis) == ("all-gather", "tp")

    def test_classify_async_done_falls_back_to_start(self):
        idx = ta.build_instruction_index(_HLO, _MESH)
        info = ta._classify("all-gather-done.4", idx)
        assert info.collective == "all-gather"
        assert info.axis == "tp"

    def test_classify_without_index_uses_name_prefix(self):
        info = ta._classify("all-to-all.9", None)
        assert info.collective == "all-to-all"
        assert info.moe is True
        assert ta._classify("fusion.3", None).collective is None


# ------------------------------------------------------------ synthetic traces
def _synthetic_space() -> bytes:
    """One device plane, "XLA Ops" line at t0=1000ns, hand-picked intervals::

        fusion.1       [0,      100_000)   compute
        all-reduce.2   [50_000, 150_000)   comm  (overlaps compute by 50_000)
        all-to-all.3   [200_000, 250_000)  comm+moe
        window = 250_000 ps, busy = 200_000, host gap = 50_000
    """
    names = {1: "fusion.1", 2: "all-reduce.2", 3: "all-to-all.3"}
    events = [_event(1, 0, 100_000), _event(2, 50_000, 100_000),
              _event(3, 200_000, 50_000)]
    return _xspace(_plane("/device:TPU:0", [_line("XLA Ops", 1000, events)],
                          names))


class TestSyntheticTrace:
    def test_parse_roundtrip(self):
        planes = ta.read_xspace(_synthetic_space())
        assert [p.name for p in planes] == ["/device:TPU:0"]
        (line,) = planes[0].lines
        assert line.name == "XLA Ops"
        assert [e.name for e in line.events] == [
            "fusion.1", "all-reduce.2", "all-to-all.3"]
        # absolute starts: line timestamp_ns * 1000 + offset_ps
        assert line.events[0].start_ps == 1_000_000
        assert line.events[1].start_ps == 1_050_000
        assert line.events[2].dur_ps == 50_000

    def test_category_math(self, tmp_path):
        p = tmp_path / "host.xplane.pb"
        p.write_bytes(_synthetic_space())
        r = ta.analyze_trace(str(p), hlo_text=_HLO, mesh_axes=_MESH,
                             steps_hint=1)
        assert r is not None and r.steps == 1
        ps = 1e-12
        assert r.window_s == pytest.approx(250_000 * ps)
        assert r.compute_s == pytest.approx(100_000 * ps)
        assert r.comm_s == pytest.approx(150_000 * ps)
        assert r.overlap_s == pytest.approx(50_000 * ps)
        assert r.host_s == pytest.approx(50_000 * ps)
        assert r.moe_a2a_s == pytest.approx(50_000 * ps)
        assert r.overlap_frac == pytest.approx(1 / 3)
        # exact per-step identity
        assert (r.compute_s + r.comm_s - r.overlap_s + r.host_s
                ) == pytest.approx(r.step_time_s, rel=1e-12)
        assert r.comm_axis_s["dp"] == pytest.approx(100_000 * ps)
        assert r.comm_axis_s["ep"] == pytest.approx(50_000 * ps)
        assert r.scope_s["attention"] == pytest.approx(100_000 * ps)
        # host_frac = 0.2 <= 0.25, comm > compute, moe < 0.5*comm -> comms
        assert r.measured_bound == "comms"

    def test_moe_bound_when_a2a_dominates(self, tmp_path):
        names = {1: "fusion.1", 3: "all-to-all.3"}
        events = [_event(1, 0, 50_000), _event(3, 0, 200_000)]
        sp = _xspace(_plane("/device:TPU:0",
                            [_line("XLA Ops", 0, events)], names))
        p = tmp_path / "host.xplane.pb"
        p.write_bytes(sp)
        r = ta.analyze_trace(str(p), steps_hint=1)
        assert r.measured_bound == "moe_a2a"
        assert r.overlap_frac == pytest.approx(0.25)

    def test_summary_row_keys(self, tmp_path):
        p = tmp_path / "host.xplane.pb"
        p.write_bytes(_synthetic_space())
        row = ta.analyze_trace(str(p), hlo_text=_HLO, mesh_axes=_MESH,
                               steps_hint=1).summary_row()
        for key in ("trace/steps", "trace/events", "trace/window_s",
                    "measured_step_time_s", "measured_t_compute_s",
                    "measured_t_comm_s", "measured_t_moe_a2a_s",
                    "measured_t_host_s", "measured_t_overlap_s",
                    "overlap_frac", "measured_bound", "measured_frac_compute",
                    "measured_frac_comm", "measured_frac_moe_a2a",
                    "measured_frac_host", "measured_comm_axis_dp_s",
                    "measured_comm_axis_ep_s", "trace/scope/attention_s"):
            assert key in row, key
        assert 0.0 <= row["overlap_frac"] <= 1.0

    def test_cpu_style_op_events_via_stats(self, tmp_path):
        """CPU thunk-executor lines aren't named "XLA Ops" — op events are
        recognized by hlo stats (with a ref-valued hlo_op resolving through
        the plane's stat_metadata), and the python TraceMe line is ignored."""
        stat_names = {10: "hlo_op", 11: "dot.4", 12: "hlo_module", 13: "jit_f"}
        ev = _event(1, 0, 70_000, stats=(
            _stat(10, ref=11), _stat(12, ref=13)))
        traceme = _event(2, 0, 500_000)  # host-side python span, no hlo stats
        sp = _xspace(_plane(
            "/host:CPU",
            [_line("tf_XLATfrtCpuClient/1", 0, [ev]),
             _line("python", 0, [traceme])],
            {1: "dot.4", 2: "TraceMe"}, stat_names))
        planes = ta.read_xspace(sp)
        evs = ta._op_events(planes)
        assert [e.name for e in evs] == ["dot.4"]
        assert evs[0].stats["hlo_op"] == "dot.4"
        assert evs[0].stats["hlo_module"] == "jit_f"
        r = ta.analyze_trace(str(_write(tmp_path, sp)), steps_hint=1)
        assert r.module == "jit_f"
        assert r.compute_s == pytest.approx(70_000 * 1e-12)

    def test_empty_trace_returns_none(self, tmp_path):
        sp = _xspace(_plane("/host:CPU", [_line("python", 0, [])], {}))
        assert ta.analyze_trace(str(_write(tmp_path, sp))) is None

    def test_dominant_module_sets_window(self, tmp_path):
        """Auxiliary executables outside the step program don't stretch the
        analysis window: the dominant (most device time) module defines it."""
        stat_names = {10: "hlo_module", 11: "jit_step", 12: "jit_aux"}
        evs = [
            _event(1, 0, 400_000, stats=(_stat(10, ref=11),)),
            # tiny helper program 1ms later must not inflate host time
            _event(2, 1_000_000_000, 1_000, stats=(_stat(10, ref=12),)),
        ]
        sp = _xspace(_plane("/device:TPU:0", [_line("XLA Ops", 0, evs)],
                            {1: "fusion.1", 2: "copy.1"}, stat_names))
        r = ta.analyze_trace(str(_write(tmp_path, sp)), steps_hint=1)
        assert r.module == "jit_step"
        assert r.window_s == pytest.approx(400_000 * 1e-12)
        assert r.host_s == 0.0


def _write(tmp_path, data: bytes):
    p = tmp_path / "host.xplane.pb"
    p.write_bytes(data)
    return p


# ------------------------------------------------------------- golden fixture
@pytest.mark.skipif(not (FIXTURES / "golden.xplane.pb").exists(),
                    reason="golden fixture not generated")
class TestGoldenFixture:
    @pytest.fixture(scope="class")
    def report(self):
        hlo = (FIXTURES / "golden_hlo.txt").read_text()
        return ta.analyze_trace(str(FIXTURES / "golden.xplane.pb"),
                                hlo_text=hlo)

    def test_find_xplane_files(self):
        found = ta.find_xplane_files(str(FIXTURES))
        assert str(FIXTURES / "golden.xplane.pb") in found

    def test_read_xspace_planes(self):
        planes = ta.read_xspace(str(FIXTURES / "golden.xplane.pb"))
        assert planes and all(isinstance(p, ta.TracePlane) for p in planes)
        assert any(line.events for p in planes for line in p.lines)

    def test_step_count_detected(self, report):
        # tools/gen_trace_fixture.py runs the jitted step exactly 3 times
        assert report is not None
        assert report.steps == 3
        assert report.module.startswith("jit_")

    def test_scope_attribution(self, report):
        # the fixture step nests named scopes "attention" and "mlp"
        assert report.scope_s.get("attention", 0) > 0
        assert report.scope_s.get("mlp", 0) > 0

    def test_identity_and_ranges(self, report):
        assert (report.compute_s + report.comm_s - report.overlap_s
                + report.host_s) == pytest.approx(report.step_time_s,
                                                  rel=1e-9)
        assert report.comm_s == 0.0  # single-device CPU step: no collectives
        assert 0.0 <= report.overlap_frac <= 1.0
        assert report.window_s > 0 and report.num_events > 0

    def test_steps_hint_overrides(self):
        r = ta.analyze_trace(str(FIXTURES / "golden.xplane.pb"), steps_hint=1)
        assert r.steps == 1 and r.steps_hint == 1
        assert r.step_time_s == pytest.approx(r.window_s)


# -------------------------------------------------------------- reconciliation
def _report(**over):
    base = dict(trace_path="t", num_events=10, module="jit_step", steps=1,
                steps_hint=None, window_s=1.0, step_time_s=1.0, compute_s=0.7,
                comm_s=0.2, moe_a2a_s=0.0, host_s=0.15, overlap_s=0.05,
                overlap_frac=0.25, comm_axis_s={}, scope_s={},
                measured_bound="compute")
    base.update(over)
    return ta.TraceReport(**base)


class TestReconcile:
    def test_agree(self):
        out = ta.reconcile_with_roofline(
            _report(), {"roofline_bound": "compute",
                        "roofline_step_time_s": 0.8})
        assert out["trace/bound_agrees"] is True
        assert out["trace/verdict"] == "agree"
        assert out["trace/roofline_vs_measured"] == pytest.approx(0.8)

    def test_memory_maps_to_compute(self):
        # the trace can't split compute- from memory-bound: both device-busy
        out = ta.reconcile_with_roofline(_report(),
                                         {"roofline_bound": "memory"})
        assert out["trace/bound_agrees"] is True

    def test_disagree_names_both(self):
        out = ta.reconcile_with_roofline(
            _report(measured_bound="comms"), {"roofline_bound": "compute"})
        assert out["trace/bound_agrees"] is False
        assert "analytic=compute" in out["trace/verdict"]
        assert "measured=comms" in out["trace/verdict"]

    def test_no_roofline_is_empty(self):
        assert ta.reconcile_with_roofline(_report(), None) == {}
        assert ta.reconcile_with_roofline(_report(), {}) == {}
