"""Test env: force a virtual 8-device CPU platform BEFORE any JAX backend initializes.

This is the TPU-native analogue of the reference's mocked torch.distributed unit tests
(tests/unit_tests/distributed/README.md:44-52) — real SPMD semantics, no hardware.

Note: the ambient environment pins JAX_PLATFORMS=axon (a single-chip TPU tunnel) and a
sitecustomize hook registers that platform at interpreter startup — before this conftest
runs. Backend *initialization* is lazy though, so overriding jax.config here (before any
test touches a device) reliably lands tests on the 8-device CPU platform.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# parity tests compare fp32 logits against torch; XLA:CPU's default (oneDNN) matmul
# path accumulates at reduced precision, which flips near-tied MoE routing decisions
jax.config.update("jax_default_matmul_precision", "float32")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(cpu_devices):
    """A (dp_shard=2, cp=2, tp=2) 8-device mesh shared across tests."""
    from automodel_tpu.parallel.mesh import MeshContext

    ctx = MeshContext(dp_shard=2, cp=2, tp=2, world_size=8)
    return ctx.build_mesh(cpu_devices)
