"""Distributed checkpointing (reference components/checkpoint/checkpointing.py:100,142).

Orbax replaces torch DCP: sharded jax arrays save/restore in parallel across hosts with
no gloo side-channels, and restore reads directly into the target sharding (the
reference's shard-then-load rules collapse into Orbax restore_args). The reference's
dual-format guarantee is kept: every model checkpoint can also be consolidated to
HF-layout safetensors so any step is ``transformers``-loadable (SURVEY.md §3.4).

Layout per save (mirrors the reference's epoch/step dirs + ``latest`` symlink,
base_recipe.py:241,383):

    <root>/step_{N}/model/        orbax pytree (sharded)
    <root>/step_{N}/optim/        orbax pytree (sharded)
    <root>/step_{N}/client.json   rng/step-scheduler/dataloader state_dicts
    <root>/step_{N}/hf/           consolidated safetensors (optional)
    <root>/latest -> step_{N}
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
from typing import Any, Callable, Mapping

import jax
import numpy as np

from automodel_tpu.checkpoint.manifest import (
    SAVING_MARKER, has_manifest, verify_manifest, write_manifest,
)
from automodel_tpu.checkpoint.reshard import (
    TOPOLOGY_KEY, ModelSignatureMismatch, describe_delta, mesh_delta,
    strip_topology,
)
from automodel_tpu.utils.retry import RetryConfig, with_retry

logger = logging.getLogger(__name__)

__all__ = ["CheckpointingConfig", "Checkpointer", "ModelSignatureMismatch"]

# Pod-agreement sentinel: a joining host with no local checkpoint view abstains
# from the restore-step minimum instead of dragging it to "nothing restorable"
# (agreed_restore_step allow_joiners). Fits int64 allgather comfortably.
_ABSTAIN = 2**31 - 1

# SAVING_MARKER (imported from manifest.py, which must exclude it from the
# inventory): written into the step dir before the first array byte, removed
# in wait() only after the integrity manifest commits. A step dir still
# carrying it was torn by a crash/kill mid-save and must never restore — even
# when it otherwise looks complete (the manifest-less "legacy" window).


@dataclasses.dataclass
class CheckpointingConfig:
    enabled: bool = True
    checkpoint_dir: str = "checkpoints"
    save_consolidated: bool = False  # also write HF safetensors per save
    keep_last_k: int | None = None  # prune old step dirs
    async_save: bool = False
    write_manifest: bool = True  # integrity manifest per save (docs/resilience.md)
    verify_on_load: bool = True  # manifest-verify a step before restoring it
    retry: dict | None = None  # transient-I/O retry tuning (utils/retry.py)


class Checkpointer:
    """Save/restore model params, optimizer state, and client (host) states."""

    def __init__(self, config: CheckpointingConfig, state_dict_adapter=None, hf_config: dict | None = None):
        self.config = config
        # orbax requires absolute paths; make relative dirs cwd-anchored up front
        self.config.checkpoint_dir = os.path.abspath(config.checkpoint_dir)
        self.state_dict_adapter = state_dict_adapter  # for consolidated HF export
        self.hf_config = hf_config
        self._ckptr = None
        self._pending = None
        self._retry = RetryConfig.from_dict(config.retry)
        # elastic-topology protocol (checkpoint/reshard.py): the recipe sets the
        # current topology (build_topology) so save() records it and load()
        # classifies mesh changes; event_sink (signature: step, event, **fields)
        # routes restore-time events — unverified_restore, elastic_restore —
        # into the resilience metric stream instead of just stderr
        self.topology: dict | None = None
        self.event_sink: Callable[..., None] | None = None

    # lazily create so importing this module never touches orbax/devices
    @property
    def ckptr(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            if self.config.async_save:
                self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
            else:
                self._ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        return self._ckptr

    # -- paths --------------------------------------------------------------
    def step_dir(self, step: int) -> str:
        return os.path.join(self.config.checkpoint_dir, f"step_{step}")

    @staticmethod
    def _parse_step(name: str) -> int | None:
        """``step_{N}`` -> N, or None for anything unparseable (a stray
        ``step_final/`` or ``step_3.bak`` must not take down resume)."""
        if not name.startswith("step_"):
            return None
        try:
            return int(name.split("_", 1)[1])
        except ValueError:
            logger.warning("ignoring non-numeric step entry %r in checkpoint dir", name)
            return None

    def _step_dirs(self) -> list[int]:
        """Completed step numbers on this host's filesystem view, sorted ascending."""
        root = self.config.checkpoint_dir
        if not os.path.isdir(root):
            return []
        steps = []
        for d in os.listdir(root):
            s = self._parse_step(d)
            if s is None or not os.path.isdir(os.path.join(root, d)):
                continue
            if self._step_complete(os.path.join(root, d)):
                steps.append(s)
        return sorted(steps)

    def latest_step(self) -> int | None:
        root = self.config.checkpoint_dir
        link = os.path.join(root, "latest")
        if os.path.islink(link):
            target = os.path.basename(os.readlink(link))
            s = self._parse_step(target)
            # the pointer is only authoritative when it resolves to a committed
            # step: a dangling or stale link (step dir pruned/lost after the
            # swap) must fall through to the scan instead of naming a step
            # load() cannot open
            if s is not None and self._step_complete(os.path.join(root, target)):
                return s
            if s is not None:
                logger.warning(
                    "latest symlink -> %s is dangling or incomplete; "
                    "falling back to a directory scan", target,
                )
        steps = self._step_dirs()
        return steps[-1] if steps else None

    @staticmethod
    def _step_complete(d: str) -> bool:
        """True when the step's arrays committed. Orbax renames its tmp dir onto
        the final name only at finalize, so a crash between an async ``save``
        and ``wait`` leaves tmp residue and/or no ``model`` tree — such a dir
        must never win the no-symlink fallback (the symlink itself is only
        written post-finalize, checkpointing.wait). The ``.saving`` intent
        marker covers the remaining window: a kill AFTER the arrays finalize
        but BEFORE the manifest leaves a complete-looking dir that would pass
        as a legacy (manifest-less) step — the marker, removed only post-
        manifest, proves it torn."""
        if not os.path.isdir(os.path.join(d, "model")):
            return False
        if os.path.exists(os.path.join(d, SAVING_MARKER)):
            return False
        return not any(".orbax-checkpoint-tmp" in name for name in os.listdir(d))

    def _emit(self, event: str, step: int = 0, **fields: Any) -> None:
        """Restore/save-time event into the resilience metric stream (no-op
        until the recipe wires ``event_sink``); reporting never takes down a
        restore."""
        if self.event_sink is None:
            return
        try:
            self.event_sink(step, event, **fields)
        except Exception:
            logger.debug("checkpoint event sink failed for %s", event, exc_info=True)

    def _gather_host_rows(self, client_states: Mapping[str, Any] | None) -> list[dict] | None:
        """All-gather each host's dataloader consumed position (collective on
        multi-host — save() reaches this on every host). None when there is no
        dataloader state to shard or the gather is unavailable."""
        if not client_states or "dataloader" not in client_states:
            return None
        dl = client_states["dataloader"]
        state = dl.state_dict() if hasattr(dl, "state_dict") else dict(dl)
        try:
            from automodel_tpu.parallel.init import allgather_host_rows

            rows = allgather_host_rows([
                int(state.get("epoch", 0)),
                int(state.get("cursor", 0)),
                int(state.get("batch_size", 0) or 0),
            ])
        except Exception:
            logger.debug("per-host dataloader gather failed; client.json "
                         "carries the local view only", exc_info=True)
            return None
        return [
            {"process_index": i, "epoch": int(r[0]), "cursor": int(r[1]),
             "batch_size": int(r[2])}
            for i, r in enumerate(rows)
        ]

    # -- save ---------------------------------------------------------------
    def save(
        self,
        step: int,
        params: Any,
        opt_state: Any = None,
        client_states: Mapping[str, Any] | None = None,
        hf_params: Any = None,
        consolidated: bool | None = None,
    ) -> str:
        """``hf_params`` overrides what the consolidated HF export writes — used by
        PEFT to export merged base+adapter weights while ``params`` stays
        adapter-only (reference checkpoint/addons.py). ``consolidated`` overrides
        ``config.save_consolidated`` for this save only: the preemption path
        drops the (slow, collective) HF export when the grace window is short
        (resilience/manager.py skip_consolidated_export). Must be uniform across
        hosts — the export's gathers are collectives."""
        if not self.config.enabled:
            return ""
        self.wait()  # finalize any in-flight async save (writes its latest symlink)
        d = self.step_dir(step)
        os.makedirs(d, exist_ok=True)
        if jax.process_index() == 0:
            # save-intent marker: Orbax's tmp-dir rename covers a crash during
            # the array write, but a kill in the window between array finalize
            # and the manifest leaves a complete-looking dir with no manifest —
            # which verify_step would wave through as "legacy". The marker is
            # removed only after the manifest commits (wait()), so any dir
            # still carrying it is torn by construction and never a restore
            # candidate.
            with open(os.path.join(d, SAVING_MARKER), "w", encoding="utf-8") as f:
                f.write(str(step))
        with_retry(self.ckptr.save, os.path.join(d, "model"), params, force=True,
                   config=self._retry, description="orbax model save")
        if opt_state is not None:
            with_retry(self.ckptr.save, os.path.join(d, "optim"), opt_state, force=True,
                       config=self._retry, description="orbax optim save")
        # per-host consumed-position shards (collective: every host contributes
        # its dataloader row BEFORE the proc-0-only writes below) — the elastic
        # restore merges these into the global consumed set when the process
        # count changes (resilience/elastic.py merge_host_states)
        host_rows = self._gather_host_rows(client_states)
        if jax.process_index() == 0 and client_states:
            client_doc = {k: _jsonify(v.state_dict() if hasattr(v, "state_dict") else v)
                          for k, v in client_states.items()}
            if host_rows is not None:
                client_doc["__hosts__"] = {"dataloader": host_rows}
            # tmp + os.replace: a crash mid-write must never leave a truncated
            # client.json that poisons the next resume
            _write_json_atomic(os.path.join(d, "client.json"), client_doc)
        if jax.process_index() == 0:
            sig: dict[str, Any] = _model_signature(params)
            if self.topology is not None:
                # the saving topology rides the signature file (one atomic
                # artifact); readers strip it before comparing param signatures
                sig[TOPOLOGY_KEY] = self.topology
            _write_json_atomic(os.path.join(d, "signature.json"), sig)
        do_consolidated = (self.config.save_consolidated
                           if consolidated is None else consolidated)
        if do_consolidated and self.state_dict_adapter is not None:
            self.save_hf(os.path.join(d, "hf"), params if hf_params is None else hf_params)
        # async: the array write may still be in flight — defer the latest symlink
        # to wait() so a crash mid-write can't leave latest -> incomplete step
        self._pending = step
        if not self.config.async_save:
            self.wait()
        self._prune()
        logger.info("saved checkpoint step=%d -> %s", step, d)
        return d

    def save_hf(self, out_dir: str, params: Any) -> None:
        """Consolidated HF-layout safetensors export (any rank count -> one HF dir).

        STREAMING: the adapter yields lazy per-tensor views (to_hf_lazy), so each
        layer/expert slice is gathered to host, transformed, written, and dropped
        one at a time — peak host memory is one <=5GB shard on the writing rank
        and one tensor elsewhere, never the model (the r2 design pulled the full
        tree to host first, capping exports at one host's RAM; the reference
        ships an 858-LoC consolidation engine for the same reason,
        consolidate_hf_safetensors.py:1). Every process walks the tensors in the
        SAME order because the per-slice gathers are collectives; only rank 0
        writes."""
        from automodel_tpu.checkpoint.safetensors_io import save_safetensors

        lazy = self.state_dict_adapter.to_hf_lazy(params, host_fn=_full_host_array)
        is_writer = jax.process_index() == 0
        save_safetensors(lazy, out_dir, write=is_writer)
        if is_writer and self.hf_config is not None:
            with open(os.path.join(out_dir, "config.json"), "w") as f:
                json.dump(self.hf_config, f, indent=2)

    def wait(self) -> None:
        """Block until an in-flight async save lands, then commit its ``latest``
        symlink (reference maybe_wait_for_staging, train_ft.py:1336)."""
        if self._ckptr is not None and hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()
        if self._pending is not None:
            if jax.process_index() == 0:
                # manifest AFTER the arrays finalize and BEFORE latest commits:
                # its presence implies a committed step (checkpoint/manifest.py)
                if self.config.write_manifest:
                    write_manifest(self.step_dir(self._pending), step=self._pending)
                # intent marker off only once the step is fully committed —
                # the ordering marker -> arrays -> manifest -> unmark -> latest
                # makes "marker present" equivalent to "torn"
                marker = os.path.join(self.step_dir(self._pending), SAVING_MARKER)
                if os.path.exists(marker):
                    os.unlink(marker)
                self._update_latest(self._pending)
            self._pending = None

    # -- load ---------------------------------------------------------------
    def load(
        self,
        params_template: Any,
        opt_state_template: Any = None,
        step: int | None = None,
        verify: bool | None = None,
    ) -> tuple[Any, Any, dict[str, Any]]:
        """Restore into the shardings/dtypes of the provided templates.

        ``verify`` (default: ``config.verify_on_load``) checks the step's
        integrity manifest host-side BEFORE the collective Orbax restore, so a
        truncated/corrupt file fails with a named problem instead of an opaque
        mid-collective error. Legacy steps without a manifest load unverified
        with a warning."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.config.checkpoint_dir!r}")
        import orbax.checkpoint as ocp

        d = self.step_dir(step)
        if verify is None:
            verify = self.config.verify_on_load
        if verify:
            if has_manifest(d):
                problems = verify_manifest(d)
                if problems:
                    raise ValueError(
                        f"checkpoint at {d!r} failed integrity verification: "
                        f"{problems[:5]}{' ...' if len(problems) > 5 else ''}"
                    )
            else:
                logger.warning("checkpoint at %s has no integrity manifest; loading unverified", d)
                # satellite of docs/resilience.md: an unverified restore must
                # land in the metric stream/timeline, not just stderr
                self._emit("unverified_restore", step=step, path=d)
        # model-signature compat check (reference base_recipe.py:768-846): fail
        # with a diff instead of orbax's opaque tree-mismatch errors when the
        # config changed between save and resume. A changed MESH is not a
        # changed model — the signature is sharding-independent and the saved
        # topology is stripped before comparing — so a reshaped pod falls
        # through to the elastic path below instead of failing here.
        delta: dict = {}
        saved_topo = None
        sig_path = os.path.join(d, "signature.json")
        if os.path.exists(sig_path):
            with open(sig_path) as f:
                saved, saved_topo = strip_topology(json.load(f))
            current = _model_signature(params_template)
            if saved != current:
                missing = sorted(set(saved) - set(current))[:5]
                added = sorted(set(current) - set(saved))[:5]
                changed = sorted(
                    k for k in set(saved) & set(current) if saved[k] != current[k]
                )[:5]
                raise ModelSignatureMismatch(
                    f"checkpoint at {d!r} was saved from a different model signature: "
                    f"missing={missing} added={added} changed={changed} "
                    f"(first 5 each; did the model config change between save and resume?)"
                )
            delta = mesh_delta(saved_topo, self.topology)
            if delta:
                # elastic restore: same model, different topology. Orbax's
                # StandardRestore reads straight into the new templates'
                # shardings (the pp-stacked (L, ...) layout is the storage
                # layout on every mesh), so the arrays need no translation —
                # announce the reshape and let the caller re-partition host
                # state from the __elastic__ marker injected below.
                logger.info(
                    "elastic restore at step %d: mesh changed (%s); restoring "
                    "into the new mesh's templates", step, describe_delta(delta),
                )
                self._emit("elastic_restore", step=step,
                           delta=describe_delta(delta))

        def _resharded(restored, template):
            # orbax can land scalars/small leaves on a single device; force every
            # leaf back onto the template's sharding so jit sees consistent placement
            def put(r, t):
                if hasattr(t, "sharding"):
                    return jax.device_put(r, t.sharding)
                return r

            return jax.tree.map(put, restored, template)

        params = _resharded(
            with_retry(self.ckptr.restore, os.path.join(d, "model"),
                       args=ocp.args.StandardRestore(params_template),
                       config=self._retry, description="orbax model restore"),
            params_template,
        )
        opt_state = None
        if opt_state_template is not None and os.path.isdir(os.path.join(d, "optim")):
            opt_state = _resharded(
                with_retry(self.ckptr.restore, os.path.join(d, "optim"),
                           args=ocp.args.StandardRestore(opt_state_template),
                           config=self._retry, description="orbax optim restore"),
                opt_state_template,
            )
        client: dict[str, Any] = {}
        cj = os.path.join(d, "client.json")
        if os.path.exists(cj):
            try:
                with open(cj) as f:
                    client = json.load(f)
            except (ValueError, OSError) as e:
                # a legacy (pre-atomic-write) crash left a truncated client.json;
                # params/optimizer are intact, so resume with fresh host state
                # instead of refusing the whole checkpoint
                logger.warning(
                    "unreadable client.json at %s (%s: %s); resuming without "
                    "rng/scheduler/dataloader state", cj, type(e).__name__, e,
                )
                client = {}
        if delta:
            # the caller (recipe _maybe_resume) pops this marker and
            # re-partitions dataloader state across the new pod
            client["__elastic__"] = {
                "from": saved_topo,
                "to": self.topology,
                "delta": {k: list(v) for k, v in delta.items()},
            }
        return params, opt_state, client

    # -- verified / fallback restore (docs/resilience.md) --------------------
    def verify_step(self, step: int) -> list[str]:
        """Integrity problems for a step (empty = verified or legacy-unverifiable)."""
        d = self.step_dir(step)
        if not self._step_complete(d):
            return [f"incomplete step dir {d!r}"]
        if not has_manifest(d):
            return []  # legacy pre-manifest save: complete dir is the best signal
        return verify_manifest(d)

    def newest_verifiable_step(self, exclude: set[int] | None = None) -> int | None:
        """Walk back from the newest complete step to the newest one that passes
        integrity verification on THIS host (local filesystem view only)."""
        exclude = exclude or set()
        for s in reversed(self._step_dirs()):
            if s in exclude:
                continue
            problems = self.verify_step(s)
            if not problems:
                return s
            logger.warning(
                "checkpoint step %d fails verification (%s); walking back",
                s, problems[:3],
            )
        return None

    def agreed_restore_step(self, exclude: set[int] | None = None,
                            allow_joiners: bool = False) -> int | None:
        """The step every host agrees to restore: each host's newest verifiable
        step, all-gathered, minimum taken — so a host whose filesystem view lags
        (checkpoint/checkpointing.py filesystem-skew hazard) can never be asked
        to restore a step it cannot see. Collective on multi-host: every host
        must call this at the same point.

        ``allow_joiners`` (elastic join/leave, docs/resilience.md): a host with
        NO verifiable local step abstains from the minimum instead of forcing
        the whole pod to ``None`` — a freshly-joined host has an empty local
        view by construction and restores whatever the veterans agree on
        (checkpoints must live on storage every host can reach). All hosts
        abstaining still yields None (genuinely fresh run)."""
        from automodel_tpu.parallel.init import agreed_min_int

        local = self.newest_verifiable_step(exclude)
        if allow_joiners:
            agreed = agreed_min_int(_ABSTAIN if local is None else local)
            return None if agreed >= _ABSTAIN else agreed
        agreed = agreed_min_int(-1 if local is None else local)
        return None if agreed < 0 else agreed

    def load_latest_verified(
        self,
        params_template: Any,
        opt_state_template: Any = None,
        allow_joiners: bool = False,
    ) -> tuple[Any, Any, dict[str, Any], int] | None:
        """Restore the newest checkpoint that verifies, walking back through
        older steps on corruption instead of crashing. Returns
        ``(params, opt_state, client, step)`` or None when nothing is restorable.
        Each candidate is re-agreed across hosts so the walk-back cannot split
        the collective restore."""
        exclude: set[int] = set()
        while True:
            step = self.agreed_restore_step(exclude, allow_joiners=allow_joiners)
            if step is None:
                return None
            try:
                params, opt_state, client = self.load(
                    params_template, opt_state_template, step=step
                )
                return params, opt_state, client, step
            except ModelSignatureMismatch:
                # a different MODEL can never be fixed by an older step of the
                # same run — walking back here would exclude every candidate
                # and silently start a fresh run on top of an incompatible
                # checkpoint dir. Surface it.
                raise
            except ValueError as e:
                # verification failure on this candidate: exclude it and walk
                # back to the next verifiable step
                logger.warning("restore of step %d failed (%s); trying an older step", step, e)
                exclude.add(step)

    # -- best tracking -------------------------------------------------------
    def _read_best(self) -> dict | None:
        best_path = os.path.join(self.config.checkpoint_dir, "best.json")
        if not os.path.exists(best_path):
            return None
        try:
            with open(best_path) as f:
                return json.load(f)
        except (ValueError, OSError):
            # a crash mid-write left a truncated file; treat as no record
            logger.warning("unreadable best.json at %s; ignoring", best_path)
            return None

    def is_best(self, val_loss: float) -> bool:
        """Would this validation loss improve on the recorded best? (read-only.)

        On multi-host runs process 0 reads best.json and DECIDES, then
        broadcasts the verdict: per-host filesystem reads can skew (a host may
        see a stale or missing best.json), and since mark_best gates a
        collective save, a split decision would deadlock the pod."""
        decision = False
        if jax.process_index() == 0:
            best = self._read_best()
            decision = best is None or float(val_loss) < best["val_loss"]
        if jax.process_count() > 1:
            import jax.numpy as jnp
            from jax.experimental import multihost_utils

            decision = bool(multihost_utils.broadcast_one_to_all(jnp.asarray(decision)))
        return decision

    def mark_best(self, step: int, val_loss: float) -> bool:
        """Record a validation result; when it improves on the best so far,
        persist it and point the ``best`` symlink at the step's directory
        (reference base_recipe.py:383-425 best-checkpoint tracking). Returns
        True when this step became the new best. Call after the step is saved."""
        if not self.config.enabled or not self.is_best(val_loss):
            return False
        if jax.process_index() == 0:
            root = self.config.checkpoint_dir
            os.makedirs(root, exist_ok=True)
            best_path = os.path.join(root, "best.json")
            tmp_json = best_path + ".tmp"
            with open(tmp_json, "w") as f:
                json.dump({"step": step, "val_loss": float(val_loss)}, f)
            os.replace(tmp_json, best_path)
            link = os.path.join(root, "best")
            tmp = link + ".tmp"
            if os.path.islink(tmp) or os.path.exists(tmp):
                os.remove(tmp)
            os.symlink(f"step_{step}", tmp)
            os.replace(tmp, link)
            logger.info("new best checkpoint: step=%d val_loss=%.6f", step, val_loss)
        return True

    def best_step(self) -> int | None:
        best = self._read_best()
        return None if best is None else int(best["step"])

    # -- internals ----------------------------------------------------------
    def _update_latest(self, step: int) -> None:
        link = os.path.join(self.config.checkpoint_dir, "latest")
        tmp = link + ".tmp"
        if os.path.islink(tmp) or os.path.exists(tmp):
            os.remove(tmp)
        os.symlink(f"step_{step}", tmp)
        os.replace(tmp, link)

    def _prune(self) -> None:
        k = self.config.keep_last_k
        if not k or jax.process_index() != 0:
            return
        root = self.config.checkpoint_dir
        steps = sorted(
            s for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
            and (s := self._parse_step(d)) is not None
        )
        best = self.best_step()
        for s in steps[:-k]:
            if s == best:
                continue  # the best checkpoint survives pruning (reference contract)
            shutil.rmtree(self.step_dir(s), ignore_errors=True)


def _write_json_atomic(path: str, obj: Any) -> None:
    """tmp + os.replace: readers see the old file or the new one, never a
    truncated half-write (the crash-mid-write hazard that poisons resume)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _model_signature(params: Any) -> dict[str, str]:
    """path -> "shape/dtype" for every param leaf (sharding-independent)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return {
        jax.tree_util.keystr(path): f"{tuple(leaf.shape)}/{np.dtype(leaf.dtype).name}"
        for path, leaf in flat
    }


def _full_host_array(a: Any) -> np.ndarray:
    """Device/sharded array -> full host array, gathering across hosts if needed."""
    if hasattr(a, "is_fully_addressable") and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    return np.asarray(a)


def _jsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return {"__ndarray__": obj.tolist(), "dtype": str(obj.dtype)}
    if isinstance(obj, (np.integer, np.floating)):
        return obj.item()
    return obj
