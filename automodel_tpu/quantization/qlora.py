"""Weight-only quantization for QLoRA base models (reference quantization/qlora.py,
which wraps bitsandbytes NF4/int8; here: pure-jnp blockwise quantization with
dequant-on-use, no CUDA kernels needed).

A quantized leaf is a :class:`QuantizedTensor` — a registered pytree node whose
children are the code/scale arrays (so jit/device_put/checkpoint traverse them) and
whose scheme/shape ride as static aux data. The base model stays quantized in HBM;
:func:`dequantize_params` reconstructs dense weights inside the jitted step right
before use (the PEFT merge), so the dense copy is a transient of the step, not a
resident.

Schemes:
- ``int8``: per-output-channel absmax symmetric int8;
- ``nf4``: 4-bit NormalFloat — blockwise absmax scaling + a 16-entry codebook of
  normal-distribution quantiles (the QLoRA paper's data type), two codes per byte.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "NF4_CODEBOOK",
    "QuantizedTensor",
    "quantize_leaf",
    "dequantize_leaf",
    "is_quantized_leaf",
    "quantize_params",
    "dequantize_params",
    "tree_nbytes",
]

# 16 code values for 4-bit NormalFloat: quantiles of N(0,1) rescaled to [-1, 1]
# with an exact zero (QLoRA paper §3; values recomputed from scipy quantiles).
NF4_CODEBOOK = np.asarray(
    [-1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
     -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
     0.07958029955625534, 0.16093020141124725, 0.24611230194568634, 0.33791524171829224,
     0.44070982933044434, 0.5626170039176941, 0.7229568362236023, 1.0],
    dtype=np.float32,
)

_NF4_BLOCK = 64


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Codes + scales as pytree children; (scheme, shape, orig_dtype) static."""

    def __init__(self, q, scale, scheme: str, shape: tuple, orig_dtype: str = "float32"):
        self.q = q
        self.scale = scale
        self.scheme = scheme
        self.shape = tuple(shape)
        self.orig_dtype = str(orig_dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.scheme, self.shape, self.orig_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    @property
    def nbytes(self) -> int:
        return self.q.size * self.q.dtype.itemsize + self.scale.size * self.scale.dtype.itemsize

    def __repr__(self):
        return f"QuantizedTensor({self.scheme}, shape={self.shape})"


def is_quantized_leaf(x) -> bool:
    return isinstance(x, QuantizedTensor)


def quantize_leaf(w, scheme: str = "int8", n_stack: int = 0) -> QuantizedTensor:
    """Quantize one weight array.

    ``n_stack`` leading dims are independent stacked weights (scan layers, experts):
    scales are computed *per stack element* so one outlier layer cannot crush the
    precision of the others.

    int8 uses jnp math end-to-end — on sharded inputs the codes inherit the
    weight's layout (no host gather, pod-safe). nf4's blockwise bit-packing
    reshapes the full tensor and is host-side; use it for single-host finetuning.
    """
    orig_dtype = str(getattr(w, "dtype", "float32"))
    if scheme == "int8":
        wj = jnp.asarray(w, jnp.float32) if not isinstance(w, jax.Array) else w.astype(jnp.float32)
        reduce_axes = tuple(range(n_stack, wj.ndim - 1))
        amax = jnp.abs(wj).max(axis=reduce_axes, keepdims=True) if reduce_axes else jnp.abs(wj)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(wj / scale), -127, 127).astype(jnp.int8)
        return QuantizedTensor(q, scale, "int8", wj.shape, orig_dtype)
    if scheme == "nf4":
        w = np.asarray(w, np.float32)
        flat = w.reshape(-1)
        pad = (-len(flat)) % _NF4_BLOCK
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        blocks = flat.reshape(-1, _NF4_BLOCK)
        scale = np.maximum(np.abs(blocks).max(axis=1, keepdims=True), 1e-12)
        normed = blocks / scale  # in [-1, 1]
        codes = np.abs(normed[..., None] - NF4_CODEBOOK).argmin(-1).astype(np.uint8)
        packed = (codes[:, 0::2] << 4) | codes[:, 1::2]  # two 4-bit codes per byte
        return QuantizedTensor(
            jnp.asarray(packed), jnp.asarray(scale[:, 0]), "nf4", w.shape, orig_dtype
        )
    raise ValueError(f"unknown qlora scheme {scheme!r} (int8 | nf4)")


def dequantize_leaf(leaf: QuantizedTensor, dtype=None) -> jnp.ndarray:
    """Dense view in ``dtype`` (default: the weight's pre-quantization dtype, so a
    bf16 base merges back to bf16 — transient footprint and consolidated saves keep
    the base precision)."""
    if dtype is None:
        dtype = jnp.dtype(leaf.orig_dtype)
    if leaf.scheme == "int8":
        return (leaf.q.astype(jnp.float32) * leaf.scale).astype(dtype)
    if leaf.scheme == "nf4":
        packed = leaf.q
        hi = (packed >> 4).astype(jnp.int32)
        lo = (packed & 0x0F).astype(jnp.int32)
        codes = jnp.stack([hi, lo], axis=-1).reshape(packed.shape[0], -1)
        blocks = jnp.asarray(NF4_CODEBOOK)[codes] * leaf.scale[:, None]
        n = int(np.prod(leaf.shape))
        return blocks.reshape(-1)[:n].reshape(leaf.shape).astype(dtype)
    raise ValueError(f"unknown qlora scheme {leaf.scheme!r}")


def quantize_params(params, paths: list[str] | dict, scheme: str = "int8"):
    """Quantize the listed dot-joined paths in a param pytree (at load time).

    ``paths`` may be a dict path -> (n_stack, split) as produced by
    peft.lora.match_lora_paths, in which case per-stack-element scales are used.
    """
    from automodel_tpu.peft.lora import _get_path, _set_path

    stacks = paths if isinstance(paths, dict) else {p: (0, None) for p in paths}
    out = params
    for path, (n_stack, _split) in stacks.items():
        out = _set_path(out, path, quantize_leaf(_get_path(out, path), scheme, n_stack))
    return out


def dequantize_params(params, dtype=None):
    """Dense view of a (partially) quantized tree — call inside jit at point of use."""
    return jax.tree.map(
        lambda x: dequantize_leaf(x, dtype) if is_quantized_leaf(x) else x,
        params,
        is_leaf=is_quantized_leaf,
    )


def tree_nbytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        total += leaf.size * leaf.dtype.itemsize
    return total
