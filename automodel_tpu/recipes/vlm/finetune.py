"""VLM finetune recipe (reference FinetuneRecipeForVLM, recipes/vlm/finetune.py:469).

Subclasses the LLM finetune recipe: image-text model factory, VLM collation with
image-token expansion, and a ``freeze`` section (reference freeze_config) that
splits params into trainable/frozen *subtrees* — frozen parts ride through the
jitted step as a non-differentiated argument (the same mechanism PEFT uses), so
optimizer state only covers what trains.

.. code-block:: yaml

    model:
      pretrained_model_name_or_path: /path/to/llava   # or config: {...}
    freeze:
      freeze_vision_tower: true      # reference default
      freeze_language_model: false
      freeze_projector: false
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.data.vlm.collate import vlm_collate
from automodel_tpu.models.auto import AutoModelForImageTextToText, load_hf_config
from automodel_tpu.ops.losses import masked_cross_entropy
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.training.train_step import make_train_step

logger = logging.getLogger(__name__)

__all__ = ["FinetuneRecipeForVLM", "main"]

_FREEZE_KEYS = {
    "freeze_vision_tower": "vision_tower",
    "freeze_language_model": "language_model",
    "freeze_projector": "projector",
}


class FinetuneRecipeForVLM(TrainFinetuneRecipeForNextTokenPrediction):
    # -- model --------------------------------------------------------------
    def _build_model_and_params(self):
        cfg = self.cfg
        pretrained = cfg.get("model.pretrained_model_name_or_path")
        with self.mesh:
            if pretrained:
                self.hf_config = load_hf_config(pretrained)
                self.model, self.params = AutoModelForImageTextToText.from_pretrained(
                    pretrained, backend=self.backend, dtype=jnp.float32, rules=self.rules
                )
            else:
                model_cfg = cfg.get("model.config")
                if model_cfg is None:
                    raise ValueError("config needs model.pretrained_model_name_or_path or model.config")
                self.hf_config = model_cfg.to_dict() if isinstance(model_cfg, ConfigNode) else dict(model_cfg)
                self.model = AutoModelForImageTextToText.from_config(self.hf_config, backend=self.backend)
                shardings = self.rules.tree_sharding(self.model.logical_axes())
                init_fn = jax.jit(lambda k: self.model.init(k, jnp.float32), out_shardings=shardings)
                self.params = init_fn(self.rng.key("model_init"))
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))
        logger.info("model: %s (%.1fM params)", type(self.model).__name__, n_params / 1e6)

    def _build_peft(self):
        if self.cfg.get("peft") is not None:
            raise NotImplementedError("peft + vlm composition is not wired yet")
        self.peft = None
        # freeze split (reference freeze_config, vlm/finetune.py:86-113)
        freeze_cfg = self.cfg.get("freeze") or ConfigNode({"freeze_vision_tower": True})
        frozen_keys = [
            tree_key for cfg_key, tree_key in _FREEZE_KEYS.items()
            if freeze_cfg.get(cfg_key, cfg_key == "freeze_vision_tower")
        ]
        self.frozen_keys = [k for k in frozen_keys if k in self.params]
        if len(self.frozen_keys) == len(self.params):
            raise ValueError("freeze config freezes every submodule; nothing to train")
        self.frozen_params = {k: self.params[k] for k in self.frozen_keys}
        self.train_params = {k: v for k, v in self.params.items() if k not in self.frozen_keys}
        logger.info("vlm freeze: frozen=%s trainable=%s", self.frozen_keys, list(self.train_params))

    # -- data ---------------------------------------------------------------
    def _wrap_dataset_and_collate(self, dataset, pad_id: int):
        mcfg = self.model.config
        return dataset, (
            lambda exs: vlm_collate(
                exs,
                tokenizer=self.tokenizer,
                seq_len=self.seq_len,
                image_token_id=mcfg.image_token_index,
                num_image_tokens=mcfg.num_image_tokens,
                image_size=mcfg.vision.image_size,
                pad_token_id=pad_id,
            )
        )

    # -- step ---------------------------------------------------------------
    def _forward_loss(self, params, batch, num_label_tokens, training=True):
        logits = self.model(
            params, batch["input_ids"], pixel_values=batch["pixel_values"],
            positions=batch["positions"], segment_ids=batch["segment_ids"],
            rules=self.rules,
        )
        return masked_cross_entropy(logits, batch["labels"], num_label_tokens)

    def _build_train_step(self):
        if self.mesh_ctx.pp > 1:
            raise NotImplementedError("vlm + pp composition is not wired yet")

        def split_loss(trainable, frozen, batch, num_label_tokens):
            return self._forward_loss({**frozen, **trainable}, batch, num_label_tokens)

        step = make_train_step(split_loss, self.optimizer, with_frozen=True)
        return jax.jit(step, donate_argnums=(0, 1))

    def run_train_validation_loop(self):
        jitted = self._train_step
        self._train_step = lambda p, o, stack: jitted(p, o, stack, self.frozen_params)
        super().run_train_validation_loop()
        # reassemble the full tree for saves/consumers
        self.params = {**self.frozen_params, **self.train_params}

    def _run_validation(self, step: int):
        if self._eval_step is None:
            from automodel_tpu.training.train_step import make_eval_step

            eval_loss = lambda t, f, b, n: self._forward_loss({**f, **t}, b, n, training=False)
            self._eval_step = jax.jit(make_eval_step(eval_loss, with_frozen=True))
        losses = []
        for batch in self.val_dataloader:
            n = int((batch["labels"] != -100).sum())
            losses.append(float(self._eval_step(self.train_params, batch, n, self.frozen_params)))
        if losses:
            val_loss = float(np.mean(losses))
            self.val_metric_logger.log(step, val_loss=val_loss)
            logger.info("validation @ step %d: loss %.4f", step, val_loss)

    def _save(self, step: int):
        client = {
            "rng": self.rng,
            "step_scheduler": self.step_scheduler,
            "dataloader": self.dataloader,
            "frozen_keys": list(self.frozen_keys),
        }
        full = {**self.frozen_params, **self.train_params}
        self.checkpointer.save(
            step, self.train_params, self.opt_state, client_states=client, hf_params=full
        )


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = FinetuneRecipeForVLM(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    return recipe


if __name__ == "__main__":
    main()
