"""Config-driven observability manager wired into the training recipes.

One object owns the pillars — goodput accounting, HBM/compile telemetry, the
stall watchdog, on-demand profiling, per-compile HLO cost/roofline
accounting, the unified trace timeline, and cross-host metric aggregation —
so a recipe integrates with a handful of hooks: ``start()``,
``track(bucket)``, ``heartbeat(step)``, ``on_step_start/end(step)``,
``compile_step(fn, args)`` at the first call of a jitted step, and
``step_metrics()`` / ``roofline_row()`` / ``host_metrics()`` merged into each
log row. Everything flows through the existing MetricLogger/experiment-logger
fan-out plus one new artifact, ``out_dir/timeline.json``.

YAML (all keys optional; the subsystem is on by default and every pillar
no-ops cleanly where its backing API is unavailable):

.. code-block:: yaml

    observability:
      enabled: true
      goodput: true
      memory: true
      hlo_costs: true
      timeline: {enabled: true, max_events: 20000}
      aggregate: {enabled: true, straggler_factor: 2.0}
      watchdog: {enabled: true, threshold_s: 600}
      profiling: {server_port: 0, trace_steps: 5, signal: SIGUSR1}
      dynamics: {enabled: true, every_n_steps: 10, spike_zscore: 6.0}
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import signal as _signal
import time
from typing import Any, Callable

from automodel_tpu.observability import compile_cache
from automodel_tpu.observability.aggregate import CrossHostAggregator, host_keys
from automodel_tpu.observability.dynamics import DynamicsConfig, DynamicsTracker
from automodel_tpu.observability.events import TraceTimeline
from automodel_tpu.observability.goodput import GoodputTracker
from automodel_tpu.observability.hlo_costs import (
    compiled_cost_metrics,
    device_specs,
    diagnose_bound,
    roofline_metrics,
    scope_output_bytes,
)
from automodel_tpu.observability.memory import device_memory_stats
from automodel_tpu.observability.memory_plan import (
    MemoryPlan,
    compiled_memory_attribution,
    reconcile,
)
from automodel_tpu.observability.oom import OOMFlightRecorder, is_oom_error
from automodel_tpu.observability.profiling import OnDemandProfiler
from automodel_tpu.observability.watchdog import StallWatchdog

logger = logging.getLogger(__name__)

__all__ = ["ObservabilityConfig", "Observability"]

# phases long enough to deserve their own timeline span; steps and compiles
# are spanned by their dedicated hooks
_TIMELINE_BUCKETS = ("eval", "checkpoint", "rollback")

# timeline span name -> the HLO scope labels that feed it; the explicit-EP a2a
# path (moe/dispatch.py) and the GSPMD dense path (moe/experts.py) label the
# same three phases under different scope names
_MOE_SPAN_SCOPES = {
    "moe_dispatch": ("ep_dispatch", "moe_dispatch"),
    "moe_experts": ("ep_experts", "moe_experts"),
    "moe_combine": ("ep_combine", "moe_combine"),
}


@dataclasses.dataclass
class ObservabilityConfig:
    enabled: bool = True
    goodput: bool = True
    memory: bool = True
    oom_report: bool = True  # OOM flight recorder (needs memory pillar on)
    oom_keep_rows: int = 20  # metric rows kept for the crash artifact
    hbm_limit_gib: float | None = None  # per-chip capacity override (mem plan)
    hlo_costs: bool = True
    timeline: bool = True
    timeline_max_events: int = 20000
    aggregate: bool = True
    straggler_factor: float = 2.0
    oom_risk_gib: float = 1.0  # flag a host when its headroom drops below this
    divergence_rtol: float = 1e-4  # replicated-scalar disagreement = desync
    dynamics: DynamicsConfig = dataclasses.field(default_factory=DynamicsConfig)
    watchdog: bool = True
    watchdog_threshold_s: float = 600.0
    watchdog_poll_interval_s: float | None = None
    profiler_port: int = 0  # 0 = no profiler server
    trace_steps: int = 5
    trace_signal: str | None = "SIGUSR1"  # None/"none" = no signal handler
    auto_trace: bool = True  # stall/excursion anomalies arm the profiler
    auto_trace_max: int = 1  # per-run budget of anomaly-triggered traces
    excursion_factor: float = 3.0  # step_time > factor x rolling median fires
    excursion_min_samples: int = 5  # dt samples before excursions are judged

    @classmethod
    def from_dict(cls, raw: Any) -> "ObservabilityConfig":
        """Build from the ``observability:`` YAML section (ConfigNode or dict)."""
        if raw is None:
            return cls()
        if hasattr(raw, "to_dict"):
            raw = raw.to_dict()
        raw = dict(raw)
        kw: dict[str, Any] = {
            k: raw[k] for k in ("enabled", "goodput", "hlo_costs") if k in raw
        }
        mem = raw.get("memory")
        if isinstance(mem, bool):
            kw["memory"] = mem
        elif isinstance(mem, dict):
            kw["memory"] = bool(mem.get("enabled", True))
            if "oom_report" in mem:
                kw["oom_report"] = bool(mem["oom_report"])
            if mem.get("oom_keep_rows") is not None:
                kw["oom_keep_rows"] = int(mem["oom_keep_rows"])
            if mem.get("hbm_limit_gib") is not None:
                kw["hbm_limit_gib"] = float(mem["hbm_limit_gib"])
        tl = raw.get("timeline")
        if isinstance(tl, bool):
            kw["timeline"] = tl
        elif isinstance(tl, dict):
            kw["timeline"] = bool(tl.get("enabled", True))
            if tl.get("max_events") is not None:
                kw["timeline_max_events"] = int(tl["max_events"])
        agg = raw.get("aggregate")
        if isinstance(agg, bool):
            kw["aggregate"] = agg
        elif isinstance(agg, dict):
            kw["aggregate"] = bool(agg.get("enabled", True))
            if agg.get("straggler_factor") is not None:
                kw["straggler_factor"] = float(agg["straggler_factor"])
            if agg.get("oom_risk_gib") is not None:
                kw["oom_risk_gib"] = float(agg["oom_risk_gib"])
            if agg.get("divergence_rtol") is not None:
                kw["divergence_rtol"] = float(agg["divergence_rtol"])
        if "dynamics" in raw:
            kw["dynamics"] = DynamicsConfig.from_dict(raw["dynamics"])
        wd = raw.get("watchdog")
        if isinstance(wd, bool):
            kw["watchdog"] = wd
        elif isinstance(wd, dict):
            kw["watchdog"] = bool(wd.get("enabled", True))
            if wd.get("threshold_s") is not None:
                kw["watchdog_threshold_s"] = float(wd["threshold_s"])
            if wd.get("poll_interval_s") is not None:
                kw["watchdog_poll_interval_s"] = float(wd["poll_interval_s"])
        prof = raw.get("profiling")
        if isinstance(prof, dict):
            kw["profiler_port"] = int(prof.get("server_port", 0))
            kw["trace_steps"] = int(prof.get("trace_steps", 5))
            kw["trace_signal"] = prof.get("signal", "SIGUSR1")
            if "auto_trace" in prof:
                kw["auto_trace"] = bool(prof["auto_trace"])
            if prof.get("auto_trace_max") is not None:
                kw["auto_trace_max"] = int(prof["auto_trace_max"])
            if prof.get("excursion_factor") is not None:
                kw["excursion_factor"] = float(prof["excursion_factor"])
            if prof.get("excursion_min_samples") is not None:
                kw["excursion_min_samples"] = int(prof["excursion_min_samples"])
        return cls(**kw)

    def resolve_signal(self) -> int | None:
        name = self.trace_signal
        if not name or str(name).lower() == "none":
            return None
        return getattr(_signal, str(name).upper())


def _tree_avals(args: Any) -> Any:
    """Shape/dtype fingerprint of an argument tree — the executor dispatch key."""
    import jax

    return jax.tree.map(
        lambda x: (getattr(x, "shape", None), str(getattr(x, "dtype", type(x).__name__))),
        args,
    )


def _avals_key(args: Any) -> Any:
    """Hashable form of :func:`_tree_avals` — the variant-dict key."""
    import jax

    leaves, treedef = jax.tree.flatten(_tree_avals(args))
    return (treedef, tuple(leaves))


class _GuardedCompiled:
    """Dispatch steps to AOT-compiled variants by shape; jit is the last resort.

    The jit dispatch cache does NOT share entries with an AOT compile of the
    same function, so after extracting costs from ``lowered.compile()`` the
    loop must execute through that same compiled object or it would pay the
    full compile twice. The executor keys compiled variants by the argument
    shape/dtype fingerprint because the step scheduler can emit more than one
    step shape — the steady accumulation stack plus a trailing partial stack
    at the epoch tail. Warm restart (docs/resilience.md) pre-compiles the
    trailing shape via :meth:`add_variant`, so every shape the scheduler emits
    runs AOT; an *unplanned* shape falls back to jit and is counted
    (``aot_shape_fallback``) so the compile_summary row exposes it.

    A sharding change demotes that variant to the jit path permanently: the
    AOT object bakes in the input shardings seen at lowering, but a step whose
    outputs carry different shardings than its inputs (e.g. adapter params
    re-sharded by constraints inside the step) feeds those back as step-2
    inputs. Plain jit handles that with a silent recompile; the Compiled
    object raises.
    """

    def __init__(self, compiled: Any, fallback: Callable, args: Any,
                 on_demote: Callable[[], None] | None = None,
                 on_shape_fallback: Callable[[], None] | None = None):
        self._variants: dict[Any, Any] = {_avals_key(args): compiled}
        self._fallback = fallback
        self._on_demote = on_demote
        self._on_shape_fallback = on_shape_fallback
        self._warned_shapes: set[Any] = set()

    def add_variant(self, args: Any, compiled: Any) -> None:
        """Register an AOT-compiled variant for this argument shape."""
        self._variants[_avals_key(args)] = compiled

    @property
    def num_variants(self) -> int:
        return sum(1 for v in self._variants.values() if v is not None)

    def __call__(self, *args: Any) -> Any:
        key = _avals_key(args)
        compiled = self._variants.get(key)
        if compiled is not None:
            try:
                return compiled(*args)
            except ValueError as e:
                if "Compiled object called with input" not in str(e):
                    raise
                logger.warning(
                    "AOT-compiled step variant rejected re-sharded inputs; "
                    "falling back to jit for this shape for the rest of the run")
                self._variants[key] = None
                if self._on_demote is not None:
                    self._on_demote()
        elif key not in self._variants:
            # unseen shape: no variant was pre-compiled for it — jit picks it
            # up, but the miss is counted so warm-restart coverage is auditable
            if key not in self._warned_shapes:
                self._warned_shapes.add(key)
                logger.info("step shape has no AOT variant; running through jit")
            if self._on_shape_fallback is not None:
                self._on_shape_fallback()
        return self._fallback(*args)


class Observability:
    """The manager a recipe holds; disabled pillars degrade to no-ops."""

    def __init__(
        self,
        config: ObservabilityConfig,
        out_dir: str,
        metric_sink: Callable[..., None] | None = None,
    ):
        self.config = config
        self.out_dir = str(out_dir)
        self.compile_time_s: float | None = None
        self.roofline: dict[str, Any] | None = None
        # set by the recipe before compile_step ({axis: size}) so collective
        # bytes get attributed to ep/dp/tp/pp in the cost row
        self.mesh_axes: dict[str, int] | None = None
        # set by the recipe ({"model": ..., "seq_len": ...}) to identify the
        # (model, mesh, seq) cell in the signals.json bundle
        self.cell_info: dict[str, Any] | None = None
        # compile_step keeps the module text + analytic costs so completed
        # traces can be classified against named scopes (trace_analysis.py)
        self._hlo_text: str | None = None
        self._costs: dict[str, Any] | None = None
        # summary_row + reconciliation of the most recent analyzed trace
        self.trace_summary: dict[str, Any] | None = None
        # AOT-vs-jit accounting across every compile_step of the run:
        # aot = primary AOT compiles, aot_variant = extra shapes pre-compiled
        # by warmup, aot_demoted = variants that rejected re-sharded inputs,
        # aot_shape_fallback = steps whose shape had no variant (ran via jit),
        # jit_fallback = step fns that never got an AOT executor at all
        self.compile_counts = {"aot": 0, "jit_fallback": 0, "aot_demoted": 0,
                               "aot_variant": 0, "aot_shape_fallback": 0}
        self._metric_sink = metric_sink
        self._step_t0: float | None = None
        # analytic HBM plan (set by the recipe once params/opt_state exist);
        # compile_step reconciles it against memory_analysis()
        self.memory_plan: MemoryPlan | None = None
        # anomaly-triggered profiling: per-run budget + step-time history
        self._auto_traces = 0
        self._dt_history: list[float] = []
        on = config.enabled
        self.goodput: GoodputTracker | None = GoodputTracker() if on and config.goodput else None
        self._memory = on and config.memory
        self.oom: OOMFlightRecorder | None = None
        if on and config.memory and config.oom_report:
            self.oom = OOMFlightRecorder(self.out_dir, keep_rows=config.oom_keep_rows)
        self.timeline: TraceTimeline | None = None
        if on and config.timeline:
            import jax

            proc = jax.process_index()
            path = os.path.join(self.out_dir, "timeline.json") if proc == 0 else None
            self.timeline = TraceTimeline(path, pid=proc,
                                          max_events=config.timeline_max_events)
        self.aggregator: CrossHostAggregator | None = None
        if on and config.aggregate:
            self.aggregator = CrossHostAggregator(
                config.straggler_factor, oom_risk_gib=config.oom_risk_gib,
                divergence_rtol=config.divergence_rtol)
        self.dynamics: DynamicsTracker | None = None
        if on and config.dynamics.enabled:
            self.dynamics = DynamicsTracker(config.dynamics, self.out_dir,
                                            metric_sink=metric_sink)
        self.watchdog: StallWatchdog | None = None
        if on and config.watchdog:
            def on_stall(event: dict, _sink=metric_sink):
                step = int(event.get("step") or 0)
                if _sink is not None:
                    _sink(step, **{k: v for k, v in event.items() if k != "step"})
                # a stalled run is exactly when a trace is worth its cost:
                # arm the profiler so the NEXT step (if the run unwedges)
                # captures what the device was doing
                self.auto_trace("stall", step, stall_s=event.get("stall_s"))
            self.watchdog = StallWatchdog(
                threshold_s=config.watchdog_threshold_s,
                dump_dir=self.out_dir,
                on_stall=on_stall,
                poll_interval_s=config.watchdog_poll_interval_s,
                # a stack dump alone says where the run is stuck; the goodput
                # snapshot says what it was doing with its time until then
                context_fn=lambda: self.goodput.snapshot() if self.goodput else {},
            )
        self.profiler: OnDemandProfiler | None = None
        if on:
            self.profiler = OnDemandProfiler(
                self.out_dir,
                trace_steps=config.trace_steps,
                server_port=config.profiler_port,
                signum=config.resolve_signal(),
            )
        # external liveness: when a supervisor (resilience/supervisor.py) set
        # AUTOMODEL_HEARTBEAT_FILE, every heartbeat() also beats that file —
        # the hang detector outside this process keys off its mtime/step
        from automodel_tpu.resilience.supervisor import HeartbeatWriter

        self.heartbeat_writer: HeartbeatWriter | None = HeartbeatWriter.from_env()

    @classmethod
    def from_config(cls, cfg: Any, out_dir: str,
                    metric_sink: Callable[..., None] | None = None) -> "Observability":
        return cls(ObservabilityConfig.from_dict(cfg), out_dir, metric_sink)

    # ------------------------------------------------------------------ lifecycle
    def start(self) -> "Observability":
        if self.watchdog is not None:
            self.watchdog.start()
        if self.profiler is not None:
            self.profiler.start()
        if self.dynamics is not None:
            self.dynamics.start()
        return self

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.profiler is not None:
            self.profiler.close()
            # a window the run end cut short still gets its analysis
            trace = self.profiler.take_completed_trace()
            if trace is not None:
                self.analyze_trace(trace, step=-1,
                                   steps_hint=self.profiler.last_window_steps)
        self.write_signals()
        if self.dynamics is not None:
            self.dynamics.close()
        if self.timeline is not None:
            self.timeline.close()

    @property
    def dynamics_enabled(self) -> bool:
        """True when the train step should be built with ``dynamics=True``."""
        return self.dynamics is not None

    def compile_summary(self) -> dict[str, Any]:
        """Run-total AOT/jit-fallback/demotion counts + compile-cache hits.

        The run_header is written before the first compile, so the per-run
        totals land here instead — the recipe logs this as a
        ``compile_summary`` event row at teardown.
        """
        out = {f"compile_{k}": v for k, v in self.compile_counts.items()}
        cache = compile_cache.counts()
        out["compile_cache_hits"] = cache["hits"]
        out["compile_cache_misses"] = cache["misses"]
        return out

    # ------------------------------------------------------------------ hooks
    def track(self, bucket: str):
        """Goodput context manager; long phases also land on the timeline."""
        stack = contextlib.ExitStack()
        if self.goodput is not None:
            stack.enter_context(self.goodput.track(bucket))
        if self.timeline is not None and bucket in _TIMELINE_BUCKETS:
            stack.enter_context(self.timeline.span(bucket, cat="phase"))
        return stack

    def compile_step(self, step_fn: Callable, args: tuple, step: int = 0) -> Callable:
        """First call of a jitted step: AOT-compile, log analytic costs +
        roofline once, and return the executor the loop should run from now on.

        Must run BEFORE the first execution — the step donates its params, so
        lowering afterwards would trace over deleted buffers. On any failure
        (backend without cost analysis, non-jit callable) the jit fn comes
        back unchanged and the run proceeds with one log line of warning.
        """
        if not (self.config.enabled and self.config.hlo_costs):
            return step_fn
        if not hasattr(step_fn, "lower"):  # plain-function executor (e.g. pp wrapper)
            logger.info("step executor is not a jit callable; no HLO cost row")
            self.compile_counts["jit_fallback"] += 1
            return step_fn
        try:
            import jax

            t0 = time.perf_counter()
            compiled = step_fn.lower(*args).compile()
            try:
                hlo = compiled.as_text()  # fetched once; as_text() is not free
            except Exception:
                hlo = None
            costs = compiled_cost_metrics(compiled, mesh_axes=self.mesh_axes,
                                          hlo_text=hlo)
            self._hlo_text = hlo
            self._costs = costs
            spec = device_specs(jax.devices()[0].device_kind)
            roof = roofline_metrics(costs, spec)
            self.roofline = roof or None
            row: dict[str, Any] = {"event": "compile_costs", **costs}
            if roof:
                for key in ("roofline_t_compute_s", "roofline_t_memory_s",
                            "roofline_t_comm_s", "roofline_step_time_s"):
                    row[key] = round(roof[key], 6)
                if "roofline_t_moe_a2a_s" in roof:
                    row["roofline_t_moe_a2a_s"] = round(roof["roofline_t_moe_a2a_s"], 6)
                row["roofline_bound"] = roof["roofline_bound"]
                row["roofline_spec"] = roof["roofline_spec"]
            if self._memory:
                # the memory pillar's compile-time half: XLA's own byte
                # attribution, reconciled against the analytic plan when the
                # recipe provided one (mem_plan/recon_rel_err)
                attribution = compiled_memory_attribution(compiled)
                if attribution:
                    if self.memory_plan is not None:
                        row.update(reconcile(self.memory_plan, attribution))
                        if self.oom is not None:
                            self.oom.set_plan_row(self.memory_plan.header_row())
                    else:
                        row.update({f"mem/{k}_gib": round(v / 2**30, 4)
                                    for k, v in attribution.items()})
                if self.timeline is not None and self.memory_plan is not None:
                    plan = self.memory_plan
                    self.timeline.counter(
                        "hbm_plan_gib",
                        params=round(plan.params_bytes / 2**30, 6),
                        opt=round(plan.opt_bytes / 2**30, 6),
                        batch=round(plan.batch_bytes / 2**30, 6),
                        act_est=round(plan.act_est_bytes / 2**30, 6),
                    )
            row["cost_extract_s"] = round(time.perf_counter() - t0, 3)
            self.compile_counts["aot"] += 1
            row["compile_aot_total"] = self.compile_counts["aot"]
            if self._metric_sink is not None:
                self._metric_sink(step, **row)
            if self.timeline is not None:
                self.timeline.instant(
                    "compile_costs", cat="compile", step=step,
                    hlo_flops=costs.get("hlo_flops"),
                    comm_bytes_total=costs.get("comm_bytes_total"),
                )
            self._emit_moe_spans(hlo, spec, step)
            def _demoted():
                self.compile_counts["aot_demoted"] += 1
            def _shape_fallback():
                self.compile_counts["aot_shape_fallback"] += 1
            return _GuardedCompiled(compiled, step_fn, args, on_demote=_demoted,
                                    on_shape_fallback=_shape_fallback)
        except Exception:
            logger.warning("HLO cost extraction failed; step runs through jit",
                           exc_info=True)
            self.compile_counts["jit_fallback"] += 1
            return step_fn

    def precompile_variant(self, executor: Callable, step_fn: Callable,
                           args: tuple, step: int = 0) -> bool:
        """AOT-compile one extra step shape into an existing executor.

        The warm-restart half of elastic resume (docs/resilience.md): the
        recipe calls this for every step shape the scheduler can emit beyond
        the steady one — e.g. the trailing partial accumulation — so no shape
        demotes to a mid-run jit compile. With a persistent compilation cache
        configured (observability/compile_cache.py) the lowering hits the
        cache and the "compile" is a deserialization. No-op (False) when the
        executor is not an AOT dispatcher or the compile fails.
        """
        if not isinstance(executor, _GuardedCompiled) or not hasattr(step_fn, "lower"):
            return False
        try:
            t0 = time.perf_counter()
            compiled = step_fn.lower(*args).compile()
            executor.add_variant(args, compiled)
            self.compile_counts["aot_variant"] += 1
            if self._metric_sink is not None:
                self._metric_sink(step, event="compile_variant",
                                  compile_s=round(time.perf_counter() - t0, 3),
                                  variants=executor.num_variants)
            return True
        except Exception:
            logger.warning("AOT warmup variant compile failed; that shape will "
                           "run through jit", exc_info=True)
            return False

    def _emit_moe_spans(self, hlo: str | None, spec: Any, step: int) -> None:
        """Analytic dispatch/experts/combine spans from the compiled module.

        No device profiler needed: the optimized HLO says how many bytes each
        MoE scope produces, and the chip spec turns that into a floor duration
        (comm bytes over ICI when the scope communicates, output bytes over
        HBM otherwise). Spans land sequentially on tid=1, cat="moe" — a
        per-compile shape of the MoE step for Perfetto, not a measurement.
        """
        if self.timeline is None or not hlo:
            return
        all_scopes = tuple(s for ss in _MOE_SPAN_SCOPES.values() for s in ss)
        vols = scope_output_bytes(hlo, all_scopes)
        if not vols:
            return
        t = self.timeline.now()
        for name, scopes in _MOE_SPAN_SCOPES.items():
            nbytes = sum(vols[s]["bytes"] for s in scopes if s in vols)
            comm = sum(vols[s]["comm_bytes"] for s in scopes if s in vols)
            if not nbytes:
                continue
            dur = (comm / (spec.ici_gbps * 1e9) if comm
                   else nbytes / (spec.hbm_gbps * 1e9))
            self.timeline.complete(name, "moe", t, dur, tid=1, step=step,
                                   bytes=nbytes, comm_bytes=comm)
            t += dur

    def record_compile(self, seconds: float) -> None:
        """Cumulative: a delayed-QAT switch compiles a second step mid-run."""
        self.compile_time_s = round((self.compile_time_s or 0.0) + float(seconds), 3)
        if self.goodput is not None:
            self.goodput.add("compile", seconds)
        if self.timeline is not None:
            self.timeline.complete("compile", "compile",
                                   self.timeline.now() - seconds, seconds)
        logger.info("jit compile + first execute: %.1fs (cumulative %.1fs)",
                    seconds, self.compile_time_s)

    def record_restore(self, seconds: float) -> None:
        """Checkpoint restore on resume (incl. the elastic re-partition path).
        Happens before this object exists, so the time is back-billed: the
        goodput wall origin rewinds by the same amount and the `restore`
        bucket absorbs it — fractions keep summing to 1 and the run ledger
        sees the restore cost instead of it vanishing into idle."""
        seconds = max(float(seconds), 0.0)
        if seconds <= 0.0:
            return
        if self.goodput is not None:
            self.goodput.bill_preceding("restore", seconds)
        if self.timeline is not None:
            self.timeline.complete("restore", "phase", 0.0, seconds)

    def heartbeat(self, step: int | None = None) -> None:
        if self.watchdog is not None:
            self.watchdog.heartbeat(step)
        if self.heartbeat_writer is not None:
            self.heartbeat_writer.beat(step)

    def on_step_start(self, step: int) -> None:
        if self.profiler is not None:
            self.profiler.on_step_start(step)
        if self.timeline is not None:
            self._step_t0 = self.timeline.now()

    def on_step_end(self, step: int, sync: Any = None) -> None:
        if self.profiler is not None:
            self.profiler.on_step_end(step, sync)
            trace = self.profiler.take_completed_trace()
            if trace is not None:
                self.analyze_trace(trace, step,
                                   steps_hint=self.profiler.last_window_steps)
        if self.dynamics is not None:
            self.dynamics.maybe_snapshot(step)
        if self.timeline is not None and self._step_t0 is not None:
            self.timeline.complete("step", "step", self._step_t0,
                                   self.timeline.now() - self._step_t0, step=step)
            self._step_t0 = None

    def dynamics_row(self, step: int, dyn_tree: Any) -> dict[str, float]:
        """One cadence sample: fold the device dynamics pytree into the
        tracker (EMAs, amax history, flight-recorder ring) and mirror the
        per-layer series onto Chrome counter tracks. Returns the flat
        ``dynamics/*`` keys the recipe merges into its log row."""
        if self.dynamics is None:
            return {}
        flat = self.dynamics.row(step, dyn_tree)
        if self.timeline is not None:
            self.timeline.counters_from_flat(flat)
        return flat

    def note_event(self, step: int, fields: dict[str, Any]) -> None:
        """Route structured events (stalls, resilience rollbacks/preemptions)
        onto the timeline; the metric fan-out already carries them as rows."""
        if self.timeline is None:
            return
        name = fields.get("event") or fields.get("resilience/event")
        if not name or name == "compile_costs":
            return
        args = {
            k.split("/")[-1]: v for k, v in fields.items()
            if isinstance(v, (int, float, str, bool)) and k.split("/")[-1]
            not in ("event", "step")
        }
        self.timeline.instant(str(name), cat="event", step=step, **args)

    # -------------------------------------------------------------- auto-trace
    def auto_trace(self, reason: str, step: int, **info: Any) -> bool:
        """Arm a throttled anomaly-triggered trace; True when actually armed.

        The throttle is a hard per-run budget (``auto_trace_max``): one
        anomaly explains itself with one trace, and a run degenerating every
        step must not fill the disk with xprof dumps. Requests while a trace
        is open or already armed coalesce (the profiler handles that); a
        manual SIGUSR1 is never budgeted — only anomaly triggers are.
        """
        if (self.profiler is None or not self.config.auto_trace
                or self._auto_traces >= self.config.auto_trace_max):
            return False
        if self.profiler.tracing or self.profiler.armed:
            return False
        self._auto_traces += 1
        self.profiler.request_trace()
        logger.warning("anomaly (%s) armed an auto-trace at step %d (%d/%d this run)",
                       reason, step, self._auto_traces, self.config.auto_trace_max)
        if self.timeline is not None:
            self.timeline.instant("auto_trace", cat="event", step=step,
                                  reason=reason, **info)
        if self._metric_sink is not None:
            self._metric_sink(step, event="auto_trace", auto_trace_reason=reason)
        return True

    def note_step_time(self, step: int, step_time_s: float | None) -> None:
        """The in-run regression detector: a step-time excursion beyond
        ``excursion_factor`` x the rolling median arms an auto-trace. Fed by
        the recipe at every log step with the same dt the row carries."""
        if step_time_s is None or step_time_s <= 0:
            return
        hist = self._dt_history
        if len(hist) >= self.config.excursion_min_samples:
            med = sorted(hist)[len(hist) // 2]
            if med > 0 and step_time_s > self.config.excursion_factor * med:
                self.auto_trace("step_time_excursion", step,
                                step_time_s=round(step_time_s, 4),
                                median_s=round(med, 4))
        hist.append(float(step_time_s))
        if len(hist) > 64:  # rolling window; excursions are vs recent history
            del hist[0]

    # ----------------------------------------------------------- trace analysis
    def analyze_trace(self, trace_dir: str, step: int = 0,
                      steps_hint: int | None = None) -> Any:
        """Machine-read one completed profiler trace (docs/observability.md
        "Measured trace attribution & signals").

        Runs automatically after every closed trace window — anomaly-triggered
        or on-demand — and on explicit call. Produces, guarded so analysis can
        never take the run down: an atomic ``out_dir/trace_report.json``, a
        ``trace_summary`` metric row carrying the ``measured_*`` /
        ``overlap_frac`` keys + the analytic-vs-measured verdict, measured
        spans on the Chrome-trace timeline, and a refreshed ``signals.json``.
        Returns the TraceReport (None when the trace is empty or analysis
        failed). Proc 0 only on multi-host — the trace is host-local and the
        artifacts belong to the coordinator.
        """
        import jax

        if jax.process_index() != 0:
            return None
        try:
            from automodel_tpu.observability import trace_analysis as ta

            report = ta.analyze_trace(trace_dir, hlo_text=self._hlo_text,
                                      mesh_axes=self.mesh_axes,
                                      steps_hint=steps_hint)
            if report is None:
                return None
            row = report.summary_row()
            row.update(ta.reconcile_with_roofline(report, self.roofline))
            self.trace_summary = row
            self._write_trace_report(report, row)
            if self._metric_sink is not None:
                self._metric_sink(max(step, 0), event="trace_summary", **row)
            self._emit_measured_spans(report, step)
            self.write_signals()
            return report
        except Exception:
            logger.warning("trace analysis failed for %s", trace_dir,
                           exc_info=True)
            return None

    def _write_trace_report(self, report: Any, row: dict[str, Any]) -> None:
        import json
        import tempfile

        doc = report.to_dict()
        doc["reconciliation"] = {
            k.split("/", 1)[-1]: v for k, v in row.items()
            if k.startswith("trace/") and not k.startswith("trace/scope/")
            and k not in ("trace/steps", "trace/events", "trace/window_s")
        }
        doc["roofline"] = self.roofline
        path = os.path.join(self.out_dir, "trace_report.json")
        os.makedirs(self.out_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.out_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise

    def _emit_measured_spans(self, report: Any, step: int) -> None:
        """Measured per-category spans next to the analytic MoE ones.

        Same rendering convention as :meth:`_emit_moe_spans` — sequential
        spans whose durations are the per-step measured times (tid=2,
        cat="measured") — but these ARE measurements, not floor estimates.
        """
        if self.timeline is None:
            return
        t = self.timeline.now()
        for name, dur in (("compute", report.compute_s),
                          ("comm", report.comm_s),
                          ("moe_a2a", report.moe_a2a_s),
                          ("host", report.host_s)):
            if dur <= 0:
                continue
            self.timeline.complete(name, "measured", t, dur, tid=2, step=step,
                                   overlap_frac=round(report.overlap_frac, 4))
            t += dur

    def write_signals(self) -> str | None:
        """Assemble + atomically write ``out_dir/signals.json`` (signals.py)
        from whatever sources exist right now; refreshed after every trace
        analysis and once more at close. Proc 0 only; never raises."""
        import jax

        if not self.config.enabled:
            return None
        try:
            if jax.process_index() != 0:
                return None
        except Exception:
            return None
        try:
            from automodel_tpu.observability import signals as sig

            doc = sig.build_signals(
                cell=self.cell_info,
                mesh_axes=self.mesh_axes,
                roofline=self.roofline,
                costs=self._costs,
                trace_summary=self.trace_summary,
                memory_plan=self.memory_plan,
                compile_summary=self.compile_summary(),
            )
            path = os.path.join(self.out_dir, "signals.json")
            sig.write_signals(path, doc)
            return path
        except Exception:
            logger.warning("signals.json write failed", exc_info=True)
            return None

    # ------------------------------------------------------------------- OOM
    def record_row(self, step: int, row: dict[str, Any]) -> None:
        """Feed the flight recorders' rings of recent metric rows (the OOM
        one and the loss-spike one share the "context for a future crash
        artifact" contract)."""
        if self.oom is not None:
            self.oom.record_row(step, row)
        if self.dynamics is not None:
            self.dynamics.recorder.record_row(step, row)

    def maybe_dump_oom(self, exc: BaseException, step: int | None = None) -> str | None:
        """Write ``oom_report.json`` when ``exc`` is an allocator exhaustion;
        returns the report path (the caller re-raises either way)."""
        if self.oom is None or not is_oom_error(exc):
            return None
        if self.oom._plan_row is None and self.memory_plan is not None:
            self.oom.set_plan_row(self.memory_plan.header_row())
        return self.oom.dump(exc, step=step)

    # ------------------------------------------------------------------ log rows
    def step_metrics(self) -> dict[str, Any]:
        """The per-log-row contribution: compile time, goodput fractions, HBM."""
        out: dict[str, Any] = {}
        if self.compile_time_s is not None:
            out["compile_time_s"] = self.compile_time_s
        if self.goodput is not None:
            out.update(self.goodput.snapshot())
        if self._memory:
            stats = device_memory_stats()
            out.update(stats)
            if stats and self.timeline is not None:
                # Perfetto counter track: HBM over the run's wall clock
                self.timeline.counter(
                    "hbm_gib",
                    in_use=stats.get("hbm_gib_in_use"),
                    peak=stats.get("hbm_gib_peak"),
                )
        return out

    def roofline_row(self, step_time_s: float | None) -> dict[str, Any]:
        """Per-row bound diagnosis + achieved fraction of the roofline."""
        if self.roofline is None:
            return {}
        data_wait_frac = 0.0
        if self.goodput is not None:
            data_wait_frac = self.goodput.snapshot().get("goodput/data_wait", 0.0)
        out: dict[str, Any] = {}
        bound = diagnose_bound(step_time_s, self.roofline, data_wait_frac)
        if bound is not None:
            out["bound"] = bound
        if step_time_s:
            # 6 digits: a test-sized model on a fast host can legitimately
            # achieve < 1e-4 of the analytic roofline — don't round it to 0
            out["roofline_frac"] = round(
                self.roofline["roofline_step_time_s"] / step_time_s, 6
            )
        return out

    def host_metrics(self, step_time_s: float | None,
                     moe_max_util: float | None = None,
                     grad_norm: float | None = None) -> dict[str, Any]:
        """Cross-host min/median/max + straggler flag for one log step.

        Collective on multi-host: every process must reach this call (the log
        step is deterministic across hosts); only proc 0 uses the result.
        MoE recipes pass their host-local max expert utilization — the wire
        format then grows the ``moe_max_util`` key (on every host, since the
        recipe config is identical pod-wide) and a ``hot_expert_host`` flag
        joins the straggler one. Dynamics runs pass the step's replicated
        ``grad_norm``, growing the wire identically; disagreement across
        hosts raises the ``divergent_host`` flag (replica desync).
        """
        if self.aggregator is None or not self.aggregator.active:
            return {}
        wanted = host_keys(
            moe=moe_max_util is not None or "moe_max_util" in self.aggregator.keys,
            dynamics=grad_norm is not None or "grad_norm" in self.aggregator.keys)
        if wanted != self.aggregator.keys:
            # first MoE/dynamics sample: widen the wire format once,
            # identically on every host (the flags derive from config shared
            # pod-wide, so every process rebuilds at the same log step)
            self.aggregator = CrossHostAggregator(
                self.aggregator.straggler_factor, keys=wanted,
                allgather_fn=self.aggregator._allgather,
                process_count=self.aggregator.process_count,
                oom_risk_gib=self.aggregator.oom_risk_gib,
                divergence_rtol=self.aggregator.divergence_rtol)
        sample: dict[str, Any] = {"step_time_s": step_time_s}
        if self.goodput is not None:
            sample["data_wait_s"] = round(self.goodput.totals().get("data_wait", 0.0), 4)
        if self._memory:
            stats = device_memory_stats()
            sample["hbm_gib_peak"] = stats.get("hbm_gib_peak")
            # allocator headroom when the platform reports it; the analytic
            # plan's otherwise — either way the pod's worst host is what the
            # oom_risk flag needs, and NaN travels where neither is known
            headroom = stats.get("hbm_headroom_gib")
            if headroom is None and self.memory_plan is not None:
                hb = self.memory_plan.headroom_bytes
                headroom = round(hb / 2**30, 4) if hb is not None else None
            sample["hbm_headroom_gib"] = headroom
        if moe_max_util is not None:
            sample["moe_max_util"] = float(moe_max_util)
        if grad_norm is not None:
            sample["grad_norm"] = float(grad_norm)
        out = self.aggregator.aggregate(sample)
        if self.timeline is not None and "straggler_host" in out:
            self.timeline.instant("straggler", cat="event",
                                  host=out["straggler_host"],
                                  ratio=out.get("straggler_ratio"))
        if self.timeline is not None and "hot_expert_host" in out:
            self.timeline.instant("hot_expert", cat="event",
                                  host=out["hot_expert_host"],
                                  ratio=out.get("hot_expert_ratio"))
        if self.timeline is not None and "divergent_host" in out:
            self.timeline.instant("divergent_replica", cat="event",
                                  host=out["divergent_host"],
                                  rel=out.get("divergence_rel"))
        return out
