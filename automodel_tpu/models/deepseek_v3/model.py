"""DeepSeek-V3 family — TPU-native (reference models/deepseek_v3/model.py:233,
layers.py:37 MLA).

Multi-head Latent Attention: queries and key/values factor through low-rank latents
(q_lora_rank / kv_lora_rank); the rope sub-dimension rides a separate single-head
stream concatenated onto every head. Interleaved (complex-pair) rope, YaRN mscale^2
softmax-scale correction. MoE layers use sigmoid noaux-tc routing with group-limited
selection, shared experts, and the loss-free balancing correction bias; the first
``first_k_dense_replace`` layers stay dense. Also serves DeepSeek-V2/V2-Lite
(q_lora_rank None -> direct q projection), Moonlight, and Kimi-K2 configs, which share
the architecture.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.moe_transformer import (
    init_moe_decoder_params,
    moe_decoder_forward,
    moe_decoder_logical_axes,
)
from automodel_tpu.models.common.transformer import _constrain
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope_interleaved, rope_frequencies

__all__ = ["DeepseekV3Config", "DeepseekV3ForCausalLM"]


@dataclasses.dataclass
class DeepseekV3Config:
    vocab_size: int = 129280
    hidden_size: int = 7168
    intermediate_size: int = 18432
    num_hidden_layers: int = 61
    num_attention_heads: int = 128
    q_lora_rank: int | None = 1536
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    first_k_dense_replace: int = 3
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: dict[str, Any] | None = None
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    moe: MoEConfig | None = None

    def __post_init__(self):
        if self.moe is None:
            raise ValueError("DeepseekV3Config requires a MoEConfig in .moe")

    # moe_decoder_forward duck-type surface (MLA has no sliding-window variants)
    sliding_window = None

    @property
    def sliding_flags(self) -> list[bool]:
        return [False] * self.num_hidden_layers

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def num_moe_layers(self) -> int:
        return self.num_hidden_layers - self.first_k_dense_replace

    @property
    def softmax_scale(self) -> float:
        """qk_head_dim^-0.5 with the YaRN mscale^2 correction
        (reference layers.py:103-117)."""
        scale = self.qk_head_dim**-0.5
        rs = self.rope_scaling
        if rs and all(k in rs for k in ("factor", "mscale", "original_max_position_embeddings")):
            mscale = float(rs["mscale"])
            if self.max_position_embeddings > rs["original_max_position_embeddings"]:
                factor = float(rs["factor"])
                if factor > 1:
                    mscale = 0.1 * mscale * math.log(factor) + 1.0
            scale = scale * mscale * mscale
        return scale

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "DeepseekV3Config":
        # V3 scores with sigmoid + noaux-tc correction bias; V2 softmaxes before a
        # greedy / group-limited-greedy top-k (HF scoring_func / topk_method fields,
        # absent on V3 configs where noaux_tc is the only mode).
        scoring = hf.get("scoring_func", "sigmoid")
        topk_method = hf.get("topk_method", "noaux_tc")
        moe = MoEConfig(
            n_routed_experts=hf["n_routed_experts"],
            n_activated_experts=hf["num_experts_per_tok"],
            dim=hf["hidden_size"],
            moe_inter_dim=hf["moe_intermediate_size"],
            n_shared_experts=hf.get("n_shared_experts", 0),
            n_expert_groups=max(hf.get("n_group") or 1, 1),
            n_limited_groups=max(hf.get("topk_group") or 1, 1),
            gate_bias_update_factor=0.001 if topk_method == "noaux_tc" else 0.0,
            score_func=scoring,
            softmax_before_topk=scoring == "softmax",
            route_scale=hf.get("routed_scaling_factor", 1.0),
            norm_topk_prob=hf.get("norm_topk_prob", True),
        )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            q_lora_rank=hf.get("q_lora_rank"),
            kv_lora_rank=hf["kv_lora_rank"],
            qk_nope_head_dim=hf["qk_nope_head_dim"],
            qk_rope_head_dim=hf["qk_rope_head_dim"],
            v_head_dim=hf["v_head_dim"],
            first_k_dense_replace=_first_k_dense(hf),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling=hf.get("rope_scaling"),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
        )


def _first_k_dense(hf: dict[str, Any]) -> int:
    """first_k_dense_replace, or a GLM4-MoE-Lite style mlp_layer_types prefix
    (["dense", "sparse", ...] — only dense-prefix patterns are supported)."""
    layer_types = hf.get("mlp_layer_types")
    if layer_types:
        flags = [t == "sparse" for t in layer_types]
        first = flags.index(True) if any(flags) else len(flags)
        if not all(flags[first:]):
            raise NotImplementedError("non-prefix dense/sparse interleavings are not supported")
        return first
    return hf.get("first_k_dense_replace", 0)


def _mla_shapes(cfg: DeepseekV3Config) -> dict[str, tuple[int, ...]]:
    d, n = cfg.hidden_size, cfg.num_attention_heads
    shapes: dict[str, tuple[int, ...]] = {"attn_norm": (d,), "mlp_norm": (d,)}
    if cfg.q_lora_rank is None:
        shapes["wq"] = (d, n, cfg.qk_head_dim)
    else:
        shapes |= {
            "wq_a": (d, cfg.q_lora_rank),
            "q_a_norm": (cfg.q_lora_rank,),
            "wq_b": (cfg.q_lora_rank, n, cfg.qk_head_dim),
        }
    shapes |= {
        "wkv_a": (d, cfg.kv_lora_rank + cfg.qk_rope_head_dim),
        "kv_a_norm": (cfg.kv_lora_rank,),
        "wkv_b": (cfg.kv_lora_rank, n, cfg.qk_nope_head_dim + cfg.v_head_dim),
        "wo": (n, cfg.v_head_dim, d),
    }
    return shapes


_MLA_AXES = {
    "attn_norm": ("norm",),
    "mlp_norm": ("norm",),
    "wq": ("embed", "heads", "head_dim"),
    "wq_a": ("embed", None),
    "q_a_norm": ("norm",),
    "wq_b": (None, "heads", "head_dim"),
    "wkv_a": ("embed", None),
    "kv_a_norm": ("norm",),
    "wkv_b": (None, "heads", "head_dim"),
    "wo": ("heads", "head_dim", "embed"),
}

def init_params(cfg: DeepseekV3Config, key: jax.Array, dtype=jnp.float32) -> dict:
    return init_moe_decoder_params(cfg, key, dtype, attn_shapes=_mla_shapes(cfg))


def logical_axes(cfg: DeepseekV3Config) -> dict:
    return moe_decoder_logical_axes(
        cfg, attn_axes=_MLA_AXES, attn_names=list(_mla_shapes(cfg))
    )


def _mla_block(cfg: DeepseekV3Config, backend: BackendConfig, lp: dict, x, positions,
               segment_ids, inv_freq, rules, bias_fn=None, bias_decode_fn=None,
               cache=None, cache_meta=None):
    """MLA attention (reference layers.py:122-198). ``bias_fn(lp, x, q_latent,
    positions, segment_ids) -> (B, S, S) additive logit bias`` is the V3.2 sparse
    indexer hook (reference deepseek_v32/layers.py:430-500).

    With ``cache=(k_cache, v_cache)`` (decode): the EXPANDED per-head k/v are
    written at ``cache_meta["write_idx"]`` and attention runs against the whole
    cache (k head-dim = nope+rope, v head-dim = v_head_dim — they differ; the
    XLA path handles the asymmetry). The latent-absorbed decode (caching only
    c_kv + k_pe) is a memory optimization left on the table — sampling
    correctness is what this path buys. Returns ``(out, (k_cache, v_cache))``."""
    q_latent = None
    if cfg.q_lora_rank is None:
        q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
    else:
        q_latent = rms_norm(jnp.einsum("bsd,dr->bsr", x, lp["wq_a"]), lp["q_a_norm"], cfg.rms_norm_eps)
        q = jnp.einsum("bsr,rnh->bsnh", q_latent, lp["wq_b"])
    q_nope, q_pe = jnp.split(q, [cfg.qk_nope_head_dim], axis=-1)

    kv = jnp.einsum("bsd,dr->bsr", x, lp["wkv_a"])
    c_kv, k_pe = jnp.split(kv, [cfg.kv_lora_rank], axis=-1)
    c_kv = rms_norm(c_kv, lp["kv_a_norm"], cfg.rms_norm_eps)

    q_pe = apply_rope_interleaved(q_pe, positions, inv_freq)
    k_pe = apply_rope_interleaved(k_pe[:, :, None, :], positions, inv_freq)

    q = jnp.concatenate([q_nope, q_pe], axis=-1)
    kv = jnp.einsum("bsr,rnh->bsnh", c_kv, lp["wkv_b"])
    k_nope, v = jnp.split(kv, [cfg.qk_nope_head_dim], axis=-1)
    k = jnp.concatenate(
        [k_nope, jnp.broadcast_to(k_pe, (*k_nope.shape[:-1], cfg.qk_rope_head_dim))], axis=-1
    )

    if cache is not None:
        from automodel_tpu.models.common.transformer import _cache_write

        extra_bias = None
        idx_out = ()
        if len(cache) == 3:
            # V3.2: third cache slot is the per-layer indexer-key cache; the
            # decode fn writes the chunk's keys and returns the (B,s,S_max)
            # sparse bias over the whole cache (deepseek_v32.make_indexer_decode_fn)
            if bias_decode_fn is None:
                raise NotImplementedError(
                    "3-slot MLA cache needs a bias_decode_fn (V3.2 indexer)"
                )
            extra_bias, idx_cache = bias_decode_fn(
                lp, x, q_latent, positions, cache[2], cache_meta
            )
            idx_out = (idx_cache,)
        elif bias_fn is not None:
            raise NotImplementedError(
                "V3.2 sparse-indexer decode needs the indexer-key cache slot "
                "(init_decode_cache) — got a 2-slot k/v cache"
            )
        k_cache = _cache_write(cache[0], k.astype(cache[0].dtype), cache_meta["write_idx"])
        v_cache = _cache_write(cache[1], v.astype(cache[1].dtype), cache_meta["write_idx"])
        out = dot_product_attention(
            q, k_cache.astype(q.dtype), v_cache.astype(q.dtype),
            causal=True,
            segment_ids_q=segment_ids,
            segment_ids_kv=cache_meta["valid"],
            positions_q=positions,
            positions_kv=cache_meta["positions"],
            softmax_scale=cfg.softmax_scale,
            extra_bias=extra_bias,
            backend="xla",  # q_len 1 / position-masked: the flash kernel doesn't apply
        )
        return jnp.einsum("bsnh,nhd->bsd", out, lp["wo"]), (k_cache, v_cache, *idx_out)

    from jax.ad_checkpoint import checkpoint_name

    q = checkpoint_name(_constrain(q, rules, ("batch", "act_attn_seq", "act_heads", None)), "attn_q")
    k = checkpoint_name(_constrain(k, rules, ("batch", "act_attn_seq", "act_heads", None)), "attn_k")
    v = checkpoint_name(v, "attn_v")
    extra_bias = None
    if bias_fn is not None:
        extra_bias = bias_fn(lp, x, q_latent, positions, segment_ids)
    mesh = rules.mesh if rules is not None else None
    use_ring = (
        backend.context_parallel == "ring"
        and mesh is not None
        and mesh.shape.get("cp", 1) > 1
        and extra_bias is None  # V3.2 sparse-indexer bias is (S_global, S_global)
    )
    if use_ring:
        # MLA ring CP (reference runs MLA through TE ring attention the same way,
        # moe/parallelizer.py:267-285): v_head_dim != qk dim is fine — the ring
        # accumulator follows v's dim
        from automodel_tpu.parallel.ring_attention import make_ring_attention

        ring = make_ring_attention(mesh, causal=True, softmax_scale=cfg.softmax_scale)
        out = ring(q, k, v, positions, segment_ids)
    else:
        out = dot_product_attention(
            q, k, v,
            causal=True,
            segment_ids_q=segment_ids,
            softmax_scale=cfg.softmax_scale,
            extra_bias=extra_bias,
            backend=backend.attention,
        )
    return jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])


def forward(
    cfg: DeepseekV3Config,
    backend: BackendConfig,
    params: dict,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    token_mask: jnp.ndarray | None = None,
    rules=None,
    return_hidden: bool = False,
    training: bool = True,
    cache=None,
):
    """moe_decoder_forward with the MLA attention hook; returns (out, stats)
    (or ``(logits, cache)`` on the decode path)."""
    return moe_decoder_forward(
        cfg, backend, params, input_ids,
        positions=positions, segment_ids=segment_ids, token_mask=token_mask,
        rules=rules, return_hidden=return_hidden, training=training,
        attention_fn=make_mla_attention_fn(cfg, backend),
        cache=cache,
    )


def mla_inv_freq(cfg: DeepseekV3Config) -> jnp.ndarray:
    """Rope frequencies for the MLA rope sub-dim; the reference applies the YaRN
    correction only when training beyond the original context
    (rope_utils.py:113-117). V3.2's indexer shares these frequencies."""
    rs = cfg.rope_scaling
    use_yarn = bool(
        rs
        and all(k in rs for k in ("factor", "beta_fast", "beta_slow", "original_max_position_embeddings"))
        and cfg.max_position_embeddings > rs["original_max_position_embeddings"]
    )
    return rope_frequencies(
        cfg.qk_rope_head_dim, cfg.rope_theta, dict(rs, rope_type="yarn") if use_yarn else None
    )


def make_mla_attention_fn(cfg: DeepseekV3Config, backend: BackendConfig, bias_fn=None,
                          bias_decode_fn=None):
    """MLA attention hook for moe_decoder_forward / the pp pipeline."""
    inv_freq = mla_inv_freq(cfg)

    def mla_attention(lp, x, positions, segment_ids, is_sliding, rules,
                      cache=None, cache_meta=None):
        del is_sliding
        with jax.named_scope("mla_attention"):
            return _mla_block(cfg, backend, lp, x, positions, segment_ids, inv_freq, rules,
                              bias_fn=bias_fn, bias_decode_fn=bias_decode_fn,
                              cache=cache, cache_meta=cache_meta)

    return mla_attention


class DeepseekV3ForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = DeepseekV3Config
    hf_architectures = ("DeepseekV3ForCausalLM", "DeepseekV2ForCausalLM")

    def __init__(self, config: DeepseekV3Config, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        return init_params(self.config, key, dtype)

    def logical_axes(self) -> dict:
        return logical_axes(self.config)

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def make_attention_fn(self):
        """Hook the pp pipeline uses to build the MLA block (parallel/pipeline.py)."""
        return make_mla_attention_fn(self.config, self.backend)

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None):
        return forward(
            self.config, self.backend, params, input_ids,
            positions=positions, segment_ids=segment_ids, token_mask=token_mask,
            rules=rules, return_hidden=return_hidden, training=training, cache=cache,
        )

    def generate(self, params, input_ids, **kw):
        """Sample with an expanded-head MLA KV cache (automodel_tpu.generation)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    def state_dict_adapter(self):
        from automodel_tpu.models.deepseek_v3.state_dict_adapter import DeepseekV3StateDictAdapter

        return DeepseekV3StateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = DeepseekV3Config.from_hf(config)
        return cls(config, backend)
