"""Benchmark recipe (reference BenchmarkingRecipeForNextTokenPrediction,
recipes/llm/benchmark.py:34): warmup + timed steps on mock data, reporting
tokens/sec(/chip), model TFLOPs/sec(/chip), and MFU vs the device's peak
(``_log_benchmark_summary`` parity, benchmark.py:342). Optional jax.profiler trace
windows replace the reference's nsys capture (cfg keys profile_start/profile_end).
"""

from __future__ import annotations

import json
import logging
import os
import time

import jax
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.utils.flops import flops_per_token, mfu

logger = logging.getLogger(__name__)

__all__ = ["BenchmarkingRecipeForNextTokenPrediction", "main"]


class BenchmarkingRecipeForNextTokenPrediction(TrainFinetuneRecipeForNextTokenPrediction):
    def run_benchmark(self) -> dict:
        cfg = self.cfg
        warmup = int(cfg.get("benchmark.warmup_steps", 3))
        steps = int(cfg.get("benchmark.timed_steps", 10))
        profile_start = cfg.get("benchmark.profile_start")
        profile_end = cfg.get("benchmark.profile_end")
        profile_dir = cfg.get("benchmark.profile_dir", "/tmp/jax_trace")

        from automodel_tpu.data.collate import stack_batches

        # pre-stage every batch on device BEFORE the timed window: per-step
        # device_put round-trips (especially through a remote-execution tunnel)
        # would otherwise bill host I/O to the step time being measured
        it = iter(self.step_scheduler)
        staged = [
            {
                k: jax.device_put(v, self.rules.sharding((None, "batch", None)))
                for k, v in stack_batches(next(it)).items()
            }
            for _ in range(warmup + steps)
        ]
        staged_it = iter(staged)
        get = lambda: next(staged_it)

        tracing = False
        with self.mesh:
            # sync via host transfer: block_until_ready does NOT block through the
            # axon remote-execution tunnel (bench.py learned this the hard way —
            # throughput numbers inflate ~1000x otherwise)
            m = None
            for _ in range(warmup):
                self.params, self.opt_state, m = self._train_step(self.params, self.opt_state, get())
            if m is not None:
                float(m["loss"])

            # time the whole window with ONE sync at each end: a per-step host
            # sync stalls the device pipeline every step (and costs a full
            # round-trip through a remote-execution tunnel)
            t0 = time.perf_counter()
            for i in range(steps):
                if profile_start is not None and i == int(profile_start):
                    jax.profiler.start_trace(profile_dir)
                    tracing = True
                self.params, self.opt_state, m = self._train_step(self.params, self.opt_state, get())
                if tracing and profile_end is not None and i >= int(profile_end):
                    float(m["loss"])  # flush before closing the trace
                    jax.profiler.stop_trace()
                    tracing = False
                    logger.info("profile written to %s", profile_dir)
            float(m["loss"])  # host transfer = real sync through the tunnel
            window = time.perf_counter() - t0
            step_times = [window / steps]
            if tracing:
                jax.profiler.stop_trace()
                logger.info("profile written to %s", profile_dir)

        n_micro = self.step_scheduler.grad_acc_steps
        tokens_per_step = n_micro * self.micro_batch_size * self.seq_len * jax.process_count()
        mean_t = float(np.mean(step_times))
        tps = tokens_per_step / mean_t
        n_chips = jax.device_count()
        fpt = flops_per_token(self.hf_config, self.seq_len)
        device_kind = jax.devices()[0].device_kind
        result = {
            "step_time_s": round(mean_t, 4),
            "tokens_per_sec": round(tps, 1),
            "tokens_per_sec_per_chip": round(tps / n_chips, 1),
            "model_tflops_per_sec_per_chip": round(tps * fpt / 1e12 / n_chips, 2),
            "mfu": round(mfu(tps, fpt, device_kind, n_chips), 4),
            "device_kind": device_kind,
            "n_chips": n_chips,
            "loss": float(m["loss"]),
        }
        logger.info("benchmark: %s", result)
        # setup() resolved (or generated) the run dir once — benchmark.json
        # must land next to training.jsonl, not in a second timestamped dir
        out_dir = getattr(self, "output_dir", None) or cfg.get("output_dir", ".")
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "benchmark.json"), "w") as f:
            json.dump(result, f, indent=2)
        return result


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = BenchmarkingRecipeForNextTokenPrediction(cfg)
    recipe.setup()
    result = recipe.run_benchmark()
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
