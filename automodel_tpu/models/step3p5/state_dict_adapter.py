"""Step-3.5 HF mapping (reference models/step3p5/state_dict_adapter.py).

HF ships experts already grouped: ``moe.gate_proj/up_proj`` (E, I, D) and
``moe.down_proj`` (E, D, I); router ``moe.gate.weight`` (E, D) with optional
``moe.router_bias``; shared expert under ``share_expert.*``. Four per-type streams
pin explicit ``layer_indices``.
"""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import _o_in, _o_out, _proj_in, _proj_out, _t

__all__ = ["Step3p5StateDictAdapter"]


def _grouped_gate_up_in(gate: np.ndarray, up: np.ndarray) -> np.ndarray:
    """HF (E, I, D) x2 -> ours (E, D, 2I) with [gate | up] concat."""
    return np.concatenate([gate.transpose(0, 2, 1), up.transpose(0, 2, 1)], axis=-1)


def _grouped_gate_up_out(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    inter = w.shape[-1] // 2
    return (
        np.ascontiguousarray(w[..., :inter].transpose(0, 2, 1)),
        np.ascontiguousarray(w[..., inter:].transpose(0, 2, 1)),
    )


def _grouped_t(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.transpose(0, 2, 1))


class Step3p5StateDictAdapter(MappingAdapter):
    def __init__(self, cfg):
        pre = "model.layers.{i}"
        dh = cfg.head_dim
        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            Entry("model.norm.weight", "final_norm"),
        ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))

        for skey, idx in cfg.stream_indices().items():
            n, kv = cfg.heads(idx[0])
            entries += [
                Entry(f"{pre}.input_layernorm.weight", f"{skey}.attn_norm", layer_indices=idx),
                Entry(f"{pre}.post_attention_layernorm.weight", f"{skey}.mlp_norm", layer_indices=idx),
                Entry(f"{pre}.self_attn.q_proj.weight", f"{skey}.wq", _proj_in(n, dh), _proj_out(n, dh), layer_indices=idx),
                Entry(f"{pre}.self_attn.k_proj.weight", f"{skey}.wk", _proj_in(kv, dh), _proj_out(kv, dh), layer_indices=idx),
                Entry(f"{pre}.self_attn.v_proj.weight", f"{skey}.wv", _proj_in(kv, dh), _proj_out(kv, dh), layer_indices=idx),
                Entry(f"{pre}.self_attn.o_proj.weight", f"{skey}.wo", _o_in(n, dh), _o_out(n, dh), layer_indices=idx),
                Entry(f"{pre}.self_attn.q_norm.weight", f"{skey}.q_norm", layer_indices=idx),
                Entry(f"{pre}.self_attn.k_norm.weight", f"{skey}.k_norm", layer_indices=idx),
            ]
            if cfg.use_head_wise_attn_gate:
                entries.append(
                    Entry(f"{pre}.self_attn.g_proj.weight", f"{skey}.wg", _t, _t, layer_indices=idx)
                )
            if skey.endswith("_mlp"):
                entries += [
                    Entry(f"{pre}.mlp.gate_proj.weight", f"{skey}.w_gate", _t, _t, layer_indices=idx),
                    Entry(f"{pre}.mlp.up_proj.weight", f"{skey}.w_up", _t, _t, layer_indices=idx),
                    Entry(f"{pre}.mlp.down_proj.weight", f"{skey}.w_down", _t, _t, layer_indices=idx),
                ]
            else:
                entries += [
                    Entry(f"{pre}.share_expert.gate_proj.weight", f"{skey}.sh_gate", _t, _t, layer_indices=idx),
                    Entry(f"{pre}.share_expert.up_proj.weight", f"{skey}.sh_up", _t, _t, layer_indices=idx),
                    Entry(f"{pre}.share_expert.down_proj.weight", f"{skey}.sh_down", _t, _t, layer_indices=idx),
                    Entry(f"{pre}.moe.gate.weight", f"{skey}.moe.gate.weight", layer_indices=idx),
                    Entry(
                        (f"{pre}.moe.gate_proj.weight", f"{pre}.moe.up_proj.weight"),
                        f"{skey}.moe.experts.gate_up_proj",
                        _grouped_gate_up_in, _grouped_gate_up_out, layer_indices=idx,
                    ),
                    Entry(f"{pre}.moe.down_proj.weight", f"{skey}.moe.experts.down_proj",
                          _grouped_t, _grouped_t, layer_indices=idx),
                ]
                if cfg.moe.router_bias:
                    entries.append(
                        Entry(f"{pre}.moe.router_bias", f"{skey}.moe.gate.bias", layer_indices=idx)
                    )

        super().__init__(entries, cfg.num_hidden_layers)
