"""Gated wandb / MLflow experiment tracking (reference loggers/wandb_utils.py,
mlflow_utils.py). Both are optional dependencies: absent packages degrade to a
warning, never an import error — only rank 0 reports.

YAML:

.. code-block:: yaml

    wandb: {project: my-proj, name: run-1, mode: offline}
    mlflow: {tracking_uri: file:/tmp/mlruns, experiment_name: exp, run_name: r1}
"""

from __future__ import annotations

import logging
from typing import Any

import jax

logger = logging.getLogger(__name__)

__all__ = ["WandbLogger", "MLflowLogger", "build_experiment_loggers"]


class WandbLogger:
    def __init__(self, **init_kwargs: Any):
        self._run = None
        if jax.process_index() != 0:
            return
        try:
            import wandb
        except ImportError:
            logger.warning("wandb section configured but wandb is not installed; skipping")
            return
        self._run = wandb.init(**init_kwargs)

    def log(self, step: int, **metrics: Any) -> None:
        if self._run is not None:
            self._run.log(metrics, step=step)

    def close(self) -> None:
        if self._run is not None:
            self._run.finish()
            self._run = None


class MLflowLogger:
    def __init__(self, tracking_uri: str | None = None, experiment_name: str | None = None,
                 run_name: str | None = None, **_ignored: Any):
        self._mlflow = None
        if jax.process_index() != 0:
            return
        try:
            import mlflow
        except ImportError:
            logger.warning("mlflow section configured but mlflow is not installed; skipping")
            return
        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        if experiment_name:
            mlflow.set_experiment(experiment_name)
        mlflow.start_run(run_name=run_name)
        self._mlflow = mlflow

    def log(self, step: int, **metrics: Any) -> None:
        if self._mlflow is not None:
            numeric = {k: float(v) for k, v in metrics.items()
                       if isinstance(v, (int, float)) and k != "step"}
            self._mlflow.log_metrics(numeric, step=step)

    def close(self) -> None:
        if self._mlflow is not None:
            self._mlflow.end_run()
            self._mlflow = None


def build_experiment_loggers(cfg) -> list:
    """Recipe hook: one tracker per configured section (train_ft.py wandb/mlflow)."""
    out = []
    wandb_cfg = cfg.get("wandb")
    if wandb_cfg is not None:
        out.append(WandbLogger(**wandb_cfg.to_dict()))
    mlflow_cfg = cfg.get("mlflow")
    if mlflow_cfg is not None:
        out.append(MLflowLogger(**mlflow_cfg.to_dict()))
    return out
