import textwrap

import pytest

from automodel_tpu.cli.app import RECIPES, _resolve, main as cli_main
from automodel_tpu.launcher.slurm import SlurmConfig, render_script
from automodel_tpu.utils.flops import PEAK_TFLOPS, flops_per_token, mfu


class TestCli:
    def test_resolve_known(self):
        fn = _resolve("finetune", "llm")
        assert callable(fn)

    def test_resolve_unknown_exits(self):
        with pytest.raises(SystemExit):
            _resolve("bogus", "llm")

    def test_help_exits_zero(self):
        with pytest.raises(SystemExit) as e:
            cli_main(["--help"])
        assert e.value.code == 0

    def test_cli_runs_recipe(self, tmp_path, cpu_devices):
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text(textwrap.dedent(f"""
            seed: 1
            output_dir: {tmp_path}/out
            model:
              config:
                architectures: [LlamaForCausalLM]
                vocab_size: 64
                hidden_size: 32
                intermediate_size: 64
                num_hidden_layers: 2
                num_attention_heads: 4
                num_key_value_heads: 2
                max_position_embeddings: 64
            distributed: {{dp_shard: 8}}
            backend: {{dtype: float32}}
            dataset:
              _target_: automodel_tpu.data.llm.mock.MockSFTDataset
              vocab_size: 64
              seq_len: 16
              num_samples: 64
            micro_batch_size: 8
            seq_len: 16
            step_scheduler: {{grad_acc_steps: 1, max_steps: 2, handle_sigterm: false}}
            optimizer: {{lr: 1.0e-3}}
            checkpoint: {{enabled: false}}
        """))
        cli_main(["finetune", "llm", "-c", str(cfg)])
        assert (tmp_path / "out" / "training.jsonl").exists()


class TestSlurm:
    def test_render_script(self):
        s = render_script(
            SlurmConfig(job_name="j", nodes=4, account="acct", container_image="img"),
            "finetune", "llm", "/x/cfg.yaml",
        )
        assert "#SBATCH --nodes=4" in s
        assert "NUM_PROCESSES=$SLURM_NNODES" in s
        assert "--container-image=img" in s
        assert "finetune llm -c /x/cfg.yaml" in s


class TestFlops:
    def test_dense_flops_sane(self):
        cfg = {
            "hidden_size": 4096, "num_hidden_layers": 32, "vocab_size": 128256,
            "num_attention_heads": 32, "num_key_value_heads": 8,
            "intermediate_size": 14336,
        }
        f = flops_per_token(cfg, 4096)
        # llama-3-8B: ~6*8e9 = 4.8e10 + attention; must be within a factor
        assert 4.5e10 < f < 8e10

    def test_moe_flops_counts_active_only(self):
        base = {
            "hidden_size": 2048, "num_hidden_layers": 4, "vocab_size": 1000,
            "num_attention_heads": 16, "num_key_value_heads": 16,
            "intermediate_size": 8192,
        }
        moe = dict(base, num_experts=64, num_experts_per_tok=4, moe_intermediate_size=1024)
        assert flops_per_token(moe, 128) < flops_per_token(base, 128)

    def test_mfu(self):
        assert mfu(1000, 1e12 / 1000, "TPU v5 lite", 1) == pytest.approx(1000 / 197000, rel=1e-3)
        assert mfu(1000, 1e9, "unknown chip") == 0.0
