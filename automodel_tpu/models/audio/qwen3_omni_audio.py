"""Qwen3-Omni audio encoder — TPU-native (HF Qwen3OmniMoeAudioEncoder,
transformers modeling_qwen3_omni_moe.py:636; the reference keeps HF's towers and
swaps only the thinker text stack, reference models/qwen3_omni_moe/model.py).

Whisper-style mel encoder: per-audio mel streams chunk into ``2*n_window``-frame
windows, three stride-2 Conv2d+GELU stages downsample 8x in time, a linear folds
(channels x mel/8) per frame, sinusoid positions add per within-chunk frame, then
pre-norm attention layers run with *windowed* bidirectional attention
(``n_window_infer`` frames per attention block) and a GELU head projects to the
text width.

TPU-first contract: chunk padding, the valid-frame gather and window segment ids
are host-side numpy (``prepare_audio_inputs``); the device function sees only
static-shaped arrays — the convs run on the padded (num_chunks, mel, chunk) block
and validity is a single gather.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import layer_norm

__all__ = ["Qwen3OmniAudioConfig", "init_audio_params", "audio_logical_axes",
           "audio_forward", "prepare_audio_inputs", "audio_output_lengths"]


@dataclasses.dataclass
class Qwen3OmniAudioConfig:
    num_mel_bins: int = 128
    d_model: int = 1280
    encoder_layers: int = 32
    encoder_attention_heads: int = 20
    encoder_ffn_dim: int = 5120
    downsample_hidden_size: int = 480
    output_dim: int = 2048
    n_window: int = 50
    n_window_infer: int = 400
    max_source_positions: int = 1500
    activation_function: str = "gelu"
    initializer_range: float = 0.02

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Qwen3OmniAudioConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in hf.items() if k in keys})

    @property
    def head_dim(self) -> int:
        return self.d_model // self.encoder_attention_heads

    @property
    def chunk_len(self) -> int:
        return 2 * self.n_window

    @property
    def conv_freq_out(self) -> int:
        f = self.num_mel_bins
        for _ in range(3):
            f = (f + 1) // 2
        return f


def _conv_out_len(n):
    """Elementwise 3x (k=3, s=2, p=1) conv output length: ceil-halving applied 3x."""
    for _ in range(3):
        n = (n + 1) // 2
    return n


def audio_output_lengths(input_lengths: np.ndarray, chunk_len: int = 100) -> np.ndarray:
    """Per-audio encoder output frame count: full chunks contribute
    conv_out(chunk_len) frames, the tail contributes conv_out(tail). Equals HF's
    _get_feat_extract_output_lengths (modeling_qwen3_omni_moe.py:79-87) for the
    shipped 100-frame chunking; computed from the actual conv math here so it stays
    consistent with prepare_audio_inputs for any chunk_len."""
    input_lengths = np.asarray(input_lengths)
    tail = input_lengths % chunk_len
    tail_out = np.where(tail > 0, _conv_out_len(tail), 0)
    return (input_lengths // chunk_len) * _conv_out_len(chunk_len) + tail_out


def init_audio_params(cfg: Qwen3OmniAudioConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    std = cfg.initializer_range
    d, f, L = cfg.d_model, cfg.encoder_ffn_dim, cfg.encoder_layers
    ch = cfg.downsample_hidden_size
    keys = iter(jax.random.split(key, 12))

    def w(shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * std).astype(dtype)

    ks = jax.random.split(next(keys), 6)
    mk = lambda kk, shape: (jax.random.normal(kk, (L, *shape), jnp.float32) * std).astype(dtype)
    layers = {
        "attn_ln_w": jnp.ones((L, d), dtype), "b_attn_ln": jnp.zeros((L, d), dtype),
        "wq": mk(ks[0], (d, d)), "b_q": jnp.zeros((L, d), dtype),
        "wk": mk(ks[1], (d, d)), "b_k": jnp.zeros((L, d), dtype),
        "wv": mk(ks[2], (d, d)), "b_v": jnp.zeros((L, d), dtype),
        "wo": mk(ks[3], (d, d)), "b_o": jnp.zeros((L, d), dtype),
        "final_ln_w": jnp.ones((L, d), dtype), "b_final_ln": jnp.zeros((L, d), dtype),
        "fc1": mk(ks[4], (d, f)), "b_fc1": jnp.zeros((L, f), dtype),
        "fc2": mk(ks[5], (f, d)), "b_fc2": jnp.zeros((L, d), dtype),
    }
    return {
        # conv weights kept in HF Conv2d layout (out, in, 3, 3)
        "conv1_w": w((ch, 1, 3, 3)), "b_conv1": jnp.zeros((ch,), dtype),
        "conv2_w": w((ch, ch, 3, 3)), "b_conv2": jnp.zeros((ch,), dtype),
        "conv3_w": w((ch, ch, 3, 3)), "b_conv3": jnp.zeros((ch,), dtype),
        "conv_out_w": w((ch * cfg.conv_freq_out, d)),
        "layers": layers,
        "post_ln_w": jnp.ones((d,), dtype), "b_post_ln": jnp.zeros((d,), dtype),
        "proj1_w": w((d, d)), "b_proj1": jnp.zeros((d,), dtype),
        "proj2_w": w((d, cfg.output_dim)), "b_proj2": jnp.zeros((cfg.output_dim,), dtype),
    }


def audio_logical_axes(cfg: Qwen3OmniAudioConfig) -> dict:
    return {
        "conv1_w": (None, None, None, None), "b_conv1": ("norm",),
        "conv2_w": (None, None, None, None), "b_conv2": ("norm",),
        "conv3_w": (None, None, None, None), "b_conv3": ("norm",),
        "conv_out_w": (None, "embed"),
        "layers": {
            "attn_ln_w": ("layers", "norm"), "b_attn_ln": ("layers", "norm"),
            "wq": ("layers", "embed", "heads"), "b_q": ("layers", "heads"),
            "wk": ("layers", "embed", "heads"), "b_k": ("layers", "heads"),
            "wv": ("layers", "embed", "heads"), "b_v": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "b_o": ("layers", "norm"),
            "final_ln_w": ("layers", "norm"), "b_final_ln": ("layers", "norm"),
            "fc1": ("layers", "embed", "mlp"), "b_fc1": ("layers", "mlp"),
            "fc2": ("layers", "mlp", "embed"), "b_fc2": ("layers", "norm"),
        },
        "post_ln_w": ("norm",), "b_post_ln": ("norm",),
        "proj1_w": ("embed", "mlp"), "b_proj1": ("norm",),
        "proj2_w": ("embed", "mlp"), "b_proj2": ("norm",),
    }


def prepare_audio_inputs(
    features: "list[np.ndarray]",  # per-audio mel (num_mel_bins, T)
    cfg: Qwen3OmniAudioConfig,
) -> dict[str, np.ndarray]:
    """Chunk + pad each audio's mel frames into (num_chunks, mel, chunk_len) and
    precompute the valid-frame gather and windowed-attention segment ids (HF
    cu_seqlens construction, modeling_qwen3_omni_moe.py:714-759)."""
    C = cfg.chunk_len
    if cfg.n_window_infer % C:
        raise ValueError(
            f"n_window_infer ({cfg.n_window_infer}) must be a multiple of the "
            f"chunk length 2*n_window ({C})"
        )
    chunks, gather, seg = [], [], []
    chunk_base = 0
    seg_id = 0
    t_out = _conv_out_len(C)
    win_frames = t_out * (cfg.n_window_infer // C)
    for mel in features:
        T = mel.shape[1]
        n_chunks = math.ceil(T / C)
        frames_this = 0
        for ci in range(n_chunks):
            part = mel[:, ci * C : (ci + 1) * C]
            valid = part.shape[1]
            if valid < C:
                part = np.pad(part, ((0, 0), (0, C - valid)))
            chunks.append(part)
            v_out = _conv_out_len(valid)
            gather.append((chunk_base + ci) * t_out + np.arange(v_out))
            frames_this += v_out
        chunk_base += n_chunks
        # windowed attention blocks of win_frames over this audio's frames
        n_full, rem = divmod(frames_this, win_frames)
        for _ in range(n_full):
            seg.append(np.full(win_frames, seg_id, np.int32))
            seg_id += 1
        if rem:
            seg.append(np.full(rem, seg_id, np.int32))
            seg_id += 1
    return {
        "chunks": np.stack(chunks).astype(np.float32),  # (N, mel, C)
        "gather_idx": np.concatenate(gather).astype(np.int32),  # (Ta,)
        "segment_ids": np.concatenate(seg),  # (Ta,)
    }


def audio_forward(
    cfg: Qwen3OmniAudioConfig,
    backend: BackendConfig,
    params: dict,
    chunks: jnp.ndarray,  # (N, mel, chunk_len)
    gather_idx: jnp.ndarray,  # (Ta,)
    segment_ids: jnp.ndarray,  # (Ta,)
) -> jnp.ndarray:
    """Returns encoded audio tokens (Ta, output_dim)."""
    dtype = backend.jnp_dtype
    d, H, dh = cfg.d_model, cfg.encoder_attention_heads, cfg.head_dim
    p = jax.tree.map(lambda a: a.astype(dtype) if a.dtype != jnp.int32 else a, params)

    x = chunks.astype(dtype)[:, None]  # (N, 1, mel, C)
    for i in (1, 2, 3):
        x = jax.lax.conv_general_dilated(
            x, p[f"conv{i}_w"], window_strides=(2, 2), padding=[(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        ) + p[f"b_conv{i}"][None, :, None, None]
        x = jax.nn.gelu(x, approximate=False)
    N, ch, fr, t_out = x.shape
    x = x.transpose(0, 3, 1, 2).reshape(N, t_out, ch * fr) @ p["conv_out_w"]

    # sinusoid positions per within-chunk frame (HF SinusoidsPositionEmbedding)
    half = d // 2
    inv = jnp.exp(-math.log(10000) / (half - 1) * jnp.arange(half, dtype=jnp.float32))
    ang = jnp.arange(t_out, dtype=jnp.float32)[:, None] * inv[None, :]
    pos = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1).astype(dtype)
    x = x + pos[None]

    h = x.reshape(N * t_out, d)[gather_idx]
    seg = segment_ids[None]

    def layer_fn(hh, lp):
        x_ = layer_norm(hh, lp["attn_ln_w"], lp["b_attn_ln"])
        q = (x_ @ lp["wq"] + lp["b_q"]).reshape(-1, H, dh)
        k = (x_ @ lp["wk"] + lp["b_k"]).reshape(-1, H, dh)
        v = (x_ @ lp["wv"] + lp["b_v"]).reshape(-1, H, dh)
        attn = dot_product_attention(
            q[None], k[None], v[None], causal=False,
            segment_ids_q=seg, segment_ids_kv=seg, backend=backend.attention,
        )[0].reshape(-1, d)
        hh = hh + (attn @ lp["wo"] + lp["b_o"])
        x_ = layer_norm(hh, lp["final_ln_w"], lp["b_final_ln"])
        hh = hh + (jax.nn.gelu(x_ @ lp["fc1"] + lp["b_fc1"], approximate=False) @ lp["fc2"] + lp["b_fc2"])
        return hh, None

    h, _ = jax.lax.scan(backend.layer_remat(layer_fn), h, p["layers"])
    h = layer_norm(h, p["post_ln_w"], p["b_post_ln"])
    h = jax.nn.gelu(h @ p["proj1_w"] + p["b_proj1"], approximate=False)
    return h @ p["proj2_w"] + p["b_proj2"]
