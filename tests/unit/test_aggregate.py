"""Cross-host metric aggregation (observability/aggregate.py): 8 simulated
hosts via an injected allgather (the suite runs one process; the real path
uses multihost_utils.process_allgather), straggler flagging, NaN handling."""

import math

import pytest

from automodel_tpu.observability.aggregate import HOST_KEYS, CrossHostAggregator


def _fake_allgather(rows):
    """An allgather_fn returning pre-baked per-host rows (ignores the local vec)."""
    return lambda vec: [list(r) for r in rows]


def _rows(n=8, step=0.5, wait=0.01, hbm=8.0, headroom=8.0):
    return [[step, wait, hbm, headroom] for _ in range(n)]


class TestAggregation:
    def test_min_median_max_over_8_hosts(self):
        rows = _rows()
        for i, r in enumerate(rows):
            r[0] = 0.5 + i * 0.01  # 0.5 .. 0.57
        agg = CrossHostAggregator(allgather_fn=_fake_allgather(rows), process_count=8)
        out = agg.aggregate({"step_time_s": 0.5, "data_wait_s": 0.01, "hbm_gib_peak": 8.0})
        assert out["host/n"] == 8
        assert out["host/step_time_s_min"] == 0.5
        assert out["host/step_time_s_max"] == 0.57
        assert 0.5 < out["host/step_time_s_median"] < 0.57
        assert out["host/hbm_gib_peak_max"] == 8.0
        assert "straggler_host" not in out  # 14% spread is not a straggler

    def test_straggler_flagged_with_host_index_and_ratio(self):
        rows = _rows()
        rows[5][0] = 1.7  # host 5 at 3.4x the median
        agg = CrossHostAggregator(straggler_factor=2.0,
                                  allgather_fn=_fake_allgather(rows), process_count=8)
        out = agg.aggregate({"step_time_s": 0.5, "data_wait_s": 0.01, "hbm_gib_peak": 8.0})
        assert out["straggler_host"] == 5
        assert out["straggler_ratio"] == pytest.approx(1.7 / 0.5, abs=0.01)

    def test_straggler_threshold_respects_factor(self):
        rows = _rows()
        rows[2][0] = 0.9  # 1.8x median
        strict = CrossHostAggregator(straggler_factor=1.5,
                                     allgather_fn=_fake_allgather(rows), process_count=8)
        loose = CrossHostAggregator(straggler_factor=2.0,
                                    allgather_fn=_fake_allgather(rows), process_count=8)
        sample = {"step_time_s": 0.5, "data_wait_s": 0.01, "hbm_gib_peak": 8.0}
        assert strict.aggregate(sample)["straggler_host"] == 2
        assert "straggler_host" not in loose.aggregate(sample)

    def test_missing_values_travel_as_nan_and_are_excluded(self):
        rows = _rows()
        rows[3][2] = math.nan  # host 3 has no HBM telemetry (e.g. CPU)
        agg = CrossHostAggregator(allgather_fn=_fake_allgather(rows), process_count=8)
        out = agg.aggregate({"step_time_s": 0.5, "data_wait_s": 0.01, "hbm_gib_peak": None})
        assert out["host/hbm_gib_peak_max"] == 8.0  # NaN row excluded, not propagated
        assert out["host/step_time_s_median"] == 0.5

    def test_all_nan_key_omitted(self):
        rows = [[0.5, 0.01, math.nan, math.nan] for _ in range(8)]
        agg = CrossHostAggregator(allgather_fn=_fake_allgather(rows), process_count=8)
        out = agg.aggregate({"step_time_s": 0.5, "data_wait_s": 0.01, "hbm_gib_peak": None})
        assert "host/hbm_gib_peak_max" not in out
        assert out["host/n"] == 8


class TestActivation:
    def test_single_process_is_inactive(self):
        agg = CrossHostAggregator(allgather_fn=_fake_allgather(_rows(1)), process_count=1)
        assert not agg.active
        assert agg.aggregate({"step_time_s": 0.5}) == {}

    def test_bad_factor_raises(self):
        with pytest.raises(ValueError, match="straggler_factor"):
            CrossHostAggregator(straggler_factor=1.0)

    def test_allgather_failure_degrades_to_empty(self):
        def boom(vec):
            raise RuntimeError("collective failed")

        agg = CrossHostAggregator(allgather_fn=boom, process_count=8)
        assert agg.aggregate({"step_time_s": 0.5}) == {}

    def test_default_keys_order_matches_sample_packing(self):
        # the wire format is positional: a key-order change is a protocol
        # break (headroom joined the wire for the oom_risk flag — appended,
        # never reordered, so mixed-version pods fail loudly on length)
        assert HOST_KEYS == ("step_time_s", "data_wait_s", "hbm_gib_peak",
                             "hbm_headroom_gib")
