"""Hard-negative mining (reference recipes/biencoder/mine_hard_negatives.py).

Embeds the corpus and the queries with a (possibly freshly initialized or trained)
biencoder tower, then for each query keeps the top-scoring non-positive passages as
hard negatives, written back as retrieval-jsonl for the training recipe.

YAML: the biencoder training config plus

.. code-block:: yaml

    mine:
      input: /data/pairs.jsonl        # rows {"query", "pos_doc"}
      output: /data/mined.jsonl
      num_negatives: 4
      margin: 0.95   # skip candidates scoring > margin * positive (likely dupes)
"""

from __future__ import annotations

import json
import logging

import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.data.llm.column_mapped import _load_rows
from automodel_tpu.data.llm.retrieval import write_retrieval_jsonl
from automodel_tpu.recipes.biencoder.train_biencoder import TrainBiencoderRecipe

logger = logging.getLogger(__name__)

__all__ = ["mine_hard_negatives", "main"]


def mine_hard_negatives(recipe: TrainBiencoderRecipe, rows: list[dict],
                        num_negatives: int = 4, margin: float = 0.95,
                        margin_type: str = "perc", query_chunk: int = 1024,
                        query_prefix: str = "", passage_prefix: str = "") -> list[dict]:
    """rows: {"query", "pos_doc"} -> rows + {"neg_doc": [...]} via dense retrieval.

    Queries are processed in chunks so memory stays O(chunk x corpus), never the
    full (Q, N) matrix. The near-duplicate filter (reference hard_neg_margin /
    hard_neg_margin_type) drops candidates scoring above the cut:

    - ``margin_type="perc"``: ``margin * pos_score`` — only meaningful for
      positive scores, so with an untrained tower (cosines can be <= 0) it
      degrades to "above the positive".
    - ``margin_type="abs"``: ``pos_score - margin`` — sign-safe absolute gap.

    ``query_prefix``/``passage_prefix`` prepend E5-style instruction prefixes
    before encoding (reference MINING_DEFAULTS query_prefix/passage_prefix).
    """
    if margin_type not in ("perc", "abs"):
        raise ValueError(f"margin_type must be perc|abs, got {margin_type!r}")
    corpus = sorted({str(r["pos_doc"]) for r in rows})
    doc_row = {d: i for i, d in enumerate(corpus)}
    doc_emb = recipe.encode([passage_prefix + d for d in corpus])  # (N, D) normalized

    mined = []
    for lo in range(0, len(rows), query_chunk):
        chunk = rows[lo:lo + query_chunk]
        q_emb = recipe.encode([query_prefix + str(r["query"]) for r in chunk])
        scores = q_emb @ doc_emb.T  # (chunk, N)
        for i, r in enumerate(chunk):
            pos_idx = doc_row[str(r["pos_doc"])]
            s = scores[i].copy()
            pos_score = s[pos_idx]
            s[pos_idx] = -np.inf
            if margin_type == "abs":
                cut = pos_score - margin
            else:
                cut = margin * pos_score if pos_score > 0 else pos_score
            s[s > cut] = -np.inf
            top = np.argsort(-s)[:num_negatives]
            negs = [corpus[j] for j in top if np.isfinite(s[j])]
            mined.append({**r, "neg_doc": negs})
    return mined


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    mine_cfg = cfg.get("mine")
    if mine_cfg is None:
        raise ValueError("config needs a mine: section (input/output)")
    recipe = TrainBiencoderRecipe(cfg)
    recipe.setup()
    rows = _load_rows(mine_cfg["input"], None)
    mined = mine_hard_negatives(
        recipe, rows,
        num_negatives=int(mine_cfg.get("num_negatives", 4)),
        margin=float(mine_cfg.get("margin", 0.95)),
        margin_type=str(mine_cfg.get("margin_type", "perc")),
        query_prefix=str(mine_cfg.get("query_prefix", "")),
        passage_prefix=str(mine_cfg.get("passage_prefix", "")),
    )
    write_retrieval_jsonl(mined, mine_cfg["output"])
    logger.info("mined %d rows -> %s", len(mined), mine_cfg["output"])
    return mined


if __name__ == "__main__":
    main()
