from automodel_tpu.data.vlm.collate import preprocess_images, vlm_collate

__all__ = ["preprocess_images", "vlm_collate"]
