"""Perf-regression gate (observability/regression.py + tools/bench_gate.py):
run-artifact parsing for all three formats, direction-aware comparison,
tolerance overrides, and CLI exit codes."""

import json

import pytest

from automodel_tpu.observability.regression import (
    DEFAULT_TOLERANCES,
    compare,
    load_baseline,
    load_run_metrics,
    main,
    summarize_rows,
    write_baseline,
)


def _training_rows(tps=1000.0, n=6):
    rows = [
        {"run_header": True, "git_sha": "abc", "jax_version": "0.4.37"},
        {"step": 1, "event": "compile_costs", "hlo_flops": 1e12},
        {"step": 1, "loss": 4.9, "tps": None},  # compile step logs null tps
    ]
    for s in range(2, n + 2):
        rows.append({"step": s, "loss": 4.0, "tps": tps + s, "mfu": 0.5,
                     "step_time_s": 0.1, "goodput": 0.8 + s * 0.01})
    return rows


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(path)


class TestSummarize:
    def test_median_skips_header_event_and_null_rows(self):
        out = summarize_rows(_training_rows())
        assert out["tps"] == pytest.approx(1004.5)  # median of 1002..1007
        assert out["mfu"] == 0.5
        assert out["goodput"] == pytest.approx(0.87)  # last row, cumulative

    def test_empty_rows(self):
        assert summarize_rows([]) == {}


class TestLoadRunMetrics:
    def test_training_jsonl(self, tmp_path):
        p = _write_jsonl(tmp_path / "training.jsonl", _training_rows())
        assert load_run_metrics(p)["tps"] == pytest.approx(1004.5)

    def test_bench_line(self, tmp_path):
        p = tmp_path / "bench.json"
        p.write_text(json.dumps({
            "ok": True, "metric": "tokens/sec", "value": 14380.0,
            "unit": "tokens/s/chip", "extra": {"mfu": 0.6},
        }))
        out = load_run_metrics(str(p))
        assert out == {"tps": 14380.0, "mfu": 0.6}

    def test_pretty_printed_benchmark_json(self, tmp_path):
        p = tmp_path / "benchmark.json"
        p.write_text(json.dumps({"tokens_per_sec": 9000.0, "mfu": 0.55,
                                 "step_time_s": 0.8}, indent=2))
        out = load_run_metrics(str(p))
        assert out["tps"] == 9000.0 and out["step_time_s"] == 0.8

    def test_baseline_doubles_as_run(self, tmp_path):
        p = tmp_path / "b.json"
        write_baseline(str(p), {"tps": 123.0})
        assert load_run_metrics(str(p)) == {"tps": 123.0}

    def test_empty_artifact_raises(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        with pytest.raises(ValueError, match="empty"):
            load_run_metrics(str(p))


class TestCompare:
    BASE = {"tps": 1000.0, "mfu": 0.5, "step_time_s": 0.1, "goodput": 0.9}

    def test_within_tolerance_passes(self):
        run = {"tps": 960.0, "mfu": 0.49, "step_time_s": 0.104, "goodput": 0.88}
        assert all(c.ok for c in compare(run, self.BASE, DEFAULT_TOLERANCES))

    def test_throughput_drop_fails_but_gain_passes(self):
        drop = compare({"tps": 900.0}, {"tps": 1000.0})
        assert [c.metric for c in drop if not c.ok] == ["tps"]
        gain = compare({"tps": 1200.0}, {"tps": 1000.0})
        assert all(c.ok for c in gain)

    def test_step_time_direction_inverted(self):
        slower = compare({"step_time_s": 0.12}, {"step_time_s": 0.1})
        assert not slower[0].ok
        faster = compare({"step_time_s": 0.08}, {"step_time_s": 0.1})
        assert faster[0].ok

    def test_missing_metric_passes_unless_required(self):
        res = compare({"tps": 1000.0}, self.BASE)
        assert all(c.ok for c in res)
        res = compare({"tps": 1000.0}, self.BASE, require=("mfu",))
        assert [c.metric for c in res if not c.ok] == ["mfu"]

    def test_tolerance_override(self):
        assert not compare({"tps": 900.0}, {"tps": 1000.0})[0].ok
        assert compare({"tps": 900.0}, {"tps": 1000.0}, {"tps": 0.15})[0].ok

    def test_zero_baseline_not_comparable_but_printable(self):
        """A CPU baseline carries mfu=0.0; the row must pass (nothing to
        compare against) and line() must not blow up on change=None."""
        res = compare({"mfu": 0.0}, {"mfu": 0.0})
        assert res[0].ok and res[0].change is None
        assert "not comparable" in res[0].line()
        # `require` guards MISSING metrics, not zero baselines: a present 0.0
        # (overlap_frac on a single-axis run) passes even when required ...
        assert compare({"mfu": 0.0}, {"mfu": 0.0}, require=("mfu",))[0].ok
        # ... while an absent required metric still fails
        assert not compare({}, {"mfu": 0.0}, require=("mfu",))[0].ok


class TestMeasuredKeys:
    """bench.py --profile rows: measured-profile keys flatten per cell and
    gate with the right directions (overlap up = good, comm frac up = bad)."""

    ROW = {
        "matrix_row": True, "model": "gpt", "seq_len": 1024, "prefetch": True,
        "tokens_per_sec_per_chip": 5000.0,
        "measured_step_time_s": 0.2, "overlap_frac": 0.4,
        "measured_frac_compute": 0.7, "measured_frac_comm": 0.2,
        "measured_frac_moe_a2a": 0.0, "measured_frac_host": 0.1,
        "measured_bound": "compute",  # diagnostic string: must NOT flatten
    }

    def test_matrix_flattening(self, tmp_path):
        p = tmp_path / "matrix.json"
        p.write_text(json.dumps({"matrix": [self.ROW]}))
        out = load_run_metrics(str(p))
        key = "matrix/gpt_s1024_pfon"
        assert out[f"{key}/overlap_frac"] == 0.4
        assert out[f"{key}/measured_step_time_s"] == 0.2
        assert out[f"{key}/measured_frac_comm"] == 0.2
        assert f"{key}/measured_bound" not in out

    def test_jsonl_capture_flattening(self, tmp_path):
        off_row = dict(self.ROW, prefetch=False, overlap_frac=0.1)
        p = _write_jsonl(tmp_path / "matrix.jsonl", [self.ROW, off_row])
        out = load_run_metrics(p)
        assert out["matrix/gpt_s1024_pfon/overlap_frac"] == 0.4
        assert out["matrix/gpt_s1024_pfoff/overlap_frac"] == 0.1

    def test_overlap_frac_higher_is_better(self):
        key = "matrix/gpt_s1024_pfon/overlap_frac"
        worse = compare({key: 0.3}, {key: 0.5})
        assert not worse[0].ok
        better = compare({key: 0.7}, {key: 0.5})
        assert better[0].ok

    def test_comm_frac_lower_is_better(self):
        key = "matrix/gpt_s1024_pfon/measured_frac_comm"
        worse = compare({key: 0.4}, {key: 0.2})
        assert not worse[0].ok
        assert compare({key: 0.1}, {key: 0.2})[0].ok

    def test_measured_step_time_lower_is_better(self):
        key = "matrix/gpt_s1024_pfon/measured_step_time_s"
        assert not compare({key: 0.3}, {key: 0.2})[0].ok
        assert compare({key: 0.15}, {key: 0.2})[0].ok

    def test_default_tolerances_present(self):
        for base in ("measured_step_time_s", "overlap_frac",
                     "measured_frac_compute", "measured_frac_comm",
                     "measured_frac_moe_a2a", "measured_frac_host"):
            assert base in DEFAULT_TOLERANCES, base


class TestCli:
    def _artifacts(self, tmp_path, run_tps=1000.0):
        run = _write_jsonl(tmp_path / "run.jsonl", _training_rows(tps=run_tps))
        base = str(tmp_path / "baseline.json")
        return run, base

    def test_write_then_match_exits_0(self, tmp_path):
        run, base = self._artifacts(tmp_path)
        assert main(["--run", run, "--baseline", base, "--write-baseline"]) == 0
        assert set(load_baseline(base)) == {"tps", "mfu", "step_time_s", "goodput"}
        assert main(["--run", run, "--baseline", base]) == 0

    def test_10pct_tps_regression_exits_1(self, tmp_path):
        run, base = self._artifacts(tmp_path)
        main(["--run", run, "--baseline", base, "--write-baseline"])
        regressed = _write_jsonl(tmp_path / "bad.jsonl", _training_rows(tps=900.0))
        assert main(["--run", regressed, "--baseline", base]) == 1

    def test_loose_tolerance_rescues(self, tmp_path):
        run, base = self._artifacts(tmp_path)
        main(["--run", run, "--baseline", base, "--write-baseline"])
        regressed = _write_jsonl(tmp_path / "bad.jsonl", _training_rows(tps=900.0))
        assert main(["--run", regressed, "--baseline", base,
                     "--tolerance", "tps=0.2", "--tolerance", "goodput=0.2"]) == 0

    def test_missing_artifact_exits_2(self, tmp_path):
        assert main(["--run", str(tmp_path / "nope.jsonl"),
                     "--baseline", str(tmp_path / "b.json")]) == 2

    def test_bad_tolerance_exits_2(self, tmp_path):
        run, base = self._artifacts(tmp_path)
        main(["--run", run, "--baseline", base, "--write-baseline"])
        assert main(["--run", run, "--baseline", base, "--tolerance", "oops"]) == 2

    def test_require_missing_metric_exits_1(self, tmp_path):
        p = tmp_path / "bench.json"
        p.write_text(json.dumps({"metric": "x", "value": 100.0}))  # no mfu
        base = str(tmp_path / "b.json")
        write_baseline(base, {"tps": 100.0, "mfu": 0.5})
        assert main(["--run", str(p), "--baseline", base]) == 0
        assert main(["--run", str(p), "--baseline", base, "--require", "mfu"]) == 1
