import json

import jax
import numpy as np
import pytest

from automodel_tpu.loggers.metric_logger import MetricLogger
from automodel_tpu.training.rng import ScopedRNG, StatefulRNG
from automodel_tpu.training.step_scheduler import StepScheduler


class TestStatefulRNG:
    def test_deterministic_streams(self):
        a = StatefulRNG(seed=7)
        b = StatefulRNG(seed=7)
        assert jax.random.uniform(a.key("x")) == jax.random.uniform(b.key("x"))

    def test_stream_advances(self):
        r = StatefulRNG(seed=7)
        k1, k2 = r.key("x"), r.key("x")
        assert jax.random.uniform(k1) != jax.random.uniform(k2)

    def test_named_streams_independent(self):
        r = StatefulRNG(seed=7)
        assert jax.random.uniform(r.key("a")) != jax.random.uniform(r.key("b"))

    def test_state_roundtrip(self):
        r = StatefulRNG(seed=3)
        r.key("x")
        r.key("x")
        state = r.state_dict()
        v_expected = jax.random.uniform(r.peek("x"))
        r2 = StatefulRNG(seed=999)
        r2.load_state_dict(state)
        assert jax.random.uniform(r2.key("x")) == v_expected

    def test_scoped(self):
        r = StatefulRNG(seed=0)
        with ScopedRNG(r, "init") as s:
            k = s.key("w")
        # scope prefixes the stream name
        assert r._counters.get("init/w") == 1


class TestStepScheduler:
    def test_grad_accum_batching(self):
        data = list(range(10))
        s = StepScheduler(grad_acc_steps=3, dataloader=data, num_epochs=1, handle_sigterm=False)
        groups = list(s)
        assert groups[0] == [0, 1, 2]
        assert groups[-1] == [9]  # trailing partial group still steps
        assert s.step == 4

    def test_max_steps(self):
        s = StepScheduler(grad_acc_steps=1, dataloader=range(100), max_steps=5, handle_sigterm=False)
        assert len(list(s)) == 5
        assert s.done

    def test_epochs(self):
        s = StepScheduler(grad_acc_steps=2, dataloader=range(4), num_epochs=3, handle_sigterm=False)
        assert len(list(s)) == 6
        assert s.epoch == 3

    def test_cadence_flags(self):
        s = StepScheduler(grad_acc_steps=1, ckpt_every_steps=2, val_every_steps=3,
                          dataloader=range(6), handle_sigterm=False)
        ckpts, vals = [], []
        for _ in s:
            if s.is_ckpt_step:
                ckpts.append(s.step)
            if s.is_val_step:
                vals.append(s.step)
        assert ckpts == [2, 4, 6]
        assert vals == [3, 6]

    def test_state_roundtrip(self):
        s = StepScheduler(grad_acc_steps=1, dataloader=range(3), handle_sigterm=False)
        s.step, s.epoch = 7, 2
        s2 = StepScheduler(grad_acc_steps=1, handle_sigterm=False)
        s2.load_state_dict(s.state_dict())
        assert s2.step == 7 and s2.epoch == 2


class TestMetricLogger:
    def test_jsonl_stream(self, tmp_path):
        p = tmp_path / "training.jsonl"
        with MetricLogger(p) as ml:
            ml.log(1, loss=np.float32(2.5), lr=1e-4)
            ml.log(2, loss=jax.numpy.asarray(2.25), grad_norm=0.9)
        lines = [json.loads(line) for line in p.read_text().splitlines()]
        assert lines[0]["step"] == 1 and lines[0]["loss"] == 2.5
        assert lines[1]["grad_norm"] == 0.9
