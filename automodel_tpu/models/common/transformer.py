"""Shared dense-decoder machinery for all Llama-lineage model families.

TPU-native counterpart of the reference's per-family model.py/layers.py pairs
(e.g. models/llama/model.py, models/qwen2/): models here are *pure functions over
param pytrees* — no modules, no wrappers — so pjit/GSPMD shards them by annotating
logical axes, and parallelism never appears in model code (the reference's
"parallelism is configuration" contract, README.md:74-80, taken to its fixed point).

Layers are stacked along a leading axis and iterated with ``lax.scan``: one layer
gets traced/compiled once regardless of depth (fast compiles at 100+ layers), and the
stacked layout is exactly what pipeline-stage slicing wants later.

Param tree layout (per layer, stacked to (L, ...) under scan):
  attn_norm (D,) | wq (D,N,H) | wk/wv (D,K,H) | wo (N,H,D) | [bq (N,H) bk/bv (K,H)]
  [q_norm/k_norm (H,)] | mlp_norm (D,) | w_gate/w_up (D,I) | w_down (I,D)
Top level: embed (V,D) | final_norm (D,) | [lm_head (D,V) unless tied].
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.fp8 import project
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import (
    apply_rope, apply_rope_interleaved, rope_attention_scaling, rope_frequencies,
)
from automodel_tpu.utils.tracing import scope_blocks

__all__ = [
    "DenseDecoderConfig",
    "init_dense_decoder_params",
    "dense_decoder_logical_axes",
    "decoder_forward",
    "make_layer_body",
    "apply_layer_stack",
]


@dataclasses.dataclass
class DenseDecoderConfig:
    """Architecture knobs shared by Llama/Qwen2/Qwen3/Mistral-style decoders."""

    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_hidden_layers: int = 16
    num_attention_heads: int = 16
    num_key_value_heads: int = 8
    head_dim: int | None = None
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: dict[str, Any] | None = None
    partial_rotary_factor: float = 1.0  # glm4/minimax: rope only the first fraction of head_dim
    rope_interleaved: bool = False  # helium/ernie4.5: consecutive-pair rotation, not half-split
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # qwen2: bias on q/k/v only
    attention_out_bias: bool = False  # gpt-oss: bias on o_proj too
    attention_sinks: bool = False  # gpt-oss: per-head sink logits absorbing mass
    qk_norm: bool = False  # qwen3: RMSNorm on per-head q/k
    qk_norm_whole: bool = False  # olmo2: RMSNorm over the WHOLE q/k projection (n*h)
    # "pre" (llama) | "post" (olmo2: norm the sublayer OUTPUT, no input norm)
    # | "sandwich" (glm4/gemma2 style: input norm AND a second norm on the output)
    norm_placement: str = "pre"
    norm_type: str = "rms"  # "rms" | "layernorm" (mean-centered, no bias — cohere)
    norm_bias: bool = False  # starcoder2/stablelm: LayerNorm with learnable bias
    norm_param: bool = True  # False (olmo-v1): non-parametric LayerNorm (no weight)
    parallel_block: bool = False  # cohere: h + attn(norm(h)) + mlp(norm(h)), ONE norm
    mlp_gated: bool = True  # False (arcee): down(act(up(x))), no gate matrix
    mlp_act: str = "silu"  # "silu" | "gelu" | "relu2" (arcee)
    mlp_bias: bool = False  # starcoder2: bias terms on the MLP projections
    clip_qkv: float | None = None  # olmo: clamp q/k/v projection outputs
    # ungated-MLP HF tensor names when they differ from up_proj/down_proj
    # (starcoder2: c_fc/c_proj); None = llama names
    hf_mlp_names: tuple[str, str] | None = None
    sliding_window: int | None = None
    layer_types: list[str] | None = None  # "full_attention" | "sliding_attention"
    # SmolLM3-style NoPE: per-layer rope enable (HF semantics: 1 = rope ON);
    # None = rope everywhere
    no_rope_layers: list | None = None
    initializer_range: float = 0.02
    causal: bool = True  # False: bidirectional encoder (llama_bidirectional)
    # Granite mup-style static scalars (all at the llama value = identity;
    # transformers modeling_granite.py applies exactly these four)
    embedding_multiplier: float = 1.0
    residual_multiplier: float = 1.0
    attention_multiplier: float | None = None  # None = 1/sqrt(head_dim)
    logits_scaling: float = 1.0
    # Ministral-3 llama-4-style long-context q scaling: q *= 1 + beta*log(1 + pos//orig)
    # (reference mistral3/model.py:282-284)
    llama4_attn_scale_beta: float | None = None
    original_max_position_embeddings: int | None = None

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads

    @property
    def sliding_flags(self) -> list[bool]:
        if self.layer_types is not None:
            return [t == "sliding_attention" for t in self.layer_types]
        if self.sliding_window is not None:
            return [True] * self.num_hidden_layers
        return [False] * self.num_hidden_layers

    @property
    def layer_flags(self) -> list[int]:
        """Per-layer bitfield scanned alongside the layer params: bit 0 =
        sliding window, bit 1 = NoPE (rope disabled). One int stream keeps the
        scan/pipeline tuple shapes unchanged as flags accrue."""
        rope_on = self.no_rope_layers or [1] * self.num_hidden_layers
        return [int(s) | (0 if rope_on[i] else 2)
                for i, s in enumerate(self.sliding_flags)]


def _layer_shapes(cfg: DenseDecoderConfig) -> dict[str, tuple[int, ...]]:
    d, n, k, h, i = (
        cfg.hidden_size,
        cfg.num_attention_heads,
        cfg.num_key_value_heads,
        cfg.head_dim,
        cfg.intermediate_size,
    )
    shapes = {
        "attn_norm": (d,),
        "wq": (d, n, h),
        "wk": (d, k, h),
        "wv": (d, k, h),
        "wo": (n, h, d),
        "mlp_norm": (d,),
        "w_gate": (d, i),
        "w_up": (d, i),
        "w_down": (i, d),
    }
    if cfg.attention_bias:
        shapes |= {"bq": (n, h), "bk": (k, h), "bv": (k, h)}
    if cfg.attention_out_bias:
        shapes |= {"bo": (d,)}
    if cfg.attention_sinks:
        shapes |= {"sinks": (n,)}
    if cfg.parallel_block:
        del shapes["mlp_norm"]  # one shared input norm (cohere)
    if not cfg.mlp_gated:
        del shapes["w_gate"]  # arcee: two-matrix ungated MLP
    if cfg.mlp_bias:
        shapes |= {"b_up": (i,), "b_down": (d,)}
        if cfg.mlp_gated:
            shapes |= {"b_gate": (i,)}
    if cfg.norm_bias:
        shapes |= {k + "_b": (d,) for k in ("attn_norm", "mlp_norm") if k in shapes}
    if not cfg.norm_param:
        # olmo-v1: LayerNorm with NO learnable weight — the params simply
        # don't exist (a trainable ones-init would drift from HF semantics)
        for k in ("attn_norm", "mlp_norm"):
            shapes.pop(k, None)
    if cfg.norm_placement == "sandwich":  # glm4: post_self_attn/post_mlp norms
        shapes |= {"attn_post_norm": (d,), "mlp_post_norm": (d,)}
    if cfg.qk_norm_whole:
        shapes |= {"q_norm": (n, h), "k_norm": (k, h)}
    elif cfg.qk_norm and cfg.norm_type == "layernorm":
        # cohere: per-head LN with per-head weights, stored (n, h)/(k, h) as HF does
        shapes |= {"q_norm": (n, h), "k_norm": (k, h)}
    elif cfg.qk_norm:
        shapes |= {"q_norm": (h,), "k_norm": (h,)}
    return shapes


_LAYER_AXES = {
    "attn_norm": ("norm",),
    "wq": ("embed", "heads", "head_dim"),
    "wk": ("embed", "kv_heads", "head_dim"),
    "wv": ("embed", "kv_heads", "head_dim"),
    "wo": ("heads", "head_dim", "embed"),
    "bq": ("heads", "head_dim"),
    "bk": ("kv_heads", "head_dim"),
    "bv": ("kv_heads", "head_dim"),
    "bo": ("embed",),
    "sinks": ("heads",),
    "q_norm": ("norm",),
    "k_norm": ("norm",),
    "mlp_norm": ("norm",),
    "attn_post_norm": ("norm",),
    "mlp_post_norm": ("norm",),
    "w_gate": ("embed", "mlp"),
    "w_up": ("embed", "mlp"),
    "w_down": ("mlp", "embed"),
    "b_gate": ("mlp",),
    "b_up": ("mlp",),
    "b_down": ("embed",),
    "attn_norm_b": ("norm",),
    "mlp_norm_b": ("norm",),
}


def init_dense_decoder_params(
    cfg: DenseDecoderConfig, key: jax.Array, dtype=jnp.float32, scan_layers: bool = True
) -> dict:
    """Random init matching HF conventions (normal(0, initializer_range), norms=1).

    Layer params are always stacked (L, ...); ``scan_layers`` only controls whether the
    forward iterates them with lax.scan or an unrolled loop.
    """
    del scan_layers
    shapes = _layer_shapes(cfg)
    keys = jax.random.split(key, len(shapes) + 2)
    std = cfg.initializer_range
    L = cfg.num_hidden_layers

    layers = {}
    for idx, (name, shape) in enumerate(shapes.items()):
        if name.endswith("_b"):  # norm biases init to zero like linear biases
            layers[name] = jnp.zeros((L, *shape), dtype)
        elif name.endswith("norm"):
            layers[name] = jnp.ones((L, *shape), dtype)
        elif name.startswith("b"):
            layers[name] = jnp.zeros((L, *shape), dtype)
        else:
            layers[name] = (jax.random.normal(keys[idx], (L, *shape), jnp.float32) * std).astype(dtype)

    params = {
        "embed": (jax.random.normal(keys[-2], (cfg.vocab_size, cfg.hidden_size), jnp.float32) * std).astype(dtype),
        "layers": layers,
    }
    if cfg.norm_param:
        params["final_norm"] = jnp.ones((cfg.hidden_size,), dtype)
    if cfg.norm_bias:
        params["final_norm_b"] = jnp.zeros((cfg.hidden_size,), dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[-1], (cfg.hidden_size, cfg.vocab_size), jnp.float32) * std
        ).astype(dtype)
    return params


def dense_decoder_logical_axes(cfg: DenseDecoderConfig, scan_layers: bool = True) -> dict:
    """Pytree of logical-axis tuples matching init_dense_decoder_params' layout."""
    del scan_layers  # layer params are always stacked (L, ...)
    layers = {name: ("layers",) + _LAYER_AXES[name] for name in _layer_shapes(cfg)}
    if cfg.qk_norm_whole or (cfg.qk_norm and cfg.norm_type == "layernorm"):
        # (n, h)-shaped norm weights
        layers["q_norm"] = ("layers", "heads", "head_dim")
        layers["k_norm"] = ("layers", "kv_heads", "head_dim")
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layers,
    }
    if cfg.norm_param:
        axes["final_norm"] = ("norm",)
    if cfg.norm_bias:
        axes["final_norm_b"] = ("norm",)
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _constrain(x, rules, names):
    if rules is None or rules.mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, rules.sharding(names))


def embed_lookup(table, input_ids, dtype, rules=None, scale: float = 1.0):
    """Token-embedding gather with the table's FSDP (hidden-dim) axes unsharded
    FIRST — a plain all-gather (FSDP's param-on-use collective). Without it the
    gather output inherits the table's hidden-dim sharding and the partitioner
    falls back to involuntary full rematerialization resharding it to the
    (batch, act_seq) activation layout (seen in the r2 cp-ring dryrun HLO).
    "vocab" stays: under TP the vocab-parallel local-gather+psum path holds.
    Shared by the dense/MoE forwards and the pipeline's stage-0 embedding."""
    table = _constrain(table.astype(dtype), rules, ("vocab", None))
    h = table[input_ids]
    if scale != 1.0:  # granite embedding_multiplier
        h = h * jnp.asarray(scale, h.dtype)
    return h


def _centered_norm(x, w, eps, b=None):
    """Mean-centered LayerNorm (CohereLayerNorm and friends): works for (d,)
    block weights and per-head (n, h) qk weights alike (stats over last dim).
    ``w=None`` is the non-parametric form (olmo-v1); ``b`` the optional bias
    (starcoder2/stablelm). Affine math stays in fp32 before the downcast,
    matching HF."""
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w.astype(jnp.float32)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(x.dtype)


def apply_final_norm(cfg, params, h, dtype):
    """Top-level final norm shared by decoder_forward and the pipeline head —
    weight/bias may be absent (olmo-v1 non-parametric LN / no-bias families)."""
    w = params.get("final_norm")
    b = params.get("final_norm_b")
    return _block_norm(cfg, h, None if w is None else w.astype(dtype),
                       None if b is None else b.astype(dtype))


def _block_norm(cfg, x, w, b=None):
    """The block-level norm the config selects (rms | mean-centered LN).
    getattr: family configs outside the dense lineage (MLA) reach here via the
    shared pipeline head and carry no norm_type — they are all RMSNorm."""
    if getattr(cfg, "norm_type", "rms") == "layernorm":
        return _centered_norm(x, w, cfg.rms_norm_eps, b)
    return rms_norm(x, w, cfg.rms_norm_eps)


def resolve_unembed(cfg, params, dtype):
    """lm_head | tied embed.T (gpt2: wte), cast to compute dtype, with granite
    logits_scaling folded in (logits/ls == unembed/ls) — the ONE copy every
    head consumer (decoder_forward, pipeline._head_pre, linear-CE recipes)
    resolves through. Returns None when the params carry no table."""
    unembed = params.get("lm_head")
    if unembed is None:
        table = params.get("embed", params.get("wte"))
        if table is None:
            return None
        unembed = table.T
    unembed = jnp.asarray(unembed).astype(dtype)
    ls = getattr(cfg, "logits_scaling", 1.0)
    return unembed / ls if ls != 1.0 else unembed


def _rms_norm_2d(x, w, eps):
    """RMSNorm with the mean over the LAST TWO dims (whole-projection norm,
    olmo2): x (..., n, h), w (n, h)."""
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=(-2, -1), keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * w


def _cache_write(cache, new, idx):
    """Write ``new (B, s, ...)`` into ``cache (B, S_max, ...)`` at per-row slot
    ``idx (B,)`` — a vmapped dynamic_update_slice (rows decode at different
    lengths when prompts are right-padded unevenly)."""
    zeros = (0,) * (cache.ndim - 2)
    return jax.vmap(
        lambda c, n, i: jax.lax.dynamic_update_slice(c, n, (i, *zeros))
    )(cache, new, idx)


def _attention_block(cfg: DenseDecoderConfig, backend: BackendConfig, lp: dict, x, positions,
                     segment_ids, inv_freq, attn_scale, sliding, rules,
                     cache=None, cache_meta=None):
    """Self-attention block. With ``cache=(k_cache, v_cache)`` (decode path) the
    freshly projected k/v are written into the cache at ``cache_meta["write_idx"]``
    and attention runs against the whole cache (masked by ``cache_meta["valid"]``
    as kv segment ids + position-causal masking); returns ``(out, (k, v))``.
    Training path (cache=None) returns just ``out``."""
    from jax.ad_checkpoint import checkpoint_name

    lin = backend.linear
    q = checkpoint_name(project(x, lp["wq"], 1, lin), "attn_q")
    k = checkpoint_name(project(x, lp["wk"], 1, lin), "attn_k")
    v = checkpoint_name(project(x, lp["wv"], 1, lin), "attn_v")
    if cfg.attention_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    if getattr(cfg, "clip_qkv", None) is not None:
        c = cfg.clip_qkv  # olmo: clamp projection outputs (post-bias, like HF)
        q, k, v = (jnp.clip(t, -c, c) for t in (q, k, v))
    if cfg.qk_norm_whole:
        # olmo2: RMSNorm over the flattened projection — mean over (heads,
        # head_dim) jointly, weight (n, h) == the flat HF (n*h,) weight reshaped
        q = _rms_norm_2d(q, lp["q_norm"], cfg.rms_norm_eps)
        k = _rms_norm_2d(k, lp["k_norm"], cfg.rms_norm_eps)
    elif cfg.qk_norm and cfg.norm_type == "layernorm":
        # cohere: per-head mean-centered LN with per-head (n, h) weights
        q = _centered_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = _centered_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    elif cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    rope = apply_rope_interleaved if cfg.rope_interleaved else apply_rope
    q = rope(q, positions, inv_freq, attn_scale)
    k = rope(k, positions, inv_freq, attn_scale)
    if cfg.llama4_attn_scale_beta is not None:
        orig = cfg.original_max_position_embeddings or cfg.max_position_embeddings
        scale = 1.0 + cfg.llama4_attn_scale_beta * jnp.log1p(
            jnp.floor(positions.astype(jnp.float32) / orig)
        )
        q = q * scale[..., None, None].astype(q.dtype)
    if cache is not None:
        k_cache = _cache_write(cache[0], k.astype(cache[0].dtype), cache_meta["write_idx"])
        v_cache = _cache_write(cache[1], v.astype(cache[1].dtype), cache_meta["write_idx"])
        out = dot_product_attention(
            q, k_cache.astype(q.dtype), v_cache.astype(q.dtype),
            causal=cfg.causal,
            segment_ids_q=segment_ids,
            segment_ids_kv=cache_meta["valid"],
            positions_q=positions,
            positions_kv=cache_meta["positions"],
            sliding_window=sliding,
            sinks=lp.get("sinks"),
            softmax_scale=cfg.attention_multiplier,
            backend="xla",  # q_len 1 / position-masked: the flash kernel doesn't apply
        )
        o = project(out, lp["wo"], 2, lin)
        if cfg.attention_out_bias:
            o = o + lp["bo"]
        return o, (k_cache, v_cache)
    q = _constrain(q, rules, ("batch", "act_attn_seq", "act_heads", None))
    k = _constrain(k, rules, ("batch", "act_attn_seq", "act_heads", None))
    mesh = rules.mesh if rules is not None else None
    use_ring = (
        backend.context_parallel == "ring"
        and mesh is not None
        and mesh.shape.get("cp", 1) > 1
        and lp.get("sinks") is None
        and sliding is None  # traced per-layer windows can't close over shard_map
    )
    if use_ring:
        from automodel_tpu.parallel.ring_attention import make_ring_attention

        ring = make_ring_attention(mesh, causal=cfg.causal,
                                   softmax_scale=cfg.attention_multiplier)
        out = checkpoint_name(ring(q, k, v, positions, segment_ids), "attn_out")
    else:
        out = checkpoint_name(dot_product_attention(
            q, k, v,
            causal=cfg.causal,
            # attention_segments=False: right-padded-unpacked fast path — causal
            # masking alone isolates real tokens from trailing pads. The
            # argument needs causality: bidirectional stacks keep their masking
            segment_ids_q=(segment_ids if (backend.attention_segments or not cfg.causal)
                           else None),
            sliding_window=sliding,
            sinks=lp.get("sinks"),
            softmax_scale=cfg.attention_multiplier,
            backend=backend.attention,
        ), "attn_out")
    o = project(out, lp["wo"], 2, lin)
    if cfg.attention_out_bias:
        o = o + lp["bo"]
    return o


_MLP_ACTS = {
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,  # tanh approximation (HF "gelu_pytorch_tanh")
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),  # HF bare "gelu"
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),  # arcee
}


def _mlp_block(cfg: DenseDecoderConfig, backend: BackendConfig, lp: dict, x, rules):
    from jax.ad_checkpoint import checkpoint_name

    lin = backend.linear
    # getattr: family configs outside the dense lineage (MLA) reach this shared
    # MLP through the MoE dense prefix and carry no mlp_* fields (all gated silu)
    act_fn = _MLP_ACTS[getattr(cfg, "mlp_act", "silu")]
    # names feed the "mlp_*" remat policies (backend.py): these (tokens,
    # intermediate) tensors are the activation-memory peak of the layer
    up = checkpoint_name(project(x, lp["w_up"], 1, lin), "mlp_up")
    if getattr(cfg, "mlp_bias", False):
        up = up + lp["b_up"]
    if getattr(cfg, "mlp_gated", True):
        gate = checkpoint_name(project(x, lp["w_gate"], 1, lin), "mlp_gate")
        if getattr(cfg, "mlp_bias", False):
            gate = gate + lp["b_gate"]
        h = act_fn(gate) * up
    else:  # arcee/starcoder2: down(act(up(x)))
        h = act_fn(up)
    h = _constrain(h, rules, ("batch", "act_attn_seq", "act_mlp"))
    out = project(h, lp["w_down"], 1, lin)
    if getattr(cfg, "mlp_bias", False):
        out = out + lp["b_down"]
    return out


def make_layer_body(cfg: DenseDecoderConfig, backend: BackendConfig, rules=None):
    """Scan body over a carried state dict {"h", "positions", ["segment_ids"]}.

    The state-dict form lets the same body serve decoder_forward's layer scan and
    the pp pipeline (parallel/pipeline.py), where positions/segment ids ride along
    with the activation between stages.
    """
    dtype = backend.jnp_dtype
    inv_freq = rope_frequencies(
        cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
        partial_rotary_factor=cfg.partial_rotary_factor,
    )
    attn_scale = rope_attention_scaling(cfg.rope_scaling)
    any_sliding = any(cfg.sliding_flags)
    window = jnp.int32(cfg.sliding_window or 0)

    def layer_fn(state, layer_inputs):
        if len(layer_inputs) == 3:
            lp, is_sliding, kv = layer_inputs  # decode: per-layer kv cache rides as xs
        else:
            (lp, is_sliding), kv = layer_inputs, None
        lp = jax.tree.map(lambda a: a.astype(dtype), lp)
        h = state["h"]
        # "disabled" window must exceed every causal q-kv distance for the actual
        # (static at trace time) sequence length, even when S > max_position_embeddings
        kv_len = h.shape[1] if kv is None else kv[0].shape[1]
        big_window = jnp.int32(cfg.max_position_embeddings + kv_len)
        # traced per-layer window (scan-compatible); None disables the mask entirely
        eff_window = jnp.where(is_sliding & 1, window, big_window) if any_sliding else None
        # bit 1: NoPE layer (SmolLM3) — rope with zeroed frequencies is identity
        inv_freq_l = inv_freq
        if cfg.no_rope_layers is not None:
            inv_freq_l = inv_freq * (1 - ((is_sliding >> 1) & 1)).astype(inv_freq.dtype)
        def attn_call(x):
            """One copy of the cache/no-cache attention dispatch for every
            block style (sequential pre/post-norm AND cohere parallel)."""
            if kv is None:
                return _attention_block(
                    cfg, backend, lp, x, state["positions"], state.get("segment_ids"),
                    inv_freq_l, attn_scale, eff_window, rules), None
            cache_meta = {k_: state[k_] for k_ in ("write_idx", "valid")}
            cache_meta["positions"] = state["kv_positions"]
            return _attention_block(
                cfg, backend, lp, x, state["positions"], state.get("segment_ids"),
                inv_freq_l, attn_scale, eff_window, rules,
                cache=kv, cache_meta=cache_meta,
            )

        def parallel_sublayer(h):
            # cohere: ONE input norm feeds attention AND the MLP; both outputs
            # add to the residual together
            x = _block_norm(cfg, h, lp.get("attn_norm"), lp.get("attn_norm_b"))
            attn_out, kv_out = attn_call(x)
            h = h + attn_out + _mlp_block(cfg, backend, lp, x, rules)
            return _constrain(h, rules, ("batch", "act_seq", "act_embed")), kv_out

        post = cfg.norm_placement == "post"
        sandwich = cfg.norm_placement == "sandwich"

        def attention_sublayer(h):
            # post (olmo2): attention reads h RAW; attn_norm applies to the
            # sublayer OUTPUT before the residual add (post_attention_layernorm).
            # sandwich (glm4): input norm AND a post norm on the output.
            x = h if post else _block_norm(cfg, h, lp.get("attn_norm"), lp.get("attn_norm_b"))
            attn_out, kv_out = attn_call(x)
            if post:
                attn_out = _block_norm(cfg, attn_out, lp.get("attn_norm"), lp.get("attn_norm_b"))
            elif sandwich:  # post_self_attn_layernorm
                attn_out = _block_norm(cfg, attn_out, lp["attn_post_norm"])
            if cfg.residual_multiplier != 1.0:  # granite
                attn_out = attn_out * cfg.residual_multiplier
            h = h + attn_out
            return _constrain(h, rules, ("batch", "act_seq", "act_embed")), kv_out

        def mlp_sublayer(h):
            x = h if post else _block_norm(cfg, h, lp.get("mlp_norm"), lp.get("mlp_norm_b"))
            mlp_out = _mlp_block(cfg, backend, lp, x, rules)
            if post:  # post_feedforward_layernorm
                mlp_out = _block_norm(cfg, mlp_out, lp.get("mlp_norm"), lp.get("mlp_norm_b"))
            elif sandwich:  # post_mlp_layernorm
                mlp_out = _block_norm(cfg, mlp_out, lp["mlp_post_norm"])
            if cfg.residual_multiplier != 1.0:
                mlp_out = mlp_out * cfg.residual_multiplier
            h = h + mlp_out
            return _constrain(h, rules, ("batch", "act_seq", "act_embed"))

        # named scopes label the profiler trace per block (the reference gets the
        # same from autonvtx module hooks, autonvtx/__init__.py:33)
        blocks = scope_blocks({
            "parallel_block": parallel_sublayer,
            "attention": attention_sublayer,
            "mlp": mlp_sublayer,
        })
        if cfg.parallel_block:
            h, kv_out = blocks["parallel_block"](h)
            return dict(state, h=h), kv_out
        h, kv_out = blocks["attention"](h)
        h = blocks["mlp"](h)
        return dict(state, h=h), kv_out

    return layer_fn


def apply_layer_stack(
    cfg: DenseDecoderConfig,
    backend: BackendConfig,
    lp_stack,  # pytree of (L, ...) stacked layer params
    sliding_flags: jnp.ndarray,  # (L,) int32
    state: dict,  # {"h": (B,S,D), "positions": (B,S), ["segment_ids": (B,S)]}
    rules=None,
    cache=None,  # decode: {"k"/"v": (L,B,S_max,KH,D), ...} -> returns (state, cache)
):
    body = backend.layer_remat(make_layer_body(cfg, backend, rules))
    if cache is not None:
        xs = (lp_stack, sliding_flags, (cache["k"], cache["v"]))
        if backend.scan_layers:
            state, (k_new, v_new) = jax.lax.scan(body, state, xs)
        else:
            num_layers = jax.tree.leaves(lp_stack)[0].shape[0]
            ks, vs = [], []
            for i in range(num_layers):
                sliced = jax.tree.map(lambda a: a[i], xs)
                state, (k_l, v_l) = body(state, sliced)
                ks.append(k_l)
                vs.append(v_l)
            k_new, v_new = jnp.stack(ks), jnp.stack(vs)
        return state, dict(cache, k=k_new, v=v_new)
    if backend.scan_layers:
        state, _ = jax.lax.scan(body, state, (lp_stack, sliding_flags))
    else:
        num_layers = jax.tree.leaves(lp_stack)[0].shape[0]
        for i in range(num_layers):
            lp = jax.tree.map(lambda a: a[i], lp_stack)
            state, _ = body(state, (lp, sliding_flags[i]))
    return state


def decoder_forward(
    cfg: DenseDecoderConfig,
    backend: BackendConfig,
    params: dict,
    input_ids: jnp.ndarray,  # (B, S) int32
    positions: jnp.ndarray | None = None,
    segment_ids: jnp.ndarray | None = None,
    rules=None,
    return_hidden: bool = False,
    inputs_embeds: jnp.ndarray | None = None,  # VLM path: pre-merged embeddings
    cache=None,  # generation.init_kv_cache dict -> returns (logits, cache)
):
    """Forward pass -> logits (B, S, V), or final hidden states for fused linear-CE.

    With ``cache`` (a :func:`automodel_tpu.generation.init_kv_cache` dict whose
    positions/valid/write_idx the generation loop has already advanced for this
    chunk) the pass serves prefill (S = prompt length) and decode (S = 1) and
    returns ``(logits, cache)``; ``segment_ids`` is then REQUIRED (it doubles as
    the q-validity mask against unfilled cache slots).
    """
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
    if cache is not None and segment_ids is None:
        raise ValueError("cache decoding requires segment_ids (1 = real token)")
    dtype = backend.jnp_dtype
    if inputs_embeds is not None:
        h = inputs_embeds
        if cfg.embedding_multiplier != 1.0:  # HF scales provided embeds too
            h = h * jnp.asarray(cfg.embedding_multiplier, h.dtype)
    else:
        h = embed_lookup(params["embed"], input_ids, dtype, rules,
                         scale=cfg.embedding_multiplier)
    h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))

    state = {"h": h, "positions": positions}
    if segment_ids is not None:
        state["segment_ids"] = segment_ids
    if cache is not None:
        state["kv_positions"] = cache["positions"]
        state["valid"] = cache["valid"]
        state["write_idx"] = cache["write_idx"]
    sliding_flags = jnp.asarray(cfg.layer_flags, dtype=jnp.int32)
    out = apply_layer_stack(cfg, backend, params["layers"], sliding_flags, state, rules,
                            cache=cache)
    state, cache = out if cache is not None else (out, None)
    h = state["h"]

    h = apply_final_norm(cfg, params, h, dtype)
    if cache is not None:
        # next-token logits ONLY (B, 1, V): unembedding the whole prefill chunk
        # would materialize a (B, S_prompt, V) tensor — an HBM spike at exactly
        # the long-prompt scales the KV cache exists for. Right-padded contract:
        # each row's last valid position is segment_ids.sum()-1.
        last = jnp.maximum(segment_ids.sum(-1) - 1, 0).astype(jnp.int32)
        h = jnp.take_along_axis(h, last[:, None, None], axis=1)  # (B, 1, D)
        if return_hidden:
            return h, cache
        logits = jnp.einsum("bsd,dv->bsv", h, resolve_unembed(cfg, params, dtype))
        return logits, cache
    if return_hidden:
        return h
    logits = jnp.einsum("bsd,dv->bsv", h, resolve_unembed(cfg, params, dtype))
    return logits
