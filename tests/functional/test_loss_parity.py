"""Loss-curve parity vs torch (SURVEY.md §7 'hard parts'): the same tiny Llama
checkpoint, batches, and AdamW hyperparameters must produce the same loss
trajectory in both frameworks — the end-to-end guarantee behind every per-module
parity test. Also pins fused linear-CE == full-logit CE in value and gradient."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.ops.losses import linear_cross_entropy, masked_cross_entropy

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

# heavyweight torch-parity leg: a full torch training loop per test. Out of the
# tier-1 budget; CI's functional job opts back in with -m "" (docs/testing)
pytestmark = pytest.mark.slow


def _tiny_hf(seed=0):
    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    return transformers.LlamaForCausalLM(cfg)


class TestLossCurveParity:
    def test_adamw_training_matches_torch(self, tmp_path):
        hf = _tiny_hf()
        d = str(tmp_path / "hf")
        hf.save_pretrained(d, safe_serialization=True)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=BackendConfig(dtype="float32", remat_policy="full")
        )

        rng = np.random.RandomState(0)
        batches = [rng.randint(0, 256, (4, 32)) for _ in range(8)]
        lr, b1, b2, eps, wd = 1e-3, 0.9, 0.95, 1e-8, 0.0

        # ---- torch side ----
        hf.train()
        opt = torch.optim.AdamW(hf.parameters(), lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd)
        torch_losses = []
        for ids in batches:
            t = torch.tensor(ids)
            out = hf(input_ids=t[:, :-1])
            ll = torch.nn.functional.cross_entropy(
                out.logits.reshape(-1, 256), t[:, 1:].reshape(-1)
            )
            opt.zero_grad()
            ll.backward()
            opt.step()
            torch_losses.append(float(ll))

        # ---- ours ----
        tx = optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, ids):
            def loss_fn(p):
                logits, _stats = model(p, ids[:, :-1]), None
                ll = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                return -jnp.take_along_axis(ll, ids[:, 1:, None], -1).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        ours_losses = []
        for ids in batches:
            params, opt_state, loss = step(params, opt_state, jnp.asarray(ids))
            ours_losses.append(float(loss))

        np.testing.assert_allclose(ours_losses, torch_losses, atol=2e-3, rtol=1e-3)
        # the optimizer must actually be applied (trajectory, not a frozen no-op)
        assert abs(ours_losses[-1] - ours_losses[0]) > 1e-4

    def test_linear_ce_matches_full_ce_and_grads(self):
        rng = np.random.RandomState(1)
        B, S, D, V = 2, 24, 16, 64
        hidden = jnp.asarray(rng.randn(B, S, D).astype(np.float32))
        unembed = jnp.asarray(rng.randn(D, V).astype(np.float32) * 0.1)
        labels = jnp.asarray(rng.randint(0, V, (B, S)))
        labels = labels.at[0, :4].set(-100)  # ignore span

        def full(h, u):
            return masked_cross_entropy(jnp.einsum("bsd,dv->bsv", h, u), labels)

        def fused(h, u):
            return linear_cross_entropy(h, u, labels, block_size=16)

        v1, g1 = jax.value_and_grad(full, argnums=(0, 1))(hidden, unembed)
        v2, g2 = jax.value_and_grad(fused, argnums=(0, 1))(hidden, unembed)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
