"""Pallas flash attention vs the XLA reference — interpret mode on CPU gives exact
kernel semantics without hardware (the reference tests kernels the same way: CPU
parity vs a naive implementation, SURVEY.md §4)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.pallas.flash_attention import flash_attention


def _rand(key, *shape):
    return jax.random.normal(jax.random.key(key), shape, jnp.float32)


def _ref(q, k, v, **kw):
    return dot_product_attention(q, k, v, backend="xla", **kw)


def _flash(q, k, v, **kw):
    return flash_attention(q, k, v, interpret=True, block_q=32, block_k=32, **kw)


class TestFlashForward:
    def test_causal_matches_xla(self):
        q, k, v = _rand(0, 2, 64, 4, 16), _rand(1, 2, 64, 4, 16), _rand(2, 2, 64, 4, 16)
        np.testing.assert_allclose(
            np.asarray(_flash(q, k, v, causal=True)),
            np.asarray(_ref(q, k, v, causal=True)),
            atol=2e-5,
        )

    def test_non_causal(self):
        q, k, v = _rand(3, 1, 32, 2, 8), _rand(4, 1, 32, 2, 8), _rand(5, 1, 32, 2, 8)
        np.testing.assert_allclose(
            np.asarray(_flash(q, k, v, causal=False)),
            np.asarray(_ref(q, k, v, causal=False)),
            atol=2e-5,
        )

    def test_gqa(self):
        q = _rand(6, 2, 64, 8, 16)
        k, v = _rand(7, 2, 64, 2, 16), _rand(8, 2, 64, 2, 16)
        np.testing.assert_allclose(
            np.asarray(_flash(q, k, v)),
            np.asarray(_ref(q, k, v)),
            atol=2e-5,
        )

    def test_segment_ids_packing(self):
        q, k, v = _rand(9, 2, 64, 4, 16), _rand(10, 2, 64, 4, 16), _rand(11, 2, 64, 4, 16)
        seg = jnp.concatenate(
            [jnp.full((2, 32), 1, jnp.int32), jnp.full((2, 32), 2, jnp.int32)], axis=1
        )
        got = _flash(q, k, v, segment_ids_q=seg)
        want = _ref(q, k, v, segment_ids_q=seg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_sliding_window(self):
        q, k, v = _rand(12, 1, 64, 2, 16), _rand(13, 1, 64, 2, 16), _rand(14, 1, 64, 2, 16)
        got = _flash(q, k, v, sliding_window=16)
        want = _ref(q, k, v, sliding_window=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_softmax_scale(self):
        q, k, v = _rand(15, 1, 32, 2, 8), _rand(16, 1, 32, 2, 8), _rand(17, 1, 32, 2, 8)
        got = _flash(q, k, v, softmax_scale=0.5)
        want = _ref(q, k, v, softmax_scale=0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_rejects_indivisible_seq(self):
        # 50 is not divisible by any block >= 8
        q = _rand(18, 1, 50, 2, 8)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, q, q, block_q=32, block_k=32, interpret=True)

    def test_block_fallback_divides_seq(self):
        # 48 % 32 != 0, but the picker falls back to 16 and matches xla
        q, k, v = _rand(19, 1, 48, 2, 8), _rand(20, 1, 48, 2, 8), _rand(21, 1, 48, 2, 8)
        out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
        ref = dot_product_attention(q, k, v, causal=True, backend="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


class TestFlashBackward:
    @pytest.mark.parametrize("bwd", ["fused", "split"])
    @pytest.mark.parametrize("case", ["causal", "gqa", "packed", "window"])
    def test_grads_match_xla(self, case, bwd, monkeypatch):
        # fused = single dq+dkv kernel (default when the kv scratch fits);
        # split = the two-kernel fallback that long-context shapes take
        import automodel_tpu.ops.pallas.flash_attention as fa

        monkeypatch.setenv("AUTOMODEL_FLASH_FUSED_BWD", "1" if bwd == "fused" else "0")
        before = fa._fused_bwd_traces
        self._check_grads(case)
        # guard against the VMEM gate silently taking the split path: the
        # "fused" parametrization must actually trace the fused kernel
        assert (fa._fused_bwd_traces > before) == (bwd == "fused")

    def _check_grads(self, case):
        kw = {}
        nh, nkv = 4, 4
        if case == "gqa":
            nkv = 2
        if case == "packed":
            kw["segment_ids_q"] = jnp.concatenate(
                [jnp.full((2, 32), 1, jnp.int32), jnp.full((2, 32), 2, jnp.int32)], axis=1
            )
        if case == "window":
            kw["sliding_window"] = 16
        q = _rand(20, 2, 64, nh, 16)
        k, v = _rand(21, 2, 64, nkv, 16), _rand(22, 2, 64, nkv, 16)

        def loss_flash(q, k, v):
            return (_flash(q, k, v, **kw) ** 2).sum()

        def loss_ref(q, k, v):
            return (_ref(q, k, v, **kw) ** 2).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), atol=5e-4,
                err_msg=f"d{name} mismatch in case {case}",
            )


class TestFusedVsSplitBackward:
    def test_everything_on_agreement(self, monkeypatch):
        """Fused and split backward agree bit-for-bit-ish with every kernel
        feature engaged at once (softcap + sinks + segments + GQA + causal)."""
        q = _rand(60, 2, 64, 4, 16)
        k, v = _rand(61, 2, 64, 2, 16), _rand(62, 2, 64, 2, 16)
        sinks = jnp.asarray([0.4, -0.2, 0.7, 0.0], jnp.float32)
        seg = jnp.concatenate(
            [jnp.full((2, 32), 1, jnp.int32), jnp.full((2, 32), 2, jnp.int32)], axis=1
        )

        def loss(q_, k_, v_, s_):
            return (_flash(q_, k_, v_, sinks=s_, segment_ids_q=seg,
                           logit_soft_cap=6.0) ** 2).sum()

        import automodel_tpu.ops.pallas.flash_attention as fa

        monkeypatch.setenv("AUTOMODEL_FLASH_FUSED_BWD", "1")
        before = fa._fused_bwd_traces
        g_fused = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, sinks)
        assert fa._fused_bwd_traces > before, "fused path did not engage"
        monkeypatch.setenv("AUTOMODEL_FLASH_FUSED_BWD", "0")
        g_split = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, sinks)
        for a, b, name in zip(g_fused, g_split, ["q", "k", "v", "sinks"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5,
                err_msg=f"fused vs split d{name}",
            )


class TestSinksAndSoftCap:
    """gpt-oss sinks and gemma-style tanh capping inside the kernel (they
    previously forced the XLA fallback, ops/attention.py round-1)."""

    def test_soft_cap_matches_xla(self):
        q, k, v = _rand(20, 2, 64, 4, 16), _rand(21, 2, 64, 4, 16), _rand(22, 2, 64, 4, 16)
        got = _flash(q, k, v, logit_soft_cap=8.0)
        want = _ref(q, k, v, logit_soft_cap=8.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_soft_cap_grads(self):
        q, k, v = _rand(23, 1, 32, 2, 8), _rand(24, 1, 32, 2, 8), _rand(25, 1, 32, 2, 8)

        def loss(fn):
            return lambda q_, k_, v_: (fn(q_, k_, v_, logit_soft_cap=5.0) ** 2).sum()

        g_got = jax.grad(loss(_flash), argnums=(0, 1, 2))(q, k, v)
        g_want = jax.grad(loss(_ref), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    def test_sinks_match_xla(self):
        q, k, v = _rand(26, 2, 64, 4, 16), _rand(27, 2, 64, 4, 16), _rand(28, 2, 64, 4, 16)
        sinks = jnp.asarray([0.5, -0.3, 1.2, 0.0], jnp.float32)
        got = _flash(q, k, v, sinks=sinks)
        want = _ref(q, k, v, sinks=sinks)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_sinks_grads_including_dsinks(self):
        q, k, v = _rand(29, 1, 32, 4, 8), _rand(30, 1, 32, 4, 8), _rand(31, 1, 32, 4, 8)
        sinks = jnp.asarray([0.2, -0.5, 0.8, 0.1], jnp.float32)

        def loss(fn):
            return lambda q_, k_, v_, s_: (fn(q_, k_, v_, sinks=s_) ** 2).sum()

        g_got = jax.grad(loss(_flash), argnums=(0, 1, 2, 3))(q, k, v, sinks)
        g_want = jax.grad(loss(_ref), argnums=(0, 1, 2, 3))(q, k, v, sinks)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    def test_sinks_with_segments_and_gqa(self):
        q = _rand(32, 2, 64, 4, 16)
        k, v = _rand(33, 2, 64, 2, 16), _rand(34, 2, 64, 2, 16)
        sinks = jnp.asarray([0.5, -0.1, 0.3, 0.9], jnp.float32)
        seg = jnp.concatenate(
            [jnp.full((2, 32), 1, jnp.int32), jnp.full((2, 32), 2, jnp.int32)], axis=1
        )
        got = _flash(q, k, v, sinks=sinks, segment_ids_q=seg)
        want = _ref(q, k, v, sinks=sinks, segment_ids_q=seg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


class TestAttentionSegmentsFastPath:
    def test_unsegmented_matches_on_right_padded_real_tokens(self):
        """backend.attention_segments=False (bench fast path): with RIGHT-padded
        unpacked batches, causal masking alone isolates real tokens from the
        trailing pads, so real-token logits must match the segmented path
        exactly; pad rows are loss-masked and may diverge."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=32,
        )
        m_seg = LlamaForCausalLM(cfg, BackendConfig(dtype="float32"))
        m_fast = LlamaForCausalLM(cfg, BackendConfig(dtype="float32",
                                                     attention_segments=False))
        params = m_seg.init(jax.random.key(0), jnp.float32)
        rng = np.random.RandomState(0)
        ids = rng.randint(1, 64, (2, 16)).astype(np.int32)
        seg = np.ones((2, 16), np.int32)
        ids[1, 10:] = 0
        seg[1, 10:] = 0
        pos = np.broadcast_to(np.arange(16, dtype=np.int32), (2, 16))
        a = np.asarray(m_seg(params, ids, positions=pos, segment_ids=jnp.asarray(seg)))
        b = np.asarray(m_fast(params, ids, positions=pos, segment_ids=jnp.asarray(seg)))
        np.testing.assert_allclose(a[seg == 1], b[seg == 1], rtol=1e-6, atol=1e-6)

    def test_packing_with_fast_path_is_refused(self, tmp_path, cpu_devices):
        import textwrap

        import pytest

        from automodel_tpu.config.loader import load_config
        from automodel_tpu.recipes.llm.train_ft import (
            TrainFinetuneRecipeForNextTokenPrediction,
        )

        cfg_text = f"""
        seed: 7
        output_dir: {tmp_path}/out
        model:
          config:
            architectures: [LlamaForCausalLM]
            vocab_size: 128
            hidden_size: 32
            intermediate_size: 64
            num_hidden_layers: 2
            num_attention_heads: 4
            num_key_value_heads: 2
            max_position_embeddings: 128
        distributed: {{dp_shard: 8}}
        backend: {{dtype: float32, attention_segments: false}}
        packed_sequence: {{packed_sequence_size: 64}}
        dataset:
          _target_: automodel_tpu.data.llm.mock.MockSFTDataset
          vocab_size: 128
          seq_len: 32
          num_samples: 64
          seed: 0
        micro_batch_size: 8
        seq_len: 32
        step_scheduler: {{grad_acc_steps: 1, max_steps: 1, handle_sigterm: false}}
        optimizer: {{lr: 1.0e-3}}
        checkpoint: {{enabled: false}}
        """
        p = tmp_path / "cfg.yaml"
        p.write_text(textwrap.dedent(cfg_text))
        r = TrainFinetuneRecipeForNextTokenPrediction(load_config(p))
        with pytest.raises(ValueError, match="attention_segments"):
            r.setup()
