"""Config-driven observability manager wired into the training recipes.

One object owns the four pillars — goodput accounting, HBM/compile telemetry,
the stall watchdog, and on-demand profiling — so a recipe integrates with five
hooks: ``start()``, ``track(bucket)``, ``heartbeat(step)``,
``on_step_start/end(step)``, and ``step_metrics()`` merged into each log row.
Everything flows through the existing MetricLogger/experiment-logger fan-out;
this module adds no new output channels.

YAML (all keys optional; the subsystem is on by default and every pillar
no-ops cleanly where its backing API is unavailable):

.. code-block:: yaml

    observability:
      enabled: true
      goodput: true
      memory: true
      watchdog: {enabled: true, threshold_s: 600}
      profiling: {server_port: 0, trace_steps: 5, signal: SIGUSR1}
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import signal as _signal
from typing import Any, Callable

from automodel_tpu.observability.goodput import GoodputTracker
from automodel_tpu.observability.memory import device_memory_stats
from automodel_tpu.observability.profiling import OnDemandProfiler
from automodel_tpu.observability.watchdog import StallWatchdog

logger = logging.getLogger(__name__)

__all__ = ["ObservabilityConfig", "Observability"]


@dataclasses.dataclass
class ObservabilityConfig:
    enabled: bool = True
    goodput: bool = True
    memory: bool = True
    watchdog: bool = True
    watchdog_threshold_s: float = 600.0
    watchdog_poll_interval_s: float | None = None
    profiler_port: int = 0  # 0 = no profiler server
    trace_steps: int = 5
    trace_signal: str | None = "SIGUSR1"  # None/"none" = no signal handler

    @classmethod
    def from_dict(cls, raw: Any) -> "ObservabilityConfig":
        """Build from the ``observability:`` YAML section (ConfigNode or dict)."""
        if raw is None:
            return cls()
        if hasattr(raw, "to_dict"):
            raw = raw.to_dict()
        raw = dict(raw)
        kw: dict[str, Any] = {k: raw[k] for k in ("enabled", "goodput", "memory") if k in raw}
        wd = raw.get("watchdog")
        if isinstance(wd, bool):
            kw["watchdog"] = wd
        elif isinstance(wd, dict):
            kw["watchdog"] = bool(wd.get("enabled", True))
            if wd.get("threshold_s") is not None:
                kw["watchdog_threshold_s"] = float(wd["threshold_s"])
            if wd.get("poll_interval_s") is not None:
                kw["watchdog_poll_interval_s"] = float(wd["poll_interval_s"])
        prof = raw.get("profiling")
        if isinstance(prof, dict):
            kw["profiler_port"] = int(prof.get("server_port", 0))
            kw["trace_steps"] = int(prof.get("trace_steps", 5))
            kw["trace_signal"] = prof.get("signal", "SIGUSR1")
        return cls(**kw)

    def resolve_signal(self) -> int | None:
        name = self.trace_signal
        if not name or str(name).lower() == "none":
            return None
        return getattr(_signal, str(name).upper())


class Observability:
    """The manager a recipe holds; disabled pillars degrade to no-ops."""

    def __init__(
        self,
        config: ObservabilityConfig,
        out_dir: str,
        metric_sink: Callable[..., None] | None = None,
    ):
        self.config = config
        self.out_dir = str(out_dir)
        self.compile_time_s: float | None = None
        on = config.enabled
        self.goodput: GoodputTracker | None = GoodputTracker() if on and config.goodput else None
        self._memory = on and config.memory
        self.watchdog: StallWatchdog | None = None
        if on and config.watchdog:
            on_stall = None
            if metric_sink is not None:
                def on_stall(event: dict, _sink=metric_sink):
                    _sink(int(event.get("step") or 0),
                          **{k: v for k, v in event.items() if k != "step"})
            self.watchdog = StallWatchdog(
                threshold_s=config.watchdog_threshold_s,
                dump_dir=self.out_dir,
                on_stall=on_stall,
                poll_interval_s=config.watchdog_poll_interval_s,
            )
        self.profiler: OnDemandProfiler | None = None
        if on:
            self.profiler = OnDemandProfiler(
                self.out_dir,
                trace_steps=config.trace_steps,
                server_port=config.profiler_port,
                signum=config.resolve_signal(),
            )

    @classmethod
    def from_config(cls, cfg: Any, out_dir: str,
                    metric_sink: Callable[..., None] | None = None) -> "Observability":
        return cls(ObservabilityConfig.from_dict(cfg), out_dir, metric_sink)

    # ------------------------------------------------------------------ lifecycle
    def start(self) -> "Observability":
        if self.watchdog is not None:
            self.watchdog.start()
        if self.profiler is not None:
            self.profiler.start()
        return self

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.profiler is not None:
            self.profiler.close()

    # ------------------------------------------------------------------ hooks
    def track(self, bucket: str):
        """Goodput context manager; nullcontext when accounting is off."""
        if self.goodput is None:
            return contextlib.nullcontext()
        return self.goodput.track(bucket)

    def record_compile(self, seconds: float) -> None:
        """Cumulative: a delayed-QAT switch compiles a second step mid-run."""
        self.compile_time_s = round((self.compile_time_s or 0.0) + float(seconds), 3)
        if self.goodput is not None:
            self.goodput.add("compile", seconds)
        logger.info("jit compile + first execute: %.1fs (cumulative %.1fs)",
                    seconds, self.compile_time_s)

    def heartbeat(self, step: int | None = None) -> None:
        if self.watchdog is not None:
            self.watchdog.heartbeat(step)

    def on_step_start(self, step: int) -> None:
        if self.profiler is not None:
            self.profiler.on_step_start(step)

    def on_step_end(self, step: int, sync: Any = None) -> None:
        if self.profiler is not None:
            self.profiler.on_step_end(step, sync)

    def step_metrics(self) -> dict[str, Any]:
        """The per-log-row contribution: compile time, goodput fractions, HBM."""
        out: dict[str, Any] = {}
        if self.compile_time_s is not None:
            out["compile_time_s"] = self.compile_time_s
        if self.goodput is not None:
            out.update(self.goodput.snapshot())
        if self._memory:
            out.update(device_memory_stats())
        return out
