"""Per-model VLM collators (reference datasets/vlm/collate_fns.py:148-394).

The reference dispatches a per-processor collate function (qwen2.5-VL,
qwen3-omni, kimi, phi4-mm); each pairs chat text containing media placeholders
with the model's native patch/feature layout and masks labels to the answer
span. The TPU versions keep every data-dependent computation on the HOST
(numpy): patchification, media-token expansion, mrope position walks, and the
models' ``prepare_*_inputs`` bookkeeping all happen here, so the jitted step
sees only static-shaped arrays.

Static-shape contract: all images are resized to ONE grid per config (unlike
the reference's native-resolution buckets, which are free on GPUs but would
retrace XLA per shape). ``image_size=(grid_h, grid_w)`` in *patches*; vary it
per config, not per batch.

Layout parity: ``qwen_patchify`` reproduces the HF Qwen2VL image processor's
patch ordering exactly (verified against it in tests), so pretrained
checkpoints see the pixel layout they were trained on.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import numpy as np

from automodel_tpu.data.collate import IGNORE_INDEX, shift_example
from automodel_tpu.data.vlm.collate import IMAGE_PLACEHOLDER, _MEAN, _STD

__all__ = [
    "qwen_patchify", "qwen_vl_collate", "kimi_patchify", "kimi_vl_collate",
    "qwen3_omni_collate", "phi4_mm_collate", "log_mel_spectrogram", "AUDIO_PLACEHOLDER",
]

AUDIO_PLACEHOLDER = "<audio>"


def _resize_hw(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """(H, W, C) -> (out_h, out_w, C) bilinear, pure numpy."""
    h, w, _ = img.shape
    if h == out_h and w == out_w:
        return img.astype(np.float32)
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = img.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def _to_chw_float(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """uint8/float (H, W, 3) -> CLIP-normalized (3, out_h, out_w) float32."""
    img = np.asarray(img)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    img = _resize_hw(img, out_h, out_w)
    return np.transpose((img - _MEAN) / _STD, (2, 0, 1))


def qwen_patchify(
    img: np.ndarray,  # (H, W, 3) uint8 or float
    *,
    patch_size: int,
    merge_size: int,
    temporal_patch_size: int,
    grid_h: int,
    grid_w: int,
) -> np.ndarray:
    """One image -> (grid_h*grid_w, 3*temporal_patch*patch^2) in the HF
    Qwen2VL processor layout (image_processing_qwen2_vl semantics: the single
    frame repeats across the temporal patch; patches are ordered merge-window
    major so the tower's spatial merge reads contiguous blocks)."""
    m, p = merge_size, patch_size
    x = _to_chw_float(img, grid_h * p, grid_w * p)  # (C, H, W)
    x = np.tile(x[None], (temporal_patch_size, 1, 1, 1))  # (tp, C, H, W)
    c = x.shape[1]
    x = x.reshape(1, temporal_patch_size, c, grid_h // m, m, p, grid_w // m, m, p)
    x = x.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    return np.ascontiguousarray(
        x.reshape(grid_h * grid_w, c * temporal_patch_size * p * p)
    )


def _encode_with_media(
    tokenizer, ex: Mapping[str, Any], seq_len: int,
    spans: Mapping[str, Sequence[Sequence[int]]],  # placeholder -> media id spans
    answer_only_loss: bool = True,
):
    """Shared text path: expand each placeholder occurrence (possibly of several
    modalities, in textual order) with its next media id span, then build shifted
    inputs/labels masked to the answer."""
    prompt = ex.get("prompt", "")
    # auto-prepend placeholders the prompt doesn't mention
    for ph, media in spans.items():
        missing = len(media) - prompt.count(ph)
        if missing < 0:
            raise ValueError(
                f"prompt has {prompt.count(ph)} {ph!r} placeholders for "
                f"{len(media)} media items"
            )
        if missing:
            prompt = ph * missing + "\n" + prompt
    # Sequence-start prefix FIRST, then every text chunk encoded raw: encoding
    # chunks with add_special_tokens=True would splice a '<bos> $A <eos>'-style
    # template's END suffix between text and vision spans (and before the
    # answer), drifting the layout vs HF processors. encode("") reproduces the
    # tokenizer's ACTUAL start prefix (empty for families like Qwen2 that define
    # bos_token_id but never emit it); trailing end markers are stripped so no
    # eos/sep from the empty-input template leaks in.
    prefix = tokenizer.encode("", add_special_tokens=True)
    enders = {
        t for t in (getattr(tokenizer, "eos_token_id", None),
                    getattr(tokenizer, "sep_token_id", None)) if t is not None
    }
    while prefix and prefix[-1] in enders:
        prefix.pop()
    ids: list[int] = list(prefix)
    cursor = {ph: iter(media) for ph, media in spans.items()}
    rest = prompt
    while rest:
        hits = [(rest.find(ph), ph) for ph in spans if ph in rest]
        if not hits:
            ids.extend(tokenizer.encode(rest, add_special_tokens=False))
            break
        pos, ph = min(hits)
        if pos:
            ids.extend(tokenizer.encode(rest[:pos], add_special_tokens=False))
        ids.extend(next(cursor[ph]))
        rest = rest[pos + len(ph):]
    prompt_len = len(ids)
    answer_ids = tokenizer.encode(str(ex["answer"]), add_special_tokens=False)
    eos = getattr(tokenizer, "eos_token_id", None)
    if eos is not None:
        answer_ids = answer_ids + [eos]
    ids = np.asarray(ids + answer_ids, np.int32)
    if prompt_len >= seq_len:
        raise ValueError(
            f"seq_len {seq_len} cannot hold the prompt + media span ({prompt_len} tokens)"
        )
    inp, tgt = shift_example({"input_ids": ids, "prompt_len": prompt_len}, answer_only_loss)
    return inp[:seq_len], tgt[:seq_len]


def _check_uniform_media(per_ex_counts: Sequence[int], what: str):
    """Static-shape contract: every example in every batch must carry the same
    media multiplicity, or stacked microbatches change shape and jit retraces
    (or crashes on np.stack). Fail loudly with the remedy."""
    if len(set(per_ex_counts)) > 1:
        raise ValueError(
            f"examples carry different numbers of {what} ({sorted(set(per_ex_counts))}); "
            f"TPU batches need a uniform media count per example — pad or filter the "
            f"dataset (static shapes are the jit contract)"
        )


def _text_batch(examples, tokenizer, seq_len, pad_token_id, per_ex_spans):
    b = len(examples)
    input_ids = np.full((b, seq_len), pad_token_id, np.int32)
    labels = np.full((b, seq_len), IGNORE_INDEX, np.int32)
    segment_ids = np.zeros((b, seq_len), np.int32)
    positions = np.zeros((b, seq_len), np.int32)
    for row, (ex, spans) in enumerate(zip(examples, per_ex_spans)):
        inp, tgt = _encode_with_media(tokenizer, ex, seq_len, spans)
        n = len(inp)
        input_ids[row, :n] = inp
        labels[row, :n] = tgt
        segment_ids[row, :n] = 1
        positions[row, :n] = np.arange(n)
    labels[segment_ids == 0] = IGNORE_INDEX
    return input_ids, labels, positions, segment_ids


def qwen_vl_collate(
    examples: Sequence[Mapping[str, Any]],
    tokenizer,
    model,  # Qwen3VLMoeForConditionalGeneration-style native model
    seq_len: int,
    pad_token_id: int = 0,
    image_size: tuple[int, int] | None = None,  # (grid_h, grid_w) in patches
) -> dict[str, np.ndarray]:
    """qwen2.5-VL / qwen3-VL collate (reference collate_fns.py qwen2_5 path).

    Examples: {"prompt": str with <image> placeholders, "answer": str,
    "image": array or "images": [array, ...]}. Emits the native model's full
    input set: flat pixel patches, prepare_vision_inputs bookkeeping, visual
    scatter coords, and 3-axis mrope positions.
    """
    cfg = model.config
    vis = cfg.vision
    if image_size is None:
        gh = gw = max(vis.spatial_merge_size * 4, 8)
    else:
        gh, gw = image_size
    ms = vis.spatial_merge_size
    if gh % ms or gw % ms:
        raise ValueError(f"image_size {gh}x{gw} must be a multiple of merge {ms}")
    n_merged = (gh // ms) * (gw // ms)

    per_ex_imgs = [
        ex.get("images", [ex["image"]] if "image" in ex else []) for ex in examples
    ]
    _check_uniform_media([len(i) for i in per_ex_imgs], "images")
    vstart = getattr(cfg, "vision_start_token_id", None)
    span = [cfg.image_token_id] * n_merged
    if vstart is not None:
        span = [vstart] + span
    per_ex_spans = [{IMAGE_PLACEHOLDER: [span] * len(imgs)} for imgs in per_ex_imgs]

    input_ids, labels, positions, segment_ids = _text_batch(
        examples, tokenizer, seq_len, pad_token_id, per_ex_spans
    )

    patches = [
        qwen_patchify(
            img, patch_size=vis.patch_size, merge_size=ms,
            temporal_patch_size=vis.temporal_patch_size, grid_h=gh, grid_w=gw,
        )
        for imgs in per_ex_imgs for img in imgs
    ]
    n_images = len(patches)
    grids = np.asarray([[1, gh, gw]] * n_images, np.int64)
    pixel_values = (
        np.concatenate(patches, 0) if patches
        else np.zeros((0, vis.in_channels * vis.temporal_patch_size * vis.patch_size**2), np.float32)
    )

    coords_b, coords_s = model.visual_token_coords(input_ids)
    batch = {
        "input_ids": input_ids,
        "labels": labels,
        "positions": positions,
        "segment_ids": segment_ids,
        "pixel_values": pixel_values.astype(np.float32),
        "vision_inputs": model.prepare_vision_inputs(grids),
        "visual_coords_b": coords_b,
        "visual_coords_s": coords_s,
        "positions3": np.asarray(model.get_mrope_positions(input_ids, grids)),
    }
    return batch


def kimi_patchify(img: np.ndarray, *, patch_size: int, grid_h: int, grid_w: int) -> np.ndarray:
    """One image -> (grid_h*grid_w, 3*patch^2) MoonViT flat patches (row-major
    patch order; kernel-merge grouping happens in prepare_moonvit_inputs)."""
    p = patch_size
    x = _to_chw_float(img, grid_h * p, grid_w * p)  # (C, H, W)
    c = x.shape[0]
    x = x.reshape(c, grid_h, p, grid_w, p).transpose(1, 3, 0, 2, 4)
    return np.ascontiguousarray(x.reshape(grid_h * grid_w, c * p * p))


def kimi_vl_collate(
    examples: Sequence[Mapping[str, Any]],
    tokenizer,
    model,  # KimiVL-style native model
    seq_len: int,
    pad_token_id: int = 0,
    image_size: tuple[int, int] | None = None,  # (grid_h, grid_w) in patches
) -> dict[str, np.ndarray]:
    """Kimi-VL collate (reference collate_fns.py kimi path): MoonViT flat
    patches + media placeholder expansion (one merged token per merge kernel)."""
    cfg = model.config
    vis = cfg.vision
    kh, kw = vis.merge_kernel_size
    if image_size is None:
        gh, gw = kh * 4, kw * 4
    else:
        gh, gw = image_size
    if gh % kh or gw % kw:
        raise ValueError(f"image_size {gh}x{gw} must be a multiple of merge {kh}x{kw}")
    n_merged = (gh // kh) * (gw // kw)

    per_ex_imgs = [
        ex.get("images", [ex["image"]] if "image" in ex else []) for ex in examples
    ]
    _check_uniform_media([len(i) for i in per_ex_imgs], "images")
    media_id = cfg.media_placeholder_token_id
    per_ex_spans = [
        {IMAGE_PLACEHOLDER: [[media_id] * n_merged] * len(imgs)} for imgs in per_ex_imgs
    ]
    input_ids, labels, positions, segment_ids = _text_batch(
        examples, tokenizer, seq_len, pad_token_id, per_ex_spans
    )

    patches = [
        kimi_patchify(img, patch_size=vis.patch_size, grid_h=gh, grid_w=gw)
        for imgs in per_ex_imgs for img in imgs
    ]
    grids = np.asarray([[gh, gw]] * len(patches), np.int64)
    pixel_values = (
        np.concatenate(patches, 0) if patches
        else np.zeros((0, vis.in_channels * vis.patch_size**2), np.float32)
    )
    b_idx, s_idx = np.where(input_ids == media_id)
    return {
        "input_ids": input_ids,
        "labels": labels,
        "positions": positions,
        "segment_ids": segment_ids,
        "pixel_values": pixel_values.astype(np.float32),
        "vision_inputs": model.prepare_vision_inputs(grids),
        "media_coords_b": b_idx.astype(np.int32),
        "media_coords_s": s_idx.astype(np.int32),
    }


def log_mel_spectrogram(
    audio: np.ndarray, *, num_mel_bins: int, sample_rate: int = 16000,
    n_fft: int = 400, hop: int = 160,
) -> np.ndarray:
    """Whisper-style log-mel features, pure numpy: (num_mel_bins, T_frames).

    The reference drives this through WhisperFeatureExtractor inside the omni
    processor; the math is the standard STFT -> mel filterbank -> log10 with
    dynamic-range clamping.
    """
    audio = np.asarray(audio, np.float32)
    n_frames = 1 + (len(audio) - n_fft) // hop if len(audio) >= n_fft else 0
    if n_frames <= 0:
        audio = np.pad(audio, (0, n_fft - len(audio)))
        n_frames = 1
    window = np.hanning(n_fft + 1)[:-1].astype(np.float32)
    frames = np.lib.stride_tricks.as_strided(
        audio, (n_frames, n_fft), (audio.strides[0] * hop, audio.strides[0]),
    )
    spec = np.abs(np.fft.rfft(frames * window, axis=-1)) ** 2  # (T, n_fft//2+1)

    # slaney-ish mel filterbank
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mel_pts = mel_to_hz(np.linspace(hz_to_mel(0.0), hz_to_mel(sample_rate / 2), num_mel_bins + 2))
    bins = np.floor((n_fft + 1) * mel_pts / sample_rate).astype(int)
    fb = np.zeros((num_mel_bins, n_fft // 2 + 1), np.float32)
    for i in range(num_mel_bins):
        l, c, r = bins[i], bins[i + 1], bins[i + 2]
        if c > l:
            fb[i, l:c] = (np.arange(l, c) - l) / (c - l)
        if r > c:
            fb[i, c:r] = (r - np.arange(c, r)) / (r - c)
    mel = np.maximum(spec @ fb.T, 1e-10)
    logmel = np.log10(mel).T  # (mel, T)
    logmel = np.maximum(logmel, logmel.max() - 8.0)
    return ((logmel + 4.0) / 4.0).astype(np.float32)


def qwen3_omni_collate(
    examples: Sequence[Mapping[str, Any]],
    tokenizer,
    model,  # Qwen3OmniMoe-style native model
    seq_len: int,
    pad_token_id: int = 0,
    image_size: tuple[int, int] | None = None,
) -> dict[str, np.ndarray]:
    """qwen3-omni collate (reference collate_fns.py qwen3_omni path): audio
    (<audio> -> mel features -> audio placeholder span) composes with the
    qwen-VL image path (<image> -> patch spans); mrope positions come from the
    omni walk over both modalities.

    Audio examples carry "audio" (raw waveform, 16kHz float) or
    "audio_features" (precomputed (mel, T)); image examples carry
    "image"/"images" like qwen_vl_collate.
    """
    import math

    from automodel_tpu.models.audio.qwen3_omni_audio import _conv_out_len

    cfg = model.config
    acfg = cfg.audio
    vis = cfg.vision

    # ---- audio features + their token spans
    per_ex_feats: list[list[np.ndarray]] = []
    for ex in examples:
        feats = []
        if "audio_features" in ex:
            feats.append(np.asarray(ex["audio_features"], np.float32))
        elif "audio" in ex:
            feats.append(log_mel_spectrogram(ex["audio"], num_mel_bins=acfg.num_mel_bins))
        per_ex_feats.append(feats)
    _check_uniform_media([len(f) for f in per_ex_feats], "audio clips")
    _check_uniform_media(
        [f.shape[1] for feats in per_ex_feats for f in feats] or [0], "audio frames"
    )
    all_feats = [f for feats in per_ex_feats for f in feats]
    audio_inputs = model.prepare_audio_inputs(all_feats) if all_feats else None

    # one audio placeholder token per valid output frame of the audio tower
    # (the sum over chunks of the 3x-conv downsampled valid lengths)
    def _n_tokens(mel: np.ndarray) -> int:
        C = acfg.chunk_len
        T = mel.shape[1]
        return sum(
            _conv_out_len(min(C, T - ci * C)) for ci in range(math.ceil(T / C))
        )

    # ---- images (same path as qwen_vl_collate)
    per_ex_imgs = [
        ex.get("images", [ex["image"]] if "image" in ex else []) for ex in examples
    ]
    _check_uniform_media([len(i) for i in per_ex_imgs], "images")
    if image_size is None:
        gh = gw = max(vis.spatial_merge_size * 4, 8)
    else:
        gh, gw = image_size
    ms = vis.spatial_merge_size
    n_merged = (gh // ms) * (gw // ms)
    vstart = getattr(cfg, "vision_start_token_id", None)
    img_span = [cfg.image_token_id] * n_merged
    if vstart is not None:
        img_span = [vstart] + img_span

    per_ex_spans = [
        {
            AUDIO_PLACEHOLDER: [[cfg.audio_token_id] * _n_tokens(f) for f in feats],
            IMAGE_PLACEHOLDER: [img_span] * len(imgs),
        }
        for feats, imgs in zip(per_ex_feats, per_ex_imgs)
    ]
    input_ids, labels, positions, segment_ids = _text_batch(
        examples, tokenizer, seq_len, pad_token_id, per_ex_spans
    )
    batch = {
        "input_ids": input_ids,
        "labels": labels,
        "positions": positions,
        "segment_ids": segment_ids,
    }
    patches = [
        qwen_patchify(
            img, patch_size=vis.patch_size, merge_size=ms,
            temporal_patch_size=vis.temporal_patch_size, grid_h=gh, grid_w=gw,
        )
        for imgs in per_ex_imgs for img in imgs
    ]
    grids = np.asarray([[1, gh, gw]] * len(patches), np.int64)
    if patches:
        vb, vs = model.visual_token_coords(input_ids)
        batch |= {
            "pixel_values": np.concatenate(patches, 0).astype(np.float32),
            "vision_inputs": model.prepare_vision_inputs(grids),
            "visual_coords_b": vb,
            "visual_coords_s": vs,
        }
    if audio_inputs is not None:
        ab, as_ = model.audio_token_coords(input_ids)
        batch |= {
            "audio_chunks": audio_inputs.pop("chunks"),
            "audio_inputs": audio_inputs,
            "audio_coords_b": ab,
            "audio_coords_s": as_,
        }
    if patches or audio_inputs is not None:
        batch["positions3"] = np.asarray(model.get_mrope_positions(input_ids, grids))
    return batch


def phi4_mm_collate(
    examples: Sequence[Mapping[str, Any]],
    tokenizer,
    seq_len: int,
    pad_token_id: int = 0,
    *,
    audio_token_id: int,
    num_mel_bins: int = 80,
    compression_rate: int = 8,
    qformer_compression_rate: int = 1,
) -> dict[str, np.ndarray]:
    """Phi-4-multimodal audio collate (reference collate_fns.py:148 phi4_mm path).

    The reference hands text+audio to the HF Phi4MM processor; here the audio is
    featurized host-side (``log_mel_spectrogram``) and each ``<audio>``
    placeholder expands to the number of post-encoder embedding slots HF's
    ``_compute_audio_embed_size`` would produce: mel frames compressed by
    ``compression_rate`` then ``qformer_compression_rate`` (both ceil-divided).
    Examples carry "audio" (16kHz waveform) or "audio_features" ((mel, T)), plus
    the prompt/answer or messages text keys shared with the other collators.

    Returns input_ids/labels/positions/segment_ids plus ``audio_features``
    (clips, mel, T_max), ``audio_frames`` (true frame counts), and the
    placeholder coordinates (``audio_coords_b/s``) for embedding merge.
    """
    per_ex_feats: list[list[np.ndarray]] = []
    for ex in examples:
        feats = []
        if "audio_features" in ex:
            feats.append(np.asarray(ex["audio_features"], np.float32))
        elif "audio" in ex:
            feats.append(log_mel_spectrogram(ex["audio"], num_mel_bins=num_mel_bins))
        per_ex_feats.append(feats)
    _check_uniform_media([len(f) for f in per_ex_feats], "audio clips")

    def _n_tokens(mel: np.ndarray) -> int:
        t = -(-mel.shape[1] // compression_rate)
        return -(-t // qformer_compression_rate)

    per_ex_spans = [
        {AUDIO_PLACEHOLDER: [[audio_token_id] * _n_tokens(f) for f in feats]}
        for feats in per_ex_feats
    ]
    input_ids, labels, positions, segment_ids = _text_batch(
        examples, tokenizer, seq_len, pad_token_id, per_ex_spans
    )
    batch = {
        "input_ids": input_ids,
        "labels": labels,
        "positions": positions,
        "segment_ids": segment_ids,
    }
    all_feats = [f for feats in per_ex_feats for f in feats]
    if all_feats:
        t_max = max(f.shape[1] for f in all_feats)
        padded = np.zeros((len(all_feats), num_mel_bins, t_max), np.float32)
        for i, f in enumerate(all_feats):
            padded[i, :, : f.shape[1]] = f
        ab, as_ = np.nonzero(input_ids == audio_token_id)
        batch |= {
            "audio_features": padded,
            "audio_frames": np.asarray([f.shape[1] for f in all_feats], np.int32),
            "audio_coords_b": ab.astype(np.int32),
            "audio_coords_s": as_.astype(np.int32),
        }
    return batch
