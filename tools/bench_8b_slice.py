"""8B-geometry layer-slice microbench (VERDICT r4 weak #3): full Llama-3-8B
can't train in bf16 on one 16GB chip, so the README's north-star #1 number is
a FLOPs-ratio extrapolation from the 1B proxy that ASSUMES MFU holds at 8B
geometry. This pins that assumption: a 4-layer slice with the exact 8B layer
dims (hidden 4096, inter 14336, 32 q / 8 kv heads, head_dim 128, vocab
128256) trains at seq 4096 on-chip, and its measured MFU is compared to the
1B bench's. Layer math dominates (the embed/head share is scaled out in the
FLOPs count), so slice MFU ~ full-model MFU at this geometry.

Run: PYTHONPATH=/root/repo:/root/.axon_site python tools/bench_8b_slice.py
"""

from __future__ import annotations

import json


def main():
    import jax

    from bench import _measure, device_peak_tflops, llama_flops_per_token
    from automodel_tpu.models.llama.model import LlamaConfig

    # exact 8B layer geometry, 4-layer slice, tied head to fit 16GB
    cfg = LlamaConfig(
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=4,
        num_attention_heads=32,
        num_key_value_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        tie_word_embeddings=True,
        max_position_embeddings=131072,
    )
    tps = _measure(cfg, seq_len=4096, micro_batch=1, n_steps=10)

    device = str(jax.devices()[0])
    peak = device_peak_tflops(device)
    f_tok = llama_flops_per_token(cfg, 4096)
    mfu = tps * f_tok / 1e12 / peak
    # the extrapolation target: full 8B at the slice's MFU
    cfg8b = LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        head_dim=128,
    )
    f_8b = llama_flops_per_token(cfg8b, 4096)
    print(json.dumps({
        "metric": "llama-8B-geometry 4-layer slice (bf16, seq 4096)",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "extra": {
            "slice_mfu": round(mfu, 4),
            "model_tflops_per_sec": round(tps * f_tok / 1e12, 1),
            "implied_8b_tokens_per_sec": round(mfu * peak * 1e12 / f_8b, 1),
            "assumed_peak_tflops": peak,
            "device": device,
        },
    }))


if __name__ == "__main__":
    main()
