"""OOM flight recorder: turn a bare RESOURCE_EXHAUSTED into a crash artifact.

An OOM on a long multi-host run is the most expensive kind of failure to
debug from nothing: the process dies with one allocator line, the buffers
are gone, and the next attempt costs a full requeue. The recorder keeps the
cheap context continuously (a ring of the last N metric rows) and harvests
the expensive context at the moment of death — the live-buffer inventory
from ``jax.live_arrays()`` grouped by (shape, dtype, sharding), per-device
allocator counters, and the memory plan — into ``out_dir/oom_report.json``,
then the train loop re-raises so orchestration still sees the failure.

The report answers the three OOM questions without a repro: *what was
resident* (inventory: is it params, optimizer moments, a leaked eval batch,
double-buffered stacks?), *what was the budget* (memory plan + bytes_limit),
and *what was the run doing* (last rows: was step time or hbm_gib_peak
creeping before the kill?).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import Any

logger = logging.getLogger(__name__)

__all__ = ["is_oom_error", "live_buffer_inventory", "OOMFlightRecorder"]

# substrings that mark an allocator exhaustion across backends/versions:
# XLA status code ("RESOURCE_EXHAUSTED: Out of memory allocating ..."),
# the TPU runtime's phrasing, and the BFC allocator's.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def is_oom_error(exc: BaseException) -> bool:
    """True when ``exc`` (or anything on its cause/context chain) is an
    allocator exhaustion. Matching is textual — jaxlib raises one
    ``XlaRuntimeError`` type for every status code."""
    seen: set[int] = set()
    node: BaseException | None = exc
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        text = f"{type(node).__name__}: {node}"
        if any(marker in text for marker in _OOM_MARKERS):
            return True
        node = node.__cause__ or node.__context__
    return False


def live_buffer_inventory(max_groups: int = 50) -> dict[str, Any]:
    """Group ``jax.live_arrays()`` by (shape, dtype, sharding) — the census
    of what was resident when the allocator gave up.

    Per-group: count, per-device shard bytes, and the group's total GiB
    (count x shard bytes — the device-local footprint). Sorted by total
    descending and truncated to ``max_groups`` with the tail summarized, so
    a run with thousands of small buffers still produces a readable report.
    """
    import jax

    from automodel_tpu.observability.memory_plan import _leaf_shard_bytes

    groups: dict[tuple, dict[str, Any]] = {}
    n_arrays = 0
    for arr in jax.live_arrays():
        try:
            shape = tuple(int(d) for d in arr.shape)
            dtype = str(arr.dtype)
            sharding = str(getattr(arr, "sharding", None))
            shard_bytes = _leaf_shard_bytes(arr)
        except Exception:  # a deleted/donated buffer mid-iteration
            continue
        n_arrays += 1
        g = groups.setdefault((shape, dtype, sharding), {
            "shape": list(shape), "dtype": dtype, "sharding": sharding,
            "count": 0, "shard_bytes": shard_bytes,
        })
        g["count"] += 1
    rows = sorted(groups.values(),
                  key=lambda g: g["count"] * g["shard_bytes"], reverse=True)
    for g in rows:
        g["total_gib"] = round(g["count"] * g["shard_bytes"] / 2**30, 6)
    kept, tail = rows[:max_groups], rows[max_groups:]
    out: dict[str, Any] = {
        "live_arrays": n_arrays,
        "groups": kept,
        "total_gib": round(sum(g["count"] * g["shard_bytes"] for g in rows) / 2**30, 6),
    }
    if tail:
        out["truncated_groups"] = len(tail)
        out["truncated_gib"] = round(
            sum(g["count"] * g["shard_bytes"] for g in tail) / 2**30, 6)
    return out


def _per_device_stats() -> list[dict[str, Any]]:
    import jax

    out = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        out.append({
            "id": int(d.id),
            "kind": str(d.device_kind),
            "stats": {k: int(v) for k, v in (stats or {}).items()
                      if isinstance(v, (int, float))},
        })
    return out


class OOMFlightRecorder:
    """Continuously cheap, expensive only at the crash.

    ``record_row`` costs one deque append per log step; ``dump`` walks the
    live buffers exactly once, when the run is already dead. ``dump`` never
    raises — a failure to write the report must not mask the original OOM.
    """

    def __init__(self, out_dir: str, keep_rows: int = 20):
        self.out_dir = str(out_dir)
        self.report_path = os.path.join(self.out_dir, "oom_report.json")
        self._rows: collections.deque = collections.deque(maxlen=max(int(keep_rows), 1))
        self._plan_row: dict[str, Any] | None = None

    def set_plan_row(self, row: dict[str, Any] | None) -> None:
        """The mem_plan/* header keys, carried into any future report."""
        self._plan_row = dict(row) if row else None

    def record_row(self, step: int, row: dict[str, Any]) -> None:
        """Ring-buffer one metric row (the same dict the loggers saw)."""
        self._rows.append({"step": int(step), **row})

    def dump(self, exc: BaseException, step: int | None = None) -> str | None:
        """Write ``oom_report.json``; returns its path, or None on failure."""
        try:
            report: dict[str, Any] = {
                "oom_report": True,
                "time_unix": time.time(),
                "step": step,
                "error": {"type": type(exc).__name__, "message": str(exc)[:4000]},
                "memory_plan": self._plan_row or {},
                "devices": _per_device_stats(),
                "live_buffers": live_buffer_inventory(),
                "last_rows": list(self._rows),
            }
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = f"{self.report_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, self.report_path)
            logger.error("OOM flight recorder: report written to %s", self.report_path)
            return self.report_path
        except Exception:
            logger.exception("OOM flight recorder failed (original error re-raised)")
            return None
