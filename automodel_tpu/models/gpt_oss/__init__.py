from automodel_tpu.models.gpt_oss.model import GptOssConfig, GptOssForCausalLM

__all__ = ["GptOssConfig", "GptOssForCausalLM"]
