"""NemotronParse: decoder parity vs HF MBartDecoder (positional embeddings zeroed —
the reference's decoder drops them, nemotron_parse/model.py:212-243), neck parity vs
torch convs, adapter round-trip, grads."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.nemotron_parse.model import (
    NemotronParseConfig,
    NemotronParseForConditionalGeneration,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from transformers.models.mbart.modeling_mbart import MBartConfig, MBartDecoder


def _cfg(**kw):
    base = dict(
        vocab_size=128, d_model=64, decoder_layers=2, decoder_attention_heads=4,
        decoder_ffn_dim=96, radio_feature_dim=40, radio_summary_dim=80, neck_dim=64,
    )
    base.update(kw)
    return NemotronParseConfig(**base)


def _fp32_backend():
    return BackendConfig(dtype="float32", remat_policy="full")


def _hf_decoder(cfg):
    hf_cfg = MBartConfig(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        decoder_layers=cfg.decoder_layers,
        decoder_attention_heads=cfg.decoder_attention_heads,
        decoder_ffn_dim=cfg.decoder_ffn_dim, scale_embedding=cfg.scale_embedding,
        activation_function="gelu", dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, decoder_layerdrop=0.0, max_position_embeddings=64,
    )
    dec = MBartDecoder(hf_cfg).eval()
    with torch.no_grad():
        dec.embed_positions.weight.zero_()  # reference decoder has no pos embeddings
    return dec


def _load_from_hf_decoder(model, dec):
    """Map the HF MBartDecoder state dict through our adapter (decoder.* prefix)."""
    sd = {f"decoder.{k}": v.numpy() for k, v in dec.state_dict().items()
          if "embed_positions" not in k}
    # adapter also expects lm_head + neck keys; synthesize them
    cfg = model.config
    rng = np.random.RandomState(0)
    sd["lm_head.weight"] = rng.randn(cfg.vocab_size, cfg.d_model).astype(np.float32) * 0.02
    sd["encoder.conv1.weight"] = rng.randn(cfg.neck_dim, cfg.radio_feature_dim, 1).astype(np.float32) * 0.02
    sd["encoder.conv1.bias"] = np.zeros(cfg.neck_dim, np.float32)
    sd["encoder.conv2.weight"] = rng.randn(cfg.neck_dim, cfg.neck_dim, 1, 4).astype(np.float32) * 0.02
    for j in (1, 2, 3):
        sd[f"encoder.layer_norm{j}.weight"] = np.ones(cfg.neck_dim, np.float32)
        sd[f"encoder.layer_norm{j}.bias"] = np.zeros(cfg.neck_dim, np.float32)
    sd["encoder.sum_proj.weight"] = rng.randn(cfg.neck_dim, cfg.radio_summary_dim).astype(np.float32) * 0.02
    sd["encoder.sum_proj.bias"] = np.zeros(cfg.neck_dim, np.float32)
    return model.state_dict_adapter().from_hf(sd), sd


class TestDecoderParity:
    def test_matches_hf_mbart_decoder(self):
        torch.manual_seed(0)
        cfg = _cfg()
        model = NemotronParseForConditionalGeneration(cfg, _fp32_backend())
        dec = _hf_decoder(cfg)
        params, _ = _load_from_hf_decoder(model, dec)

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 12))
        enc = rng.randn(2, 9, cfg.d_model).astype(np.float32)
        with torch.no_grad():
            theirs = dec(
                input_ids=torch.tensor(ids),
                encoder_hidden_states=torch.tensor(enc),
            ).last_hidden_state.numpy()
        hidden, _ = model(
            params, jnp.asarray(ids), encoder_hidden_states=jnp.asarray(enc), training=False,
        )
        # compare pre-lm_head hidden: project back via lm_head pinv? simpler:
        # run our forward and theirs through the SAME lm_head
        ours_logits = np.asarray(hidden)
        theirs_logits = theirs @ np.asarray(params["lm_head"])
        np.testing.assert_allclose(ours_logits, theirs_logits, atol=2e-4, rtol=1e-3)

    def test_neck_matches_torch_convs(self):
        cfg = _cfg()
        model = NemotronParseForConditionalGeneration(cfg, _fp32_backend())
        dec = _hf_decoder(cfg)
        params, sd = _load_from_hf_decoder(model, dec)

        rng = np.random.RandomState(1)
        h, w = 2, 8
        feats = rng.randn(2, h * w, cfg.radio_feature_dim).astype(np.float32)
        summary = rng.randn(2, cfg.radio_summary_dim).astype(np.float32)

        # torch reference (RadioWithNeck math, reference model.py:387-407)
        t = torch.tensor
        x = torch.nn.functional.conv1d(t(feats).permute(0, 2, 1), t(sd["encoder.conv1.weight"]),
                                       t(sd["encoder.conv1.bias"])).permute(0, 2, 1)
        x = torch.nn.functional.layer_norm(x, (cfg.neck_dim,), eps=1e-6)
        x = x.permute(0, 2, 1).reshape(2, cfg.neck_dim, h, w)
        x = torch.nn.functional.conv2d(x, t(sd["encoder.conv2.weight"]), stride=(1, 4))
        x = x.flatten(2).permute(0, 2, 1)
        x = torch.nn.functional.layer_norm(x, (cfg.neck_dim,), eps=1e-6)
        s = t(summary) @ t(sd["encoder.sum_proj.weight"]).T + t(sd["encoder.sum_proj.bias"])
        s = torch.nn.functional.layer_norm(s, (cfg.neck_dim,), eps=1e-6)
        ref = torch.cat([x, s[:, None]], dim=1).numpy()

        ours = np.asarray(model.encode(params, jnp.asarray(feats), jnp.asarray(summary), (h, w)))
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_end_to_end_and_grads(self):
        cfg = _cfg()
        model = NemotronParseForConditionalGeneration(cfg, _fp32_backend())
        params = model.init(jax.random.key(0), jnp.float32)
        rng = np.random.RandomState(2)
        labels = rng.randint(0, 128, (2, 10))
        dec_in = jnp.asarray(cfg.shift_tokens_right(labels))
        feats = jnp.asarray(rng.randn(2, 16, cfg.radio_feature_dim).astype(np.float32))
        summary = jnp.asarray(rng.randn(2, cfg.radio_summary_dim).astype(np.float32))

        def loss_fn(p):
            logits, _ = model(p, dec_in, encoder_features=feats, summary=summary,
                              grid_hw=(2, 8), training=True)
            ll = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.take_along_axis(ll, jnp.asarray(labels)[..., None], -1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(np.asarray(g))) for g in jax.tree.leaves(grads))
        assert np.abs(np.asarray(grads["neck"]["conv2_w"])).max() > 0

    def test_adapter_roundtrip(self):
        cfg = _cfg()
        model = NemotronParseForConditionalGeneration(cfg, _fp32_backend())
        params = model.init(jax.random.key(1), jnp.float32)
        adapter = model.state_dict_adapter()
        hf = adapter.to_hf(params)
        assert "decoder.layers.0.encoder_attn.k_proj.weight" in hf
        assert "encoder.conv2.weight" in hf
        assert hf["encoder.conv2.weight"].shape == (cfg.neck_dim, cfg.neck_dim, 1, 4)
        back = adapter.from_hf(hf)
        flat_a, flat_b = jax.tree.leaves(params), jax.tree.leaves(back)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_causality(self):
        cfg = _cfg()
        model = NemotronParseForConditionalGeneration(cfg, _fp32_backend())
        params = model.init(jax.random.key(2), jnp.float32)
        rng = np.random.RandomState(3)
        ids = jnp.asarray(rng.randint(0, 128, (1, 12)))
        enc = jnp.asarray(rng.randn(1, 5, cfg.d_model).astype(np.float32))
        a, _ = model(params, ids, encoder_hidden_states=enc, training=False)
        ids2 = ids.at[0, 8:].set((ids[0, 8:] + 1) % 128)
        b, _ = model(params, ids2, encoder_hidden_states=enc, training=False)
        np.testing.assert_allclose(np.asarray(a[0, :8]), np.asarray(b[0, :8]), atol=1e-5)
