"""LLaVA-style image-text-to-text model (the VLM composition pattern the reference
serves through NeMoAutoModelForImageTextToText, _transformers/auto_model.py:614).

CLIP vision tower -> 2-layer GELU projector -> any causal decoder. Image features
replace the embedding rows whose token id equals ``image_token_index`` (HF LLaVA
merge semantics) — implemented with a static-shape gather: every sample must carry
exactly ``num_image_tokens`` placeholders (the collator guarantees it).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM
from automodel_tpu.models.vision.clip_vit import CLIPVisionConfig, CLIPVisionTower

__all__ = ["LlavaConfig", "LlavaForConditionalGeneration"]


@dataclasses.dataclass
class LlavaConfig:
    vision: CLIPVisionConfig
    text: LlamaConfig
    image_token_index: int = 32000
    vision_feature_layer: int = -2
    vision_feature_select_strategy: str = "default"  # "default" drops CLS
    projector_hidden_act: str = "gelu"

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "LlavaConfig":
        return cls(
            vision=CLIPVisionConfig.from_hf(hf["vision_config"]),
            text=LlamaConfig.from_hf(hf["text_config"]),
            image_token_index=hf.get("image_token_index", 32000),
            vision_feature_layer=hf.get("vision_feature_layer", -2),
            vision_feature_select_strategy=hf.get("vision_feature_select_strategy", "default"),
            projector_hidden_act=hf.get("projector_hidden_act", "gelu"),
        )

    @property
    def num_image_tokens(self) -> int:
        n = self.vision.num_patches
        return n if self.vision_feature_select_strategy == "default" else n + 1


class LlavaForConditionalGeneration:
    config_class = LlavaConfig
    hf_architectures = ("LlavaForConditionalGeneration",)

    def __init__(self, config: LlavaConfig, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()
        self.vision_tower = CLIPVisionTower(config.vision, self.backend)
        self.language_model = LlamaForCausalLM(config.text, self.backend)

    # -- params -------------------------------------------------------------
    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        kv, kp, kt = jax.random.split(key, 3)
        dv, dt = self.config.vision.hidden_size, self.config.text.hidden_size
        std = self.config.text.initializer_range
        return {
            "vision_tower": self.vision_tower.init(kv, dtype),
            "projector": {
                "linear_1": (jax.random.normal(kp, (dv, dt), jnp.float32) * std).astype(dtype),
                "linear_1_b": jnp.zeros((dt,), dtype),
                "linear_2": (jax.random.normal(jax.random.fold_in(kp, 1), (dt, dt), jnp.float32) * std).astype(dtype),
                "linear_2_b": jnp.zeros((dt,), dtype),
            },
            "language_model": self.language_model.init(kt, dtype),
        }

    def logical_axes(self) -> dict:
        return {
            "vision_tower": self.vision_tower.logical_axes(),
            "projector": {
                "linear_1": (None, "embed"), "linear_1_b": ("embed",),
                "linear_2": ("embed", "embed"), "linear_2_b": ("embed",),
            },
            "language_model": self.language_model.logical_axes(),
        }

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    # -- forward ------------------------------------------------------------
    def image_features(self, params, pixel_values: jnp.ndarray) -> jnp.ndarray:
        """(B, 3, H, W) -> (B, num_image_tokens, D_text)."""
        cfg = self.config
        feats = self.vision_tower(
            params["vision_tower"], pixel_values, feature_layer=cfg.vision_feature_layer
        )
        if cfg.vision_feature_select_strategy == "default":
            feats = feats[:, 1:]  # drop CLS
        p = params["projector"]
        dtype = self.backend.jnp_dtype
        x = feats @ p["linear_1"].astype(dtype) + p["linear_1_b"].astype(dtype)
        x = jax.nn.gelu(x, approximate=False)
        return x @ p["linear_2"].astype(dtype) + p["linear_2_b"].astype(dtype)

    def merged_embeds(self, params, input_ids, pixel_values=None):
        """Token embeddings with image placeholders swapped for projected vision
        features (B, S, D) — the prefill input for generation."""
        cfg = self.config
        lm_params = params["language_model"]
        dtype = self.backend.jnp_dtype
        embeds = lm_params["embed"].astype(dtype)[input_ids]
        if pixel_values is not None:
            feats = self.image_features(params, pixel_values)  # (B, P, D)
            mask = input_ids == cfg.image_token_index  # (B, S)
            # static-shape merge: k-th placeholder in a row takes feats[b, k]
            idx = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, feats.shape[1] - 1)
            gathered = jnp.take_along_axis(feats, idx[..., None], axis=1)
            embeds = jnp.where(mask[..., None], gathered.astype(dtype), embeds)
        return embeds

    def __call__(self, params, input_ids, pixel_values=None, positions=None,
                 segment_ids=None, rules=None, return_hidden=False, cache=None,
                 inputs_embeds=None):
        cfg = self.config
        if inputs_embeds is None:
            inputs_embeds = self.merged_embeds(params, input_ids, pixel_values)
        from automodel_tpu.models.common.transformer import decoder_forward

        return decoder_forward(
            cfg.text, self.backend, params["language_model"], input_ids,
            positions=positions, segment_ids=segment_ids, rules=rules,
            return_hidden=return_hidden, inputs_embeds=inputs_embeds, cache=cache,
        )

    def generate(self, params, input_ids, pixel_values=None, **kw):
        """Image-conditioned sampling: vision features merge into the prompt's
        prefill embeddings, decode is the plain text KV-cache loop (the
        reference's vlm_generate example does the same through HF .generate)."""
        from automodel_tpu.generation import generate

        embeds = None
        if pixel_values is not None:
            embeds = self.merged_embeds(params, jnp.asarray(input_ids, jnp.int32),
                                        pixel_values)
        return generate(self, params, input_ids, inputs_embeds=embeds,
                        decode_config=self.config.text, **kw)

    # -- HF interop ---------------------------------------------------------
    def state_dict_adapter(self):
        from automodel_tpu.models.llava.state_dict_adapter import LlavaStateDictAdapter

        return LlavaStateDictAdapter(self.config, self.backend.scan_layers)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = LlavaConfig.from_hf(config)
        return cls(config, backend)
