"""HF Hub resolution: accept ``org/name`` repo ids anywhere a local HF
directory is accepted (reference pre-downloads on rank 0,
_transformers/model_init.py:194, so ``pretrained_model_name_or_path:
meta-llama/Llama-3.2-1B`` just works day-0).

Multi-host protocol: process 0 downloads first while every other process
waits at a cross-host barrier, then the others resolve — a no-op cache hit
when the HF cache is on a shared filesystem, an uncontended per-host download
when it is not (TPU pods usually have per-host local disk; either topology
works, and the barrier prevents N processes thundering the Hub for the same
blobs)."""

from __future__ import annotations

import logging
import os
import re

logger = logging.getLogger(__name__)

__all__ = ["resolve_pretrained_path", "looks_like_repo_id"]

# org/name or bare name: hub id segments are [\w.-]+, at most one slash, and a
# path that exists on disk always wins over the hub interpretation
_REPO_ID_RE = re.compile(r"^[A-Za-z0-9][\w.-]*(/[\w.-]+)?$")

# config + weights + tokenizer assets; skips .bin/.pt duplicates, images, etc.
_DEFAULT_PATTERNS = ("*.json", "*.safetensors", "*.model", "*.txt",
                     "tokenizer*", "*.tiktoken")
# tokenizer-only resolution must not pull the weight shards
TOKENIZER_PATTERNS = ("*.json", "*.model", "*.txt", "tokenizer*", "*.tiktoken")


def looks_like_repo_id(path_or_id: str) -> bool:
    return bool(_REPO_ID_RE.match(path_or_id)) and not os.path.exists(path_or_id)


def resolve_pretrained_path(path_or_id: str, *, revision: str | None = None,
                            allow_patterns=_DEFAULT_PATTERNS) -> str:
    """Local directory -> itself; HF repo id -> local snapshot directory."""
    if os.path.isdir(path_or_id):
        return path_or_id
    if not looks_like_repo_id(path_or_id):
        raise FileNotFoundError(
            f"{path_or_id!r} is neither a local HF model directory nor a "
            "hub repo id (expected 'org/name')"
        )
    return _download(path_or_id, revision=revision, allow_patterns=allow_patterns)


def _snapshot_download(repo_id: str, revision=None, allow_patterns=None) -> str:
    try:
        from huggingface_hub import snapshot_download
    except ImportError as exc:  # pragma: no cover - hub ships with transformers
        raise ImportError(
            f"loading {repo_id!r} from the HF Hub needs huggingface_hub; "
            "pass a local directory instead"
        ) from exc
    return snapshot_download(repo_id, revision=revision, allow_patterns=allow_patterns)


def _download(repo_id: str, *, revision, allow_patterns) -> str:
    idx, n_proc = _process_topology()
    fetch = lambda: _snapshot_download(  # noqa: E731
        repo_id, revision=revision, allow_patterns=allow_patterns
    )
    if n_proc == 1:
        return fetch()
    if idx == 0:
        logger.info("process 0 downloading %s from the HF Hub", repo_id)
        try:
            return fetch()
        finally:
            # reach the barrier even when the download raises (404/auth/
            # network): otherwise every other process hangs in
            # sync_global_devices until the coordination timeout instead of
            # the job surfacing process 0's clean exception
            _barrier(f"hub_download:{repo_id}")
    _barrier(f"hub_download:{repo_id}")
    return fetch()  # cache hit on shared fs; per-host fetch otherwise


def _process_topology() -> tuple[int, int]:
    import jax

    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:  # backend not initialized (e.g. pure-host tooling)
        return 0, 1


def _barrier(name: str) -> None:
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
