"""HF-checkpoint <-> param-pytree state-dict adapters (reference per-family
state_dict_adapter.py files + checkpoint/state_dict_adapter.py).

This is the day-0 HF value proposition: read HF safetensors into our stacked,
sharding-friendly layout, and write checkpoints back out HF-loadable. Adapters are
declarative tables of :class:`Entry` — an HF key template, a dotted path into the
param tree, and a pair of transforms — so new families are data, not code.

Transforms run in numpy on one tensor at a time (host RAM bounded by the largest
tensor, not the model), and layer stacking/unstacking happens here so models always
see the scan-ready (L, ...) layout.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Mapping

import numpy as np

__all__ = ["Entry", "MappingAdapter", "get_path", "set_path"]

Transform = Callable[[np.ndarray], np.ndarray]


def _identity(x: np.ndarray) -> np.ndarray:
    return x


@dataclasses.dataclass
class Entry:
    """One or more HF tensors -> one (possibly per-layer/per-expert) tree slot.

    ``hf`` may be a tuple of key templates: the tensors are passed together to
    ``to_ours(*arrays)`` (e.g. merging HF gate_proj + up_proj into one gate_up array),
    and ``to_hf`` must return a matching tuple. ``{i}`` expands over layers (within
    ``layer_range`` when set), ``{e}`` over experts; expert-stacked entries produce an
    extra leading E dim under the layer dim (the reference's MoE expert split/merge,
    moe/state_dict_mixin.py).
    """

    hf: str | tuple[str, ...]  # e.g. "model.layers.{i}.self_attn.q_proj.weight"
    ours: str  # e.g. "layers.wq"
    to_ours: Transform = _identity
    to_hf: Transform = _identity
    optional: bool = False
    layer_range: tuple[int, int] | None = None  # [start, stop) HF layer indices
    keep_dtype: bool = False  # exempt from the load-time cast (e.g. fp32 routing bias)
    # explicit HF layer indices for strided stacking (hybrid models whose layer streams
    # interleave, e.g. Qwen3-Next linear/full attention); overrides layer_range
    layer_indices: tuple[int, ...] | None = None

    @property
    def hf_keys(self) -> tuple[str, ...]:
        return (self.hf,) if isinstance(self.hf, str) else tuple(self.hf)

    @property
    def per_layer(self) -> bool:
        return "{i}" in self.hf_keys[0]

    @property
    def per_expert(self) -> bool:
        return "{e}" in self.hf_keys[0]


def get_path(tree: dict, path: str) -> Any:
    node = tree
    for part in path.split("."):
        node = node[part]
    return node


def set_path(tree: dict, path: str, value: Any) -> None:
    parts = path.split(".")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


class MappingAdapter:
    """Applies an Entry table in either direction, handling layer/expert stacking."""

    def __init__(
        self,
        entries: Iterable[Entry],
        num_layers: int,
        scan_layers: bool = True,
        num_experts: int = 0,
    ):
        self.entries = list(entries)
        self.num_layers = num_layers
        self.scan_layers = scan_layers
        self.num_experts = num_experts

    def _layers(self, e: Entry):
        if e.layer_indices is not None:
            return e.layer_indices
        if e.layer_range is not None:
            return range(*e.layer_range)
        return range(self.num_layers)

    def _load_one(self, entry: Entry, tensors: Mapping[str, np.ndarray], **fmt) -> np.ndarray | None:
        arrays = []
        for tmpl in entry.hf_keys:
            key = tmpl.format(**fmt)
            if key not in tensors:
                if entry.optional:
                    return None
                raise KeyError(f"missing tensor {key!r} in checkpoint")
            arrays.append(np.asarray(tensors[key]))
        return entry.to_ours(*arrays)

    def from_hf(self, tensors: Mapping[str, np.ndarray], dtype=None) -> dict:
        """HF flat dict -> our nested param tree (layers/experts stacked)."""
        params: dict = {}
        for e in self.entries:
            if e.per_layer:
                per = []
                for i in self._layers(e):
                    if e.per_expert:
                        experts = [
                            self._load_one(e, tensors, i=i, e=x) for x in range(self.num_experts)
                        ]
                        layer = None if any(a is None for a in experts) else np.stack(experts, axis=0)
                    else:
                        layer = self._load_one(e, tensors, i=i)
                    if layer is None:
                        break
                    per.append(layer)
                else:
                    # models consume the stacked (L, ...) layout whether or not they scan
                    stacked = np.stack(per, axis=0)
                    cast = dtype if not e.keep_dtype else None
                    set_path(params, e.ours, stacked if cast is None else stacked.astype(cast))
            else:
                t = self._load_one(e, tensors)
                if t is not None:
                    cast = dtype if not e.keep_dtype else None
                    set_path(params, e.ours, t if cast is None else t.astype(cast))
        return params

    def _store_one(self, entry: Entry, value: np.ndarray, out: dict, dtype, **fmt) -> None:
        results = entry.to_hf(value)
        if isinstance(results, np.ndarray):
            results = (results,)
        cast = dtype if not entry.keep_dtype else None
        for tmpl, t in zip(entry.hf_keys, results, strict=True):
            out[tmpl.format(**fmt)] = t if cast is None else t.astype(cast)

    def to_hf(self, params: dict, dtype=None) -> dict[str, np.ndarray]:
        """Our param tree -> HF flat dict (unstacking layers/experts)."""
        out: dict[str, np.ndarray] = {}
        for e in self.entries:
            try:
                value = get_path(params, e.ours)
            except KeyError:
                if e.optional:
                    continue
                raise
            value = np.asarray(value)
            if e.per_layer:
                for li, i in enumerate(self._layers(e)):
                    if e.per_expert:
                        for x in range(self.num_experts):
                            self._store_one(e, value[li, x], out, dtype, i=i, e=x)
                    else:
                        self._store_one(e, value[li], out, dtype, i=i)
            else:
                self._store_one(e, value, out, dtype)
        return out

    def to_hf_lazy(self, params: dict, dtype=None, host_fn=None) -> "dict[str, LazyHFTensor]":
        """Our param tree -> flat dict of DEFERRED HF tensors.

        Nothing is gathered here: each value is a :class:`LazyHFTensor` that
        pulls ONE layer/expert slice to host (via ``host_fn``, e.g. a multihost
        allgather) and applies the Entry transform only when materialized — the
        streaming-export contract (reference consolidate_hf_safetensors.py
        holds at most one tensor in flight the same way). Under a multi-host
        mesh ``host_fn`` is collective, so every process must materialize the
        mapping's values in the SAME order (the safetensors writer does).
        ``params`` leaves may be live (sharded) jax arrays."""
        host_fn = host_fn if host_fn is not None else np.asarray
        # one-slot memo: tuple-key entries (e.g. gate+up merged) produce several
        # HF tensors from one transform; adjacent consumption hits the memo
        # instead of re-gathering and re-transforming per key
        memo: dict = {"tag": None, "results": None}

        def make(e: Entry, slicer, cast, key_idx, n_keys, tag):
            def thunk():
                if memo["tag"] != tag:
                    arr = host_fn(slicer())
                    results = e.to_hf(np.asarray(arr))
                    if isinstance(results, np.ndarray):
                        results = (results,)
                    memo["tag"], memo["results"] = tag, results
                t = memo["results"][key_idx]
                return t if cast is None else t.astype(cast)

            return thunk

        out: dict[str, LazyHFTensor] = {}
        for e in self.entries:
            try:
                value = get_path(params, e.ours)
            except KeyError:
                if e.optional:
                    continue
                raise
            cast = dtype if not e.keep_dtype else None
            n_keys = len(e.hf_keys)
            itemsize = np.dtype(cast).itemsize if cast is not None else (
                np.dtype(value.dtype).itemsize)

            def add(slicer, slice_size, tag, **fmt):
                nbytes = (slice_size * itemsize) // n_keys  # shard-planning estimate
                for key_idx, tmpl in enumerate(e.hf_keys):
                    out[tmpl.format(**fmt)] = LazyHFTensor(
                        make(e, slicer, cast, key_idx, n_keys, tag), nbytes
                    )

            if e.per_layer:
                per_layer_size = int(np.prod(value.shape[1:]))
                for li, i in enumerate(self._layers(e)):
                    if e.per_expert:
                        for x in range(self.num_experts):
                            add((lambda v=value, a=li, b=x: v[a, b]),
                                per_layer_size // self.num_experts,
                                (id(e), li, x), i=i, e=x)
                    else:
                        add((lambda v=value, a=li: v[a]), per_layer_size,
                            (id(e), li), i=i)
            else:
                add((lambda v=value: v), int(np.prod(value.shape)), (id(e),))
        return out


class LazyHFTensor:
    """A deferred HF-layout tensor: ``nbytes`` is known up front (shard
    planning), the data exists only while being consumed (``np.asarray``)."""

    def __init__(self, thunk, nbytes: int):
        self._thunk = thunk
        self.nbytes = int(nbytes)

    def materialize(self) -> np.ndarray:
        return np.asarray(self._thunk())

    def __array__(self, dtype=None, copy=None):
        arr = self.materialize()
        return arr.astype(dtype) if dtype is not None else arr


class FusedTensorMixin:
    """Split fused HF checkpoint tensors into the mapping table's virtual keys
    on the way in and re-fuse on export (Phi-3 packs q|k|v and gate|up; GLM-4
    packs gate|up). Mix in BEFORE the mapping adapter and set:

    - ``_fused``:  [(fused HF suffix, [virtual part suffixes])]
    - ``_fused_splits``: {fused suffix: np.split offsets along HF dim 0}
    """

    _fused: "list[tuple[str, list[str]]]" = []
    _fused_splits: "dict[str, list[int]]" = {}

    def _fused_keys(self, i: int, fused: str, parts: "list[str]"):
        pre = f"model.layers.{i}."
        return pre + fused, [pre + p for p in parts]

    def from_hf(self, tensors, dtype=None) -> dict:
        t = dict(tensors)
        for i in range(self.num_layers):
            for fused, parts in self._fused:
                fk, pks = self._fused_keys(i, fused, parts)
                if fk not in t:
                    continue
                for pk, arr in zip(
                    pks, np.split(np.asarray(t.pop(fk)), self._fused_splits[fused], axis=0)
                ):
                    t[pk] = arr
        return super().from_hf(t, dtype)

    def to_hf(self, params, dtype=None) -> dict:
        out = super().to_hf(params, dtype)
        for i in range(self.num_layers):
            for fused, parts in self._fused:
                fk, pks = self._fused_keys(i, fused, parts)
                out[fk] = np.concatenate([out.pop(pk) for pk in pks], axis=0)
        return out

    def to_hf_lazy(self, params, dtype=None, host_fn=None) -> dict:
        out = super().to_hf_lazy(params, dtype, host_fn)
        for i in range(self.num_layers):
            for fused, parts in self._fused:
                fk, pks = self._fused_keys(i, fused, parts)
                lazies = [out.pop(pk) for pk in pks]
                out[fk] = LazyHFTensor(
                    (lambda ls=lazies: np.concatenate(
                        [x.materialize() for x in ls], axis=0)),
                    sum(x.nbytes for x in lazies),
                )
        return out
