"""Observability subsystem tests: goodput accounting, stall watchdog, HBM
telemetry, on-demand profiling — plus the timers + experiment-logger tests
(reference tests for training/timers.py and loggers/)."""

import json
import os
import signal
import time

import jax.numpy as jnp
import pytest

from automodel_tpu.loggers.experiment_loggers import (
    MLflowLogger,
    WandbLogger,
    build_experiment_loggers,
)
from automodel_tpu.training.timers import Timer, Timers


class TestTimers:
    def test_basic_timing(self):
        timers = Timers()
        with timers("work"):
            time.sleep(0.01)
        s = timers.summary()
        assert 0.005 < s["work"] < 1.0

    def test_mean_over_calls(self):
        timers = Timers()
        for _ in range(3):
            with timers("x"):
                time.sleep(0.002)
        assert timers("x").count == 3
        assert timers("x").mean < timers("x").elapsed_total

    def test_sync_blocks_on_result(self):
        t = Timer("d", sync=True)
        t.start()
        out = jnp.ones((256, 256)) @ jnp.ones((256, 256))
        dt = t.stop(out)
        assert dt > 0

    def test_double_start_raises(self):
        t = Timer("x")
        t.start()
        with pytest.raises(RuntimeError, match="already started"):
            t.start()

    def test_summary_reset(self):
        timers = Timers()
        with timers("a"):
            pass
        timers.summary(reset=True)
        assert timers.summary() == {}


class TestExperimentLoggers:
    def test_missing_packages_degrade_gracefully(self):
        # wandb/mlflow are not installed in this image: loggers become no-ops
        w = WandbLogger(project="x", mode="offline")
        w.log(1, loss=1.0)
        w.close()
        m = MLflowLogger(tracking_uri="file:/tmp/nope")
        m.log(1, loss=1.0)
        m.close()

    def test_build_from_config(self):
        from automodel_tpu.config.loader import ConfigNode

        cfg = ConfigNode({"wandb": {"project": "p", "mode": "offline"}})
        loggers = build_experiment_loggers(cfg)
        assert len(loggers) == 1
        cfg2 = ConfigNode({})
        assert build_experiment_loggers(cfg2) == []


class TestNamedScopes:
    """Profiler scope labels (autonvtx parity): block/region names must survive
    into the lowered program's metadata so trace viewers can group ops."""

    def test_moe_block_scopes_in_lowered_text(self):
        import jax

        from automodel_tpu.moe.config import MoEConfig
        from automodel_tpu.moe.layers import init_moe_params, moe_forward
        from automodel_tpu.utils.tracing import lowered_text_with_scopes

        cfg = MoEConfig(n_routed_experts=4, n_activated_experts=2, dim=16,
                        moe_inter_dim=32, n_shared_experts=1)
        p = init_moe_params(cfg, jax.random.key(0))
        x = jnp.ones((4, 16))
        txt = lowered_text_with_scopes(
            jax.jit(lambda p, x: moe_forward(cfg, p, x)[0]).lower(p, x)
        )
        for scope in ("moe_gate", "moe_experts", "moe_shared_experts"):
            assert scope in txt, scope

    def test_hybrid_family_block_scopes(self):
        import jax
        import numpy as np

        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.nemotron_v3.model import NemotronHForCausalLM, NemotronV3Config
        from automodel_tpu.moe.config import MoEConfig
        from automodel_tpu.utils.tracing import lowered_text_with_scopes

        cfg = NemotronV3Config(
            vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=4,
            layers_block_type=("mamba", "attention", "mlp", "moe"),
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            mamba_num_heads=4, mamba_head_dim=8, ssm_state_size=16, n_groups=2,
            chunk_size=16, conv_kernel=4,
            moe=MoEConfig(
                n_routed_experts=4, n_activated_experts=2, dim=64, moe_inter_dim=32,
                score_func="sigmoid", expert_activation="relu2",
            ),
        )
        model = NemotronHForCausalLM(cfg, BackendConfig(dtype="float32", remat_policy="full"))
        params = model.init(jax.random.key(0), jnp.float32)
        ids = jnp.asarray(np.zeros((1, 8), np.int32))
        txt = lowered_text_with_scopes(
            jax.jit(lambda p, i: model(p, i)[0]).lower(params, ids)
        )
        for scope in ("mamba", "attention", "mlp"):
            assert scope in txt, scope

    def test_scoped_wrapper_preserves_fn(self):
        from automodel_tpu.utils.tracing import scope_blocks, scoped

        f = scoped("thing", lambda a, b: a + b)
        assert f(1, 2) == 3
        table = scope_blocks({"x": lambda v: v * 2})
        assert table["x"](4) == 8

    def test_shared_dense_path_scopes_in_lowered_text(self):
        """The common transformer path carries attention/mlp scope labels so
        EVERY dense family's trace is legible, not just the 3 that annotate
        per-family."""
        import jax

        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.common.transformer import (
            DenseDecoderConfig, decoder_forward, init_dense_decoder_params,
        )
        from automodel_tpu.utils.tracing import lowered_text_with_scopes

        cfg = DenseDecoderConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        )
        backend = BackendConfig(dtype="float32")
        params = init_dense_decoder_params(cfg, jax.random.key(0))
        ids = jnp.zeros((1, 8), jnp.int32)
        txt = lowered_text_with_scopes(
            jax.jit(lambda p, i: decoder_forward(cfg, backend, p, i)).lower(params, ids)
        )
        for scope in ("attention", "mlp"):
            assert scope in txt, scope

    def test_shared_moe_path_scopes_in_lowered_text(self):
        import jax

        from automodel_tpu.models.common.backend import BackendConfig
        from automodel_tpu.models.common.moe_transformer import (
            MoEDecoderConfig, init_moe_decoder_params, moe_decoder_forward,
        )
        from automodel_tpu.moe.config import MoEConfig
        from automodel_tpu.utils.tracing import lowered_text_with_scopes

        cfg = MoEDecoderConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
            first_k_dense_replace=1,
            moe=MoEConfig(n_routed_experts=4, n_activated_experts=2, dim=32,
                          moe_inter_dim=32),
        )
        backend = BackendConfig(dtype="float32")
        params = init_moe_decoder_params(cfg, jax.random.key(0))
        ids = jnp.zeros((1, 8), jnp.int32)
        txt = lowered_text_with_scopes(
            jax.jit(lambda p, i: moe_decoder_forward(cfg, backend, p, i)[0]).lower(params, ids)
        )
        for scope in ("attention", "mlp", "moe"):
            assert scope in txt, scope


class TestNonFiniteJson:
    """MetricLogger must emit VALID json for NaN/Inf metrics: bare NaN/Infinity
    from json.dumps breaks every json.loads consumer of training.jsonl."""

    def test_nonfinite_roundtrips_through_json_loads(self):
        from automodel_tpu.loggers.metric_logger import MetricsSample

        line = MetricsSample(
            step=3, metrics={"loss": float("nan"), "grad_norm": float("inf"), "ok": 1.5}
        ).to_json()
        rec = json.loads(line)  # bare NaN/Infinity would raise here
        assert rec["loss"] is None
        assert rec["loss_nonfinite"] is True
        assert rec["grad_norm"] is None
        assert rec["grad_norm_nonfinite"] is True
        assert rec["ok"] == 1.5
        assert "ok_nonfinite" not in rec

    def test_nonfinite_inside_arrays_and_lists(self):
        import numpy as np

        from automodel_tpu.loggers.metric_logger import MetricsSample

        line = MetricsSample(
            step=1,
            metrics={"load": np.asarray([1.0, float("nan")]),
                     "scalar": jnp.float32(2.0)},
        ).to_json()
        rec = json.loads(line)
        assert rec["load"] == [1.0, None]
        assert rec["load_nonfinite"] is True
        assert rec["scalar"] == 2.0

    def test_logger_writes_parseable_lines(self, tmp_path):
        from automodel_tpu.loggers.metric_logger import MetricLogger

        path = tmp_path / "training.jsonl"
        with MetricLogger(path) as ml:
            ml.log(1, loss=float("nan"), tps=None, mfu=0.31)
        rows = [json.loads(line) for line in open(path)]
        assert rows[0]["loss"] is None and rows[0]["loss_nonfinite"] is True
        assert rows[0]["tps"] is None
        assert rows[0]["mfu"] == 0.31


class TestGoodputTracker:
    def test_buckets_sum_to_wall_time(self):
        from automodel_tpu.observability import GoodputTracker

        now = [0.0]
        tracker = GoodputTracker(clock=lambda: now[0])

        def spend(bucket, s):
            with tracker.track(bucket):
                now[0] += s

        spend("compile", 30.0)
        spend("data_wait", 5.0)
        for _ in range(4):
            spend("device_step", 10.0)
        spend("eval", 15.0)
        spend("checkpoint", 5.0)
        now[0] += 5.0  # unaccounted -> idle

        totals = tracker.totals()
        assert sum(totals.values()) == pytest.approx(tracker.wall_s)
        assert totals["idle"] == pytest.approx(5.0)

        snap = tracker.snapshot()
        fracs = [v for k, v in snap.items() if k.startswith("goodput/")]
        assert sum(fracs) == pytest.approx(1.0, abs=1e-3)
        assert snap["goodput"] == pytest.approx(40.0 / 100.0, abs=1e-3)
        assert snap["goodput/compile"] == pytest.approx(0.3, abs=1e-3)

    def test_add_and_unknown_bucket(self):
        from automodel_tpu.observability import GoodputTracker

        tracker = GoodputTracker()
        tracker.add("device_step", 1.0)
        tracker.add("custom", 2.0)  # ad-hoc buckets allowed
        assert tracker.totals()["custom"] == 2.0
        assert "goodput/custom" in tracker.snapshot()


class TestStallWatchdog:
    def test_fires_on_simulated_stall_and_dumps_stacks(self, tmp_path):
        from automodel_tpu.observability import StallWatchdog

        events = []
        wd = StallWatchdog(threshold_s=0.05, dump_dir=str(tmp_path),
                           on_stall=events.append, poll_interval_s=0.01)
        wd.start()
        wd.heartbeat(step=7)
        deadline = time.monotonic() + 5.0
        while not events and time.monotonic() < deadline:
            time.sleep(0.01)  # the loop is "hung": no heartbeats arrive
        wd.stop()
        assert len(events) == 1, "stall must fire exactly once per silence window"
        ev = events[0]
        assert ev["event"] == "stall"
        assert ev["step"] == 7
        assert ev["stall_s"] >= 0.0
        assert os.path.exists(ev["stack_dump"])
        dump = open(ev["stack_dump"]).read()
        # the dump must contain THIS (stalled) thread's stack
        assert "test_fires_on_simulated_stall_and_dumps_stacks" in dump
        assert "last step 7" in dump

    def test_heartbeats_rearm_and_suppress(self, tmp_path):
        from automodel_tpu.observability import StallWatchdog

        events = []
        wd = StallWatchdog(threshold_s=0.2, dump_dir=str(tmp_path),
                           on_stall=events.append, poll_interval_s=0.01)
        wd.start()
        for _ in range(10):  # steady heartbeats: never fires
            wd.heartbeat(step=1)
            time.sleep(0.01)
        assert events == []
        time.sleep(0.4)  # silence: fires once
        assert len(events) == 1
        wd.heartbeat(step=2)  # recovery re-arms
        time.sleep(0.4)  # second stall fires again
        wd.stop()
        assert len(events) == 2
        assert not wd.running

    def test_bad_threshold_raises(self, tmp_path):
        from automodel_tpu.observability import StallWatchdog

        with pytest.raises(ValueError, match="threshold_s"):
            StallWatchdog(threshold_s=0.0, dump_dir=str(tmp_path))

    def test_context_fn_merges_goodput_snapshot_into_event(self, tmp_path):
        """A stall event must carry the run's goodput snapshot + last step so
        the incident row is diagnosable without cross-referencing other rows."""
        from automodel_tpu.observability import StallWatchdog

        events = []
        wd = StallWatchdog(threshold_s=0.05, dump_dir=str(tmp_path),
                           on_stall=events.append, poll_interval_s=0.01,
                           context_fn=lambda: {"goodput": 0.42, "goodput/compile": 0.3})
        wd.start()
        wd.heartbeat(step=9)
        deadline = time.monotonic() + 5.0
        while not events and time.monotonic() < deadline:
            time.sleep(0.01)
        wd.stop()
        assert events and events[0]["event"] == "stall"
        assert events[0]["step"] == 9  # last completed step
        assert events[0]["goodput"] == 0.42
        assert events[0]["goodput/compile"] == 0.3

    def test_context_fn_failure_does_not_eat_the_event(self, tmp_path):
        from automodel_tpu.observability import StallWatchdog

        def boom():
            raise RuntimeError("snapshot failed")

        events = []
        wd = StallWatchdog(threshold_s=0.05, dump_dir=str(tmp_path),
                           on_stall=events.append, poll_interval_s=0.01,
                           context_fn=boom)
        wd.start()
        wd.heartbeat(step=1)
        deadline = time.monotonic() + 5.0
        while not events and time.monotonic() < deadline:
            time.sleep(0.01)
        wd.stop()
        assert events and events[0]["event"] == "stall"


class TestMemoryTelemetry:
    def test_cpu_noops_cleanly(self):
        """CPU devices return None from memory_stats(): telemetry degrades to
        an empty dict, never a crash (JAX_PLATFORMS=cpu in the suite)."""
        from automodel_tpu.observability import device_memory_stats

        out = device_memory_stats()
        assert isinstance(out, dict)
        for v in out.values():  # if a backend DOES report, values are numeric GiB
            assert isinstance(v, float)

    def test_fake_device_stats(self):
        from automodel_tpu.observability import device_memory_stats

        class Dev:
            def __init__(self, in_use, peak):
                self._s = {"bytes_in_use": in_use, "peak_bytes_in_use": peak}

            def memory_stats(self):
                return self._s

        class NoneDev:
            def memory_stats(self):
                return None

        out = device_memory_stats([Dev(2**30, 2 * 2**30), Dev(2**29, 3 * 2**30), NoneDev()])
        assert out["hbm_gib_in_use"] == 1.0  # max over devices
        assert out["hbm_gib_peak"] == 3.0


class TestOnDemandProfiler:
    def test_sigusr1_arms_and_close_disarms_without_server(self, tmp_path):
        """The signal handler must arm a trace request (and restore the prior
        handler on close) with NO profiler server running."""
        from automodel_tpu.observability import OnDemandProfiler

        prev = signal.getsignal(signal.SIGUSR1)
        p = OnDemandProfiler(str(tmp_path), trace_steps=2, server_port=0)
        p.start()
        assert not p.armed
        os.kill(os.getpid(), signal.SIGUSR1)
        assert p.armed
        assert not p.tracing  # arming alone must not touch the profiler
        p.close()
        assert not p.armed
        assert signal.getsignal(signal.SIGUSR1) == prev
        # after close, SIGUSR1 no longer arms this profiler
        assert not p.armed

    def test_request_trace_programmatic(self, tmp_path):
        from automodel_tpu.observability import OnDemandProfiler

        p = OnDemandProfiler(str(tmp_path), trace_steps=3, server_port=0, signum=None)
        p.start()  # signum=None: no handler installed, no server started
        p.request_trace()
        assert p.armed
        p.close()

    def test_closed_window_records_exact_step_coverage(self, tmp_path):
        """A window closed at its step boundary knows exactly how many steps
        it covered (the analyzer's steps_hint); a window cut short by run end
        does not, and must report None."""
        from automodel_tpu.observability import OnDemandProfiler

        p = OnDemandProfiler(str(tmp_path), trace_steps=2, server_port=0,
                             signum=None)
        p.start()
        p.request_trace()
        p.on_step_start(5)  # opens: window spans steps 5..6
        assert p.tracing and p.last_window_steps is None
        p.on_step_end(5)
        assert p.tracing  # still inside the window
        p.on_step_end(6)
        assert not p.tracing
        assert p.last_window_steps == 2
        assert p.take_completed_trace() is not None
        # second window cut short by close(): coverage unknown
        p.request_trace()
        p.on_step_start(9)
        p.close()
        assert p.take_completed_trace() is not None
        assert p.last_window_steps is None


class TestObservabilityManager:
    def test_from_config_nested_sections(self):
        from automodel_tpu.observability import Observability, ObservabilityConfig

        cfg = ObservabilityConfig.from_dict({
            "goodput": True,
            "watchdog": {"enabled": True, "threshold_s": 120},
            "profiling": {"server_port": 0, "trace_steps": 7, "signal": "SIGUSR1"},
        })
        assert cfg.watchdog and cfg.watchdog_threshold_s == 120.0
        assert cfg.trace_steps == 7
        assert cfg.resolve_signal() == signal.SIGUSR1
        assert ObservabilityConfig.from_dict(None) == ObservabilityConfig()
        assert ObservabilityConfig.from_dict({"watchdog": False}).watchdog is False

        obs = Observability(cfg, out_dir="/tmp/obs-test")
        assert obs.watchdog is not None and obs.profiler is not None
        obs.close()

    def test_from_config_perf_observability_sections(self, tmp_path):
        from automodel_tpu.observability import Observability, ObservabilityConfig

        cfg = ObservabilityConfig.from_dict({
            "hlo_costs": False,
            "timeline": {"enabled": True, "max_events": 500},
            "aggregate": {"enabled": True, "straggler_factor": 3.5},
        })
        assert cfg.hlo_costs is False
        assert cfg.timeline is True and cfg.timeline_max_events == 500
        assert cfg.aggregate is True and cfg.straggler_factor == 3.5
        # bool shorthands
        off = ObservabilityConfig.from_dict({"timeline": False, "aggregate": False})
        assert off.timeline is False and off.aggregate is False

        obs = Observability(cfg, out_dir=str(tmp_path))
        assert obs.timeline is not None and obs.timeline.max_events == 500
        assert obs.aggregator is not None and obs.aggregator.straggler_factor == 3.5
        assert not obs.aggregator.active  # single-process suite: no gathers
        # hlo_costs disabled: compile_step hands the fn back untouched
        fn = object()
        assert obs.compile_step(fn, ()) is fn
        obs.close()

    def test_guarded_compiled_demotes_to_jit_on_sharding_rejection(self):
        """A PEFT step re-shards its adapter params inside the step, so step-2
        inputs no longer match the shardings the AOT object was lowered with.
        The guard must hand those calls to the jit fallback permanently, not
        crash the run (plain jit would have recompiled silently)."""
        from automodel_tpu.observability.manager import _GuardedCompiled

        calls = []

        class Rejecting:
            def __call__(self, *args):
                calls.append("aot")
                raise ValueError(
                    "Compiled object called with input sharding(s) does not "
                    "match the sharding(s) the computation was compiled with.")

        fn = _GuardedCompiled(Rejecting(), lambda *a: calls.append("jit") or "ok", (1,))
        assert fn(1) == "ok"
        assert fn(1) == "ok"
        assert calls == ["aot", "jit", "jit"]  # demotion sticks: one AOT attempt

        class Broken:
            def __call__(self, *args):
                raise ValueError("something unrelated")

        fn = _GuardedCompiled(Broken(), lambda *a: "ok", (1,))
        with pytest.raises(ValueError, match="unrelated"):
            fn(1)

    def test_timeline_written_on_close_with_compile_and_step_spans(self, tmp_path):
        from automodel_tpu.observability import Observability

        obs = Observability.from_config({"watchdog": False, "memory": False},
                                        str(tmp_path))
        obs.record_compile(0.5)
        obs.on_step_start(1)
        obs.on_step_end(1)
        with obs.track("checkpoint"):
            pass
        obs.note_event(1, {"resilience/event": "rollback", "resilience/from_step": 1})
        obs.close()
        doc = json.load(open(os.path.join(str(tmp_path), "timeline.json")))
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"compile", "step", "checkpoint", "rollback"} <= names

    def test_disabled_manager_noops(self, tmp_path):
        from automodel_tpu.observability import Observability

        obs = Observability.from_config({"enabled": False}, str(tmp_path))
        obs.start()
        with obs.track("device_step"):
            pass
        obs.heartbeat(1)
        obs.on_step_start(1)
        obs.on_step_end(1)
        assert obs.step_metrics() == {}
        obs.close()

    def test_step_metrics_carries_compile_and_goodput(self, tmp_path):
        from automodel_tpu.observability import Observability

        obs = Observability.from_config({"watchdog": False, "memory": False},
                                        str(tmp_path))
        obs.record_compile(12.5)
        obs.record_compile(0.5)  # delayed-QAT second compile accumulates
        with obs.track("device_step"):
            pass
        m = obs.step_metrics()
        assert m["compile_time_s"] == 13.0
        assert "goodput" in m and "goodput/idle" in m
        obs.close()

    def test_stall_event_reaches_metric_sink(self, tmp_path):
        from automodel_tpu.observability import Observability

        rows = []
        obs = Observability.from_config(
            {"watchdog": {"threshold_s": 0.05, "poll_interval_s": 0.01},
             "goodput": False, "memory": False},
            str(tmp_path),
            metric_sink=lambda step, **kw: rows.append((step, kw)),
        )
        obs.start()
        obs.heartbeat(4)
        deadline = time.monotonic() + 5.0
        while not rows and time.monotonic() < deadline:
            time.sleep(0.01)
        obs.close()
        assert rows, "stall event must flow through the metric sink"
        step, fields = rows[0]
        assert step == 4 and fields["event"] == "stall"
        assert os.path.exists(fields["stack_dump"])
