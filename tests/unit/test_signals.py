"""Unit tests for observability/signals.py (the tuner signals bundle)."""
from __future__ import annotations

import json

import pytest

from automodel_tpu.observability import signals as sig

_ROOFLINE = {
    "roofline_bound": "compute", "roofline_step_time_s": 0.5,
    "roofline_t_compute_s": 0.5, "roofline_t_memory_s": 0.3,
    "roofline_t_comm_s": 0.1,
}
_TRACE_SUMMARY = {
    "measured_bound": "compute", "measured_step_time_s": 0.55,
    "overlap_frac": 0.4, "measured_frac_compute": 0.8,
    "measured_frac_comm": 0.1, "measured_frac_moe_a2a": 0.0,
    "measured_frac_host": 0.15,
    "trace/analytic_bound": "compute", "trace/bound_agrees": True,
    "trace/verdict": "agree",
}


class _Plan:
    total_bytes = 6 * 2**30
    headroom_bytes = 10 * 2**30
    hbm_limit_bytes = 16 * 2**30
    fits = True


def _full_doc():
    return sig.build_signals(
        cell={"model": "m", "seq_len": 2048}, mesh_axes={"dp": 4, "tp": 2},
        roofline=_ROOFLINE, costs={"hlo_flops": 1e12,
                                   "comm_bytes_total": 1e9,
                                   "comm_bytes_moe_a2a": 0},
        trace_summary=_TRACE_SUMMARY, memory_plan=_Plan(),
        compile_summary={"compile_cache_hits": 2, "compile_cache_misses": 1,
                         "compile_aot": 3, "compile_jit_fallback": 0})


class TestBuild:
    def test_full_document_validates(self):
        doc = _full_doc()
        assert sig.validate_signals(doc) == []
        (cell,) = doc["cells"]
        assert cell["cell"] == {"model": "m", "mesh": {"dp": 4, "tp": 2},
                                "seq_len": 2048}
        assert cell["analytic"]["roofline_bound"] == "compute"
        assert cell["measured"]["overlap_frac"] == 0.4
        assert cell["reconciliation"]["agrees"] is True
        assert cell["memory"]["total_gib"] == 6.0
        assert cell["memory"]["hbm_headroom_gib"] == 10.0
        assert cell["compile_cache"] == {"hits": 2, "misses": 1, "aot": 3,
                                         "jit_fallback": 0}

    def test_absent_sources_are_explicit_null(self):
        doc = sig.build_signals(cell={"model": "m", "seq_len": 128})
        assert sig.validate_signals(doc) == []
        (cell,) = doc["cells"]
        for section in ("analytic", "measured", "reconciliation", "memory",
                        "compile_cache"):
            assert section in cell and cell[section] is None

    def test_prebuilt_cells_list(self):
        c = sig.build_cell(cell={"model": "a", "seq_len": 1})
        doc = sig.build_signals([c, c])
        assert len(doc["cells"]) == 2
        assert sig.validate_signals(doc) == []

    def test_partial_roofline_degrades_to_null(self):
        # missing roofline_t_* keys must not produce a half-filled section
        doc = sig.build_signals(cell={}, roofline={"roofline_bound": "compute"})
        assert doc["cells"][0]["analytic"] is None


class TestValidate:
    def test_rejects_wrong_version(self):
        doc = _full_doc()
        doc["version"] = 99
        assert any("version" in p for p in sig.validate_signals(doc))

    def test_rejects_missing_section_key(self):
        doc = _full_doc()
        del doc["cells"][0]["measured"]
        assert any("measured key missing" in p for p in sig.validate_signals(doc))

    def test_rejects_bool_in_numeric_field(self):
        doc = _full_doc()
        doc["cells"][0]["measured"]["overlap_frac"] = True
        assert any("is bool" in p for p in sig.validate_signals(doc))

    def test_rejects_overlap_frac_out_of_range(self):
        doc = _full_doc()
        doc["cells"][0]["measured"]["overlap_frac"] = 1.5
        assert any("outside [0, 1]" in p for p in sig.validate_signals(doc))

    def test_rejects_null_required_field(self):
        doc = _full_doc()
        doc["cells"][0]["reconciliation"]["verdict"] = None
        assert any("null but required" in p for p in sig.validate_signals(doc))

    def test_non_dict_document(self):
        assert sig.validate_signals([1, 2]) != []


class TestWrite:
    def test_atomic_write_and_roundtrip(self, tmp_path):
        path = tmp_path / "signals.json"
        sig.write_signals(str(path), _full_doc())
        loaded = json.loads(path.read_text())
        assert sig.validate_signals(loaded) == []
        assert not list(tmp_path.glob("*.tmp"))

    def test_refuses_invalid_document(self, tmp_path):
        doc = _full_doc()
        doc["cells"][0]["measured"]["overlap_frac"] = 2.0
        with pytest.raises(ValueError, match="schema"):
            sig.write_signals(str(tmp_path / "signals.json"), doc)
        assert not (tmp_path / "signals.json").exists()
