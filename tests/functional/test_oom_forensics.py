"""OOM flight recorder and anomaly-triggered auto-trace, end to end: a real
recipe run on the 8-device mesh whose step executor dies with a
RESOURCE_EXHAUSTED after real steps must leave a complete ``oom_report.json``
behind (and still re-raise); a simulated step-time excursion must produce
exactly one throttled trace directory under ``profiles/``."""

import json
import textwrap

import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction


def _write_cfg(tmp_path, extra=""):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 128
      seed: 0
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: 1
      max_steps: 8
      num_epochs: 10
      handle_sigterm: false
    optimizer:
      lr: 1.0e-3
    checkpoint:
      enabled: false
    {extra}
    """
    p = tmp_path / "cfg.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


class TestOOMFlightRecorderE2E:
    def test_forced_oom_leaves_complete_report_and_reraises(self, tmp_path, cpu_devices):
        """Kill the run with an allocator-exhaustion error after two REAL
        steps: the report must carry the memory plan, a live-buffer census,
        per-device entries, and the metric rows the run actually logged —
        and the original exception must still reach the caller."""
        cfg = load_config(_write_cfg(
            tmp_path, extra="observability:\n      memory:\n        hbm_limit_gib: 64\n"))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()

        real_step = recipe._train_step
        calls = {"n": 0}

        def dying_step(*args):  # plain function: compile_step falls back to jit
            calls["n"] += 1
            if calls["n"] >= 3:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory allocating "
                    "17179869184 bytes (simulated)")
            return real_step(*args)

        recipe._train_step = dying_step
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            recipe.run_train_validation_loop()

        report = json.load(open(tmp_path / "out" / "oom_report.json"))
        assert report["oom_report"] is True
        assert report["error"]["type"] == "RuntimeError"
        assert "RESOURCE_EXHAUSTED" in report["error"]["message"]
        # the analytic plan rode along (hbm_limit_gib override => verdict too)
        assert report["memory_plan"]["mem_plan/params_gib"] > 0
        assert report["memory_plan"]["mem_plan/fits"] is True
        # per-device entries for all 8 virtual devices (stats empty on CPU)
        assert len(report["devices"]) == 8
        # live-buffer census: params/opt_state were resident at the crash
        assert report["live_buffers"]["live_arrays"] > 0
        assert report["live_buffers"]["groups"]
        assert report["live_buffers"]["total_gib"] >= 0
        # the ring captured the real rows logged before death
        assert report["last_rows"], "expected metric rows before the crash"
        assert all("loss" in r for r in report["last_rows"])

    def test_non_oom_failures_leave_no_report(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()

        def dying_step(*args):
            raise RuntimeError("INVALID_ARGUMENT: shapes do not match (simulated)")

        recipe._train_step = dying_step
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
            recipe.run_train_validation_loop()
        assert not (tmp_path / "out" / "oom_report.json").exists()


class TestAutoTraceE2E:
    def test_excursion_produces_exactly_one_trace_dir(self, tmp_path, cpu_devices):
        """Drive the manager's hooks the way the train loop does, with a real
        profiler on the CPU backend: the step-time excursion arms a trace, the
        next step opens a REAL trace window under out/profiles, and a second
        excursion stays inside the per-run budget — exactly one capture."""
        import jax.numpy as jnp

        from automodel_tpu.observability import Observability, ObservabilityConfig

        out = tmp_path / "run"
        obs = Observability(ObservabilityConfig(
            watchdog=False, aggregate=False, hlo_costs=False,
            trace_steps=1, trace_signal=None,
            excursion_factor=3.0, excursion_min_samples=3,
        ), out_dir=str(out)).start()
        try:
            x = jnp.ones((8,))
            for step in range(3):
                obs.on_step_start(step)
                obs.on_step_end(step, sync=x)
                obs.note_step_time(step, 0.1)
            assert not obs.profiler.armed
            obs.note_step_time(3, 2.0)  # 20x the median: anomaly
            assert obs.profiler.armed
            # next steps: the armed request opens and closes a real window
            for step in (4, 5):
                obs.on_step_start(step)
                obs.on_step_end(step, sync=x)
            assert not obs.profiler.tracing
            profile_dirs = sorted(p.name for p in (out / "profiles").iterdir())
            assert profile_dirs == ["step_000004"]
            # a later excursion must NOT buy a second trace (budget = 1)
            obs.note_step_time(6, 3.0)
            assert not obs.profiler.armed
            for step in (7, 8):
                obs.on_step_start(step)
                obs.on_step_end(step, sync=x)
            assert sorted(p.name for p in (out / "profiles").iterdir()) == [
                "step_000004"]
        finally:
            obs.close()

    def test_stall_event_arms_trace_and_logs_row(self, tmp_path, cpu_devices):
        """The watchdog's on_stall callback routes through auto_trace: a
        simulated stall event arms the profiler and emits the auto_trace
        metric row through the sink."""
        from automodel_tpu.observability import Observability, ObservabilityConfig

        events = []
        obs = Observability(
            ObservabilityConfig(watchdog=False, aggregate=False,
                                trace_signal=None),
            out_dir=str(tmp_path),
            metric_sink=lambda step, **f: events.append({"step": step, **f}),
        ).start()
        try:
            assert obs.auto_trace("stall", 11, stall_s=630.0) is True
            assert obs.profiler.armed
            assert [e for e in events if e.get("event") == "auto_trace"]
        finally:
            obs.close()
