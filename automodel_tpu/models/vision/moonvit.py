"""MoonViT vision tower (Kimi-VL) — TPU-native (reference kimivl/model.py:163-377).

Native-resolution ViT: per-image (h, w) patch grids packed into one token stream,
2D complex-pair rope, a *learnable* position embedding bicubically resized to each
grid, LayerNorm pre-norm blocks with biased qkv, and a 2x2 patch merger feeding the
projector.

Also serves the MoonViT3d variant (Kimi-K2.5, reference kimi_k25_vl/model.py:228-490):
temporal frames add a fixed sincos time embedding, spatial rope repeats per frame,
and the merger mean-pools over frames — expressed here as a host-precomputed
scatter-mean (out_idx/out_w) that degenerates to a pure permutation for t=1.

TPU-first contract: all data-dependent bookkeeping is host-side numpy
(``prepare_moonvit_inputs``): rope angles, per-image segment ids, the row-major ->
merge-unit permutation, and — the interesting one — the bicubic resize expressed as
a precomputed 16-tap gather (indices + cubic-convolution weights) so the device-side
interpolation is a differentiable weighted gather over the learned table with
static shapes (no per-grid recompilation, exact torch F.interpolate semantics,
align_corners=False, a=-0.75).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import layer_norm

__all__ = ["MoonViTConfig", "init_moonvit_params", "moonvit_logical_axes",
           "moonvit_forward", "prepare_moonvit_inputs"]


@dataclasses.dataclass
class MoonViTConfig:
    patch_size: int = 14
    init_pos_emb_height: int = 64
    init_pos_emb_width: int = 64
    num_attention_heads: int = 16
    num_hidden_layers: int = 27
    hidden_size: int = 1152
    intermediate_size: int = 4304
    merge_kernel_size: tuple[int, int] = (2, 2)
    in_channels: int = 3
    initializer_range: float = 0.02
    # >1 enables the MoonViT3d temporal path (Kimi-K2.5): fixed sincos time
    # embedding per frame + temporal mean-pooling in the merger
    pos_emb_time: int = 1

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "MoonViTConfig":
        keys = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in hf.items() if k in keys}
        if "merge_kernel_size" in kwargs:
            kwargs["merge_kernel_size"] = tuple(kwargs["merge_kernel_size"])
        return cls(**kwargs)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.patch_size**2


def init_moonvit_params(cfg: MoonViTConfig, key: jax.Array, dtype=jnp.float32) -> dict:
    std = cfg.initializer_range
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    keys = iter(jax.random.split(key, 8))

    def w(shape, s=std):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(dtype)

    ks = jax.random.split(next(keys), 4)
    mk = lambda kk, shape, s: (jax.random.normal(kk, (L, *shape), jnp.float32) * s).astype(dtype)
    blocks = {
        "ln0_w": jnp.ones((L, d), dtype), "b_ln0": jnp.zeros((L, d), dtype),
        "ln1_w": jnp.ones((L, d), dtype), "b_ln1": jnp.zeros((L, d), dtype),
        "wqkv": mk(ks[0], (d, 3 * d), std), "b_qkv": jnp.zeros((L, 3 * d), dtype),
        "wo": mk(ks[1], (d, d), std), "b_o": jnp.zeros((L, d), dtype),
        # reference MoonVitMLP trunc-normal init with std sqrt(2/fan_in)
        "fc0": mk(ks[2], (d, i), (2 / d) ** 0.5), "b_fc0": jnp.zeros((L, i), dtype),
        "fc1": mk(ks[3], (i, d), (2 / i) ** 0.5), "b_fc1": jnp.zeros((L, d), dtype),
    }
    return {
        "patch_w": w((cfg.patch_dim, d)),
        "b_patch": jnp.zeros((d,), dtype),
        # reference inits pos_emb with normal(0, 1)
        "pos_emb": (jax.random.normal(next(keys), (cfg.init_pos_emb_height, cfg.init_pos_emb_width, d), jnp.float32)).astype(dtype),
        "blocks": blocks,
        "final_ln_w": jnp.ones((d,), dtype),
        "b_final_ln": jnp.zeros((d,), dtype),
    }


def moonvit_logical_axes(cfg: MoonViTConfig) -> dict:
    return {
        "patch_w": (None, "embed"), "b_patch": ("norm",),
        "pos_emb": (None, None, "embed"),
        "blocks": {
            "ln0_w": ("layers", "norm"), "b_ln0": ("layers", "norm"),
            "ln1_w": ("layers", "norm"), "b_ln1": ("layers", "norm"),
            "wqkv": ("layers", "embed", "heads"), "b_qkv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "b_o": ("layers", "norm"),
            "fc0": ("layers", "embed", "mlp"), "b_fc0": ("layers", "mlp"),
            "fc1": ("layers", "mlp", "embed"), "b_fc1": ("layers", "norm"),
        },
        "final_ln_w": ("norm",), "b_final_ln": ("norm",),
    }


def _cubic_taps(dst: int, src: int) -> tuple[np.ndarray, np.ndarray]:
    """4-tap cubic-convolution (a=-0.75) indices/weights per output coordinate,
    torch F.interpolate bicubic semantics (align_corners=False, clamped borders)."""
    a = -0.75
    scale = src / dst
    x = (np.arange(dst) + 0.5) * scale - 0.5
    x0 = np.floor(x).astype(np.int64)
    t = x - x0

    def k(u):
        u = np.abs(u)
        return np.where(
            u <= 1, ((a + 2) * u - (a + 3)) * u * u + 1,
            np.where(u < 2, (((u - 5) * u + 8) * u - 4) * a, 0.0),
        )

    offs = np.array([-1, 0, 1, 2])
    idx = x0[:, None] + offs[None, :]
    wts = k(t[:, None] - offs[None, :])
    idx = np.clip(idx, 0, src - 1)
    return idx, wts


def _sincos_1d(dim: int, t_size: int) -> np.ndarray:
    """MAE-style [sin | cos] temporal embedding (reference kimi_k25_vl/model.py:169-190)."""
    omega = 1.0 / 10000 ** (np.arange(dim // 2, dtype=np.float32) / (dim / 2.0))
    out = np.arange(t_size, dtype=np.float32)[:, None] * omega[None, :]
    return np.concatenate([np.sin(out), np.cos(out)], axis=1)


def prepare_moonvit_inputs(grid_hws: np.ndarray, cfg: MoonViTConfig) -> dict[str, np.ndarray]:
    """Host-side bookkeeping per packed image: rope angles, segment ids, 16-tap
    bicubic gather for the learned pos-emb table, the fixed temporal embedding, and
    the merger scatter (mean over frames; pure permutation for t=1 grids).

    ``grid_hws`` rows are (h, w) or (t, h, w)."""
    dh = cfg.head_dim
    d = cfg.hidden_size
    Hp, Wp = cfg.init_pos_emb_height, cfg.init_pos_emb_width
    kh, kw = cfg.merge_kernel_size
    n_freq = dh // 4
    freqs = 1.0 / (10000.0 ** (np.arange(0, dh, 4)[:n_freq].astype(np.float64) / dh))
    time_table = _sincos_1d(d, max(cfg.pos_emb_time, 1))

    grids = np.asarray(grid_hws)
    if grids.shape[1] == 2:
        grids = np.concatenate([np.ones((len(grids), 1), grids.dtype), grids], axis=1)

    angles, seg, pos_idx, pos_w, time_emb, out_idx, out_w = [], [], [], [], [], [], []
    seg_id, merged_offset = 0, 0
    for t, h, w in grids:
        t, h, w = int(t), int(h), int(w)
        if h % kh or w % kw:
            raise ValueError(f"grid ({h}, {w}) not divisible by merge kernel ({kh}, {kw})")
        if t > max(cfg.pos_emb_time, 1):
            raise ValueError(f"t={t} exceeds pos_emb_time={cfg.pos_emb_time}")
        # 2D rope: interleave (x*f, y*f) per frequency, repeated over frames
        # (reference Rope2DPosEmb / Rope2DPosEmbRepeated)
        ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        xa = xs.reshape(-1, 1) * freqs[None, :]
        ya = ys.reshape(-1, 1) * freqs[None, :]
        ang = np.stack([xa, ya], axis=-1).reshape(h * w, -1)  # (h*w, dh/2)
        angles.append(np.tile(ang, (t, 1)))
        # one attention segment per image (all frames attend jointly,
        # reference cu_seqlens over t*h*w)
        seg.append(np.full((t * h * w,), seg_id, np.int32))
        seg_id += 1
        # bicubic taps: outer product of per-axis 4-tap kernels -> 16 taps
        iy, wy = _cubic_taps(h, Hp)
        ix, wx = _cubic_taps(w, Wp)
        flat_idx = (iy[:, None, :, None] * Wp + ix[None, :, None, :]).reshape(h * w, 16)
        flat_w = (wy[:, None, :, None] * wx[None, :, None, :]).reshape(h * w, 16)
        pos_idx.append(np.tile(flat_idx, (t, 1)))
        pos_w.append(np.tile(flat_w, (t, 1)))
        # fixed sincos time embedding per frame (zero for single-frame images,
        # reference Learnable2DInterpPosEmbDividedFixed: t==1 skips the add)
        if t > 1:
            time_emb.append((t, h * w))
        else:
            time_emb.append((1, h * w))
        # row-major -> merge-unit order, then mean over frames: token (f, y, x)
        # lands in merged slot (block, intra) with weight 1/t
        p = (
            np.arange(h * w)
            .reshape(h // kh, kh, w // kw, kw)
            .transpose(0, 2, 1, 3)
            .reshape(-1)
        )
        inv = np.empty_like(p)
        inv[p] = np.arange(h * w)  # row-major token -> merge-unit slot
        oi = np.tile(inv, t) + merged_offset
        out_idx.append(oi)
        out_w.append(np.full((t * h * w,), 1.0 / t, np.float32))
        merged_offset += h * w
    out = {
        "rope_angles": np.concatenate(angles).astype(np.float32),  # (T, dh/2)
        "segment_ids": np.concatenate(seg),  # (T,)
        "pos_idx": np.concatenate(pos_idx).astype(np.int32),  # (T, 16)
        "pos_w": np.concatenate(pos_w).astype(np.float32),  # (T, 16)
        "out_idx": np.concatenate(out_idx).astype(np.int32),  # (T,)
        "out_w": np.concatenate(out_w).astype(np.float32),  # (T,)
    }
    if any(int(t) > 1 for t, _, _ in grids):
        # only multi-frame batches carry the temporal embedding (zeros otherwise);
        # built lazily so all-image batches never allocate the (T, hidden) block
        out["time_emb"] = np.concatenate(
            [
                np.repeat(time_table[:t], hw, axis=0) if t > 1 else np.zeros((hw, d), np.float32)
                for t, hw in time_emb
            ]
        ).astype(np.float32)  # (T, hidden)
    return out


def _rope_interleaved_angles(x: jnp.ndarray, angles: jnp.ndarray) -> jnp.ndarray:
    """Complex-pair rotation with per-token angles; x (T, H, dh), angles (T, dh/2)."""
    dtype = x.dtype
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    xf = x.astype(jnp.float32)
    x0, x1 = xf[..., 0::2], xf[..., 1::2]
    out = jnp.stack([x0 * cos - x1 * sin, x0 * sin + x1 * cos], axis=-1)
    return out.reshape(x.shape).astype(dtype)


def moonvit_forward(
    cfg: MoonViTConfig,
    backend: BackendConfig,
    params: dict,
    patches: jnp.ndarray,  # (T, patch_dim)
    rope_angles: jnp.ndarray,  # (T, dh/2)
    segment_ids: jnp.ndarray,  # (T,)
    pos_idx: jnp.ndarray,  # (T, 16)
    pos_w: jnp.ndarray,  # (T, 16)
    out_idx: jnp.ndarray,  # (T,) merged-slot scatter indices
    out_w: jnp.ndarray,  # (T,) scatter weights (1/t per frame)
    num_merged_units: int,  # static: total merged slots (= sum h*w per image)
    time_emb: jnp.ndarray | None = None,  # (T, hidden) fixed temporal sincos (3d)
) -> jnp.ndarray:
    """Returns merged features (num_merged_units // mu, mu, hidden) for the projector."""
    dtype = backend.jnp_dtype
    d, H, dh = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
    mu = cfg.merge_kernel_size[0] * cfg.merge_kernel_size[1]
    p = jax.tree.map(lambda a: a.astype(dtype) if a.dtype not in (jnp.int32,) else a, params)

    h = patches.astype(dtype) @ p["patch_w"] + p["b_patch"]
    table = p["pos_emb"].reshape(-1, d)
    h = h + (table[pos_idx] * pos_w[..., None].astype(dtype)).sum(axis=1)
    if time_emb is not None:
        h = h + time_emb.astype(dtype)

    seg = segment_ids[None]

    def block_fn(hh, lp):
        x = layer_norm(hh, lp["ln0_w"], lp["b_ln0"])
        qkv = (x @ lp["wqkv"] + lp["b_qkv"]).reshape(-1, 3, H, dh)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        q = _rope_interleaved_angles(q, rope_angles)
        k = _rope_interleaved_angles(k, rope_angles)
        attn = dot_product_attention(
            q[None], k[None], v[None], causal=False,
            segment_ids_q=seg, segment_ids_kv=seg, backend=backend.attention,
        )[0].reshape(-1, d)
        hh = hh + (attn @ lp["wo"] + lp["b_o"])
        x = layer_norm(hh, lp["ln1_w"], lp["b_ln1"])
        hh = hh + (jax.nn.gelu(x @ lp["fc0"] + lp["b_fc0"], approximate=True) @ lp["fc1"] + lp["b_fc1"])
        return hh, None

    h, _ = jax.lax.scan(backend.layer_remat(block_fn), h, p["blocks"])
    h = layer_norm(h, p["final_ln_w"], p["b_final_ln"])
    # merge-unit regroup + mean over frames as one scatter-add
    merged = jnp.zeros((int(num_merged_units), d), h.dtype)
    merged = merged.at[out_idx].add(h * out_w[:, None].astype(h.dtype))
    return merged.reshape(-1, mu, d)
