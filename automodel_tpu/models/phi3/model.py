"""Phi-3 / Phi-3.5 / Phi-4 family — TPU-native.

The reference serves Phi through its generic HF factory
(_transformers/model_init.py:89). Architecturally Phi-3 IS the llama decoder —
silu-gated MLP, GQA rotate-half rope, RMSNorm — with three packaging deltas:
fused qkv_proj / gate_up_proj checkpoint tensors (split/merged in the adapter),
all-layer sliding-window attention, and "longrope" scaling (ops/rope.py) for the
128k variants. So the family rides LlamaForCausalLM directly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM

__all__ = ["Phi3Config", "Phi3ForCausalLM"]


@dataclasses.dataclass
class Phi3Config(LlamaConfig):
    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Phi3Config":
        rope_scaling = hf.get("rope_scaling")
        if rope_scaling:
            # longrope reads the original/current windows (both top-level Phi-3
            # config keys) to pick factors and the attention scale
            rope_scaling = dict(
                rope_scaling,
                original_max_position_embeddings=hf.get(
                    "original_max_position_embeddings",
                    hf.get("max_position_embeddings", 4096),
                ),
                max_position_embeddings=hf.get("max_position_embeddings", 4096),
            )
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            original_max_position_embeddings=hf.get("original_max_position_embeddings"),
            rope_theta=hf.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            partial_rotary_factor=hf.get("partial_rotary_factor", 1.0),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            sliding_window=hf.get("sliding_window"),
            initializer_range=hf.get("initializer_range", 0.02),
        )


class Phi3ForCausalLM(LlamaForCausalLM):
    """Phi-3/3.5 text checkpoints. (Phi-4-multimodal is NOT claimed here: its
    checkpoints wrap projections in LoRA `base_layer` names and carry audio/vision
    towers this adapter does not map — the phi4-mm collator ships for dataset
    parity, usable once those towers exist.)"""

    config_class = Phi3Config
    hf_architectures = ("Phi3ForCausalLM",)

    def state_dict_adapter(self):
        from automodel_tpu.models.phi3.state_dict_adapter import Phi3StateDictAdapter

        return Phi3StateDictAdapter(self.config, scan_layers=self.backend.scan_layers)

    @classmethod
    def from_config(cls, config, backend=None):
        if isinstance(config, dict):
            config = Phi3Config.from_hf(config)
        return cls(config, backend)
