"""Qwen3-VL-MoE: full logits parity vs HF with images (vision tower + deepstack +
mrope), rope-index parity, text-only path, adapter key parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForImageTextToText
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from transformers.models.qwen3_vl_moe import Qwen3VLMoeConfig as HFConfig
from transformers.models.qwen3_vl_moe.modeling_qwen3_vl_moe import (
    Qwen3VLMoeForConditionalGeneration as HFModel,
)

IMG, VSTART = 120, 121


def tiny_cfg():
    return HFConfig(
        text_config=dict(
            vocab_size=128, hidden_size=64, intermediate_size=96, moe_intermediate_size=32,
            num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            num_experts=8, num_experts_per_tok=2, max_position_embeddings=128,
            rope_scaling={"rope_type": "default", "mrope_section": [4, 2, 2], "mrope_interleaved": True},
        ),
        vision_config=dict(
            depth=3, hidden_size=32, intermediate_size=48, num_heads=4, patch_size=4,
            spatial_merge_size=2, temporal_patch_size=2, out_hidden_size=64,
            num_position_embeddings=16, deepstack_visual_indexes=[0, 2], in_channels=3,
        ),
        image_token_id=IMG, video_token_id=122, vision_start_token_id=VSTART,
    )


def _fp32_backend():
    return BackendConfig(dtype="float32", remat_policy="full")


def _build(tmp_path, hf):
    d = str(tmp_path / "hf")
    hf.save_pretrained(d, safe_serialization=True)
    return AutoModelForImageTextToText.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())


def _batch(rng, grid=(1, 8, 8), seq=24):
    """input_ids with one image span + matching random pixels."""
    t, h, w = grid
    n_merged = t * (h // 2) * (w // 2)
    n_patches = t * h * w
    ids = rng.randint(0, 100, (1, seq))
    ids[0, 2] = VSTART
    ids[0, 3 : 3 + n_merged] = IMG
    pixels = rng.randn(n_patches, 3 * 2 * 4 * 4).astype(np.float32)
    return ids, pixels, np.array([grid])


class TestQwen3VLMoeParity:
    def test_logits_match_hf_with_image(self, tmp_path):
        torch.manual_seed(0)
        hf = HFModel(tiny_cfg()).eval()
        model, params = _build(tmp_path, hf)
        rng = np.random.RandomState(0)
        ids, pixels, grid = _batch(rng)

        with torch.no_grad():
            theirs = hf(
                input_ids=torch.tensor(ids),
                pixel_values=torch.tensor(pixels),
                image_grid_thw=torch.tensor(grid),
            ).logits.float().numpy()

        vin = {k: jnp.asarray(v) for k, v in model.prepare_vision_inputs(grid).items()}
        coords = model.visual_token_coords(ids)
        pos3 = model.get_mrope_positions(ids, grid)
        ours, stats = model(
            params, jnp.asarray(ids), pixel_values=jnp.asarray(pixels),
            vision_inputs=vin, visual_coords=tuple(jnp.asarray(c) for c in coords),
            positions3=jnp.asarray(pos3), training=False,
        )
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3, rtol=1e-3)
        assert stats["expert_load"].shape == (3, 8)

    def test_text_only_matches_hf(self, tmp_path):
        torch.manual_seed(1)
        hf = HFModel(tiny_cfg()).eval()
        model, params = _build(tmp_path, hf)
        ids = np.random.RandomState(1).randint(0, 100, (2, 16))
        with torch.no_grad():
            theirs = hf(input_ids=torch.tensor(ids)).logits.float().numpy()
        ours, _ = model(params, jnp.asarray(ids), training=False)
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=5e-4, rtol=1e-3)

    def test_rope_index_matches_hf(self, tmp_path):
        torch.manual_seed(2)
        hf = HFModel(tiny_cfg())
        model, _ = _build(tmp_path, hf)
        rng = np.random.RandomState(2)
        ids, _, grid = _batch(rng, grid=(1, 4, 8), seq=20)
        theirs, _ = hf.model.get_rope_index(
            torch.tensor(ids), image_grid_thw=torch.tensor(grid)
        )
        ours = model.get_mrope_positions(ids, grid)
        np.testing.assert_array_equal(ours, theirs.numpy())

    def test_rope_index_matches_hf_video(self, tmp_path):
        """Video spans: HF splits t>1 grids into per-frame t=1 runs (timestamp
        encoding); placeholder runs are per-frame, separated by text."""
        torch.manual_seed(5)
        hf = HFModel(tiny_cfg())
        model, _ = _build(tmp_path, hf)
        t, h, w = 2, 4, 4
        per_frame = (h // 2) * (w // 2)
        ids = np.random.RandomState(5).randint(0, 100, (1, 20))
        # <ts><vstart><frame1 tokens><ts><vstart><frame2 tokens>
        ids[0, 1] = VSTART
        ids[0, 2 : 2 + per_frame] = 122  # video token id
        ids[0, 7] = VSTART
        ids[0, 8 : 8 + per_frame] = 122
        grid = np.array([[t, h, w]])
        theirs, _ = hf.model.get_rope_index(
            torch.tensor(ids), video_grid_thw=torch.tensor(grid)
        )
        ours = model.get_mrope_positions(ids, None, video_grid_thw=grid)
        np.testing.assert_array_equal(ours, theirs.numpy())

    def test_adapter_key_parity(self, tmp_path):
        torch.manual_seed(3)
        hf = HFModel(tiny_cfg())
        model, params = _build(tmp_path, hf)
        hf_dict = model.state_dict_adapter().to_hf(params)
        theirs = {k for k in hf.state_dict()}
        assert set(hf_dict) == theirs

    def test_grads_finite_with_image(self, tmp_path):
        torch.manual_seed(4)
        hf = HFModel(tiny_cfg())
        model, params = _build(tmp_path, hf)
        rng = np.random.RandomState(4)
        ids, pixels, grid = _batch(rng)
        vin = {k: jnp.asarray(v) for k, v in model.prepare_vision_inputs(grid).items()}
        coords = tuple(jnp.asarray(c) for c in model.visual_token_coords(ids))
        pos3 = jnp.asarray(model.get_mrope_positions(ids, grid))
        jids = jnp.asarray(ids)

        def loss_fn(p):
            logits, _ = model(
                p, jids[:, :-1], pixel_values=jnp.asarray(pixels),
                vision_inputs=vin,
                visual_coords=coords, positions3=pos3[:, :, :-1], training=True,
            )
            ll = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.take_along_axis(ll, jids[:, 1:, None], -1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(np.asarray(g))) for g in jax.tree.leaves(grads))
