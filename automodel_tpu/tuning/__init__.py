"""Signals-driven autotuner (ROADMAP item 4): search {remat ladder, microbatch,
prefetch depths, MoE dispatcher, layout} against the observability signals and
emit a tuned config per (model, mesh, seq) cell with a fully auditable trial
ledger (docs/observability.md "Autotuning & the perf lab")."""

from automodel_tpu.tuning.policy import attribute_winner, order_trials, prune
from automodel_tpu.tuning.runner import (
    TrialLedger,
    apply_tuned_config,
    run_search,
    write_tuned_config,
)
from automodel_tpu.tuning.space import REMAT_LADDER, SearchSpace, Trial

__all__ = [
    "REMAT_LADDER",
    "SearchSpace",
    "Trial",
    "TrialLedger",
    "apply_tuned_config",
    "attribute_winner",
    "order_trials",
    "prune",
    "run_search",
    "write_tuned_config",
]
