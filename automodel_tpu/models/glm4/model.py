"""GLM-4 dense (glm-4-9b lineage) — llama lineage + three config deltas
(reference serves it through the HF wrapper; transformers modeling_glm4.py):

- SANDWICH norms: input_layernorm + post_self_attn_layernorm around attention,
  post_attention_layernorm + post_mlp_layernorm around the MLP
  (norm_placement="sandwich" in the shared dense block)
- interleaved rope over the FIRST HALF of head_dim (partial_rotary_factor 0.5)
- fused gate_up_proj checkpoint tensors (split/merged by the adapter, the same
  pattern Phi-3's fused qkv uses)
"""

from __future__ import annotations

from automodel_tpu.models.llama.model import LlamaForCausalLM

__all__ = ["Glm4ForCausalLM"]


class Glm4ForCausalLM(LlamaForCausalLM):
    hf_architectures = ("Glm4ForCausalLM",)

    def state_dict_adapter(self):
        from automodel_tpu.models.glm4.state_dict_adapter import Glm4StateDictAdapter

        return Glm4StateDictAdapter(self.config, scan_layers=self.backend.scan_layers)
