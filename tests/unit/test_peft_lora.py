"""LoRA/DoRA unit tests (mirror of reference tests/unit_tests/_peft/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.llama.model import LlamaForCausalLM
from automodel_tpu.peft.lora import (
    PeftConfig,
    count_lora_params,
    init_lora_params,
    lora_logical_axes,
    match_lora_paths,
    merge_lora_params,
    wildcard_match,
)

TINY = {
    "architectures": ["LlamaForCausalLM"],
    "vocab_size": 64,
    "hidden_size": 32,
    "intermediate_size": 64,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "max_position_embeddings": 64,
}


@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaForCausalLM.from_config(TINY, BackendConfig(dtype="float32"))
    params = model.init(jax.random.key(0), jnp.float32)
    return model, params


class TestMatching:
    def test_wildcard_semantics(self):
        # reference module_matcher.py docstring examples
        assert wildcard_match("*.layers.0.*.linear_qkv", "decoder.layers.0.self_attention.linear_qkv")
        assert not wildcard_match("*.layers.0.*.linear_qkv", "decoder.layers.1.self_attention.linear_qkv")

    def test_default_targets_match_all_projections(self, tiny_model):
        model, _ = tiny_model
        matched = match_lora_paths(model.logical_axes(), PeftConfig())
        assert set(matched) == {
            "layers.wq", "layers.wk", "layers.wv", "layers.wo",
            "layers.w_gate", "layers.w_up", "layers.w_down",
        }
        # wo contracts (heads, head_dim): split after stack dim + 2
        assert matched["layers.wo"] == (1, 3)
        assert matched["layers.wq"] == (1, 2)

    def test_hf_alias_and_exclude(self, tiny_model):
        model, _ = tiny_model
        cfg = PeftConfig(target_modules=["q_proj", "v_proj"])
        assert set(match_lora_paths(model.logical_axes(), cfg)) == {"layers.wq", "layers.wv"}
        cfg = PeftConfig(match_all_linear=True, exclude_modules=["lm_head"])
        matched = match_lora_paths(model.logical_axes(), cfg)
        assert "lm_head" not in matched
        assert "embed" not in matched  # embedding is never a lora target
        assert "layers.attn_norm" not in matched  # norms are not matrices

    def test_biases_never_matched(self):
        # qwen2-style attention biases: (L, heads, head_dim) leaves must not become
        # degenerate fan_out=1 adapters under match_all_linear
        model = LlamaForCausalLM.from_config(
            {**TINY, "attention_bias": True}, BackendConfig(dtype="float32")
        )
        matched = match_lora_paths(model.logical_axes(), PeftConfig(match_all_linear=True))
        assert not any(p.startswith("layers.b") for p in matched)
        assert "layers.wq" in matched

    def test_no_match_raises(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(ValueError, match="matched no params"):
            init_lora_params(params, model.logical_axes(), PeftConfig(target_modules=["nope"]),
                             jax.random.key(0))


class TestInitAndMerge:
    def test_shapes_and_zero_init_identity(self, tiny_model):
        model, params = tiny_model
        cfg = PeftConfig(dim=4, alpha=8)
        lora = init_lora_params(params, model.logical_axes(), cfg, jax.random.key(1))
        # wq (L, d, n*h) factorization
        L, d = 2, 32
        assert lora["layers"]["wq"]["lora_a"].shape == (L, d, 4)
        assert lora["layers"]["wq"]["lora_b"].shape == (L, 4, 32)
        # wo contracts (n, h): fan_in = 4*8
        assert lora["layers"]["wo"]["lora_a"].shape == (L, 32, 4)
        # B zero-init -> merged params == base params exactly
        merged = merge_lora_params(params, lora, cfg)
        for leaf_m, leaf_p in zip(jax.tree.leaves(merged), jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(leaf_m), np.asarray(leaf_p))

    def test_merge_matches_manual_delta(self, tiny_model):
        model, params = tiny_model
        cfg = PeftConfig(dim=4, alpha=8, target_modules=["*w_up"])
        lora = init_lora_params(params, model.logical_axes(), cfg, jax.random.key(1))
        b = jax.random.normal(jax.random.key(2), lora["layers"]["w_up"]["lora_b"].shape)
        lora["layers"]["w_up"]["lora_b"] = b
        merged = merge_lora_params(params, lora, cfg)
        a = lora["layers"]["w_up"]["lora_a"]
        expect = np.asarray(params["layers"]["w_up"]) + 2.0 * np.einsum("lir,lro->lio", a, b)
        np.testing.assert_allclose(np.asarray(merged["layers"]["w_up"]), expect, rtol=1e-4, atol=1e-6)
        # untouched leaves are the same objects
        assert merged["layers"]["wq"] is params["layers"]["wq"]

    def test_dora_magnitude_init_and_renorm(self, tiny_model):
        model, params = tiny_model
        cfg = PeftConfig(dim=4, alpha=4, use_dora=True, target_modules=["*w_gate"])
        lora = init_lora_params(params, model.logical_axes(), cfg, jax.random.key(1))
        w = np.asarray(params["layers"]["w_gate"], np.float32)
        # magnitude starts at column norms of W (reference lora.py:196-200)
        np.testing.assert_allclose(
            np.asarray(lora["layers"]["w_gate"]["magnitude"]),
            np.linalg.norm(w, axis=-2), rtol=1e-6,
        )
        # with B=0: ||W|| / ||W|| * m == W -> identity at init too
        merged = merge_lora_params(params, lora, cfg)
        np.testing.assert_allclose(np.asarray(merged["layers"]["w_gate"]), w, rtol=1e-5)

    def test_gradients_flow_only_through_lora(self, tiny_model):
        model, params = tiny_model
        cfg = PeftConfig(dim=4, alpha=8)
        lora = init_lora_params(params, model.logical_axes(), cfg, jax.random.key(1))
        ids = jnp.arange(8).reshape(1, 8) % 64

        def loss_fn(lora_tree):
            merged = merge_lora_params(params, lora_tree, cfg)
            logits = model(merged, ids)
            return (logits**2).mean()

        grads = jax.grad(loss_fn)(lora)
        ga = np.asarray(grads["layers"]["wq"]["lora_b"])
        assert np.abs(ga).max() > 0  # b gets gradient through a@b even though b=0...
        # a's grad is zero at init (d/dA of A@B with B=0), b's is not
        assert np.abs(np.asarray(grads["layers"]["wq"]["lora_a"])).max() == 0

    def test_lora_logical_axes_mirror(self, tiny_model):
        model, _ = tiny_model
        cfg = PeftConfig(dim=4)
        axes = lora_logical_axes(model.logical_axes(), cfg)
        assert axes["layers"]["wq"]["lora_a"] == ("layers", None, None)
        lora = init_lora_params(
            model.init(jax.random.key(0), jnp.float32), model.logical_axes(), cfg, jax.random.key(1)
        )
        # same nested paths: every lora leaf has a matching axes entry of equal rank
        flat_lora = jax.tree_util.tree_flatten_with_path(lora)[0]
        for path, leaf in flat_lora:
            node = axes
            for p in path:
                node = node[p.key]
            assert len(node) == leaf.ndim, (path, node, leaf.shape)
        assert count_lora_params(lora) > 0


class TestLoraDropout:
    def test_dropout_masks_features_and_rescales(self):
        from automodel_tpu.peft.lora import PeftConfig, merge_lora_params

        cfg = PeftConfig(target_modules=["*w_gate"], dim=4, alpha=8, dropout=0.5)
        rng = np.random.RandomState(0)
        base = {"layers": {"w_gate": jnp.zeros((2, 16, 8), jnp.float32)}}
        lora = {"layers": {"w_gate": {
            "lora_a": jnp.asarray(rng.randn(2, 16, 4), jnp.float32),
            "lora_b": jnp.asarray(rng.randn(2, 4, 8), jnp.float32),
        }}}
        det = merge_lora_params(base, lora, cfg)
        k1 = jax.random.key(1)
        drop1 = merge_lora_params(base, lora, cfg, dropout_rng=k1)
        drop2 = merge_lora_params(base, lora, cfg, dropout_rng=jax.random.key(2))
        # stochastic: different keys -> different merges; no key -> deterministic
        assert not np.allclose(np.asarray(drop1["layers"]["w_gate"]),
                               np.asarray(drop2["layers"]["w_gate"]))
        np.testing.assert_array_equal(
            np.asarray(merge_lora_params(base, lora, cfg)["layers"]["w_gate"]),
            np.asarray(det["layers"]["w_gate"]),
        )
        # expectation preserved: mean over many keys approaches the deterministic delta
        acc = np.zeros_like(np.asarray(det["layers"]["w_gate"]))
        n = 300
        for i in range(n):
            acc += np.asarray(merge_lora_params(
                base, lora, cfg, dropout_rng=jax.random.key(100 + i)
            )["layers"]["w_gate"])
        mean_err = np.abs(acc / n - np.asarray(det["layers"]["w_gate"])).mean()
        assert mean_err < 0.25, f"dropout must preserve the expected delta, err {mean_err}"

    def test_dropout_zero_ignores_rng(self):
        from automodel_tpu.peft.lora import PeftConfig, merge_lora_params

        cfg = PeftConfig(target_modules=["*w_gate"], dim=4, alpha=8, dropout=0.0)
        base = {"layers": {"w_gate": jnp.ones((2, 16, 8), jnp.float32)}}
        lora = {"layers": {"w_gate": {
            "lora_a": jnp.ones((2, 16, 4), jnp.float32),
            "lora_b": jnp.ones((2, 4, 8), jnp.float32),
        }}}
        a = merge_lora_params(base, lora, cfg, dropout_rng=jax.random.key(0))
        b = merge_lora_params(base, lora, cfg)
        np.testing.assert_array_equal(np.asarray(a["layers"]["w_gate"]),
                                      np.asarray(b["layers"]["w_gate"]))
