#!/usr/bin/env python
"""Static lint: emitted metric keys and docs/observability.md must agree.

The observability pillars emit flat namespaced metric keys (``goodput/*``,
``mem_plan/*``, ``mem/*``, ``moe/*``, ``moe_load/*``, ``dynamics/*``) that ride
the training.jsonl rows; docs/observability.md is the contract downstream
dashboards are built against. The two drift silently: a new key lands in code
without a docs entry, or a doc promises a key that was renamed away. This tool
makes the drift a CI failure in both directions:

- every tracked-family key (or key *pattern*) emitted by ``automodel_tpu/``
  source must match something documented in docs/observability.md, and
- every tracked-family key documented there must match something the code can
  emit.

Key extraction is AST-based, not regex-over-source: string constants and
f-strings are collected (docstrings excluded), with f-string interpolations
normalized to ``*`` wildcards — ``f"dynamics/{bucket}/{metric}"`` becomes the
pattern ``dynamics/*/*``. Two resolution passes keep the patterns tight:

- module-level string constants and parameter string defaults substitute into
  f-strings (``f"dynamics/{NUMERICS_BUCKET}/grad_amax"`` -> literal), and
- emitters parameterized by a ``prefix`` argument (moe/metrics.py serves both
  the ``moe_load/*`` and ``moe/*`` families) expand over the parameter default
  plus every constant ``prefix=`` value found at call sites.

Docs-side keys come from inline code spans and fenced code blocks only (prose
mentions of file paths never match), with ``<placeholder>`` / ``{placeholder}``
segments normalized to the same ``*`` wildcard.

Exit 0 when the two sets cover each other, 1 with a report otherwise.
"""

from __future__ import annotations

import argparse
import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SOURCE_ROOT = REPO / "automodel_tpu"
DOC = REPO / "docs" / "observability.md"

# the namespaced families under contract ("mem" before "moe" is irrelevant —
# matching is anchored) plus the bare "goodput" headline scalar
FAMILIES = ("goodput", "mem_plan", "mem", "moe_load", "moe", "dynamics",
            "trace", "signals", "tuner", "supervisor", "ledger", "badput")
_FAMILY_RE = re.compile(r"^(?:%s)/[^ ]+$" % "|".join(FAMILIES))
BARE_KEYS = {"goodput", "overlap_frac", "a2a_byte_share"}
# bare-prefix family: the measured trace-attribution keys ride log rows
# without a slash namespace (measured_frac_compute, measured_t_comm_s,
# measured_comm_axis_<ax>_s, measured_bound, ...); "*" appears in normalized
# f-string/doc-placeholder patterns
_BARE_PREFIX_RE = re.compile(r"^measured_[\w*]+$")

# strings that carry a family prefix but are not metric keys (paths, globs)
_NOT_A_KEY = re.compile(r"\.(py|json|jsonl|yaml|md)\b|[ :(),]|\.\*")


def _pattern_ok(p: str) -> bool:
    if p.endswith(("_", "/")):  # a startswith() prefix literal, not a key
        return False
    if p in BARE_KEYS or _BARE_PREFIX_RE.match(p):
        return not _NOT_A_KEY.search(p)
    return bool(_FAMILY_RE.match(p)) and not _NOT_A_KEY.search(p)


# ---------------------------------------------------------------- code side


def _docstring_ids(tree: ast.AST) -> set[int]:
    """ids of Constant nodes that are module/class/function docstrings."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr):
                v = body[0].value
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(id(v))
    return out


def _module_consts(tree: ast.Module) -> dict[str, str]:
    """Module-level NAME = "literal" bindings, for f-string substitution."""
    out: dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
    return out


def _param_defaults(fn: ast.AST) -> dict[str, str]:
    """param -> constant-string default for one function definition."""
    out: dict[str, str] = {}
    a = fn.args
    pos = a.posonlyargs + a.args
    for arg, default in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if isinstance(default, ast.Constant) and isinstance(default.value, str):
            out[arg.arg] = default.value
    for arg, default in zip(a.kwonlyargs, a.kw_defaults):
        if default is not None and isinstance(default, ast.Constant) \
                and isinstance(default.value, str):
            out[arg.arg] = default.value
    return out


def _prefix_call_values(tree: ast.AST) -> set[str]:
    """Constant values passed as a prefix= keyword anywhere in this module."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "prefix" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out.add(kw.value.value)
    return out


def _fstring_patterns(
    node: ast.JoinedStr, scope: dict[str, str], prefix_values: set[str]
) -> list[str]:
    """Wildcard patterns for one f-string; >1 when a prefix param fans out."""
    parts: list[list[str]] = [[""]]

    def _append(texts: list[str]) -> None:
        nonlocal parts
        parts = [p + [t] for p in parts for t in texts]

    for v in node.values:
        if isinstance(v, ast.Constant):
            _append([str(v.value)])
        elif isinstance(v, ast.FormattedValue) and isinstance(v.value, ast.Name) \
                and v.value.id in scope:
            if v.value.id == "prefix":
                _append(sorted({scope[v.value.id], *prefix_values}))
            else:
                _append([scope[v.value.id]])
        else:
            _append(["*"])
    return ["".join(p) for p in parts]


def code_patterns(root: Path = SOURCE_ROOT) -> dict[str, list[str]]:
    """pattern -> list of "file:line" emit sites for every tracked key."""
    out: dict[str, list[str]] = {}
    # prefix= fan-out values are collected repo-wide: the emitter
    # (moe/metrics.py) and its callers (observability/moe_stats.py) are
    # different modules
    prefix_values: set[str] = set()
    trees: list[tuple[Path, ast.Module]] = []
    for path in sorted(root.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as exc:  # pragma: no cover - repo must stay parseable
            print(f"[metric-lint] cannot parse {path}: {exc}", file=sys.stderr)
            continue
        trees.append((path, tree))
        prefix_values |= _prefix_call_values(tree)

    for path, tree in trees:
        skip = _docstring_ids(tree)
        consts = _module_consts(tree)
        rel = path.relative_to(REPO)

        def visit(node: ast.AST, scope: dict[str, str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(child, {**scope, **_param_defaults(child)})
                    continue
                if isinstance(child, ast.Constant) and isinstance(child.value, str):
                    if id(child) not in skip and _pattern_ok(child.value):
                        out.setdefault(child.value, []).append(
                            f"{rel}:{child.lineno}")
                    continue
                if isinstance(child, ast.JoinedStr):
                    for pat in _fstring_patterns(child, scope, prefix_values):
                        if _pattern_ok(pat):
                            out.setdefault(pat, []).append(f"{rel}:{child.lineno}")
                    continue  # don't re-collect the f-string's Constant parts
                visit(child, scope)

        visit(tree, consts)
    return out


# ---------------------------------------------------------------- docs side

_CODE_SPAN = re.compile(r"`([^`\n]+)`")
_FENCE = re.compile(r"```[^\n]*\n(.*?)```", re.S)
_DOC_TOKEN = re.compile(r"[\w*{}<>./-]+")


def doc_patterns(doc: Path = DOC) -> dict[str, list[str]]:
    """pattern -> mention count holder for every documented tracked key."""
    text = doc.read_text()
    spans: list[str] = _CODE_SPAN.findall(text) + _FENCE.findall(text)
    # JSON examples quote keys; the token regex below doesn't cross quotes
    out: dict[str, list[str]] = {}
    for span in spans:
        for token in _DOC_TOKEN.findall(span):
            token = token.strip(".,")
            # <layer> / {rank} placeholders are the docs' wildcard spelling
            pat = re.sub(r"<[^/>]*>|\{[^/}]*\}", "*", token)
            if _pattern_ok(pat):
                out.setdefault(pat, []).append(token)
    return out


# ---------------------------------------------------------------- matching


def _seg_regex(seg: str) -> re.Pattern:
    return re.compile(".+".join(re.escape(p) for p in seg.split("*")) or ".+")


def _seg_match(a: str, b: str) -> bool:
    if a == "*" or b == "*":
        return True
    return bool(
        _seg_regex(a).fullmatch(b.replace("*", "x"))
        or _seg_regex(b).fullmatch(a.replace("*", "x"))
    )


def patterns_match(a: str, b: str) -> bool:
    """True when key-patterns a and b can name the same metric key.

    Segment-wise; ``*`` (and doc placeholders, already normalized to ``*``)
    match any non-empty segment text. A trailing bare ``*`` is glob-like and
    absorbs any number of remaining segments, so the docs' family shorthand
    ``mem_plan/*`` covers the whole family.
    """
    sa, sb = a.split("/"), b.split("/")
    if len(sa) != len(sb):
        if sa[-1] == "*" and len(sb) > len(sa):
            sa = sa[:-1] + ["*"] * (len(sb) - len(sa) + 1)
        elif sb[-1] == "*" and len(sa) > len(sb):
            sb = sb[:-1] + ["*"] * (len(sa) - len(sb) + 1)
        else:
            return False
    return all(_seg_match(x, y) for x, y in zip(sa, sb))


def _is_bare_shorthand(pat: str) -> bool:
    """True for a family-wide glob like ``moe_load/*`` (docs prose shorthand)."""
    return pat.split("/", 1)[-1] == "*" and pat.split("/")[0] in FAMILIES


def check(code: dict[str, list[str]], docs: dict[str, list[str]]):
    """(undocumented, unemitted): the two one-directional failure lists."""
    # a prose mention of "the moe_load/* family" is not documentation of any
    # specific key — only non-shorthand doc patterns satisfy the code side
    specific_docs = [d for d in docs if not _is_bare_shorthand(d)]
    undocumented = {
        pat: sites for pat, sites in code.items()
        if not any(patterns_match(pat, d) for d in specific_docs)
    }
    unemitted = {
        pat: toks for pat, toks in docs.items()
        if not any(patterns_match(pat, c) for c in code)
    }
    return undocumented, unemitted


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--list", action="store_true",
                        help="print the extracted key patterns and exit")
    args = parser.parse_args(argv)

    code = code_patterns()
    docs = doc_patterns()
    if args.list:
        for pat in sorted(code):
            print(f"code {pat}  ({code[pat][0]})")
        for pat in sorted(docs):
            print(f"docs {pat}")
        return 0

    undocumented, unemitted = check(code, docs)
    for pat, sites in sorted(undocumented.items()):
        print(f"UNDOCUMENTED {pat}  emitted at {', '.join(sites[:3])}"
              f" — add it to {DOC.relative_to(REPO)}")
    for pat, toks in sorted(unemitted.items()):
        print(f"UNEMITTED    {pat}  documented as {toks[0]!r}"
              f" — no automodel_tpu/ source emits it")
    if undocumented or unemitted:
        print(f"\nmetric-key lint: {len(undocumented)} undocumented, "
              f"{len(unemitted)} unemitted (families: {', '.join(FAMILIES)})")
        return 1
    print(f"metric-key lint: {len(code)} code patterns <-> {len(docs)} doc "
          "patterns, all covered")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
