"""Chaos-driven loss-spike detection end-to-end (docs/observability.md,
"Training dynamics & numerics").

A finite gradient spike — one layer's params scaled by 1e3 with metrics left
untouched — must be detected *organically* at the next step: the loss z-score
trips the flight recorder, ``spike_report.json`` names the poisoned layer via
the per-layer EMA excursion, the anomaly verdict escalates to a rollback that
cites the same layer, and training recovers cleanly to the final step.
"""

import json
import textwrap

import numpy as np
import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import (
    TrainFinetuneRecipeForNextTokenPrediction,
)

from .test_train_recipe import _read_jsonl, _write_cfg


class TestDynamicsChaosSpike:
    # spike lm_head at step 6 (after the step-4 checkpoint): metrics at step 6
    # stay clean, step 7's loss explodes; dynamics on every step so the spiked
    # step itself is a sample and the param-norm EMA excursion names lm_head
    _extra = textwrap.dedent("""\
    observability:
      dynamics:
        enabled: true
        every_n_steps: 1
        spike_min_history: 4
        spike_zscore: 6.0
    resilience:
      enabled: true
      anomaly: {window: 20, min_history: 5}
      max_skipped_updates: 0
      rollback: {max_rollbacks: 2, skip_steps: 0}
      chaos:
        enabled: true
        grad_spike_steps: [6]
        grad_spike_factor: 1000.0
        grad_spike_layer: lm_head
    """).replace("\n", "\n    ")

    @pytest.fixture(scope="class")
    def chaos_run(self, tmp_path_factory, cpu_devices):
        tmp = tmp_path_factory.mktemp("dyn_chaos")
        cfg = load_config(_write_cfg(tmp, extra=self._extra, ckpt=True,
                                     max_steps=10, grad_acc=1))
        cfg["step_scheduler"]["ckpt_every_steps"] = 4
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        return {
            "tmp": tmp,
            "rows": _read_jsonl(tmp / "out" / "training.jsonl"),
            "report": json.loads((tmp / "out" / "spike_report.json").read_text()),
        }

    def test_spike_report_names_poisoned_layer(self, chaos_run):
        report = chaos_run["report"]
        assert report["reason"] == "loss_zscore"
        assert report["step"] == 7
        assert report["suspect"]["layer"] == "lm_head"
        # the excursion ratio is the param-norm blowup vs its EMA: ~1e3
        assert report["suspect"]["ratio_vs_ema"] > 100.0
        # forensics context rode along: the loss window, the dynamics ring
        # (including the spiked step itself), and the batch fingerprint
        assert len(report["loss_window"]) >= 4
        assert any("dynamics/lm_head/param_norm" in row
                   for row in report["dynamics_history"])
        assert "input_ids_shape" in report["batch"]
        # the dump is mirrored onto the metric stream as a resilience event
        rows = chaos_run["rows"]
        spike_events = [r for r in rows
                        if r.get("resilience/event") == "spike_report"]
        assert spike_events and spike_events[0]["resilience/layer"] == "lm_head"
        assert spike_events[0]["resilience/path"].endswith("spike_report.json")

    def test_rollback_verdict_cites_layer_and_recovers(self, chaos_run):
        rows = chaos_run["rows"]
        events = [r["resilience/event"] for r in rows if "resilience/event" in r]
        assert "rollback" in events and "rollback_done" in events
        # the spiked update landed in params, so recovery is a checkpoint
        # rollback — and the verdict cites the layer the dynamics named
        done = next(r for r in rows
                    if r.get("resilience/event") == "rollback_done")
        assert done["resilience/from_step"] == 7
        assert done["resilience/to_step"] == 4
        assert done["resilience/layer"] == "lm_head"

        # clean recovery: the poisoned step never logs a metric row, the rerun
        # trajectory is finite throughout and reaches max_steps
        losses = {r["step"]: r["loss"] for r in rows if "loss" in r}
        assert 7 not in losses or np.isfinite(losses[7])
        assert all(np.isfinite(v) for v in losses.values())
        assert max(losses) == 10
        assert losses[10] < 10.0  # back on a sane trajectory, not the spike

    def test_dynamics_rows_ride_the_metric_stream(self, chaos_run):
        rows = chaos_run["rows"]
        metric_rows = [r for r in rows if "loss" in r]
        keyed = [r for r in metric_rows
                 if "dynamics/lm_head/grad_norm" in r]
        assert keyed, "no metric row carried the per-layer dynamics sample"
        r = keyed[0]
        for bucket in ("lm_head", "embed", "layers.attention", "layers.mlp"):
            assert f"dynamics/{bucket}/grad_norm" in r
            assert f"dynamics/{bucket}/param_norm" in r
            assert f"dynamics/{bucket}/upd_ratio" in r
        assert "dynamics/num/grad_amax" in r
        assert "dynamics/lm_head/grad_norm_ema" in r
