"""MoE routing as pure functions (reference Gate, components/moe/layers.py:201).

The reference Gate is a stateful nn.Module accumulating expert load across grad-accum
microbatches and updating its correction bias in-place. Here routing is a pure function
returning ``(weights, indices, aux_loss, expert_load)``; the caller accumulates
``expert_load`` in the train-step carry and applies :func:`update_gate_bias` as a pure
param update at optimizer-step time. Under pjit the ``jnp.sum`` over tokens is already a
global (cross-data-shard) sum, so the reference's DTensor Partial/Replicate dance
(layers.py:400-436) disappears.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from automodel_tpu.moe.config import MoEConfig

__all__ = [
    "init_gate_params",
    "gate_logical_axes",
    "route",
    "fake_balanced_route",
    "update_gate_bias",
    "make_gate_bias_post_update",
]


def init_gate_params(cfg: MoEConfig, key: jax.Array, dtype=jnp.float32, init_std: float = 0.02) -> dict:
    """weight (E, D); optional bias (E,); correction bias kept fp32 (layers.py:262-266:
    small bf16 quantization errors flip routing decisions, so it never downcasts)."""
    params = {
        "weight": (jax.random.normal(key, (cfg.n_routed_experts, cfg.dim), jnp.float32) * init_std).astype(dtype)
    }
    if cfg.router_bias:
        params["bias"] = jnp.zeros((cfg.n_routed_experts,), dtype)
    if cfg.has_correction_bias:
        params["score_correction_bias"] = jnp.zeros((cfg.n_routed_experts,), jnp.float32)
    return params


def gate_logical_axes(cfg: MoEConfig) -> dict:
    axes = {"weight": (None, "embed")}
    if cfg.router_bias:
        axes["bias"] = (None,)
    if cfg.has_correction_bias:
        axes["score_correction_bias"] = (None,)
    return axes


def route(
    cfg: MoEConfig,
    gate_params: dict,
    x: jnp.ndarray,  # (T, D)
    token_mask: jnp.ndarray | None = None,  # (T,) bool
    *,
    training: bool = True,
):
    """Select top-k experts per token.

    Returns ``(weights (T, K), indices (T, K) int32, aux_loss scalar|None,
    expert_load (E,) fp32)``. ``expert_load`` counts valid tokens routed to each expert
    (reference _compute_expert_load, layers.py:444); aux_loss is the sequence-wise
    f_i·P_i balance loss (layers.py:467) when ``aux_loss_coeff > 0``.
    """
    T = x.shape[0]
    E, K = cfg.n_routed_experts, cfg.n_activated_experts
    if token_mask is None:
        token_mask = jnp.ones((T,), bool)

    # Gate math in fp32 regardless of activation dtype (reference gate_precision).
    # train_gate=False freezes the router (reference sets requires_grad, layers.py:244).
    gp = gate_params if cfg.train_gate else jax.lax.stop_gradient(gate_params)
    scores = x.astype(jnp.float32) @ gp["weight"].astype(jnp.float32).T
    if "bias" in gp:
        scores = scores + gp["bias"].astype(jnp.float32)

    if cfg.score_func == "softmax":
        # Selection and the aux loss always work on softmax *probabilities* (softmax is
        # monotone, so top-k on probs == top-k on logits; raw logits as P_i would make
        # the balance loss sign-indefinite, and a 0.0 group-mask fill could outrank
        # negative logits). softmax_before_topk only changes how WEIGHTS are computed.
        original_scores = jax.nn.softmax(scores, axis=-1)
        cand = original_scores
    else:  # sigmoid (DeepSeek-V3 noaux-tc)
        original_scores = jax.nn.sigmoid(scores)
        cand = original_scores
        if "score_correction_bias" in gp:
            cand = cand + gp["score_correction_bias"]

    # Group-limited (device-limited) selection: DeepSeek-V3 noaux-tc and
    # DeepSeek-V2 group_limited_greedy both mask all but the top n_limited_groups.
    if cfg.n_expert_groups > 1:
        grouped = cand.reshape(T, cfg.n_expert_groups, -1)
        if "score_correction_bias" in gp:
            group_scores = jax.lax.top_k(grouped, 2)[0].sum(-1)
        else:
            group_scores = grouped.max(-1)
        top_groups = jax.lax.top_k(group_scores, cfg.n_limited_groups)[1]
        group_mask = jnp.zeros((T, cfg.n_expert_groups), bool)
        group_mask = group_mask.at[jnp.arange(T)[:, None], top_groups].set(True)
        cand = jnp.where(group_mask[:, :, None], grouped, 0.0).reshape(T, E)

    indices = jax.lax.top_k(cand, K)[1]
    if cfg.score_func == "softmax" and not cfg.softmax_before_topk:
        # re-normalize over the selected k (gpt-oss / Mixtral convention)
        weights = jax.nn.softmax(jnp.take_along_axis(scores, indices, axis=-1), axis=-1)
    else:
        weights = jnp.take_along_axis(original_scores, indices, axis=-1)

    if cfg.norm_topk_prob and K > 1:
        weights = weights / (weights.sum(-1, keepdims=True) + 1e-20)
        original_scores = original_scores / (original_scores.sum(-1, keepdims=True) + 1e-20)
    weights = weights * cfg.route_scale

    valid = token_mask.astype(jnp.float32)
    # (T, K) one-hot sum -> (E,) load of valid tokens per expert.
    expert_load = jnp.zeros((E,), jnp.float32).at[indices].add(valid[:, None])

    aux_loss = None
    if cfg.aux_loss_coeff > 0 and training:
        # max(count, 1): an all-masked batch (e.g. a pipeline warmup/drain tick
        # carrying garbage) must yield aux 0, not 0/0 = NaN — which would poison
        # the whole loss even after the schedule masks the tick out (0 * NaN)
        context_length = jnp.maximum(valid.sum(), 1.0)
        expert_scores = (original_scores * valid[:, None]).sum(0)  # (E,)
        f_i = expert_load * E / (K * context_length)
        p_i = expert_scores / context_length
        aux_loss = jnp.sum(f_i * p_i)

    return weights.astype(x.dtype), indices.astype(jnp.int32), aux_loss, expert_load


def fake_balanced_route(
    cfg: MoEConfig,
    x: jnp.ndarray,  # (T, D)
    *,
    noise: float = 0.0,
    skip_first_n_experts: int = 0,
):
    """Uniform round-robin routing for benchmarking (reference FakeBalancedGate,
    layers.py:116): isolates compute perf from data-dependent routing imbalance.

    ``noise > 0`` adds content-seeded randomness (same x -> same routing, so remat
    recompute stays consistent — the reference derives the seed the same way,
    layers.py:166).
    """
    T = x.shape[0]
    E, K = cfg.n_routed_experts, cfg.n_activated_experts
    avail = E - skip_first_n_experts
    if noise > 0:
        seed = jnp.abs(jnp.sum(x.reshape(-1)[:4].astype(jnp.float32)) * 1e6).astype(jnp.int32)
        key = jax.random.key(seed)
        k1, k2, k3 = jax.random.split(key, 3)
        uniform = jnp.full((T, K), 1.0 / K)
        raw = jax.random.uniform(k1, (T, K))
        raw = raw / raw.sum(-1, keepdims=True)
        weights = (1 - noise) * uniform + noise * raw
        expert_bias = jax.random.normal(k2, (avail,)) * noise * 0.1
        scores = jax.random.uniform(k3, (T, avail)) + expert_bias
        indices = jax.lax.top_k(scores, K)[1] + skip_first_n_experts
    else:
        weights = jnp.full((T, K), 1.0 / K)
        indices = jnp.arange(T * K, dtype=jnp.int32).reshape(T, K) % avail + skip_first_n_experts
    expert_load = jnp.zeros((E,), jnp.float32).at[indices].add(1.0)
    return weights.astype(x.dtype), indices.astype(jnp.int32), None, expert_load


def update_gate_bias(
    score_correction_bias: jnp.ndarray,  # (E,) fp32
    cumulative_expert_load: jnp.ndarray,  # (E,) fp32, already global (pjit-summed)
    update_factor: float,
) -> jnp.ndarray:
    """DeepSeek-V3 loss-free balancing (reference Gate.update_bias, layers.py:379):
    push bias up for under-loaded experts, down for over-loaded, by sign(mean - load).

    Pure: returns the new bias; call once per optimizer step with the load accumulated
    over all microbatches, then reset the accumulator.
    """
    load = cumulative_expert_load.astype(jnp.float32)
    bias_update = jnp.sign(load.mean() - load)
    return score_correction_bias + bias_update * update_factor


def make_gate_bias_post_update(update_factor: float):
    """Train-step ``post_update`` hook applying :func:`update_gate_bias` per layer
    from the accumulated ``expert_load`` aux (single copy shared by the recipe and
    the driver dryrun)."""

    def post_update(params, aux):
        gate = params["moe_layers"]["moe"]["gate"]
        new_bias = jax.vmap(update_gate_bias, in_axes=(0, 0, None))(
            gate["score_correction_bias"], aux["expert_load"], update_factor
        )
        gate = dict(gate, score_correction_bias=new_bias)
        moe_layers = dict(params["moe_layers"], moe=dict(params["moe_layers"]["moe"], gate=gate))
        return dict(params, moe_layers=moe_layers)

    return post_update
