"""Tokenizer wrapper with BOS/EOS enforcement
(reference NeMoAutoTokenizer, _transformers/auto_tokenizer.py:50 and
tokenization/nemo_auto_tokenizer.py:19).

Delegates to ``transformers.AutoTokenizer`` and guarantees encode() emits BOS/EOS
when the model expects them — several HF tokenizers ship with add_bos/eos disabled,
which silently degrades SFT quality.
"""

from __future__ import annotations

__all__ = ["AutoTokenizer"]


class AutoTokenizer:
    @classmethod
    def from_pretrained(
        cls,
        path: str,
        ensure_bos: bool = True,
        ensure_eos: bool = False,
        **kwargs,
    ):
        # mistral-common routing (reference tokenization/registry.py): repos that
        # ship tekken.json / tokenizer.model.v* use Mistral's official tokenizer —
        # HF artifacts for those repos are absent or drift from the real template
        from automodel_tpu.models.tokenization_mistral import (
            MistralCommonTokenizer, find_mistral_tokenizer_file, mistral_common_available,
        )

        import os

        from automodel_tpu.models.hub import (
            TOKENIZER_PATTERNS, looks_like_repo_id, resolve_pretrained_path,
        )

        if looks_like_repo_id(path):
            # hub ids resolve process-0-first like model weights (models/hub.py)
            # so the mistral-file sniffing below sees real local files;
            # tokenizer-only patterns: don't pull the weight shards
            path = resolve_pretrained_path(path, allow_patterns=TOKENIZER_PATTERNS)

        if find_mistral_tokenizer_file(path):
            has_hf = os.path.isfile(os.path.join(path, "tokenizer.json")) or os.path.isfile(
                os.path.join(path, "tokenizer_config.json")
            )
            if mistral_common_available():
                return MistralCommonTokenizer.from_pretrained(path)
            if not has_hf:
                # no fallback possible: fail with the actionable message
                return MistralCommonTokenizer.from_pretrained(path)

        import transformers

        tok = transformers.AutoTokenizer.from_pretrained(path, **kwargs)
        return _EnforcingTokenizer(tok, ensure_bos=ensure_bos, ensure_eos=ensure_eos)


class _EnforcingTokenizer:
    def __init__(self, tok, ensure_bos: bool, ensure_eos: bool):
        self._tok = tok
        self.ensure_bos = ensure_bos and tok.bos_token_id is not None
        self.ensure_eos = ensure_eos and tok.eos_token_id is not None

    def __getattr__(self, name):
        return getattr(self._tok, name)

    def encode(self, text: str, **kwargs) -> list[int]:
        ids = list(self._tok.encode(text, **kwargs))
        if self.ensure_bos and (not ids or ids[0] != self._tok.bos_token_id):
            ids = [self._tok.bos_token_id] + ids
        if self.ensure_eos and (not ids or ids[-1] != self._tok.eos_token_id):
            ids = ids + [self._tok.eos_token_id]
        return ids

    def __call__(self, *args, **kwargs):
        return self._tok(*args, **kwargs)

    def __len__(self) -> int:
        return len(self._tok)
