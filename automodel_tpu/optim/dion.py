"""Dion optimizer — distributed orthonormalized updates (reference optim/utils.py
integrates the external ``dion`` package; implemented natively here as an optax
transform, per Ahn et al., "Dion: Distributed Orthonormalized Updates",
arXiv:2504.05295 Algorithm 1).

Per matrix parameter W (m, n) with momentum M and a persistent right factor
Q (n, r):

    M  += g
    P   = orthonormalize(M @ Q)          (QR, column space power iteration)
    R   = M^T @ P
    M  -= (1 - mu) * P @ R^T             (error feedback: only the applied
                                          low-rank part decays from momentum)
    Q   = column_normalize(R)
    dW  = -lr * (sqrt(m / n) * P @ Q^T + weight_decay * W)

Leading stack dims (layer scan, experts) are vmapped. Non-matrix leaves
(norms, biases) and token-dimension leaves (embeddings, lm_head) take the
reference's fallback path: plain AdamW with its own lr.

TPU notes: QR on (m, r) tall matrices maps to XLA's householder pipeline; the
whole update is jit-friendly (no data-dependent shapes). The Q state lives in
the *canonical flattened* geometry (stack..., cols, r): ``opt_state_shardings``
shards its leading stack dims like the parameter's and replicates the rest
(cols x r is rank_fraction^2 of the weight's footprint per stack entry; at very
large widths shard it explicitly before reaching for rank_fraction >= 0.5).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["dion", "build_dion_optimizer"]


class DionState(NamedTuple):
    momentum: Any  # pytree matching matrix leaves
    q: Any  # pytree of right factors


def _orthonormalize(p: jnp.ndarray) -> jnp.ndarray:
    # reduced QR (unguarded: rank-deficient columns give arbitrary-but-valid
    # orthonormal completions, which the error feedback absorbs next step)
    q, _ = jnp.linalg.qr(p.astype(jnp.float32))
    return q


def _col_normalize(r: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    return r / (jnp.linalg.norm(r, axis=-2, keepdims=True) + eps)


def _dion_update_2d(g, m, q, mu: float):
    """One Dion step for a single (m, n) matrix; returns (update, m_new, q_new)."""
    g = g.astype(jnp.float32)
    m = m + g
    p = _orthonormalize(m @ q)  # (rows, r)
    r = m.T @ p  # (cols, r)
    m = m - (1.0 - mu) * (p @ r.T)
    q_new = _col_normalize(r)
    rows, cols = g.shape[-2], g.shape[-1]
    scale = jnp.sqrt(jnp.asarray(rows / cols, jnp.float32))
    # positive ascent direction; the caller applies the -lr (optax convention)
    update = scale * (p @ q_new.T)
    return update, m, q_new


def _leaf_name(path: tuple) -> str:
    return (getattr(path[-1], "key", str(path[-1])) if path else "").lower()


_STACK_AXES = ("layers", "expert", "experts", "blocks")


def _axes_canon_shape(shape: tuple, axes) -> tuple | None:
    """Canonical (stack..., rows, cols) from the model's logical axis names.

    Leading ``layers``/``expert`` axes stay vmapped stacks; consecutive runs of
    head-split axes (any name containing "head": heads, kv_heads, head_dim) merge
    into one matrix dim. Returns None when the leaf does not reduce to exactly a
    2-D matrix (biases, norms, conv kernels, exotic 3-way layouts) — the caller
    routes those to AdamW."""
    if axes is None or len(axes) != len(shape):
        return None
    n_stack = 0
    for a in axes:
        if a in _STACK_AXES:
            n_stack += 1
        else:
            break
    sizes: list[int] = []
    prev_head = False
    for dim, name in zip(shape[n_stack:], axes[n_stack:]):
        is_head = "head" in (name or "")
        if is_head and prev_head:
            sizes[-1] *= dim
        else:
            sizes.append(dim)
        prev_head = is_head
    if len(sizes) != 2 or min(sizes) < 2:
        return None
    return (*shape[:n_stack], *sizes)


def _canon_shape(path: tuple, shape: tuple, axes_by_path: dict | None = None) -> tuple:
    """Canonical (stack..., rows, cols) view of a matrix leaf.

    Head-split attention projections must be orthonormalized as their full matmul
    matrix, not per-head blocks. When the model's logical axes are available
    (``build_dion_optimizer(logical_axes=...)``) the grouping is layout-driven and
    covers every family (MLA wq_b/wkv_b, DeltaNet wqkvz, ...). The name fallback
    handles only the classic stacked 4-D cases: wq/wk/wv (L, D, N, H) ->
    (L, D, N*H) and wo (L, N, H, D) -> (L, N*H, D); 3-D leaves are left alone
    (a stacked (L, d, d) projection is already a per-layer matrix)."""
    if axes_by_path is not None:
        canon = _axes_canon_shape(shape, axes_by_path.get(jax.tree_util.keystr(path)))
        if canon is not None:
            return canon
    name = _leaf_name(path)
    if len(shape) >= 4 and name in ("wq", "wk", "wv"):
        return (*shape[:-2], shape[-2] * shape[-1])
    if len(shape) >= 4 and name == "wo":
        return (*shape[:-3], shape[-3] * shape[-2], shape[-1])
    return tuple(shape)


def dion(
    learning_rate: optax.ScalarOrSchedule,
    mu: float = 0.95,
    rank_fraction: float = 0.25,
    min_rank: int = 1,
    axes_by_path: dict | None = None,
) -> optax.GradientTransformation:
    """Dion for matrix leaves (canonical matrix view; leading dims vmapped as stacks).

    Wrap with ``optax.masked`` / ``multi_transform`` for mixed parameter groups —
    or use :func:`build_dion_optimizer`, which applies the reference's grouping.
    """

    def rank_of(shape) -> int:
        return max(min_rank, int(min(shape[-2], shape[-1]) * rank_fraction))

    def init_fn(params):
        def init_leaf(path, p):
            if p.ndim < 2:
                raise ValueError("dion() only handles matrix leaves; mask others out")
            shape = _canon_shape(path, p.shape, axes_by_path)
            r = rank_of(shape)
            # deterministic per-shape init; orthonormalized on first use
            key = jax.random.key(len(shape) * 1000 + shape[-1])
            q = jax.random.normal(key, (*shape[:-2], shape[-1], r), jnp.float32)
            return q

        momentum = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        qs = jax.tree_util.tree_map_with_path(init_leaf, params)
        return DionState(momentum=momentum, q=qs)

    def update_fn(updates, state, params=None):
        del params
        lr = learning_rate

        def leaf(path, g, m, q):
            flat = _canon_shape(path, g.shape, axes_by_path)
            gf, mf = g.reshape(flat), m.reshape(flat)
            fn = _dion_update_2d
            for _ in range(len(flat) - 2):
                fn = jax.vmap(fn, in_axes=(0, 0, 0, None))
            u, m2, q2 = fn(gf, mf, q, mu)
            # dict result (not tuple): optax.MaskedNode is a tuple subclass and must
            # pass through untouched under multi_transform
            return {"u": u.reshape(g.shape), "m": m2.reshape(g.shape), "q": q2}

        is_res = lambda x: isinstance(x, dict) and set(x) == {"u", "m", "q"}
        out = jax.tree_util.tree_map_with_path(leaf, updates, state.momentum, state.q)
        upd = jax.tree.map(lambda o: o["u"], out, is_leaf=is_res)
        m_new = jax.tree.map(lambda o: o["m"], out, is_leaf=is_res)
        q_new = jax.tree.map(lambda o: o["q"], out, is_leaf=is_res)
        if callable(lr):
            # schedules thread through optax.scale_by_schedule (build_dion_optimizer)
            raise ValueError("pass schedules via build_dion_optimizer")
        upd = jax.tree.map(lambda u: -lr * u, upd)
        return upd, DionState(momentum=m_new, q=q_new)

    return optax.GradientTransformation(init_fn, update_fn)


def _is_matrix_path(path: tuple, leaf) -> bool:
    """Reference dion grouping (optim/utils.py:34-151): matmul weights get Dion;
    embeddings / unembeddings / norms / biases / conv kernels fall back to AdamW.

    Stacked layer params keep their leading scan dim, so the check is name-based
    (a stacked norm is (L, d) and must NOT be orthonormalized)."""
    parts = [getattr(k, "key", str(k)).lower() for k in path]
    name = "/".join(parts)
    if leaf.ndim < 2 or min(leaf.shape[-2:]) < 2:
        return False
    if any(tok in name for tok in ("embed", "lm_head", "pos_emb", "score_correction", "conv", "norm")):
        return False
    if any(
        pt.startswith("b_")
        or "bias" in pt
        # per-head attention bias vectors (bq (N,H) etc.) are AdamW leaves even
        # though their trailing dims look matrix-shaped
        or pt in ("bq", "bk", "bv", "bo", "ba", "sinks", "a_log", "d_skip")
        for pt in parts
    ):
        return False
    return True


def build_dion_optimizer(
    learning_rate: optax.ScalarOrSchedule,
    mu: float = 0.95,
    rank_fraction: float = 0.25,
    adamw_lr_scale: float = 1.0,
    weight_decay: float = 0.0,
    b1: float = 0.9,
    b2: float = 0.95,
    eps: float = 1e-8,
    max_grad_norm: float | None = None,
    logical_axes: Any = None,
) -> optax.GradientTransformation:
    """Dion on matrix params + AdamW on the rest, with optional global clipping.

    ``logical_axes`` (the model's ``logical_axes()`` pytree) makes the matrix
    canonicalization layout-driven: head-split dims merge into the true matmul
    matrix and leaves that do not reduce to a 2-D matrix fall back to AdamW.
    Without it, a conservative name-based heuristic covers the standard
    wq/wk/wv/wo stacked layouts.

    Decoupled weight decay applies to BOTH groups, masked off norms/biases (the
    same no_decay_mask contract as build_optimizer's adamw path)."""
    from automodel_tpu.optim.builder import no_decay_mask as masked_decay_mask

    axes_by_path = None
    if logical_axes is not None:
        flat = jax.tree_util.tree_flatten_with_path(
            logical_axes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        axes_by_path = {jax.tree_util.keystr(p): v for p, v in flat}

    def is_dion_leaf(path, leaf) -> bool:
        if not _is_matrix_path(path, leaf):
            return False
        if axes_by_path is not None:
            axes = axes_by_path.get(jax.tree_util.keystr(path))
            # known layout that doesn't reduce to a matrix -> AdamW
            if axes is not None and _axes_canon_shape(tuple(leaf.shape), axes) is None:
                return False
        return True

    def label_fn(params):
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: "dion" if is_dion_leaf(path, leaf) else "adamw", params
        )

    neg_lr = (lambda c: -learning_rate(c)) if callable(learning_rate) else -learning_rate
    decay = (
        [optax.add_decayed_weights(weight_decay, mask=masked_decay_mask)]
        if weight_decay
        else []
    )
    dion_tx = optax.chain(
        # lr=-1 cancels dion()'s internal descent sign, leaving the raw ascent
        # direction for the standard optax add_decayed_weights -> scale(-lr) tail
        dion(-1.0, mu=mu, rank_fraction=rank_fraction, axes_by_path=axes_by_path),
        *decay,
        optax.scale_by_schedule(neg_lr) if callable(learning_rate) else optax.scale(neg_lr),
    )
    adamw_lr = (
        (lambda c: adamw_lr_scale * learning_rate(c)) if callable(learning_rate)
        else adamw_lr_scale * learning_rate
    )
    adamw_tx = optax.adamw(
        adamw_lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
        mask=masked_decay_mask if weight_decay else None,
    )

    tx = optax.multi_transform({"dion": dion_tx, "adamw": adamw_tx}, label_fn)
    if max_grad_norm:
        tx = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
    return tx
