from automodel_tpu.models.kimivl.model import KimiVLConfig, KimiVLForConditionalGeneration

__all__ = ["KimiVLConfig", "KimiVLForConditionalGeneration"]
