"""MoE family logit parity vs HF transformers (torch CPU) — Qwen3-MoE, GPT-OSS, DSv3."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.models.auto import AutoModelForCausalLM
from automodel_tpu.models.common.backend import BackendConfig

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _fp32_backend(**kw):
    return BackendConfig(dtype="float32", remat_policy="full", **kw)


def _save_hf(model, tmp_path):
    d = str(tmp_path / "hf")
    model.save_pretrained(d, safe_serialization=True)
    return d


def _compare(hf_model, tmp_path, atol=5e-4, seq=16):
    hf_model.eval()
    d = _save_hf(hf_model, tmp_path)
    model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, hf_model.config.vocab_size, (2, seq))
    ours, stats = model(params, jnp.asarray(ids), training=False)
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=atol, rtol=1e-3)
    return model, params, stats


def tiny_qwen3_moe_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96, moe_intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_experts=8, num_experts_per_tok=2, decoder_sparse_step=1, mlp_only_layers=[],
        norm_topk_prob=True, max_position_embeddings=128,
    )
    base.update(kw)
    return transformers.Qwen3MoeConfig(**base)


def tiny_gpt_oss_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_local_experts=4, num_experts_per_tok=2, sliding_window=8,
        layer_types=["sliding_attention", "full_attention"],
        max_position_embeddings=128, rope_scaling=None, swiglu_limit=7.0,
    )
    base.update(kw)
    return transformers.GptOssConfig(**base)


def tiny_dsv3_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96, moe_intermediate_size=32,
        num_hidden_layers=3, num_attention_heads=4, q_lora_rank=24, kv_lora_rank=32,
        qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
        n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
        n_group=2, topk_group=1, routed_scaling_factor=2.5, norm_topk_prob=True,
        first_k_dense_replace=1, max_position_embeddings=128, rope_scaling=None,
    )
    base.update(kw)
    return transformers.models.deepseek_v3.DeepseekV3Config(**base)


class TestQwen3MoeParity:
    def test_logits_match_hf(self, tmp_path):
        torch.manual_seed(0)
        hf = transformers.Qwen3MoeForCausalLM(tiny_qwen3_moe_cfg())
        _, _, stats = _compare(hf, tmp_path)
        assert stats["expert_load"].shape == (2, 8)

    def test_roundtrip_and_key_parity(self, tmp_path):
        torch.manual_seed(1)
        hf = transformers.Qwen3MoeForCausalLM(tiny_qwen3_moe_cfg())
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
        adapter = model.state_dict_adapter()
        hf_dict = adapter.to_hf(params)
        theirs = {k for k in hf.state_dict() if "rotary_emb" not in k}
        assert set(hf_dict) == theirs
        params2 = adapter.from_hf(hf_dict)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, jax.tree.map(jnp.asarray, params2),
        )


class TestGptOssParity:
    def test_logits_match_hf(self, tmp_path):
        torch.manual_seed(2)
        hf = transformers.GptOssForCausalLM(tiny_gpt_oss_cfg())
        model, params, _ = _compare(hf, tmp_path, seq=24)
        # sliding window flag wired through layer_types
        assert model.config.sliding_flags == [True, False]

    def test_logits_match_hf_flash_kernel(self, tmp_path):
        """Full model through the Pallas kernel (interpret): sinks + traced
        per-layer sliding windows run INSIDE flash now, not the XLA fallback."""
        torch.manual_seed(2)
        hf = transformers.GptOssForCausalLM(tiny_gpt_oss_cfg())
        hf.eval()
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=_fp32_backend(attention="flash_interpret")
        )
        ids = np.random.RandomState(0).randint(0, hf.config.vocab_size, (2, 24))
        ours, _ = model(params, jnp.asarray(ids), training=False)
        with torch.no_grad():
            theirs = hf(torch.tensor(ids)).logits.float().numpy()
        np.testing.assert_allclose(np.asarray(ours), theirs, atol=5e-4, rtol=1e-3)

    def test_key_parity(self, tmp_path):
        torch.manual_seed(3)
        hf = transformers.GptOssForCausalLM(tiny_gpt_oss_cfg())
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
        ours = set(model.state_dict_adapter().to_hf(params))
        theirs = {k for k in hf.state_dict() if "rotary_emb" not in k}
        assert ours == theirs


class TestDeepseekV3Parity:
    def test_logits_match_hf(self, tmp_path):
        torch.manual_seed(4)
        hf = transformers.models.deepseek_v3.DeepseekV3ForCausalLM(tiny_dsv3_cfg())
        model, params, stats = _compare(hf, tmp_path)
        # dense prefix + 2 MoE layers
        assert "dense_layers" in params and stats["expert_load"].shape == (2, 8)
        # correction bias loaded fp32
        assert params["moe_layers"]["moe"]["gate"]["score_correction_bias"].dtype == jnp.float32

    def test_no_q_lora(self, tmp_path):
        torch.manual_seed(5)
        hf = transformers.models.deepseek_v3.DeepseekV3ForCausalLM(tiny_dsv3_cfg(q_lora_rank=None))
        _compare(hf, tmp_path)

    def test_deepseek_v2_softmax_routing(self, tmp_path):
        # V2: softmax-before-topk greedy routing, no correction bias, no bias updates
        cfg = transformers.DeepseekV2Config(
            vocab_size=128, hidden_size=64, intermediate_size=96, moe_intermediate_size=32,
            num_hidden_layers=2, num_attention_heads=4, q_lora_rank=None, kv_lora_rank=32,
            qk_nope_head_dim=16, qk_rope_head_dim=8, v_head_dim=16,
            n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
            n_group=None, topk_group=None, routed_scaling_factor=1.0, norm_topk_prob=False,
            scoring_func="softmax", topk_method="greedy",
            first_k_dense_replace=1, max_position_embeddings=128, rope_scaling=None,
        )
        torch.manual_seed(8)
        hf = transformers.DeepseekV2ForCausalLM(cfg)
        model, params, _ = _compare(hf, tmp_path)
        assert model.config.moe.score_func == "softmax"
        assert model.config.moe.gate_bias_update_factor == 0.0
        assert "score_correction_bias" not in params["moe_layers"]["moe"]["gate"]

    def test_key_parity(self, tmp_path):
        torch.manual_seed(6)
        hf = transformers.models.deepseek_v3.DeepseekV3ForCausalLM(tiny_dsv3_cfg())
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
        ours = set(model.state_dict_adapter().to_hf(params))
        theirs = {k for k in hf.state_dict() if "rotary_emb" not in k}
        assert ours == theirs


class TestShardedMoEForward:
    def test_dsv3_sharded_forward_runs(self, tmp_path, mesh8):
        from automodel_tpu.parallel.mesh import default_sharding_rules

        torch.manual_seed(7)
        hf = transformers.models.deepseek_v3.DeepseekV3ForCausalLM(tiny_dsv3_cfg())
        hf.eval()
        d = _save_hf(hf, tmp_path)
        rules = default_sharding_rules().with_mesh(mesh8)
        model, params = AutoModelForCausalLM.from_pretrained(
            d, dtype=jnp.float32, backend=_fp32_backend(), rules=rules
        )
        rng = np.random.RandomState(0)
        ids = rng.randint(0, hf.config.vocab_size, (4, 16))
        with jax.sharding.set_mesh(rules.mesh):
            logits, _ = jax.jit(lambda p, i: model(p, i, rules=rules, training=False))(
                params, jnp.asarray(ids)
            )
        with torch.no_grad():
            theirs = hf(torch.tensor(ids)).logits.float().numpy()
        np.testing.assert_allclose(np.asarray(logits), theirs, atol=2e-3, rtol=1e-3)


def tiny_mixtral_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=8, num_experts_per_tok=2, router_aux_loss_coef=0.02,
        max_position_embeddings=128, sliding_window=None,
    )
    base.update(kw)
    return transformers.MixtralConfig(**base)


class TestMixtralParity:
    def test_logits_match_hf(self, tmp_path):
        torch.manual_seed(5)
        hf = transformers.MixtralForCausalLM(tiny_mixtral_cfg())
        _, _, stats = _compare(hf, tmp_path)
        assert stats["expert_load"].shape == (2, 8)

    def test_roundtrip_and_key_parity(self, tmp_path):
        torch.manual_seed(6)
        hf = transformers.MixtralForCausalLM(tiny_mixtral_cfg())
        d = _save_hf(hf, tmp_path)
        model, params = AutoModelForCausalLM.from_pretrained(d, dtype=jnp.float32, backend=_fp32_backend())
        adapter = model.state_dict_adapter()
        hf_dict = adapter.to_hf(params)
        theirs = {k for k in hf.state_dict() if "rotary_emb" not in k}
        assert set(hf_dict) == theirs
        params2 = adapter.from_hf(hf_dict)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params, jax.tree.map(jnp.asarray, params2),
        )
