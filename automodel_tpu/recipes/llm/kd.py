"""Knowledge-distillation recipe (reference KnowledgeDistillationRecipeForNextTokenPrediction,
recipes/llm/kd.py:145).

A teacher model runs forward-only next to the student; the loss blends hard-label CE
with forward-KL to the teacher's temperature-softened distribution:

    loss = (1 - kd_ratio) * CE(student, labels) + kd_ratio * KL(teacher || student)

The teacher rides through the jitted step as a *frozen* pytree argument (the same
``with_frozen`` path PEFT uses) — no gradients, no optimizer state, donated nothing.

YAML adds two sections to the finetune contract:

.. code-block:: yaml

    teacher_model:
      pretrained_model_name_or_path: /path/to/teacher   # or config: {...}
    kd: {temperature: 1.0, kd_ratio: 0.5}
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.models.auto import AutoModelForCausalLM, load_hf_config
from automodel_tpu.ops.losses import kd_loss, masked_cross_entropy
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.training.train_step import make_train_step

logger = logging.getLogger(__name__)

__all__ = ["KnowledgeDistillationRecipe", "main"]


class KnowledgeDistillationRecipe(TrainFinetuneRecipeForNextTokenPrediction):
    def _build_teacher(self):
        cfg = self.cfg
        t_cfg = cfg.get("teacher_model")
        if t_cfg is None:
            raise ValueError("kd recipe needs a teacher_model section")
        pretrained = t_cfg.get("pretrained_model_name_or_path")
        with self.mesh:
            if pretrained:
                self.teacher, self.teacher_params = AutoModelForCausalLM.from_pretrained(
                    pretrained, backend=self.backend, dtype=jnp.float32, rules=self.rules
                )
            else:
                model_cfg = t_cfg.get("config")
                if model_cfg is None:
                    raise ValueError("teacher_model needs pretrained_model_name_or_path or config")
                hf = model_cfg.to_dict() if isinstance(model_cfg, ConfigNode) else dict(model_cfg)
                self.teacher = AutoModelForCausalLM.from_config(hf, backend=self.backend)
                shardings = self.rules.tree_sharding(self.teacher.logical_axes())
                init_fn = jax.jit(lambda k: self.teacher.init(k, jnp.float32), out_shardings=shardings)
                self.teacher_params = init_fn(self.rng.key("teacher_init"))
        n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.teacher_params))
        logger.info("teacher: %s (%.1fM params)", type(self.teacher).__name__, n / 1e6)

    def _build_train_step(self):
        if self.mesh_ctx.pp > 1:
            raise NotImplementedError("kd + pp composition is not wired yet")
        self._build_teacher()
        temperature = float(self.cfg.get("kd.temperature", 1.0))
        kd_ratio = float(self.cfg.get("kd.kd_ratio", 0.5))

        def kd_core(student_params, teacher_params, batch, num_label_tokens):
            student_logits = self.model(
                student_params, batch["input_ids"], positions=batch["positions"],
                segment_ids=batch["segment_ids"], rules=self.rules,
            )
            teacher_logits = jax.lax.stop_gradient(
                self.teacher(
                    teacher_params, batch["input_ids"], positions=batch["positions"],
                    segment_ids=batch["segment_ids"], rules=self.rules,
                )
            )
            ce = masked_cross_entropy(student_logits, batch["labels"], num_label_tokens)
            kd = kd_loss(
                student_logits, teacher_logits, batch["labels"],
                temperature=temperature, num_label_tokens=num_label_tokens,
            )
            return (1.0 - kd_ratio) * ce + kd_ratio * kd

        if self.peft is not None:
            # kd + peft (reference composes them, infrastructure.py:303): the
            # frozen slot carries BOTH the teacher and the student's lora base
            if self.peft.dropout:
                raise NotImplementedError(
                    "kd + lora dropout is not wired (the KD step does not thread "
                    "a dropout rng); set peft.dropout: 0"
                )
            from automodel_tpu.peft.lora import merge_lora_params

            def kd_forward(lora, frozen, batch, num_label_tokens):
                merged = merge_lora_params(frozen["base"], lora, self.peft)
                return kd_core(merged, frozen["teacher"], batch, num_label_tokens)
        else:
            def kd_forward(params, frozen, batch, num_label_tokens):
                return kd_core(params, frozen["teacher"], batch, num_label_tokens)

        step = make_train_step(kd_forward, self.optimizer, with_frozen=True,
                               guard_nonfinite=self._check_nan_grads)
        return jax.jit(step, donate_argnums=(0, 1))

    @property
    def _kd_frozen_arg(self):
        frozen = {"teacher": self.teacher_params}
        if self.peft is not None:
            frozen["base"] = self.params
        return frozen

    def run_train_validation_loop(self):
        # thread the teacher (and, under peft, the student base) through the
        # frozen slot; *_ swallows the base loop's peft extra
        jitted = self._train_step
        self._train_step = lambda p, o, stack, *_: jitted(p, o, stack, self._kd_frozen_arg)
        super().run_train_validation_loop()


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = KnowledgeDistillationRecipe(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    return recipe


if __name__ == "__main__":
    main()
