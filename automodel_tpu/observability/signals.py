"""The tuner signals bundle: everything ROADMAP item 4 consumes, one artifact.

The autotuner needs, per (model, mesh, seq) cell, the analytic roofline, the
measured trace breakdown (trace_analysis.py), whether the two agree, the HBM
headroom (memory_plan.py), and the compile-cache state — scattered today
across the compile_costs row, trace_report.json, the run_header, and the
compile_summary row. ``build_signals`` assembles them into one
``signals.json`` document with a machine-checkable schema (documented in
docs/observability.md "Measured trace attribution & signals"); absent sources
produce explicit ``null`` sections, never missing keys, so a consumer can
distinguish "not captured" from "captured as zero".

Schema (version 1)::

    {"version": 1, "cells": [{
        "cell":           {"model": str|null, "mesh": {axis: int}|null,
                           "seq_len": int|null},
        "analytic":       {"roofline_bound": str, "roofline_step_time_s": num,
                           "roofline_t_compute_s": num, "roofline_t_memory_s": num,
                           "roofline_t_comm_s": num, "hlo_flops": num|null,
                           "comm_bytes_total": num|null,
                           "comm_bytes_moe_a2a": num|null} | null,
        "measured":       {"measured_bound": str, "measured_step_time_s": num,
                           "overlap_frac": num, "measured_frac_compute": num,
                           "measured_frac_comm": num, "measured_frac_moe_a2a": num,
                           "measured_frac_host": num} | null,
        "reconciliation": {"analytic_bound": str, "measured_bound": str,
                           "agrees": bool, "verdict": str} | null,
        "memory":         {"hbm_headroom_gib": num|null, "hbm_limit_gib": num|null,
                           "total_gib": num, "fits": bool|null} | null,
        "compile_cache":  {"hits": num, "misses": num, "aot": num,
                           "jit_fallback": num} | null}]}
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any

logger = logging.getLogger(__name__)

__all__ = ["SIGNALS_VERSION", "build_signals", "validate_signals",
           "write_signals"]

SIGNALS_VERSION = 1

# section -> {field: (types, required)}; numbers accept int or float, and a
# field marked optional may be null (absent sources stay explicit)
_NUM = (int, float)
_SECTIONS: dict[str, dict[str, tuple[tuple, bool]]] = {
    "analytic": {
        "roofline_bound": ((str,), True),
        "roofline_step_time_s": (_NUM, True),
        "roofline_t_compute_s": (_NUM, True),
        "roofline_t_memory_s": (_NUM, True),
        "roofline_t_comm_s": (_NUM, True),
        "hlo_flops": (_NUM, False),
        "comm_bytes_total": (_NUM, False),
        "comm_bytes_moe_a2a": (_NUM, False),
    },
    "measured": {
        "measured_bound": ((str,), True),
        "measured_step_time_s": (_NUM, True),
        "overlap_frac": (_NUM, True),
        "measured_frac_compute": (_NUM, True),
        "measured_frac_comm": (_NUM, True),
        "measured_frac_moe_a2a": (_NUM, True),
        "measured_frac_host": (_NUM, True),
    },
    "reconciliation": {
        "analytic_bound": ((str,), True),
        "measured_bound": ((str,), True),
        "agrees": ((bool,), True),
        "verdict": ((str,), True),
    },
    "memory": {
        "hbm_headroom_gib": (_NUM, False),
        "hbm_limit_gib": (_NUM, False),
        "total_gib": (_NUM, True),
        "fits": ((bool,), False),
    },
    "compile_cache": {
        "hits": (_NUM, True),
        "misses": (_NUM, True),
        "aot": (_NUM, True),
        "jit_fallback": (_NUM, True),
    },
}


def _analytic_section(roofline: dict | None, costs: dict | None) -> dict | None:
    if not roofline:
        return None
    out = {k: roofline.get(k) for k in
           ("roofline_bound", "roofline_step_time_s", "roofline_t_compute_s",
            "roofline_t_memory_s", "roofline_t_comm_s")}
    if any(v is None for v in out.values()):
        return None
    costs = costs or {}
    out["hlo_flops"] = costs.get("hlo_flops")
    out["comm_bytes_total"] = costs.get("comm_bytes_total")
    out["comm_bytes_moe_a2a"] = costs.get("comm_bytes_moe_a2a")
    return out


def _measured_section(trace_summary: dict | None) -> dict | None:
    if not trace_summary:
        return None
    out = {k: trace_summary.get(k) for k in _SECTIONS["measured"]}
    if out["measured_bound"] is None:
        return None
    return out


def _reconciliation_section(trace_summary: dict | None) -> dict | None:
    if not trace_summary or "trace/bound_agrees" not in trace_summary:
        return None
    return {
        "analytic_bound": trace_summary.get("trace/analytic_bound"),
        "measured_bound": trace_summary.get("measured_bound"),
        "agrees": bool(trace_summary["trace/bound_agrees"]),
        "verdict": trace_summary.get("trace/verdict"),
    }


def _memory_section(plan: Any) -> dict | None:
    if plan is None:
        return None
    head = plan.headroom_bytes
    limit = plan.hbm_limit_bytes
    return {
        "hbm_headroom_gib": round(head / 2**30, 4) if head is not None else None,
        "hbm_limit_gib": round(limit / 2**30, 4) if limit is not None else None,
        "total_gib": round(plan.total_bytes / 2**30, 4),
        "fits": plan.fits,
    }


def _compile_cache_section(compile_summary: dict | None) -> dict | None:
    if not compile_summary:
        return None
    return {
        "hits": int(compile_summary.get("compile_cache_hits", 0)),
        "misses": int(compile_summary.get("compile_cache_misses", 0)),
        "aot": int(compile_summary.get("compile_aot", 0)),
        "jit_fallback": int(compile_summary.get("compile_jit_fallback", 0)),
    }


def build_cell(cell: dict | None = None, mesh_axes: dict | None = None,
               roofline: dict | None = None, costs: dict | None = None,
               trace_summary: dict | None = None, memory_plan: Any = None,
               compile_summary: dict | None = None) -> dict[str, Any]:
    """One schema-shaped cell from whatever sources exist right now."""
    cell = dict(cell or {})
    return {
        "cell": {
            "model": cell.get("model"),
            "mesh": ({str(k): int(v) for k, v in mesh_axes.items()}
                     if mesh_axes else cell.get("mesh")),
            "seq_len": cell.get("seq_len"),
        },
        "analytic": _analytic_section(roofline, costs),
        "measured": _measured_section(trace_summary),
        "reconciliation": _reconciliation_section(trace_summary),
        "memory": _memory_section(memory_plan),
        "compile_cache": _compile_cache_section(compile_summary),
    }


def build_signals(cells: list[dict] | dict | None = None,
                  **one_cell_kwargs: Any) -> dict[str, Any]:
    """The signals.json document. Either pass pre-built cells (a list, or one
    dict) or the :func:`build_cell` kwargs for a single-cell document."""
    if one_cell_kwargs:
        assert not cells, "pass cells OR build_cell kwargs, not both"
        cells = [build_cell(**one_cell_kwargs)]
    elif isinstance(cells, dict):
        cells = [cells]
    return {"version": SIGNALS_VERSION, "cells": list(cells or [])}


def validate_signals(doc: Any) -> list[str]:
    """Schema-check a signals document; returns problems ([] = valid)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected object"]
    if doc.get("version") != SIGNALS_VERSION:
        problems.append(f"version is {doc.get('version')!r}, "
                        f"expected {SIGNALS_VERSION}")
    cells = doc.get("cells")
    if not isinstance(cells, list):
        return problems + ["cells is not a list"]
    for i, cell in enumerate(cells):
        where = f"cells[{i}]"
        if not isinstance(cell, dict):
            problems.append(f"{where} is not an object")
            continue
        ident = cell.get("cell")
        if not isinstance(ident, dict):
            problems.append(f"{where}.cell missing or not an object")
        for section, fields in _SECTIONS.items():
            if section not in cell:
                problems.append(f"{where}.{section} key missing "
                                "(null it explicitly when not captured)")
                continue
            val = cell[section]
            if val is None:
                continue
            if not isinstance(val, dict):
                problems.append(f"{where}.{section} is not an object or null")
                continue
            for field, (types, required) in fields.items():
                if field not in val:
                    problems.append(f"{where}.{section}.{field} missing")
                    continue
                v = val[field]
                if v is None:
                    if required:
                        problems.append(f"{where}.{section}.{field} is null "
                                        "but required")
                    continue
                # bool is an int subclass; keep booleans out of numeric fields
                if isinstance(v, bool) and bool not in types:
                    problems.append(f"{where}.{section}.{field} is bool, "
                                    f"expected {'/'.join(t.__name__ for t in types)}")
                elif not isinstance(v, types):
                    problems.append(f"{where}.{section}.{field} is "
                                    f"{type(v).__name__}, expected "
                                    f"{'/'.join(t.__name__ for t in types)}")
        measured = cell.get("measured")
        if isinstance(measured, dict):
            frac = measured.get("overlap_frac")
            if isinstance(frac, (int, float)) and not 0.0 <= float(frac) <= 1.0:
                problems.append(f"{where}.measured.overlap_frac={frac} "
                                "outside [0, 1]")
    return problems


def write_signals(path: str, doc: dict[str, Any]) -> None:
    """Atomic write (tmp + rename): a crash mid-write must not leave a torn
    artifact for the tuner to parse."""
    problems = validate_signals(doc)
    if problems:  # never ship an artifact the schema check would reject
        raise ValueError("signals document fails its own schema: "
                         + "; ".join(problems[:5]))
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
