"""Step-3.5 — TPU-native (reference models/step3p5/model.py:346, layers.py).

Distinctives: zero-centered (1+w) RMSNorms throughout; alternating full/sliding
attention where sliding layers may use *different* head counts
(``attention_other_setting``); per-head q/k norms; optional head-wise sigmoid
attention gate (g_proj); per-layer rope theta / partial rotary factor / rope on-off;
MoE at an arbitrary ``moe_layers_enum`` index set with a separate clamped-SwiGLU
shared expert per MoE layer; dense layers use clamped SwiGLU (clamp after silu on
the gate, symmetric clamp on up — reference layers.py:152-160). Routed experts are
plain SwiGLU (the reference's swiglu path ignores activation_limit).

TPU-first structure: four param streams keyed (attention kind × ffn kind); the
forward groups consecutive layers with identical static behavior (stream + rope
meta + clamp) and ``lax.scan``s each group, so compile time scales with the number
of behavior switches.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.common.transformer import _constrain
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.dispatch import make_moe_block_forward
from automodel_tpu.moe.layers import cast_moe_compute_params, init_moe_params, moe_logical_axes
from automodel_tpu.utils.tracing import scoped
from automodel_tpu.ops.attention import dot_product_attention
from automodel_tpu.ops.norms import rms_norm
from automodel_tpu.ops.rope import apply_rope_angles, rope_frequencies

__all__ = ["Step3p5Config", "Step3p5ForCausalLM"]


@dataclasses.dataclass
class Step3p5Config:
    vocab_size: int = 1024
    hidden_size: int = 256
    intermediate_size: int = 512
    num_hidden_layers: int = 4
    num_attention_heads: int = 4
    num_attention_groups: int = 2  # kv heads (HF step3p5 naming)
    head_dim: int | None = None
    layer_types: tuple[str, ...] | None = None  # "full_attention" | "sliding_attention"
    attention_other_setting: dict[str, int] | None = None  # sliding-layer head counts
    sliding_window: int | None = None
    use_head_wise_attn_gate: bool = False
    rope_theta: "float | tuple[float, ...]" = 10000.0
    partial_rotary_factors: tuple[float, ...] | None = None
    use_rope_layers: tuple[bool, ...] | None = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    moe_layers_enum: tuple[int, ...] = ()
    share_expert_dim: int | None = None
    swiglu_limits: tuple[float, ...] | None = None  # routed experts (unused: plain swiglu)
    swiglu_limits_shared: tuple[float, ...] | None = None  # dense MLP + shared expert
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    moe: MoEConfig | None = None

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if self.layer_types is None:
            self.layer_types = ("full_attention",) * self.num_hidden_layers
        if self.moe_layers_enum and self.moe is None:
            raise ValueError("moe_layers_enum set but no MoEConfig")

    @classmethod
    def from_hf(cls, hf: dict[str, Any]) -> "Step3p5Config":
        moe = None
        moe_layers = hf.get("moe_layers_enum") or ()
        if isinstance(moe_layers, str):
            moe_layers = tuple(int(x) for x in moe_layers.split(",") if x.strip())
        else:
            moe_layers = tuple(int(x) for x in moe_layers)
        if moe_layers:
            moe = MoEConfig(
                n_routed_experts=hf["moe_num_experts"],
                n_activated_experts=hf.get("moe_top_k", 2),
                dim=hf["hidden_size"],
                moe_inter_dim=hf.get("moe_intermediate_size", hf["intermediate_size"]),
                n_shared_experts=0,  # shared expert handled separately (own clamp/dim)
                score_func="sigmoid" if hf.get("moe_router_activation", "softmax") == "sigmoid" else "softmax",
                softmax_before_topk=hf.get("moe_router_activation", "softmax") == "softmax",
                route_scale=hf.get("moe_router_scaling_factor", 1.0),
                norm_topk_prob=True,
                router_bias=hf.get("use_moe_router_bias", False),
            )
        theta = hf.get("rope_theta", 10000.0)
        return cls(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_attention_groups=hf.get("num_attention_groups", hf["num_attention_heads"]),
            head_dim=hf.get("head_dim"),
            layer_types=tuple(hf["layer_types"]) if hf.get("layer_types") else None,
            attention_other_setting=hf.get("attention_other_setting"),
            sliding_window=hf.get("sliding_window"),
            use_head_wise_attn_gate=hf.get("use_head_wise_attn_gate", False),
            rope_theta=tuple(theta) if isinstance(theta, (list, tuple)) else theta,
            partial_rotary_factors=tuple(hf["partial_rotary_factors"]) if hf.get("partial_rotary_factors") else None,
            use_rope_layers=tuple(hf["use_rope_layers"]) if hf.get("use_rope_layers") else None,
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            moe_layers_enum=moe_layers,
            share_expert_dim=hf.get("share_expert_dims", hf.get("share_expert_dim")),
            swiglu_limits=tuple(hf["swiglu_limits"]) if hf.get("swiglu_limits") else None,
            swiglu_limits_shared=tuple(hf["swiglu_limits_shared"]) if hf.get("swiglu_limits_shared") else None,
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            initializer_range=hf.get("initializer_range", 0.02),
            moe=moe,
        )

    # ---- per-layer static metadata ----

    def attn_kind(self, i: int) -> str:
        return "sliding" if self.layer_types[i] == "sliding_attention" else "full"

    def heads(self, i: int) -> tuple[int, int]:
        """(n_heads, n_kv_heads) for layer i."""
        if self.attn_kind(i) == "sliding" and self.attention_other_setting:
            return (
                self.attention_other_setting.get("num_attention_heads", self.num_attention_heads),
                self.attention_other_setting.get("num_attention_groups", self.num_attention_groups),
            )
        return self.num_attention_heads, self.num_attention_groups

    def ffn_kind(self, i: int) -> str:
        return "moe" if i in set(self.moe_layers_enum) else "mlp"

    def theta(self, i: int) -> float:
        return float(self.rope_theta[i]) if isinstance(self.rope_theta, (list, tuple)) else float(self.rope_theta)

    def prf(self, i: int) -> float:
        return float(self.partial_rotary_factors[i]) if self.partial_rotary_factors else 1.0

    def use_rope(self, i: int) -> bool:
        if self.use_rope_layers is not None and len(self.use_rope_layers) > i:
            return bool(self.use_rope_layers[i])
        return True

    def shared_limit(self, i: int) -> float | None:
        v = self.swiglu_limits_shared[i] if self.swiglu_limits_shared else None
        # reference treats 0 as "no clamp" (model.py:93-102), so falsy-zero is correct
        return float(v) if v else None

    def stream_key(self, i: int) -> str:
        return f"{self.attn_kind(i)}_{self.ffn_kind(i)}"

    def meta_key(self, i: int):
        """Everything that changes the traced layer body."""
        return (self.stream_key(i), self.theta(i), self.prf(i), self.use_rope(i), self.shared_limit(i))

    def stream_indices(self) -> dict[str, tuple[int, ...]]:
        out: dict[str, list[int]] = {}
        for i in range(self.num_hidden_layers):
            out.setdefault(self.stream_key(i), []).append(i)
        return {k: tuple(v) for k, v in out.items()}


def _stream_shapes(cfg: Step3p5Config, key: str) -> dict[str, tuple[int, ...]]:
    d, dh = cfg.hidden_size, cfg.head_dim
    fkind = key.split("_")[1]
    i0 = next(i for i in range(cfg.num_hidden_layers) if cfg.stream_key(i) == key)
    n, kv = cfg.heads(i0)
    shapes = {
        "attn_norm": (d,),
        "mlp_norm": (d,),
        "wq": (d, n, dh),
        "wk": (d, kv, dh),
        "wv": (d, kv, dh),
        "wo": (n, dh, d),
        "q_norm": (dh,),
        "k_norm": (dh,),
    }
    if cfg.use_head_wise_attn_gate:
        shapes["wg"] = (d, n)
    if fkind == "mlp":
        shapes |= {
            "w_gate": (d, cfg.intermediate_size),
            "w_up": (d, cfg.intermediate_size),
            "w_down": (cfg.intermediate_size, d),
        }
    else:
        sh = cfg.share_expert_dim or cfg.intermediate_size
        shapes |= {"sh_gate": (d, sh), "sh_up": (d, sh), "sh_down": (sh, d)}
    return shapes


_AXES = {
    "attn_norm": ("norm",), "mlp_norm": ("norm",),
    "wq": ("embed", "heads", "head_dim"), "wk": ("embed", "kv_heads", "head_dim"),
    "wv": ("embed", "kv_heads", "head_dim"), "wo": ("heads", "head_dim", "embed"),
    "q_norm": ("norm",), "k_norm": ("norm",), "wg": ("embed", "heads"),
    "w_gate": ("embed", "mlp"), "w_up": ("embed", "mlp"), "w_down": ("mlp", "embed"),
    "sh_gate": ("embed", "mlp"), "sh_up": ("embed", "mlp"), "sh_down": ("mlp", "embed"),
}


def _clamped_swiglu(x, w_gate, w_up, w_down, limit):
    """Step3p5MLP: clamp AFTER silu on the gate, symmetric clamp on up
    (reference layers.py:152-160)."""
    gate = jax.nn.silu(jnp.einsum("bsd,di->bsi", x, w_gate))
    up = jnp.einsum("bsd,di->bsi", x, w_up)
    if limit is not None:
        gate = jnp.minimum(gate, limit)
        up = jnp.clip(up, -limit, limit)
    return jnp.einsum("bsi,id->bsd", gate * up, w_down)


class Step3p5ForCausalLM:
    """Functional model: holds config + backend, operates on param pytrees."""

    config_class = Step3p5Config
    hf_architectures = ("Step3p5ForCausalLM",)

    def __init__(self, config: Step3p5Config, backend: BackendConfig | None = None):
        self.config = config
        self.backend = backend or BackendConfig()

    # ---- params ----

    def init(self, key: jax.Array, dtype=jnp.float32) -> dict:
        cfg = self.config
        std = cfg.initializer_range
        keys = iter(jax.random.split(key, 12))
        params: dict = {
            "embed": (jax.random.normal(next(keys), (cfg.vocab_size, cfg.hidden_size), jnp.float32) * std).astype(dtype),
            "final_norm": jnp.zeros((cfg.hidden_size,), dtype),  # zero-centered (1+w)
        }
        for skey, idx in cfg.stream_indices().items():
            shapes = _stream_shapes(cfg, skey)
            ks = jax.random.split(next(keys), len(shapes))
            stack = {}
            for j, (name, shape) in enumerate(shapes.items()):
                if name.endswith("norm"):
                    stack[name] = jnp.zeros((len(idx), *shape), dtype)  # (1+w) convention
                else:
                    stack[name] = (jax.random.normal(ks[j], (len(idx), *shape), jnp.float32) * std).astype(dtype)
            if skey.endswith("_moe"):
                stack["moe"] = jax.vmap(lambda k: init_moe_params(cfg.moe, k, dtype, std))(
                    jax.random.split(next(keys), len(idx))
                )
            params[skey] = stack
        if not cfg.tie_word_embeddings:
            params["lm_head"] = (
                jax.random.normal(next(keys), (cfg.hidden_size, cfg.vocab_size), jnp.float32) * std
            ).astype(dtype)
        return params

    def abstract_params(self, dtype=jnp.bfloat16) -> dict:
        return jax.eval_shape(lambda k: self.init(k, dtype), jax.random.key(0))

    def logical_axes(self) -> dict:
        cfg = self.config
        axes: dict = {"embed": ("vocab", "embed"), "final_norm": ("norm",)}
        for skey in cfg.stream_indices():
            stream = {name: ("layers",) + _AXES[name] for name in _stream_shapes(cfg, skey)}
            if skey.endswith("_moe"):
                stream["moe"] = jax.tree.map(
                    lambda tp: ("layers",) + tp,
                    moe_logical_axes(cfg.moe),
                    is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
                )
            axes[skey] = stream
        if not cfg.tie_word_embeddings:
            axes["lm_head"] = ("embed", "vocab")
        return axes

    # ---- forward ----

    def __call__(self, params, input_ids, positions=None, segment_ids=None, token_mask=None,
                 rules=None, return_hidden=False, training=True, cache=None):
        cfg, backend = self.config, self.backend
        dtype = backend.jnp_dtype
        B, S = input_ids.shape
        eps = cfg.rms_norm_eps
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        if cache is not None:
            if segment_ids is None:
                raise ValueError("cache decoding requires segment_ids (1 = real token)")
            return self._decode_forward(params, input_ids, positions, segment_ids, cache, dtype)
        emit_aux = (
            cfg.moe is not None and cfg.moe.aux_loss_coeff > 0 and training
            and not backend.fake_balanced_gate
        )
        moe_fwd = (
            make_moe_block_forward(cfg.moe, backend, rules, training=training)
            if cfg.moe is not None else None
        )

        # per-distinct-rope-meta angle tables, computed once
        angle_cache: dict = {}

        def angles_for(i):
            mk = (cfg.theta(i), cfg.prf(i))
            if mk not in angle_cache:
                inv_freq = rope_frequencies(cfg.head_dim, mk[0], None, partial_rotary_factor=mk[1])
                angle_cache[mk] = positions[..., None].astype(jnp.float32) * inv_freq
            return angle_cache[mk]

        def make_body(i):
            """Layer body for the behavior class of layer i (shared by its run)."""
            akind, fkind = cfg.attn_kind(i), cfg.ffn_kind(i)
            window = cfg.sliding_window if akind == "sliding" else None
            use_rope = cfg.use_rope(i)
            angles = angles_for(i) if use_rope else None
            limit = cfg.shared_limit(i)

            def body(h, lp):
                moe_params = lp.pop("moe", None)
                lp = jax.tree.map(lambda a: a.astype(dtype), lp)
                x = rms_norm(h, lp["attn_norm"], eps, offset=1.0)
                q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
                k = jnp.einsum("bsd,dnh->bsnh", x, lp["wk"])
                v = jnp.einsum("bsd,dnh->bsnh", x, lp["wv"])
                q = rms_norm(q, lp["q_norm"], eps, offset=1.0)
                k = rms_norm(k, lp["k_norm"], eps, offset=1.0)
                if use_rope:
                    q = apply_rope_angles(q, angles)
                    k = apply_rope_angles(k, angles)
                out = dot_product_attention(
                    q, k, v, causal=True, segment_ids_q=segment_ids,
                    sliding_window=window, backend=backend.attention,
                )
                if cfg.use_head_wise_attn_gate:
                    gate = jax.nn.sigmoid(jnp.einsum("bsd,dn->bsn", x, lp["wg"]))
                    out = out * gate[..., None]
                h = h + jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])
                h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))

                x = rms_norm(h, lp["mlp_norm"], eps, offset=1.0)
                if fkind == "mlp":
                    h = h + _clamped_swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"], limit)
                    stats = (
                        jnp.float32(0),
                        jnp.zeros((cfg.moe.n_routed_experts if cfg.moe else 1,), jnp.float32),
                        jnp.float32(0),
                    )
                else:
                    share = _clamped_swiglu(x, lp["sh_gate"], lp["sh_up"], lp["sh_down"], limit)
                    moe_params = cast_moe_compute_params(moe_params, dtype)
                    y, aux, load, dropped = moe_fwd(moe_params, x, token_mask)
                    h = h + share + y
                    stats = (aux if (aux is not None and emit_aux) else jnp.float32(0), load, dropped)
                h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))
                return h, stats

            # profiler label per behavior class (autonvtx parity): sliding vs
            # full attention x mlp vs moe regions separate in the trace
            return backend.layer_remat(scoped(f"{akind}_{fkind}", body))

        h = params["embed"].astype(dtype)[input_ids]
        h = _constrain(h, rules, ("batch", "act_seq", "act_embed"))

        stream_offsets = dict.fromkeys(cfg.stream_indices(), 0)
        auxs, loads, droppeds, load_is_moe = [], [], [], []
        layer_ids = range(cfg.num_hidden_layers)
        for mkey, group in itertools.groupby(layer_ids, key=cfg.meta_key):
            group = list(group)
            i0 = group[0]
            skey = cfg.stream_key(i0)
            o = stream_offsets[skey]
            n = len(group)
            run_params = jax.tree.map(lambda a: a[o : o + n], params[skey])
            stream_offsets[skey] = o + n
            body = make_body(i0)
            if backend.scan_layers and n > 1:
                h, (aux_r, load_r, drop_r) = jax.lax.scan(
                    lambda hh, lp: body(hh, dict(lp)), h, run_params
                )
                auxs.append(aux_r)
                loads.append(load_r)
                droppeds.append(drop_r)
            else:
                for j in range(n):
                    lp = jax.tree.map(lambda a: a[j], run_params)
                    h, (aux, load, dropped) = body(h, dict(lp))
                    auxs.append(aux[None])
                    loads.append(load[None])
                    droppeds.append(dropped[None])
            load_is_moe += [cfg.ffn_kind(i) == "moe" for i in group]

        aux_all = jnp.concatenate(auxs)
        load_all = jnp.concatenate(loads)
        drop_all = jnp.concatenate(droppeds)
        moe_sel = np.asarray(load_is_moe, bool)
        stats = {
            "aux_loss": aux_all.sum() if emit_aux else None,
            "expert_load": load_all[moe_sel] if cfg.moe is not None else load_all[:0],
        }
        if backend.dispatcher == "a2a" and cfg.moe is not None:
            stats["dropped_token_frac"] = drop_all[moe_sel].mean()

        h = rms_norm(h, params["final_norm"].astype(dtype), eps, offset=1.0)
        if return_hidden:
            return h, stats
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, stats

    # ---- decode ----

    def init_decode_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> dict:
        """Per-layer KV tuples: sliding layers may use DIFFERENT head counts
        (attention_other_setting), so the cache is a tuple of per-layer arrays
        rather than one stacked (L, ...) tensor."""
        cfg = self.config
        ks, vs = [], []
        for i in range(cfg.num_hidden_layers):
            _, kv = cfg.heads(i)
            ks.append(jnp.zeros((batch_size, max_len, kv, cfg.head_dim), dtype))
            vs.append(jnp.zeros((batch_size, max_len, kv, cfg.head_dim), dtype))
        return {
            "k": tuple(ks),
            "v": tuple(vs),
            "positions": jnp.zeros((batch_size, max_len), jnp.int32),
            "valid": jnp.zeros((batch_size, max_len), jnp.int32),
            "write_idx": jnp.zeros((batch_size,), jnp.int32),
        }

    def _decode_forward(self, params, input_ids, positions, segment_ids, cache, dtype):
        """Unrolled cached forward (prefill S>1, decode S=1) across the mixed
        attention geometries; MoE routing runs eval-mode."""
        from automodel_tpu.models.common.transformer import _cache_write

        cfg = self.config
        eps = cfg.rms_norm_eps
        B, S = input_ids.shape
        token_mask = segment_ids != 0
        moe_fwd = (
            make_moe_block_forward(cfg.moe, self.backend, None, training=False)
            if cfg.moe is not None else None
        )
        h = params["embed"].astype(dtype)[input_ids]
        ks = list(cache["k"])
        vs = list(cache["v"])
        stream_offsets = dict.fromkeys(cfg.stream_indices(), 0)
        for i in range(cfg.num_hidden_layers):
            skey = cfg.stream_key(i)
            o = stream_offsets[skey]
            stream_offsets[skey] = o + 1
            lp = jax.tree.map(lambda a: a[o], params[skey])
            moe_params = lp.pop("moe", None)
            lp = jax.tree.map(lambda a: a.astype(dtype), lp)
            akind, fkind = cfg.attn_kind(i), cfg.ffn_kind(i)
            window = cfg.sliding_window if akind == "sliding" else None
            x = rms_norm(h, lp["attn_norm"], eps, offset=1.0)
            q = jnp.einsum("bsd,dnh->bsnh", x, lp["wq"])
            k = jnp.einsum("bsd,dnh->bsnh", x, lp["wk"])
            v = jnp.einsum("bsd,dnh->bsnh", x, lp["wv"])
            q = rms_norm(q, lp["q_norm"], eps, offset=1.0)
            k = rms_norm(k, lp["k_norm"], eps, offset=1.0)
            if cfg.use_rope(i):
                inv_freq = rope_frequencies(
                    cfg.head_dim, cfg.theta(i), None, partial_rotary_factor=cfg.prf(i)
                )
                angles = positions[..., None].astype(jnp.float32) * inv_freq
                q = apply_rope_angles(q, angles)
                k = apply_rope_angles(k, angles)
            ks[i] = _cache_write(ks[i], k.astype(ks[i].dtype), cache["write_idx"])
            vs[i] = _cache_write(vs[i], v.astype(vs[i].dtype), cache["write_idx"])
            out = dot_product_attention(
                q, ks[i].astype(q.dtype), vs[i].astype(q.dtype),
                causal=True, segment_ids_q=segment_ids,
                segment_ids_kv=cache["valid"],
                positions_q=positions, positions_kv=cache["positions"],
                sliding_window=window, backend="xla",
            )
            if cfg.use_head_wise_attn_gate:
                gate = jax.nn.sigmoid(jnp.einsum("bsd,dn->bsn", x, lp["wg"]))
                out = out * gate[..., None]
            h = h + jnp.einsum("bsnh,nhd->bsd", out, lp["wo"])

            x = rms_norm(h, lp["mlp_norm"], eps, offset=1.0)
            limit = cfg.shared_limit(i)
            if fkind == "mlp":
                h = h + _clamped_swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"], limit)
            else:
                share = _clamped_swiglu(x, lp["sh_gate"], lp["sh_up"], lp["sh_down"], limit)
                moe_params = cast_moe_compute_params(moe_params, dtype)
                y, _, _, _ = moe_fwd(moe_params, x, token_mask)
                h = h + share + y
        h = rms_norm(h, params["final_norm"].astype(dtype), eps, offset=1.0)
        last = jnp.maximum(segment_ids.sum(-1) - 1, 0).astype(jnp.int32)
        h = jnp.take_along_axis(h, last[:, None, None], axis=1)
        unembed = params.get("lm_head")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsd,dv->bsv", h, unembed.astype(dtype))
        return logits, dict(cache, k=tuple(ks), v=tuple(vs))

    def generate(self, params, input_ids, **kw):
        """Sample with the per-layer-geometry KV cache (automodel_tpu.generation)."""
        from automodel_tpu.generation import generate

        return generate(self, params, input_ids, **kw)

    # ---- interop ----

    def state_dict_adapter(self):
        from automodel_tpu.models.step3p5.state_dict_adapter import Step3p5StateDictAdapter

        return Step3p5StateDictAdapter(self.config)

    @classmethod
    def from_config(cls, config, backend: BackendConfig | None = None):
        if isinstance(config, dict):
            config = Step3p5Config.from_hf(config)
        return cls(config, backend)
