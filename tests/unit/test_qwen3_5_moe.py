"""Qwen3.5-MoE text stack: separate-projection adapter round-trip onto the shared
qwen3_next hybrid machinery. (transformers here ships no qwen3_5_moe — the
reference gates this family on HF availability too, so checks are structural.)"""

import numpy as np

import jax
import jax.numpy as jnp

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.qwen3_5_moe.model import Qwen3_5MoeForCausalLM


def _hf_cfg():
    return dict(
        architectures=["Qwen3_5MoeForConditionalGeneration"],
        text_config=dict(
            vocab_size=128, hidden_size=64, moe_intermediate_size=24,
            shared_expert_intermediate_size=48, num_hidden_layers=4,
            layer_types=["linear_attention", "linear_attention", "linear_attention", "full_attention"],
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            linear_num_value_heads=4, linear_num_key_heads=2, linear_key_head_dim=16,
            linear_value_head_dim=16, linear_conv_kernel_dim=4,
            num_experts=8, num_experts_per_tok=2, norm_topk_prob=True,
            max_position_embeddings=128, partial_rotary_factor=0.25,
        ),
    )


class TestQwen3_5Moe:
    def test_forward_and_roundtrip(self):
        model = Qwen3_5MoeForCausalLM.from_config(
            _hf_cfg(), BackendConfig(dtype="float32", remat_policy="full")
        )
        params = model.init(jax.random.key(0), jnp.float32)
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
        logits, _ = model(params, ids, training=False)
        assert np.all(np.isfinite(np.asarray(logits)))

        adapter = model.state_dict_adapter()
        hf = adapter.to_hf(params)
        for k in (
            "model.language_model.layers.0.linear_attn.in_proj_qkv.weight",
            "model.language_model.layers.0.linear_attn.in_proj_z.weight",
            "model.language_model.layers.0.linear_attn.in_proj_b.weight",
            "model.language_model.layers.3.self_attn.q_proj.weight",
            "model.language_model.layers.2.mlp.experts.gate_up_proj",
        ):
            assert k in hf, k
        # packed expert layout (E, 2I, D) / (E, D, I)
        assert hf["model.language_model.layers.0.mlp.experts.gate_up_proj"].shape == (8, 48, 64)
        back = adapter.from_hf(hf)
        flat_a, flat_b = jax.tree.leaves(params), jax.tree.leaves(back)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_separate_projection_fusion_semantics(self):
        """Splitting the fused wqkvz back out and re-fusing must be exact, and the
        separate q|k|v rows must land on the conv channel order the kernel uses."""
        model = Qwen3_5MoeForCausalLM.from_config(
            _hf_cfg(), BackendConfig(dtype="float32", remat_policy="full")
        )
        params = model.init(jax.random.key(1), jnp.float32)
        adapter = model.state_dict_adapter()
        hf = adapter.to_hf(params)
        qkv = hf["model.language_model.layers.0.linear_attn.in_proj_qkv.weight"]
        # rows: q (Hk*dk=32) | k (32) | v (Hv*dv=64) over D=64 columns
        assert qkv.shape == (128, 64)
