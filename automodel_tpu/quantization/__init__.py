from automodel_tpu.quantization.qlora import (
    dequantize_leaf,
    is_quantized_leaf,
    quantize_leaf,
    quantize_params,
)
from automodel_tpu.quantization.qat import QATConfig, fake_quant

__all__ = [
    "QATConfig",
    "dequantize_leaf",
    "fake_quant",
    "is_quantized_leaf",
    "quantize_leaf",
    "quantize_params",
]
