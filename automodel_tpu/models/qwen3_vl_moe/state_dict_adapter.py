"""Qwen3-VL-MoE HF mapping (reference models/qwen3_vl_moe/state_dict_adapter.py).

Text keys live under ``model.language_model.*`` with experts already packed
(gate_up_proj (E, D, 2I) / down_proj (E, I, D) — exactly our layout, no per-expert
split). Vision keys under ``model.visual.*``; the Conv3D patch embed flattens to a
matmul weight because kernel == stride.
"""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import _o_in, _o_out, _proj_in, _proj_out, _t

__all__ = ["Qwen3VLMoeStateDictAdapter"]


def _conv3d_in(w: np.ndarray) -> np.ndarray:
    # (D, C, tp, P, P) -> (C*tp*P*P, D); processor flattens pixels in the same order
    return np.ascontiguousarray(w.reshape(w.shape[0], -1).T)


def _conv3d_out_factory(cfg_v):
    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(
            -1, cfg_v.in_channels, cfg_v.temporal_patch_size, cfg_v.patch_size, cfg_v.patch_size
        )

    return f


def vision_entries(
    v,
    prefix: str = "model.visual",
    merger_norm: str = "norm",
    merger_fc: "tuple[str, str]" = ("linear_fc1", "linear_fc2"),
    ds_list_name: str = "deepstack_merger_list",
) -> list[Entry]:
    """Qwen3-VL-family vision tower entries, shared with the omni adapter (which
    differs only in key prefix and merger sub-key names)."""
    vb = f"{prefix}.blocks.{{i}}"
    vis_range = (0, v.depth)
    entries = [
        Entry(f"{prefix}.patch_embed.proj.weight", "visual.patch_w",
              _conv3d_in, _conv3d_out_factory(v)),
        Entry(f"{prefix}.patch_embed.proj.bias", "visual.b_patch"),
        Entry(f"{prefix}.pos_embed.weight", "visual.pos_embed"),
        Entry(f"{vb}.norm1.weight", "visual.blocks.ln1_w", layer_range=vis_range),
        Entry(f"{vb}.norm1.bias", "visual.blocks.b_ln1", layer_range=vis_range),
        Entry(f"{vb}.norm2.weight", "visual.blocks.ln2_w", layer_range=vis_range),
        Entry(f"{vb}.norm2.bias", "visual.blocks.b_ln2", layer_range=vis_range),
        Entry(f"{vb}.attn.qkv.weight", "visual.blocks.qkv_w", _t, _t, layer_range=vis_range),
        Entry(f"{vb}.attn.qkv.bias", "visual.blocks.b_qkv", layer_range=vis_range),
        Entry(f"{vb}.attn.proj.weight", "visual.blocks.proj_w", _t, _t, layer_range=vis_range),
        Entry(f"{vb}.attn.proj.bias", "visual.blocks.b_proj", layer_range=vis_range),
        Entry(f"{vb}.mlp.linear_fc1.weight", "visual.blocks.fc1_w", _t, _t, layer_range=vis_range),
        Entry(f"{vb}.mlp.linear_fc1.bias", "visual.blocks.b_fc1", layer_range=vis_range),
        Entry(f"{vb}.mlp.linear_fc2.weight", "visual.blocks.fc2_w", _t, _t, layer_range=vis_range),
        Entry(f"{vb}.mlp.linear_fc2.bias", "visual.blocks.b_fc2", layer_range=vis_range),
    ]
    fc1, fc2 = merger_fc
    for hf_part, ours in (("merger", "visual.merger"),):
        entries += [
            Entry(f"{prefix}.{hf_part}.{merger_norm}.weight", f"{ours}.norm_w"),
            Entry(f"{prefix}.{hf_part}.{merger_norm}.bias", f"{ours}.b_norm"),
            Entry(f"{prefix}.{hf_part}.{fc1}.weight", f"{ours}.fc1_w", _t, _t),
            Entry(f"{prefix}.{hf_part}.{fc1}.bias", f"{ours}.b_fc1"),
            Entry(f"{prefix}.{hf_part}.{fc2}.weight", f"{ours}.fc2_w", _t, _t),
            Entry(f"{prefix}.{hf_part}.{fc2}.bias", f"{ours}.b_fc2"),
        ]
    n_ds = len(v.deepstack_visual_indexes)
    dsm = f"{prefix}.{ds_list_name}" + ".{i}"
    ds_range = (0, n_ds)
    entries += [
        Entry(f"{dsm}.{merger_norm}.weight", "visual.ds_mergers.norm_w", layer_range=ds_range),
        Entry(f"{dsm}.{merger_norm}.bias", "visual.ds_mergers.b_norm", layer_range=ds_range),
        Entry(f"{dsm}.{fc1}.weight", "visual.ds_mergers.fc1_w", _t, _t, layer_range=ds_range),
        Entry(f"{dsm}.{fc1}.bias", "visual.ds_mergers.b_fc1", layer_range=ds_range),
        Entry(f"{dsm}.{fc2}.weight", "visual.ds_mergers.fc2_w", _t, _t, layer_range=ds_range),
        Entry(f"{dsm}.{fc2}.bias", "visual.ds_mergers.b_fc2", layer_range=ds_range),
    ]
    return entries


class Qwen3VLMoeStateDictAdapter(MappingAdapter):
    def __init__(self, cfg):
        t, v = cfg.text, cfg.vision
        n, kvh, hd = t.num_attention_heads, t.num_key_value_heads, t.head_dim
        lm = "model.language_model.layers.{i}"

        entries = [
            Entry("model.language_model.embed_tokens.weight", "embed"),
            Entry("model.language_model.norm.weight", "final_norm"),
            # text decoder (all layers MoE)
            Entry(f"{lm}.input_layernorm.weight", "moe_layers.attn_norm"),
            Entry(f"{lm}.post_attention_layernorm.weight", "moe_layers.mlp_norm"),
            Entry(f"{lm}.self_attn.q_proj.weight", "moe_layers.wq", _proj_in(n, hd), _proj_out(n, hd)),
            Entry(f"{lm}.self_attn.k_proj.weight", "moe_layers.wk", _proj_in(kvh, hd), _proj_out(kvh, hd)),
            Entry(f"{lm}.self_attn.v_proj.weight", "moe_layers.wv", _proj_in(kvh, hd), _proj_out(kvh, hd)),
            Entry(f"{lm}.self_attn.o_proj.weight", "moe_layers.wo", _o_in(n, hd), _o_out(n, hd)),
            Entry(f"{lm}.self_attn.q_norm.weight", "moe_layers.q_norm"),
            Entry(f"{lm}.self_attn.k_norm.weight", "moe_layers.k_norm"),
            Entry(f"{lm}.mlp.gate.weight", "moe_layers.moe.gate.weight"),
            # packed expert tensors map 1:1 (HF chunks gate|up exactly like ours)
            Entry(f"{lm}.mlp.experts.gate_up_proj", "moe_layers.moe.experts.gate_up_proj"),
            Entry(f"{lm}.mlp.experts.down_proj", "moe_layers.moe.experts.down_proj"),
        ]
        entries += vision_entries(v)
        if not t.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))
        super().__init__(entries, t.num_hidden_layers, num_experts=t.moe.n_routed_experts)
