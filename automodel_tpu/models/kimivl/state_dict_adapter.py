"""Kimi-VL HF mapping (reference kimivl/model.py:768 KimiVLStateDictAdapter).

HF layout: ``vision_tower.*`` (MoonViT), ``multi_modal_projector.*``,
``language_model.model.*`` (DeepSeek-V2/V3 keys), ``language_model.lm_head.weight``.
The text part reuses DeepseekV3StateDictAdapter with re-prefixed HF keys.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.deepseek_v3.state_dict_adapter import DeepseekV3StateDictAdapter
from automodel_tpu.models.llama.state_dict_adapter import _t

__all__ = ["KimiVLStateDictAdapter"]


def _conv2d_in(w: np.ndarray) -> np.ndarray:
    # (D, C, P, P) -> (C*P*P, D)
    return np.ascontiguousarray(w.reshape(w.shape[0], -1).T)


def _conv2d_out_factory(v):
    def f(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(-1, v.in_channels, v.patch_size, v.patch_size)

    return f


class KimiVLStateDictAdapter(MappingAdapter):
    def __init__(self, cfg):
        v = cfg.vision
        vb = "vision_tower.encoder.blocks.{i}"
        vis_range = (0, v.num_hidden_layers)

        # text = DSv3 keys under the language_model. prefix
        # ("model.layers..." -> "language_model.model.layers...", same for lm_head)
        text_adapter = DeepseekV3StateDictAdapter(cfg.text)
        entries = []
        for e in text_adapter.entries:
            new = tuple("language_model." + k for k in e.hf_keys)
            entries.append(dataclasses.replace(e, hf=new if len(new) > 1 else new[0]))

        entries += [
            Entry("vision_tower.patch_embed.proj.weight", "visual.patch_w",
                  _conv2d_in, _conv2d_out_factory(v)),
            Entry("vision_tower.patch_embed.proj.bias", "visual.b_patch"),
            Entry("vision_tower.patch_embed.pos_emb.weight", "visual.pos_emb"),
            Entry(f"{vb}.norm0.weight", "visual.blocks.ln0_w", layer_range=vis_range),
            Entry(f"{vb}.norm0.bias", "visual.blocks.b_ln0", layer_range=vis_range),
            Entry(f"{vb}.norm1.weight", "visual.blocks.ln1_w", layer_range=vis_range),
            Entry(f"{vb}.norm1.bias", "visual.blocks.b_ln1", layer_range=vis_range),
            Entry(f"{vb}.wqkv.weight", "visual.blocks.wqkv", _t, _t, layer_range=vis_range),
            Entry(f"{vb}.wqkv.bias", "visual.blocks.b_qkv", layer_range=vis_range),
            Entry(f"{vb}.wo.weight", "visual.blocks.wo", _t, _t, layer_range=vis_range),
            Entry(f"{vb}.wo.bias", "visual.blocks.b_o", layer_range=vis_range),
            Entry(f"{vb}.mlp.fc0.weight", "visual.blocks.fc0", _t, _t, layer_range=vis_range),
            Entry(f"{vb}.mlp.fc0.bias", "visual.blocks.b_fc0", layer_range=vis_range),
            Entry(f"{vb}.mlp.fc1.weight", "visual.blocks.fc1", _t, _t, layer_range=vis_range),
            Entry(f"{vb}.mlp.fc1.bias", "visual.blocks.b_fc1", layer_range=vis_range),
            Entry("vision_tower.encoder.final_layernorm.weight", "visual.final_ln_w"),
            Entry("vision_tower.encoder.final_layernorm.bias", "visual.b_final_ln"),
            Entry("multi_modal_projector.pre_norm.weight", "projector.pre_ln_w"),
            Entry("multi_modal_projector.pre_norm.bias", "projector.b_pre_ln"),
            Entry("multi_modal_projector.linear_1.weight", "projector.w1", _t, _t),
            Entry("multi_modal_projector.linear_1.bias", "projector.b1"),
            Entry("multi_modal_projector.linear_2.weight", "projector.w2", _t, _t),
            Entry("multi_modal_projector.linear_2.bias", "projector.b2"),
        ]
        super().__init__(
            entries, cfg.text.num_hidden_layers,
            num_experts=cfg.text.moe.n_routed_experts,
        )
