"""Analytic training-FLOPs formulas + MFU (reference utils/flops_utils.py:18-830).

``flops_per_token`` covers dense decoders and MoE (active-expert counting, MLA
projections); train FLOPs = 3x forward (fwd + 2x bwd). Peak TFLOPs table carries the
common TPU generations; MFU = achieved / peak.
"""

from __future__ import annotations

from typing import Any

__all__ = ["flops_per_token", "mfu", "PEAK_TFLOPS"]

# bf16 dense peak per chip
PEAK_TFLOPS: dict[str, float] = {
    "tpu v4": 275.0,
    "tpu v5e": 197.0,
    "tpu v5 lite": 197.0,
    "tpu v5p": 459.0,
    "tpu v6e": 918.0,
    "h100": 989.0,
    "a100": 312.0,
}


def flops_per_token(cfg: Any, seq_len: int, training: bool = True) -> float:
    """FLOPs per token for a decoder config (ours or an HF-config-like dict)."""
    get = (lambda k, d=None: cfg.get(k, d)) if isinstance(cfg, dict) else (
        lambda k, d=None: getattr(cfg, k, d)
    )
    d = get("hidden_size")
    L = get("num_hidden_layers")
    v = get("vocab_size")
    n = get("num_attention_heads")
    k = get("num_key_value_heads", n) or n
    h = get("head_dim") or d // n
    inter = get("intermediate_size")

    # attention projections + scores
    qkv = 2 * d * (n + 2 * k) * h
    o = 2 * n * h * d
    scores = 2 * 2 * seq_len * n * h  # QK^T + PV, causal ~ /2 but count full (ref does)

    # MLP: dense or MoE (active experts + shared)
    n_routed = get("num_experts") or get("n_routed_experts") or 0
    if n_routed:
        top_k = get("num_experts_per_tok") or get("top_k") or 1
        moe_inter = get("moe_intermediate_size") or inter
        shared = get("n_shared_experts") or 0
        dense_layers = get("first_k_dense_replace") or 0
        moe_mlp = 3 * 2 * d * moe_inter * (top_k + shared)
        dense_mlp = 3 * 2 * d * inter
        mlp_total = dense_layers * dense_mlp + (L - dense_layers) * moe_mlp
        attn_total = L * (qkv + o + scores)
        fwd = attn_total + mlp_total + 2 * d * v
    else:
        mlp = 3 * 2 * d * inter
        fwd = L * (qkv + o + scores + mlp) + 2 * d * v
    return 3.0 * fwd if training else fwd


def mfu(tokens_per_sec: float, flops_per_tok: float, device_kind: str, n_devices: int = 1) -> float:
    """Model FLOPs utilization in [0,1]; 0.0 if the device kind is unknown."""
    key = device_kind.lower()
    peak = None
    for name, tf in PEAK_TFLOPS.items():
        if name in key:
            peak = tf
            break
    if peak is None:
        return 0.0
    achieved = tokens_per_sec * flops_per_tok / 1e12
    return achieved / (peak * n_devices)
