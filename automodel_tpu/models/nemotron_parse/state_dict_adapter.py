"""NemotronParse HF mapping (reference nemotron_parse/model.py HF layout:
``decoder.*`` mBART keys, ``encoder.conv1/conv2/layer_norm*/sum_proj`` neck keys,
``lm_head``, ``decoder.extra_heads/extra_proj``)."""

from __future__ import annotations

import numpy as np

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import (
    _bias_in,
    _bias_out,
    _o_in,
    _o_out,
    _proj_in,
    _proj_out,
    _t,
)

__all__ = ["NemotronParseStateDictAdapter"]


def _conv1_in(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w[:, :, 0].T)  # (out, in, 1) -> (in, out)


def _conv1_out(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)[:, :, None]


def _conv2_in(w: np.ndarray) -> np.ndarray:
    # (out, in, 1, K) -> (K*in, out): output = sum_k x[w_pos+k] @ W[:, :, 0, k]^T
    out_d, in_d, _, K = w.shape
    return np.ascontiguousarray(w[:, :, 0].transpose(2, 1, 0).reshape(K * in_d, out_d))


def _conv2_out_factory(cfg):
    def f(w: np.ndarray) -> np.ndarray:
        K = cfg.neck_merge
        in_d = cfg.neck_dim
        return np.ascontiguousarray(
            w.reshape(K, in_d, -1).transpose(2, 1, 0)[:, :, None, :]
        )

    return f


class NemotronParseStateDictAdapter(MappingAdapter):
    def __init__(self, cfg):
        H, dh = cfg.decoder_attention_heads, cfg.head_dim
        pre = "decoder.layers.{i}"

        def attn(hf_prefix, ours_prefix):
            return [
                Entry(f"{pre}.{hf_prefix}.q_proj.weight", f"layers.{ours_prefix}_wq",
                      _proj_in(H, dh), _proj_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.q_proj.bias", f"layers.{ours_prefix}_bq",
                      _bias_in(H, dh), _bias_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.k_proj.weight", f"layers.{ours_prefix}_wk",
                      _proj_in(H, dh), _proj_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.k_proj.bias", f"layers.{ours_prefix}_bk",
                      _bias_in(H, dh), _bias_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.v_proj.weight", f"layers.{ours_prefix}_wv",
                      _proj_in(H, dh), _proj_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.v_proj.bias", f"layers.{ours_prefix}_bv",
                      _bias_in(H, dh), _bias_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.out_proj.weight", f"layers.{ours_prefix}_wo",
                      _o_in(H, dh), _o_out(H, dh)),
                Entry(f"{pre}.{hf_prefix}.out_proj.bias", f"layers.{ours_prefix}_bo"),
            ]

        entries = [
            Entry("decoder.embed_tokens.weight", "embed"),
            Entry("decoder.layernorm_embedding.weight", "emb_ln_w"),
            Entry("decoder.layernorm_embedding.bias", "b_emb_ln"),
            Entry("decoder.layer_norm.weight", "final_ln_w"),
            Entry("decoder.layer_norm.bias", "b_final_ln"),
            Entry("lm_head.weight", "lm_head", _t, _t),
            *attn("self_attn", "self"),
            Entry(f"{pre}.self_attn_layer_norm.weight", "layers.self_ln_w"),
            Entry(f"{pre}.self_attn_layer_norm.bias", "layers.b_self_ln"),
            *attn("encoder_attn", "cross"),
            Entry(f"{pre}.encoder_attn_layer_norm.weight", "layers.cross_ln_w"),
            Entry(f"{pre}.encoder_attn_layer_norm.bias", "layers.b_cross_ln"),
            Entry(f"{pre}.fc1.weight", "layers.fc1", _t, _t),
            Entry(f"{pre}.fc1.bias", "layers.b_fc1"),
            Entry(f"{pre}.fc2.weight", "layers.fc2", _t, _t),
            Entry(f"{pre}.fc2.bias", "layers.b_fc2"),
            Entry(f"{pre}.final_layer_norm.weight", "layers.final_ln_w"),
            Entry(f"{pre}.final_layer_norm.bias", "layers.b_final_ln"),
            Entry("encoder.conv1.weight", "neck.conv1_w", _conv1_in, _conv1_out),
            Entry("encoder.conv1.bias", "neck.b_conv1"),
            Entry("encoder.layer_norm1.weight", "neck.ln1_w"),
            Entry("encoder.layer_norm1.bias", "neck.b_ln1"),
            Entry("encoder.conv2.weight", "neck.conv2_w", _conv2_in, _conv2_out_factory(cfg)),
            Entry("encoder.layer_norm2.weight", "neck.ln2_w"),
            Entry("encoder.layer_norm2.bias", "neck.b_ln2"),
            Entry("encoder.sum_proj.weight", "neck.sum_w", _t, _t),
            Entry("encoder.sum_proj.bias", "neck.b_sum"),
            Entry("encoder.layer_norm3.weight", "neck.ln3_w"),
            Entry("encoder.layer_norm3.bias", "neck.b_ln3"),
        ]
        super().__init__(entries, cfg.decoder_layers)
