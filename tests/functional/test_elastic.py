"""Elastic topology end-to-end on the virtual 8-device mesh
(docs/resilience.md "Elastic restore & warm restart"): a checkpoint saved on
one mesh shape must restore onto a different one with bitwise-identical
params and a continuous data stream, the AOT warmup must keep epoch-tail
shapes out of the jit-fallback path, and chaos topology injection must drive
the whole loop."""

import json
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction


def _write_cfg(tmp_path, name="cfg", *, dp_shard=8, tp=1, max_steps=6,
               grad_acc=1, num_samples=256, ckpt_dir=None, ckpt_every=3,
               extra=""):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/{name}_out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: {dp_shard}
      tp: {tp}
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: {num_samples}
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: {grad_acc}
      max_steps: {max_steps}
      num_epochs: 100
      handle_sigterm: false
      ckpt_every_steps: {ckpt_every if ckpt_dir else 0}
    optimizer:
      lr: 1.0e-2
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: {str(ckpt_dir is not None).lower()}
      checkpoint_dir: {ckpt_dir or f"{tmp_path}/{name}_ckpt"}
    {extra}
    """
    p = tmp_path / f"{name}.yaml"
    p.write_text(textwrap.dedent(cfg))
    return p


def _rows(tmp_path, name):
    with open(tmp_path / f"{name}_out" / "training.jsonl") as f:
        return [json.loads(line) for line in f]


def _flat(params):
    return {jax.tree_util.keystr(k): np.asarray(jax.device_get(v))
            for k, v in jax.tree_util.tree_flatten_with_path(params)[0]}


class TestElasticReshapeResume:
    def test_dp8_to_dp4_tp2_resume_is_bitwise_and_continuous(self, tmp_path, cpu_devices):
        """The headline elastic scenario: save on a dp_shard=8 slice, restart
        on dp_shard=4 x tp=2 — the restore must classify as a mesh change (not
        a model change), hand back bitwise-identical params, re-partition the
        dataloader cursor, and keep training for 10 more steps."""
        ckpt = tmp_path / "shared_ckpt"
        elastic = textwrap.dedent("""\
        resilience:
          enabled: true
          anomaly: {enabled: false}
          elastic: {enabled: true, allow_joiners: true}
        """).replace("\n", "\n    ")

        cfg_a = load_config(_write_cfg(tmp_path, "a", dp_shard=8, tp=1,
                                       max_steps=6, ckpt_dir=ckpt, extra=elastic))
        ra = TrainFinetuneRecipeForNextTokenPrediction(cfg_a).setup()
        ra.run_train_validation_loop()
        rows_a = _rows(tmp_path, "a")
        last_loss_a = [r["loss"] for r in rows_a if "loss" in r][-1]
        params_a = _flat(ra.train_params)

        cfg_b = load_config(_write_cfg(tmp_path, "b", dp_shard=4, tp=2,
                                       max_steps=16, ckpt_dir=ckpt, extra=elastic))
        rb = TrainFinetuneRecipeForNextTokenPrediction(cfg_b).setup()
        assert rb.step_scheduler.step == 6
        assert rb.mesh.shape["dp_shard"] == 4 and rb.mesh.shape["tp"] == 2

        # bitwise: orbax resharded into the new mesh's templates, values intact
        params_b = _flat(rb.train_params)
        assert params_a.keys() == params_b.keys()
        for k in params_a:
            np.testing.assert_array_equal(params_a[k], params_b[k], err_msg=k)

        rb.run_train_validation_loop()
        rows_b = _rows(tmp_path, "b")

        restore = [r for r in rows_b
                   if r.get("resilience/event") == "elastic_restore"]
        assert len(restore) == 1
        assert "dp_shard 8->4" in restore[0]["resilience/delta"]
        assert "tp 1->2" in restore[0]["resilience/delta"]

        repart = [r for r in rows_b
                  if r.get("event") == "elastic_data_repartition"]
        assert len(repart) == 1
        # single-process: the global batch size did not change, so the reshape
        # is example-exact — nothing re-fed, nothing dropped
        assert "refed_examples" not in repart[0]
        assert repart[0]["new_cursor"] * repart[0]["new_batch_size"] \
            == repart[0]["consumed_examples"]

        losses = {r["step"]: r["loss"] for r in rows_b if "loss" in r}
        assert sorted(losses) == list(range(7, 17))  # 10 continued steps
        assert all(np.isfinite(v) for v in losses.values())
        # continuity: the first resumed step continues A's trajectory (tp=2
        # changes reduction order, so tolerance — not equality)
        assert abs(losses[7] - last_loss_a) < 0.5

    def test_same_mesh_resume_is_not_elastic(self, tmp_path, cpu_devices):
        ckpt = tmp_path / "ckpt"
        cfg = _write_cfg(tmp_path, "s1", dp_shard=8, max_steps=3, ckpt_dir=ckpt)
        TrainFinetuneRecipeForNextTokenPrediction(load_config(cfg)).setup() \
            .run_train_validation_loop()
        cfg2 = _write_cfg(tmp_path, "s2", dp_shard=8, max_steps=6, ckpt_dir=ckpt)
        r2 = TrainFinetuneRecipeForNextTokenPrediction(load_config(cfg2)).setup()
        assert r2.step_scheduler.step == 3
        r2.run_train_validation_loop()
        rows = _rows(tmp_path, "s2")
        assert not any(r.get("resilience/event") == "elastic_restore"
                       for r in rows)
        assert not any(r.get("event") == "elastic_data_repartition"
                       for r in rows)


class TestPPStackToPureFSDP:
    def test_pp_ep_checkpoint_reshards_into_fsdp(self, tmp_path, cpu_devices):
        """Checkpoint-level half of the pp-stacked -> pure-FSDP reshape: params
        laid out over a pp=2 x dp_shard=2 x ep=2 mesh restore bitwise onto a
        dp_shard=8 mesh. (Training under pp is exercised elsewhere —
        tests/functional/test_train_recipe.py — and CPU pp compiles are gated
        by jax_compat.SHIMMED; the reshard itself is mesh-math only.)"""
        from automodel_tpu.checkpoint.checkpointing import (
            Checkpointer, CheckpointingConfig,
        )
        from automodel_tpu.checkpoint.reshard import build_topology
        from automodel_tpu.parallel.mesh import MeshContext

        ctx_a = MeshContext(pp=2, dp_shard=2, ep=2)
        ctx_b = MeshContext(dp_shard=8)
        mesh_a, mesh_b = ctx_a.build_mesh(), ctx_b.build_mesh()

        rng = np.random.RandomState(3)
        host = {
            "embed": np.asarray(rng.randn(16, 8), np.float32),
            "layers": {"wq": np.asarray(rng.randn(4, 8, 8), np.float32)},
        }
        spec_a = {"embed": P("dp_shard", None),
                  "layers": {"wq": P("pp", ("dp_shard", "ep"), None)}}
        spec_b = {"embed": P("dp_shard", None),
                  "layers": {"wq": P(None, "dp_shard", None)}}
        params_a = jax.tree.map(
            lambda v, s: jax.device_put(jnp.asarray(v), NamedSharding(mesh_a, s)),
            host, spec_a, is_leaf=lambda x: isinstance(x, np.ndarray))

        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        ck.topology = build_topology(ctx_a)
        ck.save(1, params_a)

        events = []
        ck2 = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        ck2.topology = build_topology(ctx_b)
        ck2.event_sink = lambda step, event, **f: events.append((event, f))
        template = jax.tree.map(
            lambda v, s: jax.device_put(jnp.zeros_like(jnp.asarray(v)),
                                        NamedSharding(mesh_b, s)),
            host, spec_b, is_leaf=lambda x: isinstance(x, np.ndarray))
        restored, _, client = ck2.load(template, step=1)

        delta = client["__elastic__"]["delta"]
        assert delta["pp"] == [2, 1] and delta["ep"] == [2, 1]
        assert delta["dp_shard"] == [2, 8]
        assert [e for e, _ in events] == ["elastic_restore"]

        wq = restored["layers"]["wq"]
        assert wq.sharding.mesh.shape["dp_shard"] == 8
        assert wq.sharding.spec == spec_b["layers"]["wq"]
        np.testing.assert_array_equal(np.asarray(jax.device_get(wq)),
                                      host["layers"]["wq"])
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored["embed"])), host["embed"])


class TestWarmRestartWarmup:
    """AOT warmup of the epoch-tail shape: 40 samples / batch 8 = 5 batches,
    grad_acc 2 -> steps of 2,2,1 microbatches per epoch. The 1-micro trailing
    stack is a second step shape: without warmup it falls through to jit
    (counted), with warmup it is pre-compiled into the executor's variant
    table and the whole run stays on the AOT path."""

    def _run(self, tmp_path, name, warmup):
        extra = textwrap.dedent(f"""\
        compile_cache:
          warmup: {str(warmup).lower()}
        """).replace("\n", "\n    ")
        cfg = load_config(_write_cfg(tmp_path, name, dp_shard=8, max_steps=6,
                                     grad_acc=2, num_samples=40, extra=extra))
        TrainFinetuneRecipeForNextTokenPrediction(cfg).setup() \
            .run_train_validation_loop()
        rows = _rows(tmp_path, name)
        summary = next(r for r in rows if r.get("event") == "compile_summary")
        return rows, summary

    def test_warmup_precompiles_trailing_shape(self, tmp_path, cpu_devices):
        rows, summary = self._run(tmp_path, "warm", warmup=True)
        assert summary["compile_aot"] >= 1
        assert summary["compile_aot_variant"] == 1  # the 1-micro tail shape
        assert summary["compile_aot_shape_fallback"] == 0
        assert summary["compile_aot_demoted"] == 0
        assert summary["compile_jit_fallback"] == 0
        variant_rows = [r for r in rows if r.get("event") == "compile_variant"]
        assert len(variant_rows) == 1 and variant_rows[0]["variants"] == 2
        losses = [r["loss"] for r in rows if "loss" in r]
        assert len(losses) == 6 and np.isfinite(losses).all()

    def test_without_warmup_tail_shape_falls_back(self, tmp_path, cpu_devices):
        _, summary = self._run(tmp_path, "cold", warmup=False)
        assert summary["compile_aot_variant"] == 0
        # every epoch tail (steps 3 and 6) ran the fallback path, and each
        # occurrence is counted — silent jit demotion was the bug
        assert summary["compile_aot_shape_fallback"] >= 1


@pytest.mark.chaos
@pytest.mark.elastic
class TestChaosElastic:
    def test_injected_topology_change_drives_elastic_resume(self, tmp_path, cpu_devices):
        """Deterministic chaos (resilience/chaos.py): at step 4 the injector
        checkpoints and raises ElasticTopologyChange carrying the resized
        mesh; the harness (this test) restarts the recipe on that mesh and
        resume takes the elastic path."""
        from automodel_tpu.resilience.elastic import ElasticTopologyChange

        ckpt = tmp_path / "ckpt"
        chaos = textwrap.dedent("""\
        resilience:
          enabled: true
          anomaly: {enabled: false}
          elastic: {enabled: true, allow_joiners: true}
          chaos:
            enabled: true
            elastic_steps: [4]
            elastic_mesh: {dp_shard: 4, tp: 2}
        """).replace("\n", "\n    ")
        cfg = load_config(_write_cfg(tmp_path, "c1", dp_shard=8, max_steps=8,
                                     ckpt_dir=ckpt, ckpt_every=100, extra=chaos))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        with pytest.raises(ElasticTopologyChange) as exc_info:
            recipe.run_train_validation_loop()
        exc = exc_info.value
        assert exc.step == 4
        assert exc.new_mesh == {"dp_shard": 4, "tp": 2}
        # the injector checkpointed before dying — that is the contract that
        # makes the restart lossless
        assert (ckpt / "step_4").is_dir()
        assert recipe.checkpointer.latest_step() == 4

        # "restart" on the mesh the exception prescribes
        elastic = textwrap.dedent("""\
        resilience:
          enabled: true
          anomaly: {enabled: false}
          elastic: {enabled: true, allow_joiners: true}
        """).replace("\n", "\n    ")
        cfg2 = load_config(_write_cfg(
            tmp_path, "c2", dp_shard=exc.new_mesh["dp_shard"],
            tp=exc.new_mesh["tp"], max_steps=8, ckpt_dir=ckpt, ckpt_every=100,
            extra=elastic))
        r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg2).setup()
        assert r2.step_scheduler.step == 4
        r2.run_train_validation_loop()
        rows = _rows(tmp_path, "c2")
        restore = [r for r in rows
                   if r.get("resilience/event") == "elastic_restore"]
        assert len(restore) == 1
        assert "dp_shard 8->4" in restore[0]["resilience/delta"]
        losses = {r["step"]: r["loss"] for r in rows if "loss" in r}
        assert sorted(losses) == [5, 6, 7, 8]
        assert all(np.isfinite(v) for v in losses.values())
