"""Persistent XLA compile-cache hit/miss accounting for the run_header.

A 1024-chip restart that recompiles every step shape burns minutes of fleet
time the persistent compilation cache exists to save — but jax only reports
cache traffic through its internal monitoring events, so nothing in the run
artifacts says whether the cache is working. This module registers one
process-wide listener for ``/jax/compilation_cache/cache_hits`` /
``cache_misses`` (installed at observability package import, before the
recipe's model-init compiles) and exposes the tallies plus the
persistent-cache configuration for the MetricLogger ``run_header`` row.

The counts keep accumulating after the header is written; the run-total view
lands in the ``compile_summary`` event row at teardown
(:meth:`automodel_tpu.observability.manager.Observability.compile_summary`).

Everything degrades to zeros/False when the jax-internal monitoring API moves
— reporting must never take the run down.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger(__name__)

__all__ = ["install", "counts", "reset", "snapshot"]

_EVENTS = {
    "/jax/compilation_cache/cache_hits": "hits",
    "/jax/compilation_cache/cache_misses": "misses",
    # pre-0.4.30 spelling of a miss
    "/jax/compilation_cache/cache_misses_because_no_entry": "misses",
}
_counts = {"hits": 0, "misses": 0}
_lock = threading.Lock()
_installed = False


def _listener(event: str, **_kwargs) -> None:
    key = _EVENTS.get(event)
    if key is not None:
        with _lock:
            _counts[key] += 1


def install() -> bool:
    """Register the monitoring listener once per process; True if active."""
    global _installed
    if _installed:
        return True
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_listener)
        _installed = True
    except Exception:
        logger.debug("jax monitoring API unavailable; compile-cache counts "
                     "stay at zero", exc_info=True)
    return _installed


def counts() -> dict[str, int]:
    """Hit/miss tallies since install (or zeros if never installed)."""
    with _lock:
        return dict(_counts)


def reset() -> None:
    """Zero the tallies (tests only — the listener stays registered)."""
    with _lock:
        for k in _counts:
            _counts[k] = 0


def snapshot() -> dict[str, object]:
    """run_header-ready view: cache config + traffic seen so far.

    Written at setup time, so the counts cover model-init / eval-shape
    compiles only; the run totals come from ``compile_summary`` at teardown.
    """
    out: dict[str, object] = {"listener": _installed, **counts()}
    try:
        from jax._src import compilation_cache

        out["persistent_enabled"] = bool(
            compilation_cache.is_persistent_cache_enabled())
    except Exception:
        out["persistent_enabled"] = False
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
        if cache_dir:
            out["dir"] = str(cache_dir)
    except Exception:
        logger.debug("compilation cache dir unreadable", exc_info=True)
    return out
