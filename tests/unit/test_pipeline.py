"""Pipeline parallelism: pp-sharded layer scan + ppermute ticks vs the plain decoder."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.common.backend import BackendConfig
from automodel_tpu.models.llama.model import LlamaConfig, LlamaForCausalLM
from automodel_tpu.ops.losses import masked_cross_entropy
from automodel_tpu.parallel.mesh import MeshContext
from automodel_tpu.parallel.pipeline import make_dense_decoder_pp_loss
from automodel_tpu.utils import jax_compat


# On pre-0.5 jax, XLA CPU's SPMD partitioner cannot lower the PartitionId
# instruction that a *partial*-manual shard_map body taking axis_index
# produces (UNIMPLEMENTED) — the pp ring needs axis_index for its stage id
# and the test meshes carry dp_shard/tp/ep axes alongside pp. TPU lowers it.
pp_partial_manual_compiles = pytest.mark.skipif(
    jax_compat.SHIMMED and jax.default_backend() == "cpu",
    reason="jax<0.5 XLA CPU cannot lower PartitionId under partial-manual "
    "shard_map (pp ring axis_index)",
)


@pytest.fixture(scope="module")
def pp_mesh():
    devs = jax.devices()
    assert len(devs) == 8
    return MeshContext(pp=2, dp_shard=2, tp=2, world_size=8).build_mesh(devs)


def _setup(n_layers=4):
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=n_layers, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    backend = BackendConfig(dtype="float32")
    model = LlamaForCausalLM(cfg, backend)
    params = model.init(jax.random.key(0), jnp.float32)
    return cfg, backend, model, params


def _batch_stack(cfg, n_micro=4, b=4, s=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (n_micro, b, s)).astype(np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "labels": jnp.asarray(ids.copy()),
        "positions": jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), ids.shape),
        "segment_ids": jnp.ones((n_micro, b, s), jnp.int32),
    }


def _pp_loss_fn(cfg, backend, mesh):
    model = LlamaForCausalLM(cfg, backend)
    return make_dense_decoder_pp_loss(model, mesh)


def _ref_loss(cfg, backend, model, params, batch_stack, n):
    losses = []
    for i in range(batch_stack["input_ids"].shape[0]):
        mb = jax.tree.map(lambda a: a[i], batch_stack)
        logits = model(params, mb["input_ids"], positions=mb["positions"],
                       segment_ids=mb["segment_ids"])
        losses.append(masked_cross_entropy(logits, mb["labels"], n))
    return sum(losses)


class TestPipeline:
    @pp_partial_manual_compiles
    def test_loss_matches_reference(self, pp_mesh):
        cfg, backend, model, params = _setup()
        batch = _batch_stack(cfg)
        n = float((batch["labels"] != -100).sum())
        pp_loss = _pp_loss_fn(cfg, backend, pp_mesh)
        with jax.sharding.set_mesh(pp_mesh):
            got = jax.jit(pp_loss)(params, batch, n)
        want = _ref_loss(cfg, backend, model, params, batch, n)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    @pp_partial_manual_compiles
    def test_grads_match_reference(self, pp_mesh):
        cfg, backend, model, params = _setup()
        batch = _batch_stack(cfg, seed=1)
        n = float((batch["labels"] != -100).sum())
        pp_loss = _pp_loss_fn(cfg, backend, pp_mesh)
        with jax.sharding.set_mesh(pp_mesh):
            g_pp = jax.jit(jax.grad(pp_loss))(params, batch, n)
        g_ref = jax.grad(lambda p: _ref_loss(cfg, backend, model, p, batch, n))(params)
        flat_pp = jax.tree.leaves_with_path(g_pp)
        flat_ref = dict(jax.tree.leaves_with_path(g_ref))
        for path, leaf in flat_pp:
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(flat_ref[path]), atol=1e-5,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
            )

    @pp_partial_manual_compiles
    def test_circular_virtual_stages_match_reference(self, pp_mesh):
        """Interleaved schedule (V=2 rounds over pp=2, 8 layers -> 4 blocks of 2,
        round-major) reproduces the plain decoder loss exactly."""
        cfg, backend, model, params = _setup(n_layers=8)
        batch = _batch_stack(cfg, n_micro=4, seed=3)
        n = float((batch["labels"] != -100).sum())
        pp_loss = make_dense_decoder_pp_loss(model, pp_mesh, circular_repeats=2)
        with jax.sharding.set_mesh(pp_mesh):
            got = jax.jit(pp_loss)(params, batch, n)
        want = _ref_loss(cfg, backend, model, params, batch, n)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    @pp_partial_manual_compiles
    def test_circular_grads_match(self, pp_mesh):
        cfg, backend, model, params = _setup(n_layers=8)
        batch = _batch_stack(cfg, n_micro=4, seed=4)
        n = float((batch["labels"] != -100).sum())
        pp_loss = make_dense_decoder_pp_loss(model, pp_mesh, circular_repeats=2)
        with jax.sharding.set_mesh(pp_mesh):
            g_pp = jax.jit(jax.grad(pp_loss))(params, batch, n)
        g_ref = jax.grad(lambda p: _ref_loss(cfg, backend, model, p, batch, n))(params)
        flat_ref = dict(jax.tree.leaves_with_path(g_ref))
        for path, leaf in jax.tree.leaves_with_path(g_pp):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(flat_ref[path]), atol=1e-5,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
            )

    @pp_partial_manual_compiles
    def test_pp_linear_ce_matches(self, pp_mesh):
        """linear_ce head under PP (no full logits) equals the masked_ce reference."""
        cfg, backend, model, params = _setup()
        batch = _batch_stack(cfg, seed=5)
        n = float((batch["labels"] != -100).sum())
        pp_loss = make_dense_decoder_pp_loss(model, pp_mesh, loss_name="linear_ce")
        with jax.sharding.set_mesh(pp_mesh):
            got = jax.jit(pp_loss)(params, batch, n)
        want = _ref_loss(cfg, backend, model, params, batch, n)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    def test_tick_counts_and_bubble(self):
        from automodel_tpu.parallel.pipeline import pipeline_ticks

        assert pipeline_ticks(8, 4) == 11
        assert pipeline_ticks(8, 4, circular_repeats=2) == 19
        # bubble fraction shrinks ~V-fold: (pp-1)/(V*n + pp - 1)
        bubble_v1 = (11 - 8) / 11
        bubble_v2 = (19 - 16) / 19
        assert bubble_v2 < bubble_v1 / 1.7

    @pp_partial_manual_compiles
    def test_uneven_micro_count(self, pp_mesh):
        # n_micro not a multiple of pp still schedules correctly
        cfg, backend, model, params = _setup()
        batch = _batch_stack(cfg, n_micro=3, seed=2)
        n = float((batch["labels"] != -100).sum())
        pp_loss = _pp_loss_fn(cfg, backend, pp_mesh)
        with jax.sharding.set_mesh(pp_mesh):
            got = jax.jit(pp_loss)(params, batch, n)
        want = _ref_loss(cfg, backend, model, params, batch, n)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


class TestMoEPPAuxExactWeighting:
    @pp_partial_manual_compiles
    def test_aux_matches_nonpp_with_uneven_labels(self):
        """Per-microbatch aux terms are weighted by each microbatch's OWN
        label-token fraction (riding the ring with the activation), matching the
        non-pp objective exactly even when label counts are uneven — the r2
        design divided by n_micro, exact only for equal counts."""
        from automodel_tpu.models.auto import AutoModelForCausalLM
        from automodel_tpu.parallel.pipeline import make_moe_pp_loss

        mesh = MeshContext(pp=2, dp_shard=2, ep=2, world_size=8).build_mesh(jax.devices())
        hf_cfg = {
            "architectures": ["Qwen3MoeForCausalLM"],
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
            "moe_intermediate_size": 32, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
            "num_experts": 8, "num_experts_per_tok": 2, "norm_topk_prob": True,
            "router_aux_loss_coef": 0.05, "max_position_embeddings": 64,
        }
        model = AutoModelForCausalLM.from_config(hf_cfg, BackendConfig(dtype="float32"))
        params = model.init(jax.random.key(1), jnp.float32)

        rng = np.random.RandomState(3)
        n_micro, b, s = 2, 2, 16
        ids = rng.randint(0, 128, (n_micro, b, s)).astype(np.int32)
        labels = ids.copy()
        # sharply uneven label counts: microbatch 0 keeps 4 labels, 1 keeps all
        labels[0, :, :-2] = -100
        batch_stack = {
            "input_ids": jnp.asarray(ids),
            "labels": jnp.asarray(labels),
            "positions": jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), ids.shape),
            "segment_ids": jnp.ones((n_micro, b, s), jnp.int32),
        }
        n = float((labels != -100).sum())

        with mesh:
            pp_loss = make_moe_pp_loss(model, mesh)
            got, aux = jax.jit(lambda p, bs: pp_loss(p, bs, jnp.float32(n)))(
                params, batch_stack
            )

        # non-pp reference: per-microbatch CE + aux * (mb_tokens / n)
        want = 0.0
        coeff = model.config.moe.aux_loss_coeff
        for i in range(n_micro):
            mb = jax.tree.map(lambda a: a[i], batch_stack)
            logits, stats = model(
                params, mb["input_ids"], positions=mb["positions"],
                segment_ids=mb["segment_ids"], training=True,
            )
            mb_tokens = float((np.asarray(mb["labels"]) != -100).sum())
            want += float(masked_cross_entropy(logits, mb["labels"], n))
            want += coeff * float(stats["aux_loss"]) * (mb_tokens / n)
        np.testing.assert_allclose(float(got), want, rtol=2e-5)
        assert aux["expert_load"].shape == (2, 8)


class TestMoEPPA2AComposition:
    """a2a x PP: the pipeline's manual region is flattened to one manual mesh
    over {pp, ep}, so the explicit EP dispatch runs INSIDE the pp stage body
    (no nested shard_map). A pp2 x ep4 world-8 mesh is fully manual — every
    axis of size > 1 is manual — which the shimmed CPU shard_map compiles, so
    unlike the partial-manual pp meshes above these tests need no skip."""

    HF_CFG = {
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 96,
        "moe_intermediate_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2, "head_dim": 16,
        "num_experts": 8, "num_experts_per_tok": 2, "norm_topk_prob": True,
        "router_aux_loss_coef": 0.0, "max_position_embeddings": 64,
    }

    def _build(self, **backend_kw):
        from automodel_tpu.models.auto import AutoModelForCausalLM

        return AutoModelForCausalLM.from_config(
            self.HF_CFG,
            BackendConfig(dtype="float32", dispatcher="a2a", **backend_kw))

    def _batch(self, n_micro=2, b=4, s=16):
        rng = np.random.RandomState(3)
        ids = rng.randint(0, 128, (n_micro, b, s)).astype(np.int32)
        stack = {
            "input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids.copy()),
            "positions": jnp.broadcast_to(
                jnp.arange(s, dtype=jnp.int32), ids.shape),
            "segment_ids": jnp.ones((n_micro, b, s), jnp.int32),
        }
        return stack, jnp.float32(n_micro * b * s)

    def _mesh(self):
        return MeshContext(pp=2, ep=4, world_size=8).build_mesh(jax.devices())

    def test_steps_and_trains_with_drop_accounting(self):
        from automodel_tpu.parallel.pipeline import make_moe_pp_loss

        mesh = self._mesh()
        model = self._build(ep_capacity_factor=8.0)
        params = model.init(jax.random.key(1), jnp.float32)
        batch_stack, n = self._batch()
        with mesh:
            pp_loss = make_moe_pp_loss(model, mesh)
            loss, aux = jax.jit(lambda p, bs: pp_loss(p, bs, n))(
                params, batch_stack)
            g = jax.jit(jax.grad(lambda p, bs: pp_loss(p, bs, n)[0]))(
                params, batch_stack)
        assert np.isfinite(float(loss))
        # ample capacity: the exact drop accounting reports zero
        assert float(aux["dropped_token_frac"]) == 0.0
        # the a2a path actually trained the experts on both pp stages
        eg = np.asarray(g["moe_layers"]["moe"]["experts"]["gate_up_proj"])
        assert np.isfinite(eg).all() and np.abs(eg).max() > 0

    def test_ce_matches_dense_dispatcher_reference(self):
        """With ample capacity (no drops) and aux coeff 0, pp+a2a reproduces
        the non-pp dense-dispatcher CE. (The a2a aux term is pmean'd over ep
        shards — per-shard load stats, not the global-batch aux — so CE is
        the exact cross-dispatcher contract; see moe/dispatch.py.)"""
        from automodel_tpu.models.auto import AutoModelForCausalLM
        from automodel_tpu.parallel.pipeline import make_moe_pp_loss

        mesh = self._mesh()
        model = self._build(ep_capacity_factor=8.0)
        params = model.init(jax.random.key(1), jnp.float32)
        batch_stack, n = self._batch()
        with mesh:
            got, _ = jax.jit(
                lambda p, bs: make_moe_pp_loss(model, mesh)(p, bs, n))(
                params, batch_stack)

        ref_model = AutoModelForCausalLM.from_config(
            self.HF_CFG, BackendConfig(dtype="float32"))
        want = 0.0
        for i in range(batch_stack["input_ids"].shape[0]):
            mb = jax.tree.map(lambda a: a[i], batch_stack)
            logits, _ = ref_model(
                params, mb["input_ids"], positions=mb["positions"],
                segment_ids=mb["segment_ids"], training=True)
            want += float(masked_cross_entropy(logits, mb["labels"], n))
        np.testing.assert_allclose(float(got), want, rtol=2e-5)

    def test_tight_capacity_reports_drops(self):
        from automodel_tpu.parallel.pipeline import make_moe_pp_loss

        mesh = self._mesh()
        model = self._build(ep_capacity_factor=0.5)
        params = model.init(jax.random.key(1), jnp.float32)
        batch_stack, n = self._batch()
        with mesh:
            _, aux = jax.jit(
                lambda p, bs: make_moe_pp_loss(model, mesh)(p, bs, n))(
                params, batch_stack)
        assert 0.0 < float(aux["dropped_token_frac"]) <= 1.0

    def test_chunked_dispatch_under_pp_bit_identical(self):
        from automodel_tpu.parallel.pipeline import make_moe_pp_loss

        mesh = self._mesh()
        params = self._build(ep_capacity_factor=8.0).init(
            jax.random.key(1), jnp.float32)
        batch_stack, n = self._batch()
        losses = {}
        with mesh:
            for nch in (1, 3):
                model = self._build(ep_capacity_factor=8.0, a2a_chunks=nch)
                losses[nch] = float(jax.jit(
                    lambda p, bs, m=model: make_moe_pp_loss(m, mesh)(p, bs, n))(
                    params, batch_stack)[0])
        assert losses[1] == losses[3]

    def test_pallas_experts_under_pp_a2a(self):
        from automodel_tpu.parallel.pipeline import make_moe_pp_loss

        mesh = self._mesh()
        params = self._build(ep_capacity_factor=8.0).init(
            jax.random.key(1), jnp.float32)
        batch_stack, n = self._batch()
        losses = {}
        with mesh:
            for eb in ("ragged_dot", "pallas"):
                model = self._build(ep_capacity_factor=8.0, experts_backend=eb)
                losses[eb] = float(jax.jit(
                    lambda p, bs, m=model: make_moe_pp_loss(m, mesh)(p, bs, n))(
                    params, batch_stack)[0])
        np.testing.assert_allclose(losses["pallas"], losses["ragged_dot"],
                                   rtol=1e-5)
