"""Overlapped input pipeline end-to-end: with ``dataloader.prefetch.enabled``
the recipe must produce the identical loss trajectory (same batches, same
order), resume exactly through in-flight batches, and survive the resilience
paths (chaos rollback, SIGTERM preemption) without deadlocking the worker."""

import json
import textwrap
import threading
import time

import numpy as np
import pytest

from automodel_tpu.config.loader import load_config
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction

PREFETCH = textwrap.dedent("""\
dataloader:
  prefetch:
    enabled: true
    host_depth: 3
    device_depth: 2
""").replace("\n", "\n    ")


def _write_cfg(tmp_path, extra="", max_steps=6, grad_acc=2, ckpt=False,
               ckpt_every=3, name="cfg.yaml"):
    cfg = f"""
    seed: 7
    output_dir: {tmp_path}/out
    model:
      config:
        architectures: [LlamaForCausalLM]
        vocab_size: 128
        hidden_size: 64
        intermediate_size: 128
        num_hidden_layers: 2
        num_attention_heads: 4
        num_key_value_heads: 2
        max_position_embeddings: 128
    distributed:
      dp_shard: 4
      tp: 2
    backend:
      dtype: float32
    dataset:
      _target_: automodel_tpu.data.llm.mock.MockSFTDataset
      vocab_size: 128
      seq_len: 32
      num_samples: 256
      seed: 0
      pattern: arith
    micro_batch_size: 8
    seq_len: 32
    step_scheduler:
      grad_acc_steps: {grad_acc}
      max_steps: {max_steps}
      num_epochs: 10
      handle_sigterm: false
      ckpt_every_steps: {ckpt_every if ckpt else 0}
    optimizer:
      lr: 1.0e-2
      weight_decay: 0.0
      max_grad_norm: 1.0
    lr_scheduler:
      lr_warmup_steps: 2
    checkpoint:
      enabled: {str(ckpt).lower()}
      checkpoint_dir: {tmp_path}/ckpt
    {extra}
    """
    p = tmp_path / name
    p.write_text(textwrap.dedent(cfg))
    return p


def _rows(tmp_path):
    rows = [json.loads(line) for line in open(tmp_path / "out" / "training.jsonl")]
    return [r for r in rows
            if "run_header" not in r
            and r.get("event") not in ("compile_costs", "compile_summary")]


class TestPrefetchTrajectory:
    def test_identical_losses_and_depth_logged(self, tmp_path, cpu_devices):
        sync_dir = tmp_path / "sync"
        sync_dir.mkdir()
        cfg = load_config(_write_cfg(sync_dir))
        TrainFinetuneRecipeForNextTokenPrediction(cfg).setup().run_train_validation_loop()
        sync_rows = _rows(sync_dir)

        pf_dir = tmp_path / "prefetch"
        pf_dir.mkdir()
        cfg = load_config(_write_cfg(pf_dir, extra=PREFETCH))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        pf_rows = _rows(pf_dir)

        assert [r["step"] for r in pf_rows] == [r["step"] for r in sync_rows]
        for s, p in zip(sync_rows, pf_rows):
            # identical batches in identical order -> bitwise-identical math
            assert p["loss"] == s["loss"], f"step {p['step']} diverged"
        # observability satellite: every prefetch row reports pipeline depth
        assert all("prefetch_depth" in r for r in pf_rows)
        assert all("prefetch_depth" not in r for r in sync_rows)
        # the pipeline must be torn down with the pass
        assert recipe._pipeline is None

    def test_resume_exact_with_in_flight_batches(self, tmp_path, cpu_devices):
        """The step-3 checkpoint is written while the worker has run ahead;
        the persisted state must be the consumed position, so the resumed run
        replays steps 4..6 bit-identically."""
        cfg = load_config(_write_cfg(tmp_path, extra=PREFETCH, ckpt=True))
        TrainFinetuneRecipeForNextTokenPrediction(cfg).setup().run_train_validation_loop()
        rows1 = _rows(tmp_path)

        import shutil

        shutil.rmtree(tmp_path / "ckpt" / "step_6")
        (tmp_path / "ckpt" / "latest").unlink()
        (tmp_path / "out" / "training.jsonl").unlink()
        cfg2 = load_config(_write_cfg(tmp_path, extra=PREFETCH, name="cfg2.yaml",
                                      ckpt=True))
        r2 = TrainFinetuneRecipeForNextTokenPrediction(cfg2).setup()
        assert r2.step_scheduler.step == 3
        r2.run_train_validation_loop()
        rows2 = _rows(tmp_path)

        l1 = {r["step"]: r["loss"] for r in rows1}
        l2 = {r["step"]: r["loss"] for r in rows2}
        for s in (4, 5, 6):
            assert l2[s] == pytest.approx(l1[s], rel=1e-6), f"step {s} diverged"


class TestPrefetchResilience:
    _resilience = textwrap.dedent("""\
    resilience:
      enabled: true
      anomaly: {window: 20, min_history: 5}
      max_skipped_updates: 0
      rollback: {max_rollbacks: 2, skip_steps: 0}
      chaos:
        enabled: true
        nan_grad_steps: [6]
    """).replace("\n", "\n    ")

    def test_chaos_rollback_with_pipeline_active(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, extra=self._resilience + "\n    " + PREFETCH,
                                     ckpt=True, ckpt_every=4, max_steps=10, grad_acc=1))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()
        recipe.run_train_validation_loop()
        rows = _rows(tmp_path)

        events = [r["resilience/event"] for r in rows if "resilience/event" in r]
        assert "rollback" in events and "rollback_done" in events
        done = next(r for r in rows if r.get("resilience/event") == "rollback_done")
        assert done["resilience/from_step"] == 6
        assert done["resilience/to_step"] == 4

        losses = {r["step"]: r["loss"] for r in rows if "loss" in r}
        assert 6 not in losses
        assert all(np.isfinite(v) for v in losses.values())
        assert max(losses) == 10  # recovered and finished the run
        # the replacement pass got its own pipeline; the old worker is gone
        assert recipe._pipeline is None

    def test_empty_buffer_truncation_takes_preemption_path(self, tmp_path,
                                                           cpu_devices, monkeypatch):
        """The input-bound deadlock case: the flag lands AFTER the consumer's
        step-K agreed check but BEFORE the worker's post-yield-K flag check,
        with nothing buffered ahead — the worker ends the stream and
        pipeline.get() returns None. The loop must not conclude "done" (on a
        pod the other hosts are still stepping and their agreed allgather
        would hang); it rebuilds the pipeline, consumes step K+1, and the
        agreed check preempts the run there."""
        from automodel_tpu.data import prefetch as prefetch_mod

        K = 3
        release = threading.Event()
        pause_at = {"n": K}
        real_iter_source = prefetch_mod.HostPrefetcher._iter_source

        def paused_iter_source(self):
            inner = real_iter_source(self)

            def gen():
                produced = 0
                for item in inner:
                    produced += 1
                    yield item
                    # resumed here only when the worker asks for the NEXT
                    # item, i.e. after it stacked+enqueued this one and
                    # before the underlying iterator's post-yield flag
                    # check — exactly the window the race needs
                    if pause_at["n"] is not None and produced == pause_at["n"]:
                        pause_at["n"] = None
                        release.wait(timeout=30.0)

            return gen()

        monkeypatch.setattr(prefetch_mod.HostPrefetcher, "_iter_source",
                            paused_iter_source)

        cfg = load_config(_write_cfg(tmp_path, extra=PREFETCH, ckpt=True,
                                     ckpt_every=50, max_steps=50, grad_acc=1))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()

        real_agreed = recipe.step_scheduler.sigterm_agreed_at
        fired = {}

        def agreed(step):
            out = real_agreed(step)
            if step == K and not out and "at" not in fired:
                # consumer just cleared step K; raise the flag and only then
                # let the paused worker reach its flag check — it truncates
                # with the buffers empty
                fired["at"] = step
                recipe.step_scheduler._sigterm.set()
                recipe.step_scheduler.sigterm_time = time.monotonic()
                release.set()
            return out

        monkeypatch.setattr(recipe.step_scheduler, "sigterm_agreed_at", agreed)
        recipe.run_train_validation_loop()
        assert fired.get("at") == K

        rows = _rows(tmp_path)
        steps = [r["step"] for r in rows if "loss" in r]
        # one rebuild, one more consumed step, then the agreed preemption save
        assert max(steps) == K + 1
        import os

        latest = os.path.realpath(tmp_path / "ckpt" / "latest")
        assert latest.endswith(f"step_{K + 1}")
        assert recipe._pipeline is None
        live = [th for th in threading.enumerate() if th.name == "host-prefetch"]
        assert not live, "prefetch worker leaked past truncation recovery"

    def test_sigterm_preemption_drains_without_deadlock(self, tmp_path, cpu_devices):
        cfg = load_config(_write_cfg(tmp_path, extra=PREFETCH, ckpt=True,
                                     ckpt_every=50, max_steps=50, grad_acc=1))
        recipe = TrainFinetuneRecipeForNextTokenPrediction(cfg).setup()

        fired = {}

        def fire_sigterm():
            # raise the local flag mid-run, as the cluster's SIGTERM would
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if (tmp_path / "out" / "training.jsonl").exists() and _rows(tmp_path):
                    recipe.step_scheduler._sigterm.set()
                    recipe.step_scheduler.sigterm_time = time.monotonic()
                    fired["at"] = time.monotonic()
                    return
                time.sleep(0.02)

        t = threading.Thread(target=fire_sigterm, daemon=True)
        t.start()
        recipe.run_train_validation_loop()
        t.join(timeout=5.0)
        assert "at" in fired, "sigterm thread never fired"

        rows = _rows(tmp_path)
        steps = [r["step"] for r in rows if "loss" in r]
        assert steps, "no steps completed before preemption"
        last = max(steps)
        assert last < 50, "run was not preempted"
        # the preemption checkpoint holds the consumed step, not the worker's
        import os

        latest = os.path.realpath(tmp_path / "ckpt" / "latest")
        assert latest.endswith(f"step_{last}")
        # worker thread exited with the pipeline
        assert recipe._pipeline is None
        live = [th for th in threading.enumerate() if th.name == "host-prefetch"]
        assert not live, "prefetch worker leaked past preemption"
