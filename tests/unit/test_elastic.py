"""Elastic topology unit coverage (docs/resilience.md "Elastic restore & warm
restart"): reshard topology metadata + restore classification, deterministic
dataloader-state re-partitioning, joiner-aware pod agreement, the hardened
latest pointer, chaos topology injection, and the multi-variant AOT executor.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from automodel_tpu.checkpoint.checkpointing import (
    Checkpointer, CheckpointingConfig, ModelSignatureMismatch, _ABSTAIN,
)
from automodel_tpu.checkpoint.reshard import (
    TOPOLOGY_KEY, build_topology, describe_delta, mesh_delta, read_topology,
    strip_topology,
)
from automodel_tpu.parallel.mesh import MeshContext
from automodel_tpu.resilience.elastic import (
    ElasticTopologyChange, merge_host_states, plan_warmup_micro_counts,
    repartition_dataloader_state,
)


def _params(seed=0, d=8):
    rng = np.random.RandomState(seed)
    return {
        "embed": jnp.asarray(rng.randn(16, d), jnp.float32),
        "layers": {"wq": jnp.asarray(rng.randn(2, d, d), jnp.float32)},
    }


def _topo(**axes):
    return build_topology(MeshContext(world_size=8, **axes), process_count=1)


class TestReshardMetadata:
    def test_build_topology_records_axes_and_pod(self):
        t = build_topology(MeshContext(dp_shard=4, tp=2, world_size=8),
                           process_count=3)
        assert t["mesh"]["dp_shard"] == 4 and t["mesh"]["tp"] == 2
        assert t["process_count"] == 3 and t["world_size"] == 8

    def test_strip_topology_roundtrip(self):
        sig = {"a": "f32/(2, 2)", TOPOLOGY_KEY: _topo(dp_shard=8)}
        clean, topo = strip_topology(sig)
        assert TOPOLOGY_KEY not in clean and clean == {"a": "f32/(2, 2)"}
        assert topo["mesh"]["dp_shard"] == 8
        # legacy signature: no topology key
        clean2, topo2 = strip_topology({"a": "f32/(2, 2)"})
        assert topo2 is None and clean2 == {"a": "f32/(2, 2)"}

    def test_mesh_delta_names_only_changed_axes(self):
        delta = mesh_delta(_topo(dp_shard=8), _topo(dp_shard=4, tp=2))
        assert delta == {"dp_shard": (8, 4), "tp": (1, 2)}
        assert "dp_shard 8->4" in describe_delta(delta)
        assert "tp 1->2" in describe_delta(delta)

    def test_mesh_delta_same_mesh_is_empty(self):
        assert mesh_delta(_topo(dp_shard=8), _topo(dp_shard=8)) == {}
        # either side unknown (legacy checkpoint / unwired recipe) -> same-mesh
        assert mesh_delta(None, _topo(dp_shard=8)) == {}
        assert mesh_delta(_topo(dp_shard=8), None) == {}

    def test_mesh_delta_process_count_change(self):
        a = build_topology(MeshContext(dp_shard=8, world_size=8), process_count=4)
        b = build_topology(MeshContext(dp_shard=8, world_size=8), process_count=2)
        assert mesh_delta(a, b) == {"process_count": (4, 2)}

    def test_read_topology_missing_dir(self, tmp_path):
        assert read_topology(str(tmp_path / "nope")) is None


class TestRepartition:
    def _state(self, cursor=10, bs=16):
        return {"epoch": 1, "cursor": cursor, "seed": 5, "batch_size": bs,
                "process_count": 2}

    def test_exact_shrink(self):
        out, info = repartition_dataloader_state(self._state(), 8)
        assert out["cursor"] == 20 and out["batch_size"] == 8
        assert out["epoch"] == 1 and out["seed"] == 5
        assert info["consumed_examples"] == 160
        assert "refed_examples" not in info

    def test_exact_grow(self):
        out, info = repartition_dataloader_state(self._state(), 32)
        assert out["cursor"] == 5
        assert "refed_examples" not in info

    def test_nondivisible_refeeds_never_drops(self):
        out, info = repartition_dataloader_state(self._state(), 12)
        # 160 consumed -> cursor 13 (156 examples) + 4 re-fed, none dropped
        assert out["cursor"] == 13
        assert info["refed_examples"] == 4
        assert out["cursor"] * 12 + info["refed_examples"] == 160

    def test_legacy_state_without_batch_size(self):
        out, info = repartition_dataloader_state({"epoch": 0, "cursor": 7}, 8)
        assert out["cursor"] == 7  # assumed same-size: cursor passes through
        assert info["old_batch_size"] == 8

    def test_bad_batch_size_raises(self):
        with pytest.raises(ValueError, match="new_batch_size"):
            repartition_dataloader_state(self._state(), 0)

    def test_merge_host_states_consistent_rows(self):
        rows = [{"process_index": i, "epoch": 1, "cursor": 10, "batch_size": 16}
                for i in range(4)]
        merged, info = merge_host_states(rows, {"epoch": 9, "cursor": 9})
        assert merged["cursor"] == 10 and merged["epoch"] == 1
        assert "host_cursor_skew" not in info

    def test_merge_host_states_divergent_takes_minimum(self):
        rows = [
            {"process_index": 0, "epoch": 1, "cursor": 12},
            {"process_index": 1, "epoch": 1, "cursor": 10},  # stale host wins
            {"process_index": 2, "epoch": 1, "cursor": 12},
        ]
        merged, info = merge_host_states(rows, {"epoch": 0, "cursor": 0})
        assert merged["cursor"] == 10
        assert info["host_cursor_skew"] == 2

    def test_merge_orders_by_epoch_then_cursor(self):
        rows = [
            {"process_index": 0, "epoch": 2, "cursor": 1},
            {"process_index": 1, "epoch": 1, "cursor": 30},  # earlier epoch wins
        ]
        merged, _ = merge_host_states(rows, {})
        assert (merged["epoch"], merged["cursor"]) == (1, 30)

    def test_merge_empty_rows_keeps_fallback(self):
        merged, info = merge_host_states(None, {"epoch": 3, "cursor": 4})
        assert merged == {"epoch": 3, "cursor": 4} and info == {}

    def test_repartition_uses_host_rows(self):
        rows = [{"process_index": 0, "epoch": 1, "cursor": 10, "batch_size": 16},
                {"process_index": 1, "epoch": 1, "cursor": 9, "batch_size": 16}]
        out, info = repartition_dataloader_state(self._state(cursor=10), 8,
                                                 host_rows=rows)
        assert out["cursor"] == 18  # min cursor 9 * 16 / 8
        assert info["host_cursor_skew"] == 1


class TestWarmupPlan:
    def test_trailing_partial_shape(self):
        assert plan_warmup_micro_counts(10, 4) == [2]

    def test_divisible_epoch_has_no_extra_shape(self):
        assert plan_warmup_micro_counts(12, 4) == []

    def test_no_accumulation_or_unsized(self):
        assert plan_warmup_micro_counts(10, 1) == []
        assert plan_warmup_micro_counts(None, 4) == []


class TestDataLoaderElasticState:
    def _loader(self, bs=8):
        from automodel_tpu.data.loader import DataLoader

        return DataLoader(list(range(64)), batch_size=bs, seed=3)

    def test_state_dict_carries_geometry(self):
        dl = self._loader()
        next(iter(dl))
        s = dl.state_dict()
        assert s["batch_size"] == 8 and s["process_count"] == 1
        assert dl.consumed_examples == 8

    def test_load_rejects_mismatched_batch_size(self):
        dl = self._loader(bs=8)
        state = dict(dl.state_dict(), batch_size=16)
        with pytest.raises(ValueError, match="repartition"):
            dl.load_state_dict(state)

    def test_load_tolerates_legacy_state(self):
        dl = self._loader()
        dl.load_state_dict({"epoch": 2, "cursor": 3})  # pre-elastic checkpoint
        assert dl.epoch == 2 and dl._cursor == 3

    def test_repartitioned_state_consumes_same_examples(self):
        # the invariant the whole elastic path rests on: the consumed set is
        # the first cursor*batch_size permutation entries, so after an exact
        # reshape the new loader resumes at the identical example boundary
        dl = self._loader(bs=16)
        it = iter(dl)
        next(it), next(it)
        new_state, _ = repartition_dataloader_state(dl.state_dict(), 8)
        dl2 = self._loader(bs=8)
        dl2.load_state_dict(new_state)
        assert dl2.consumed_examples == dl.consumed_examples == 32


class TestTopologyAwareCheckpoint:
    def _ck(self, tmp_path, topo=None, events=None):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        ck.topology = topo
        if events is not None:
            ck.event_sink = lambda step, event, **f: events.append((event, f))
        return ck

    def test_save_embeds_topology_in_signature(self, tmp_path):
        ck = self._ck(tmp_path, topo=_topo(dp_shard=8))
        ck.save(1, _params())
        sig = json.load(open(os.path.join(ck.step_dir(1), "signature.json")))
        assert sig[TOPOLOGY_KEY]["mesh"]["dp_shard"] == 8
        assert read_topology(ck.step_dir(1))["mesh"]["dp_shard"] == 8

    def test_same_mesh_restore_is_not_elastic(self, tmp_path):
        events = []
        ck = self._ck(tmp_path, topo=_topo(dp_shard=8), events=events)
        p = _params()
        ck.save(1, p)
        _, _, client = ck.load(jax.tree.map(jnp.zeros_like, p), step=1)
        assert "__elastic__" not in client
        assert not any(e == "elastic_restore" for e, _ in events)

    def test_mesh_change_classified_elastic_and_bitwise_equal(self, tmp_path):
        events = []
        ck = self._ck(tmp_path, topo=_topo(dp_shard=8))
        p = _params()
        ck.save(2, p, client_states={"step": 2})
        ck2 = self._ck(tmp_path, topo=_topo(dp_shard=4, tp=2), events=events)
        restored, _, client = ck2.load(jax.tree.map(jnp.zeros_like, p), step=2)
        marker = client["__elastic__"]
        assert marker["delta"]["dp_shard"] == [8, 4]
        assert marker["from"]["mesh"]["dp_shard"] == 8
        assert [e for e, _ in events] == ["elastic_restore"]
        assert "dp_shard 8->4" in events[0][1]["delta"]
        np.testing.assert_array_equal(np.asarray(restored["layers"]["wq"]),
                                      np.asarray(p["layers"]["wq"]))

    def test_model_change_still_hard_fails(self, tmp_path):
        ck = self._ck(tmp_path, topo=_topo(dp_shard=8))
        ck.save(1, _params(d=8))
        ck2 = self._ck(tmp_path, topo=_topo(dp_shard=4, tp=2))
        # a changed MODEL must never be mistaken for a changed mesh
        with pytest.raises(ValueError, match="different model signature"):
            ck2.load(_params(d=16), step=1)
        with pytest.raises(ModelSignatureMismatch):
            ck2.load(_params(d=16), step=1)

    def test_legacy_checkpoint_without_topology(self, tmp_path):
        ck = self._ck(tmp_path, topo=None)  # pre-elastic writer
        p = _params()
        ck.save(1, p)
        sig = json.load(open(os.path.join(ck.step_dir(1), "signature.json")))
        assert TOPOLOGY_KEY not in sig
        ck2 = self._ck(tmp_path, topo=_topo(dp_shard=4, tp=2))
        _, _, client = ck2.load(jax.tree.map(jnp.zeros_like, p), step=1)
        assert "__elastic__" not in client  # unknown saved mesh -> not elastic

    def test_missing_manifest_emits_unverified_restore(self, tmp_path):
        events = []
        ck = self._ck(tmp_path, events=events)
        p = _params()
        ck.save(1, p)
        manifest = os.path.join(ck.step_dir(1), "manifest.json")
        if os.path.exists(manifest):
            os.remove(manifest)
        ck.load(jax.tree.map(jnp.zeros_like, p), step=1)
        assert "unverified_restore" in [e for e, _ in events]

    def test_save_records_host_rows_in_client(self, tmp_path):
        ck = self._ck(tmp_path)
        dl_state = {"epoch": 0, "cursor": 3, "seed": 1, "batch_size": 8,
                    "process_count": 1}
        ck.save(1, _params(), client_states={"dataloader": dl_state})
        client = json.load(open(os.path.join(ck.step_dir(1), "client.json")))
        rows = client["__hosts__"]["dataloader"]
        assert rows == [{"process_index": 0, "epoch": 0, "cursor": 3,
                         "batch_size": 8}]


class TestLatestPointerHardening:
    def test_dangling_symlink_falls_back_to_scan(self, tmp_path):
        cfg = CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"))
        ck = Checkpointer(cfg)
        ck.save(2, _params())
        latest = tmp_path / "ck" / "latest"
        os.remove(latest)
        os.symlink("step_9", latest)  # points at a pruned/never-written step
        assert Checkpointer(cfg).latest_step() == 2

    def test_symlink_to_incomplete_dir_falls_back(self, tmp_path):
        cfg = CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"))
        ck = Checkpointer(cfg)
        ck.save(2, _params())
        d9 = ck.step_dir(9)
        os.makedirs(os.path.join(d9, "model.orbax-checkpoint-tmp-42"))
        latest = tmp_path / "ck" / "latest"
        os.remove(latest)
        os.symlink("step_9", latest)  # crashed save that somehow won the swap
        assert Checkpointer(cfg).latest_step() == 2

    def test_healthy_symlink_stays_authoritative(self, tmp_path):
        cfg = CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck"))
        ck = Checkpointer(cfg)
        ck.save(2, _params())
        ck.save(5, _params())
        assert Checkpointer(cfg).latest_step() == 5


class TestPodAgreement:
    """Divergent per-host views of agreed_restore_step/newest_verifiable_step:
    the collective is simulated by monkeypatching agreed_min_int with another
    host's (possibly lagging or abstaining) local answer."""

    def _ck_with_steps(self, tmp_path, steps=(2, 4, 6)):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        for s in steps:
            ck.save(s, _params())
        return ck

    def _pod(self, monkeypatch, remote_values):
        """agreed_min_int = min(local, *remote_values)."""
        import automodel_tpu.parallel.init as pinit

        monkeypatch.setattr(
            pinit, "agreed_min_int",
            lambda v: int(min(int(v), *[int(r) for r in remote_values])),
        )

    def test_newest_verifiable_with_overlapping_excludes(self, tmp_path):
        ck = self._ck_with_steps(tmp_path)
        assert ck.newest_verifiable_step() == 6
        assert ck.newest_verifiable_step({6}) == 4
        assert ck.newest_verifiable_step({4, 6}) == 2
        # overlapping sets excluding already-gone steps change nothing
        assert ck.newest_verifiable_step({4, 6, 99}) == 2
        assert ck.newest_verifiable_step({2, 4, 6}) is None

    def test_agreed_takes_min_over_divergent_hosts(self, tmp_path, monkeypatch):
        ck = self._ck_with_steps(tmp_path)
        self._pod(monkeypatch, [4])  # remote host's filesystem view lags at 4
        assert ck.agreed_restore_step() == 4
        # excluding the remote's answer locally still yields the pod minimum
        self._pod(monkeypatch, [6])
        assert ck.agreed_restore_step({6}) == 4

    def test_joiner_abstains_instead_of_forcing_fresh(self, tmp_path, monkeypatch):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        assert ck.newest_verifiable_step() is None  # empty local view
        self._pod(monkeypatch, [6])  # veterans agree on 6
        # legacy semantics: one empty host drags the pod to None
        assert ck.agreed_restore_step() is None
        # elastic join: the joiner abstains and restores what veterans agree on
        assert ck.agreed_restore_step(allow_joiners=True) == 6

    def test_all_hosts_abstaining_is_a_fresh_run(self, tmp_path, monkeypatch):
        ck = Checkpointer(CheckpointingConfig(checkpoint_dir=str(tmp_path / "ck")))
        self._pod(monkeypatch, [_ABSTAIN])
        assert ck.agreed_restore_step(allow_joiners=True) is None

    def test_veteran_with_joiners_present(self, tmp_path, monkeypatch):
        ck = self._ck_with_steps(tmp_path, steps=(3,))
        self._pod(monkeypatch, [_ABSTAIN])  # the other host just joined
        assert ck.agreed_restore_step(allow_joiners=True) == 3


class TestChaosElastic:
    def test_config_parses_elastic_fields(self):
        from automodel_tpu.resilience.chaos import ChaosConfig

        cfg = ChaosConfig.from_dict({
            "enabled": True, "elastic_steps": [3, 7],
            "elastic_mesh": {"dp_shard": 4, "tp": 2},
        })
        assert cfg.elastic_steps == (3, 7)
        assert cfg.elastic_mesh == {"dp_shard": 4, "tp": 2}
        assert ChaosConfig.from_dict({"enabled": True}).elastic_steps == ()

    def test_injector_fires_once_per_step(self):
        from automodel_tpu.resilience.chaos import ChaosConfig, ChaosInjector

        inj = ChaosInjector(ChaosConfig(
            enabled=True, elastic_steps=(3,), elastic_mesh={"dp_shard": 2}))
        assert not inj.should_elastic(2)
        assert inj.should_elastic(3)
        assert inj.elastic_change(3) == {"dp_shard": 2}
        assert not inj.should_elastic(3)  # fired

    def test_no_mesh_means_no_injection(self):
        from automodel_tpu.resilience.chaos import ChaosConfig, ChaosInjector

        inj = ChaosInjector(ChaosConfig(enabled=True, elastic_steps=(3,)))
        assert not inj.should_elastic(3)

    def test_exception_carries_step_and_mesh(self):
        exc = ElasticTopologyChange(7, {"dp_shard": 4})
        assert exc.step == 7 and exc.new_mesh == {"dp_shard": 4}
        assert "step 7" in str(exc)


class TestElasticConfig:
    def test_defaults_and_parsing(self):
        from automodel_tpu.resilience.config import ResilienceConfig

        cfg = ResilienceConfig.from_dict(None)
        assert cfg.elastic.enabled and cfg.elastic.allow_joiners
        cfg = ResilienceConfig.from_dict(
            {"elastic": {"enabled": False, "allow_joiners": False}})
        assert not cfg.elastic.enabled and not cfg.elastic.allow_joiners


class TestGuardedCompiledVariants:
    def _executor(self, fn, args, counters):
        from automodel_tpu.observability.manager import _GuardedCompiled

        compiled = fn.lower(*args).compile()
        return _GuardedCompiled(
            compiled, fn, args,
            on_demote=lambda: counters.__setitem__(
                "demoted", counters["demoted"] + 1),
            on_shape_fallback=lambda: counters.__setitem__(
                "shape", counters["shape"] + 1),
        )

    def test_known_shape_runs_variant(self):
        counters = {"demoted": 0, "shape": 0}
        fn = jax.jit(lambda x: x * 2)
        g = self._executor(fn, (jnp.arange(8.0),), counters)
        np.testing.assert_array_equal(np.asarray(g(jnp.arange(8.0))),
                                      np.arange(8.0) * 2)
        assert counters == {"demoted": 0, "shape": 0}
        assert g.num_variants == 1

    def test_unseen_shape_counts_fallback(self):
        counters = {"demoted": 0, "shape": 0}
        fn = jax.jit(lambda x: x * 2)
        g = self._executor(fn, (jnp.arange(8.0),), counters)
        out = g(jnp.arange(4.0))  # trailing partial shape: no variant yet
        np.testing.assert_array_equal(np.asarray(out), np.arange(4.0) * 2)
        assert counters["shape"] == 1

    def test_add_variant_silences_fallback(self):
        counters = {"demoted": 0, "shape": 0}
        fn = jax.jit(lambda x: x * 2)
        g = self._executor(fn, (jnp.arange(8.0),), counters)
        small = (jnp.arange(4.0),)
        g.add_variant(small, fn.lower(*small).compile())
        assert g.num_variants == 2
        g(*small)
        g(jnp.arange(8.0))
        assert counters == {"demoted": 0, "shape": 0}

    def test_demotion_is_per_variant(self):
        from automodel_tpu.observability.manager import _GuardedCompiled

        counters = {"demoted": 0, "shape": 0}
        fn = jax.jit(lambda x: x * 2)

        def bad_compiled(*a):
            raise ValueError("Compiled object called with input sharding X")

        g = _GuardedCompiled(
            bad_compiled, fn, (jnp.arange(8.0),),
            on_demote=lambda: counters.__setitem__(
                "demoted", counters["demoted"] + 1),
            on_shape_fallback=lambda: counters.__setitem__(
                "shape", counters["shape"] + 1),
        )
        out = g(jnp.arange(8.0))  # rejected -> demote, jit answers
        np.testing.assert_array_equal(np.asarray(out), np.arange(8.0) * 2)
        assert counters["demoted"] == 1
        g(jnp.arange(8.0))  # demoted variant: jit again, no double count
        assert counters == {"demoted": 1, "shape": 0}

    def test_unrelated_valueerror_propagates(self):
        from automodel_tpu.observability.manager import _GuardedCompiled

        fn = jax.jit(lambda x: x * 2)

        def exploding(*a):
            raise ValueError("something else entirely")

        g = _GuardedCompiled(exploding, fn, (jnp.arange(8.0),))
        with pytest.raises(ValueError, match="something else"):
            g(jnp.arange(8.0))


class TestCompileCacheConfigure:
    def test_none_and_missing_dir_are_noops(self):
        from automodel_tpu.observability import compile_cache

        assert compile_cache.configure(None) == {}
        assert compile_cache.configure({"min_entry_size_bytes": 0}) == {}

    def test_configure_applies_and_snapshot_reports(self, tmp_path):
        from automodel_tpu.observability import compile_cache

        old_dir = jax.config.jax_compilation_cache_dir
        try:
            applied = compile_cache.configure({
                "dir": str(tmp_path / "xla_cache"),
                "min_entry_size_bytes": 0,
                "min_compile_time_secs": 0,
            })
            assert applied["dir"] == str(tmp_path / "xla_cache")
            snap = compile_cache.snapshot()
            assert snap["dir"] == str(tmp_path / "xla_cache")
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
