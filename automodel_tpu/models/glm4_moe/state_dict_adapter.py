"""GLM4-MoE HF key/layout mapping (reference models/glm4_moe/state_dict_adapter.py).

Qwen3-MoE-style per-expert tensors plus the DeepSeek-style extras: the gate's
``e_score_correction_bias`` and one ``shared_experts`` MLP per MoE layer.
"""

from __future__ import annotations

from automodel_tpu.models.common.state_dict import Entry, MappingAdapter
from automodel_tpu.models.llama.state_dict_adapter import _t
from automodel_tpu.models.qwen3_moe.state_dict_adapter import (
    attention_entries,
    moe_expert_entries,
)

__all__ = ["Glm4MoeStateDictAdapter"]


def shared_expert_entries(moe_range) -> list[Entry]:
    pre = "model.layers.{i}.mlp.shared_experts"
    ours = "moe_layers.moe.shared_experts"
    return [
        Entry(f"{pre}.gate_proj.weight", f"{ours}.w_gate", _t, _t, layer_range=moe_range),
        Entry(f"{pre}.up_proj.weight", f"{ours}.w_up", _t, _t, layer_range=moe_range),
        Entry(f"{pre}.down_proj.weight", f"{ours}.w_down", _t, _t, layer_range=moe_range),
    ]


class Glm4MoeStateDictAdapter(MappingAdapter):
    def __init__(self, cfg, scan_layers: bool = True):
        k = cfg.first_k_dense_replace
        L = cfg.num_hidden_layers
        moe_range = (k, L)
        entries = [
            Entry("model.embed_tokens.weight", "embed"),
            Entry("model.norm.weight", "final_norm"),
            *attention_entries(cfg, "moe_layers", layer_range=moe_range),
            Entry("model.layers.{i}.mlp.gate.weight", "moe_layers.moe.gate.weight",
                  layer_range=moe_range),
            Entry("model.layers.{i}.mlp.gate.e_score_correction_bias",
                  "moe_layers.moe.gate.score_correction_bias", layer_range=moe_range),
            *moe_expert_entries("model.layers.{i}.mlp", "moe_layers.moe", layer_range=moe_range),
        ]
        if cfg.moe.n_shared_experts > 0:
            entries += shared_expert_entries(moe_range)
        if k > 0:
            entries += [
                *attention_entries(cfg, "dense_layers", layer_range=(0, k)),
                Entry("model.layers.{i}.mlp.gate_proj.weight", "dense_layers.w_gate", _t, _t, layer_range=(0, k)),
                Entry("model.layers.{i}.mlp.up_proj.weight", "dense_layers.w_up", _t, _t, layer_range=(0, k)),
                Entry("model.layers.{i}.mlp.down_proj.weight", "dense_layers.w_down", _t, _t, layer_range=(0, k)),
            ]
        if not cfg.tie_word_embeddings:
            entries.append(Entry("lm_head.weight", "lm_head", _t, _t))
        super().__init__(entries, L, scan_layers, num_experts=cfg.moe.n_routed_experts)
