"""VLM finetune recipe (reference FinetuneRecipeForVLM, recipes/vlm/finetune.py:469).

Subclasses the LLM finetune recipe: image-text model factory, VLM collation with
image-token expansion, and a ``freeze`` section (reference freeze_config) that
splits params into trainable/frozen *subtrees* — frozen parts ride through the
jitted step as a non-differentiated argument (the same mechanism PEFT uses), so
optimizer state only covers what trains.

.. code-block:: yaml

    model:
      pretrained_model_name_or_path: /path/to/llava   # or config: {...}
    freeze:
      freeze_vision_tower: true      # reference default
      freeze_language_model: false
      freeze_projector: false
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.config.loader import ConfigNode
from automodel_tpu.config.cli_overrides import parse_args_and_load_config
from automodel_tpu.data.vlm.collate import vlm_collate
from automodel_tpu.models.auto import AutoModelForImageTextToText, load_hf_config
from automodel_tpu.ops.losses import masked_cross_entropy
from automodel_tpu.recipes.llm.train_ft import TrainFinetuneRecipeForNextTokenPrediction
from automodel_tpu.training.train_step import make_train_step

logger = logging.getLogger(__name__)

__all__ = ["FinetuneRecipeForVLM", "main"]

# freeze-config key -> candidate param-subtree names across families (llava
# splits vision_tower/language_model/projector; qwen-vl nests the merger inside
# a flat "visual" tower beside flat language keys; omni adds "audio")
_FREEZE_KEYS = {
    "freeze_vision_tower": ("vision_tower", "visual"),
    "freeze_audio_tower": ("audio",),
    "freeze_language_model": (
        "language_model", "embed", "final_norm", "layers", "dense_layers",
        "moe_layers", "lm_head",
    ),
    "freeze_projector": ("projector",),
}


class FinetuneRecipeForVLM(TrainFinetuneRecipeForNextTokenPrediction):
    # -- model --------------------------------------------------------------
    def _build_model_and_params(self):
        cfg = self.cfg
        pretrained = cfg.get("model.pretrained_model_name_or_path")
        with self.mesh:
            if pretrained:
                self.hf_config = load_hf_config(pretrained)
                # fence BEFORE the rules-applied load: an unsupported family
                # would otherwise die on vision-block sharding divisibility
                # with an opaque pjit error instead of the clean fence
                self.model = AutoModelForImageTextToText.from_config(
                    self.hf_config, backend=self.backend
                )
                self._check_pp_support()
                self.model, self.params = AutoModelForImageTextToText.from_pretrained(
                    pretrained, backend=self.backend, dtype=jnp.float32, rules=self.rules
                )
            else:
                model_cfg = cfg.get("model.config")
                if model_cfg is None:
                    raise ValueError("config needs model.pretrained_model_name_or_path or model.config")
                self.hf_config = model_cfg.to_dict() if isinstance(model_cfg, ConfigNode) else dict(model_cfg)
                self.model = AutoModelForImageTextToText.from_config(self.hf_config, backend=self.backend)
                self._check_pp_support()
                shardings = self.rules.tree_sharding(self.model.logical_axes())
                init_fn = jax.jit(lambda k: self.model.init(k, jnp.float32), out_shardings=shardings)
                self.params = init_fn(self.rng.key("model_init"))
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))
        logger.info("model: %s (%.1fM params)", type(self.model).__name__, n_params / 1e6)

    def _check_pp_support(self):
        """Fence BEFORE param init: under pp the sharding rules put the layer
        axis on pp, which only makes sense for families whose text stack we
        pipeline (vision-tower blocks of an unsupported family would otherwise
        fail sharding-divisibility first with an opaque pjit error)."""
        if self.mesh_ctx.pp <= 1:
            return
        if hasattr(self.model, "merged_embeds"):
            return  # LLaVA lineage: dense text stack behind merged embeds
        if getattr(self.model, "pp_hidden_supported", False):
            return  # mrope/deepstack families with a model-provided pp hidden path
        raise NotImplementedError(
            "vlm + pp is wired for models exposing merged_embeds (LLaVA lineage) "
            "or a make_pp_hidden pipelined path (qwen3-vl deepstack); this "
            "family interleaves vision state into the layer stream without one"
        )

    def _build_peft(self):
        # freeze split (reference freeze_config, vlm/finetune.py:86-113)
        freeze_cfg = self.cfg.get("freeze") or ConfigNode({"freeze_vision_tower": True})
        frozen_keys = [
            key
            for cfg_key, tree_keys in _FREEZE_KEYS.items()
            if freeze_cfg.get(cfg_key, cfg_key == "freeze_vision_tower")
            for key in tree_keys
        ]
        self.frozen_keys = [k for k in frozen_keys if k in self.params]
        if len(self.frozen_keys) == len(self.params):
            raise ValueError("freeze config freezes every submodule; nothing to train")
        self.frozen_params = {k: self.params[k] for k in self.frozen_keys}
        self.train_params = {k: v for k, v in self.params.items() if k not in self.frozen_keys}
        logger.info("vlm freeze: frozen=%s trainable=%s", self.frozen_keys, list(self.train_params))

        # vlm + peft (reference composes them freely, infrastructure.py:303):
        # LoRA factors attach to the UNFROZEN subtrees; the base becomes part of
        # the frozen argument and only the adapter trains
        self.peft = None
        peft_cfg = self.cfg.get("peft")
        if peft_cfg is not None:
            from automodel_tpu.peft.lora import (
                PeftConfig, count_lora_params, init_lora_params, lora_logical_axes,
            )

            self.peft = PeftConfig.from_dict(peft_cfg.to_dict())
            axes = {k: v for k, v in self.model.logical_axes().items()
                    if k in self.train_params}
            host_lora = init_lora_params(
                self.train_params, axes, self.peft, self.rng.key("lora_init")
            )
            shardings = self.rules.tree_sharding(lora_logical_axes(axes, self.peft))
            self.lora_base = self.train_params  # frozen base of the trainable subtrees
            self.train_params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), host_lora, shardings
            )
            logger.info(
                "vlm peft: %d adapter params on %s",
                count_lora_params(self.train_params), list(axes),
            )

    # -- data ---------------------------------------------------------------
    def _wrap_dataset_and_collate(self, dataset, pad_id: int):
        from automodel_tpu.data.vlm.collate_fns import (
            kimi_vl_collate, qwen3_omni_collate, qwen_vl_collate,
        )

        mcfg = self.model.config
        name = type(self.model).__name__
        # vlm.image_size: (grid_h, grid_w) in PATCHES — one fixed grid per config
        # keeps every media shape static under jit
        image_size = self.cfg.get("vlm.image_size")
        if image_size is not None:
            image_size = tuple(image_size)
        if name == "Qwen3OmniMoeThinkerForConditionalGeneration":
            fn = lambda exs: qwen3_omni_collate(
                exs, self.tokenizer, self.model, self.seq_len, pad_id,
                image_size=image_size,
            )
        elif name == "Qwen3VLMoeForConditionalGeneration":
            fn = lambda exs: qwen_vl_collate(
                exs, self.tokenizer, self.model, self.seq_len, pad_id,
                image_size=image_size,
            )
        elif name in ("KimiVLForConditionalGeneration", "KimiK25VLForConditionalGeneration"):
            fn = lambda exs: kimi_vl_collate(
                exs, self.tokenizer, self.model, self.seq_len, pad_id,
                image_size=image_size,
            )
        else:  # LLaVA composition (single-image, fixed token count)
            fn = lambda exs: vlm_collate(
                exs,
                tokenizer=self.tokenizer,
                seq_len=self.seq_len,
                image_token_id=mcfg.image_token_index,
                num_image_tokens=mcfg.num_image_tokens,
                image_size=mcfg.vision.image_size,
                pad_token_id=pad_id,
            )
        return dataset, fn

    # -- step ---------------------------------------------------------------
    _RESERVED = ("input_ids", "labels", "positions", "segment_ids")

    def _model_kwargs(self, batch):
        """Reassemble the collator's extra batch keys into model-call kwargs
        (coord pairs ride as separate _b/_s arrays so the batch stays a flat
        array pytree)."""
        kw = {}
        for k, v in batch.items():
            if k in self._RESERVED or k.endswith(("_coords_b", "_coords_s")):
                continue
            kw[k] = v
        for prefix in ("visual", "media", "audio"):
            b, s = batch.get(f"{prefix}_coords_b"), batch.get(f"{prefix}_coords_s")
            if b is not None:
                kw[f"{prefix}_coords"] = (b, s)
        return kw

    def _model_forward(self, params, batch, training):
        """Model call with the collator's extra modalities; the shared base
        ``_forward_loss`` keeps the loss + MoE aux/expert-load handling."""
        import inspect

        if not hasattr(self, "_model_call_params"):
            self._model_call_params = set(
                inspect.signature(type(self.model).__call__).parameters
            )
        kw = self._model_kwargs(batch)
        kw["segment_ids"] = batch["segment_ids"]
        kw["rules"] = self.rules if self.mesh.size > 1 else None
        kw["training"] = training
        kw["token_mask"] = batch["segment_ids"] != 0
        if "positions3" not in kw:
            kw["positions"] = batch.get("positions")
        kw = {k: v for k, v in kw.items() if k in self._model_call_params}
        return self.model(params, batch["input_ids"], **kw)

    def _build_stack_shardings(self):
        shardings = super()._build_stack_shardings()
        shardings["replicated"] = self.rules.sharding((None,))
        return shardings

    def _device_put_stack(self, stack):
        """Per-key shardings: (n_micro, B, S) token streams shard over batch;
        flat media tensors (patches, coords, grids) replicate. Shardings are
        built once in setup() — rebuilding NamedShardings per key per batch
        was pure host overhead on the input path."""
        tokens = self._stack_shardings["tokens"]
        replicated = self._stack_shardings["replicated"]
        return {
            k: jax.device_put(v, tokens if k in self._RESERVED else replicated)
            for k, v in stack.items()
        }

    def _build_train_step(self):
        if self.mesh_ctx.pp > 1:
            return self._build_pp_train_step()
        use_dropout = self.peft is not None and self.peft.dropout > 0.0
        if self.peft is not None:
            from automodel_tpu.peft.lora import lora_merged_loss

            split_loss = lora_merged_loss(
                lambda merged, fr, b, n: self._forward_loss(
                    {**fr["frozen"], **merged}, b, n),
                lambda fr: fr["lora_base"], self.peft, use_dropout,
            )
        else:
            def split_loss(trainable, frozen, batch, num_label_tokens):
                return self._forward_loss(
                    {**frozen["frozen"], **trainable}, batch, num_label_tokens
                )

        self._step_needs_rng = use_dropout
        step = make_train_step(split_loss, self.optimizer, with_frozen=True,
                               pass_rng=use_dropout)
        return jax.jit(step, donate_argnums=(0, 1))

    def _build_pp_train_step(self):
        """vlm x pp (reference pipelines the wrapped VLM module the same way,
        infrastructure.py:303): the vision tower + embed merge run per microbatch
        in plain GSPMD (lax.map — one microbatch's vision activations at a
        time), the TEXT layer stack pipelines over pp via the shared dense
        hidden-states pipeline, and the head+CE close outside the manual region.
        Wired for families exposing ``merged_embeds`` over a standard dense text
        stack (LLaVA lineage); mrope/deepstack families (qwen-vl, kimi, omni)
        interleave vision state into the layer stream and stay fenced."""
        from automodel_tpu.parallel.pipeline import (
            _make_head_loss, make_dense_decoder_pp_hidden,
        )
        from automodel_tpu.training.train_step import make_pp_train_step

        model = self.model
        self._check_pp_support()
        cfg_t = model.config.text
        backend = model.backend
        dtype = backend.jnp_dtype
        virtual = int(self.cfg.get("distributed.pp_virtual_stages", 1))
        # honors loss_name (linear_ce for big-vocab VLMs — the scale pp exists
        # for); additive per-microbatch contract, divided by n below
        head_loss = _make_head_loss(cfg_t, dtype, self.loss_name)

        if not hasattr(model, "merged_embeds"):
            # mrope/deepstack families (qwen3-vl): the model owns the pipelined
            # hidden path (vision per microbatch outside the manual region,
            # deepstack features riding the ring — qwen3_vl_moe.make_pp_hidden)
            vl_hidden = model.make_pp_hidden(
                self.mesh, self.rules, seq_len_hint=self.seq_len,
                circular_repeats=virtual,
            )

            def pp_core(full, batch_stack, n):
                h_stack, aux_loss, extras = vl_hidden(full, batch_stack, n)
                other = {k: v for k, v in full.items()
                         if k not in ("moe_layers", "visual")}
                losses = jax.lax.map(
                    lambda args: head_loss(other, {"h": args[0]}, {"labels": args[1]}),
                    (h_stack, batch_stack["labels"]),
                )
                return losses.sum() / n + aux_loss, extras
        else:
            hidden_fn = make_dense_decoder_pp_hidden(
                cfg_t, backend, self.mesh, circular_repeats=virtual
            )

            def pp_core(full, batch_stack, n):
                lm = full["language_model"]

                def embed_mb(mb):
                    return model.merged_embeds(full, mb["input_ids"], mb.get("pixel_values"))

                embed_keys = {
                    k: batch_stack[k] for k in ("input_ids", "pixel_values")
                    if k in batch_stack
                }
                x_stack = {
                    "h": jax.lax.map(embed_mb, embed_keys),
                    "positions": batch_stack["positions"],
                    "segment_ids": batch_stack["segment_ids"],
                }
                h_stack = hidden_fn(lm["layers"], x_stack)
                losses = jax.lax.map(
                    lambda args: head_loss(lm, {"h": args[0]}, {"labels": args[1]}),
                    (h_stack, batch_stack["labels"]),
                )
                return losses.sum() / n

        use_dropout = self.peft is not None and self.peft.dropout > 0.0
        if self.peft is not None:
            from automodel_tpu.peft.lora import lora_merged_loss

            split_loss = lora_merged_loss(
                lambda merged, fr, bs, n: pp_core({**fr["frozen"], **merged}, bs, n),
                lambda fr: fr["lora_base"], self.peft, use_dropout,
            )
        else:
            def split_loss(trainable, frozen, batch_stack, n):
                return pp_core({**frozen["frozen"], **trainable}, batch_stack, n)

        self._step_needs_rng = use_dropout
        step = make_pp_train_step(split_loss, self.optimizer, with_frozen=True,
                                  guard_nonfinite=self._check_nan_grads,
                                  pass_rng=use_dropout)
        return jax.jit(step, donate_argnums=(0, 1))

    @property
    def _frozen_arg(self):
        frozen = {"frozen": self.frozen_params}
        if self.peft is not None:
            frozen["lora_base"] = self.lora_base
        return frozen

    def run_train_validation_loop(self):
        jitted = self._train_step
        # the base loop's peft extra is replaced by _frozen_arg (the VLM step
        # threads its own frozen/base trees); its trailing dropout rng passes
        self._train_step = lambda p, o, stack, *extra: jitted(
            p, o, stack, self._frozen_arg,
            *((extra[-1],) if self._step_needs_rng else ()),
        )
        super().run_train_validation_loop()
        # reassemble the full tree for saves/consumers
        if self.peft is not None:
            from automodel_tpu.peft.lora import merge_lora_params

            merged = merge_lora_params(self.lora_base, self.train_params, self.peft)
            self.params = {**self.frozen_params, **merged}
        else:
            self.params = {**self.frozen_params, **self.train_params}

    def _run_validation(self, step: int):
        if self._eval_step is None:
            from automodel_tpu.training.train_step import make_eval_step

            if self.peft is not None:
                from automodel_tpu.peft.lora import merge_lora_params

                eval_loss = lambda t, f, b, n: self._forward_loss(
                    {**f["frozen"], **merge_lora_params(f["lora_base"], t, self.peft)},
                    b, n, training=False,
                )
            else:
                eval_loss = lambda t, f, b, n: self._forward_loss(
                    {**f["frozen"], **t}, b, n, training=False
                )
            self._eval_step = jax.jit(make_eval_step(eval_loss, with_frozen=True))
        total, count = 0.0, 0
        for batch in self._iter_val_batches():
            n = int((batch["labels"] != -100).sum())
            total += float(self._eval_step(self.train_params, batch, n, self._frozen_arg)) * n
            count += n
        self._log_val_loss(step, total, count)

    def _save(self, step: int, consolidated: bool | None = None):
        # ``consolidated`` matches the base signature: the inherited preemption
        # path passes it to drop the HF export under a short grace window
        self._last_saved_step = step
        client = {
            "rng": self.rng,
            "step_scheduler": self.step_scheduler,
            "dataloader": self.dataloader,
            "resilience": self.resilience,
            "frozen_keys": list(self.frozen_keys),
        }
        if self._pipeline is not None:
            # prefetch: checkpoint the consumed-position snapshots, not the
            # worker-advanced live scheduler/dataloader (train_ft._save)
            client.update(self._pipeline.client_states())
        if self.peft is not None:
            from automodel_tpu.peft.lora import merge_lora_params

            merged = merge_lora_params(self.lora_base, self.train_params, self.peft)
            full = {**self.frozen_params, **merged}
        else:
            full = {**self.frozen_params, **self.train_params}
        self.checkpointer.save(
            step, self.train_params, self.opt_state, client_states=client,
            hf_params=full, consolidated=consolidated,
        )
        self.resilience.record_checkpoint(step)


def main(cfg: ConfigNode | None = None, argv=None):
    if cfg is None:
        cfg = parse_args_and_load_config(argv)
    recipe = FinetuneRecipeForVLM(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    return recipe


if __name__ == "__main__":
    main()
